"""Mixture-of-experts routing — top-k gating with two dispatch back-ends.

TPU-first design (the GShard/Switch recipe rather than a torch-style gather
loop), with the implementation picked per mesh (``moe_ffn``):

- **sorted** (long sequences / drop-free capacity): claims sort by expert id
  and the expert FFNs run as ``lax.ragged_dot`` grouped matmuls over
  expert-contiguous rows — O(B·S·k) routing memory, drop-free safe at any
  sequence length (the einsum path is O(B·S·E·C) = O(S²) at Mixtral's
  drop-free capacity).
- **einsum** (ep > 1, and the measured winner at short S — see ``moe_ffn``):
  dense one-hot dispatch/combine tensors and batched einsums over a leading
  expert dim. Under GSPMD, sharding that dim on ``ep`` partitions the expert
  FFNs the way row-parallel TP partitions a matmul: dispatch stays
  device-local, and the combine contracts the sharded expert dim — one
  all-reduce over ``ep`` per layer, inserted by XLA. ragged_dot's group dim
  is opaque to the partitioner, so this remains the ep-sharded form.

Both share one routing semantics (same capacity drop rule, same Switch aux
loss) — pinned by ``tests/test_moe.py::test_sorted_and_einsum_dispatch_agree``.

Reference context: the reference has no MoE implementation of its own (only
DeepSpeed-MoE passthrough flags, ``utils/dataclasses.py``); this is a native
capability of the framework (SURVEY.md §2.4 lists EP as a note-only strategy
for the reference).

Shapes (per group = batch row): x (B, S, h); router (h, E); k choices per
token; capacity C per expert per group.

- ``dispatch`` (B, S, E, C) one-hot: token (b, s) occupies slot c of expert e.
- ``combine``  (B, S, E, C) = dispatch · gate: weights for the return trip.
- expert inputs  = einsum('bsec,bsh->ebch', dispatch, x)
- expert outputs = SwiGLU with weights (E, h, i) via 'ebch,ehi->ebci'
- token outputs  = einsum('ebch,bsec->bsh', expert_out, combine)

The auxiliary load-balancing loss is the Switch formulation:
``E · Σ_e  f_e · p̄_e`` (token fraction × mean router prob).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def router_capacity(tokens_per_group: int, num_experts: int, k: int, capacity_factor: float) -> int:
    """Slots per expert per group; multiples of 8 keep the lanes happy."""
    cap = int(np.ceil(tokens_per_group * k * capacity_factor / num_experts))
    return max(8, int(np.ceil(cap / 8)) * 8)


def _route(router_logits, k: int, capacity: int):
    """Shared routing front-end for BOTH dispatch back-ends — the single source
    of the capacity-drop semantics and the Switch aux loss.

    Returns ``(expert_idx (B,S,k), gate_vals (B,S,k) normalized, onehot
    (B,S,k,E), pos (B,S·k,E) claim rank per expert, keep (B,S·k,E) kept-claim
    one-hot, aux_loss scalar)``. Earlier tokens (and higher-priority choices)
    claim an expert's ``capacity`` slots per batch row first; the Switch aux
    loss is ``E · Σ_e f_e · p̄_e`` (≈1 at perfect balance)."""
    B, S, E = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)  # (B,S,E)
    gate_vals, expert_idx = jax.lax.top_k(probs, k)  # (B,S,k)
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # (B,S,k,E)
    flat = onehot.reshape(B, S * k, E)
    # Position of each claim within its expert's slots (count of prior claims).
    pos = jnp.cumsum(flat, axis=1) - flat  # (B, S·k, E)
    keep = flat * (pos < capacity)

    top1 = jax.nn.one_hot(expert_idx[..., 0], E, dtype=jnp.float32)
    aux_loss = E * jnp.sum(jnp.mean(top1, axis=(0, 1)) * jnp.mean(probs, axis=(0, 1)))
    return expert_idx, gate_vals, onehot, pos, keep, aux_loss


def top_k_routing(router_logits, k: int, capacity: int, dtype=jnp.float32):
    """Build dispatch/combine tensors from router logits (the einsum back-end).

    router_logits: (B, S, E). Returns (dispatch (B,S,E,C), combine (B,S,E,C),
    aux_loss scalar), dispatch/combine in ``dtype``. Tokens beyond an expert's
    capacity are dropped (their combine weights are zero → they ride the
    residual stream only, the standard Switch behavior).

    ``dtype`` sizes the C-width one-hot intermediates — the path's dominant
    HBM traffic. Routing arithmetic (softmax, cumsum ranks, aux) stays fp32
    regardless; one-hot values are exact in any float dtype, and gate values
    were cast to the compute dtype at the combine einsum anyway, so bf16 here
    changes traffic, not semantics.

    Construction collapses the k dim BEFORE any C-width tensor exists:
    ``top_k`` returns distinct experts per token, so a token holds at most
    one claim per expert and the per-(token, expert) claim rank / kept flag /
    gate reduce over k in O(B·S·k·E) — the C-width one-hot is then built
    once at (B,S,E,C). The previous form materialized the (B,S·k,E,C) slot
    tensor (k× the traffic) plus a 5-D max and a C-width combine einsum; the
    r5 on-chip attribution measured that front-end at 5.1 ms/layer against
    9.2 ms of expert matmuls (benchmarks/moe_op_attribution.py), which is
    what paid for this rewrite.
    """
    B, S, E = router_logits.shape
    expert_idx, gate_vals, onehot, pos, keep, aux_loss = _route(router_logits, k, capacity)
    keep4 = keep.reshape(B, S, k, E)  # {0,1}: claim kept under capacity
    # Per (token, expert): rank of its (unique) claim, kept flag, gate value.
    rank = jnp.sum(pos.reshape(B, S, k, E) * keep4, axis=2)  # (B,S,E)
    claimed = jnp.max(keep4, axis=2)  # (B,S,E)
    gate_e = jnp.einsum("bske,bsk->bse", keep4, gate_vals)  # 0 when dropped

    slotoh = jax.nn.one_hot(rank.astype(jnp.int32), capacity, dtype=dtype)  # (B,S,E,C)
    dispatch = claimed.astype(dtype)[..., None] * slotoh
    combine = gate_e.astype(dtype)[..., None] * slotoh
    return dispatch, combine, aux_loss


def moe_ffn_sorted(x, router_w, w_gate, w_up, w_down, *, k: int, capacity_factor: float = 1.25):
    """Sort-by-expert MoE layer — O(S·k) dispatch memory (VERDICT r2 #4).

    Claims (token, choice) are grouped by expert id so each expert's tokens
    are contiguous and the three FFN matmuls run as ``lax.ragged_dot``
    (grouped matmul over expert-contiguous rows — the MXU-native megablocks
    shape). No (B,S,E,C) one-hot ever exists: peak routing intermediates are
    O(B·S·k·max(E,h)) versus the einsum path's O(B·S·E·C) — quadratic in S at
    Mixtral's drop-free capacity. Drop semantics match the einsum path exactly
    (same per-batch-row capacity rule; dropped claims keep gate 0).

    The grouping permutation is a COUNTING sort built from the routing
    cumsum's per-expert claim ranks — ``dest = expert_base + row_base +
    rank_within(row, expert)`` — not a comparison ``argsort``: the O(n·log²n)
    bitonic sort was the wrapper's dominant VPU cost (r5 on-chip: 25.5% →
    35.9% active-MFU at the bench shape). The inverse permutation is
    materialized with one tiny int32 scatter so token rows move with a
    GATHER, and the combine re-gathers each claim's output row at ``dest`` —
    sum over the k choices — so no scatter-add touches (T·k, h) data at all.
    Identical claim order to the old stable argsort (by (expert, batch row,
    claim index)), so numerics are unchanged.
    """
    B, S, h = x.shape
    E = router_w.shape[-1]
    capacity = router_capacity(S, E, k, capacity_factor)
    router_logits = (x @ router_w.astype(x.dtype)).astype(jnp.float32)
    expert_idx, gate_vals, onehot, pos, keep, aux = _route(router_logits, k, capacity)
    gates = gate_vals * jnp.sum(keep.reshape(B, S, k, E), axis=-1)  # dropped → 0

    Sk = S * k
    N = B * Sk
    e_claim = expert_idx.reshape(B, Sk)
    # Rank of each claim within (its batch row, its expert) — already computed
    # by the routing cumsum; the capacity clamp never applies to ranks here
    # (dropped claims still occupy a ragged row; only their gate is zero).
    rank = jnp.take_along_axis(pos, e_claim[..., None], axis=2)[..., 0].astype(jnp.int32)
    counts = jnp.sum(onehot.reshape(B, Sk, E), axis=1).astype(jnp.int32)  # (B, E)
    row_base = jnp.cumsum(counts, axis=0) - counts  # claims of e in earlier rows
    group_sizes = jnp.sum(counts, axis=0)  # (E,)
    expert_base = jnp.cumsum(group_sizes) - group_sizes
    dest = (
        jnp.take(expert_base, e_claim, axis=0)
        + jnp.take_along_axis(row_base, e_claim, axis=1)
        + rank
    ).reshape(N)
    # Inverse permutation via one (N,) int32 scatter; rows then move by gather.
    inv = jnp.zeros((N,), jnp.int32).at[dest].set(jnp.arange(N, dtype=jnp.int32))

    claim_x = jnp.broadcast_to(x[:, :, None], (B, S, k, h)).reshape(N, h)
    sorted_in = jnp.take(claim_x, inv, axis=0)  # (N, h) expert-contiguous

    # f32 inputs (tests / CPU) get exact accumulation; bf16 keeps the MXU fast path.
    prec = jax.lax.Precision.HIGHEST if x.dtype == jnp.float32 else None
    rd = lambda lhs, rhs: jax.lax.ragged_dot(
        lhs, rhs.astype(x.dtype), group_sizes, precision=prec
    )
    gated = jax.nn.silu(rd(sorted_in, w_gate)) * rd(sorted_in, w_up)
    sorted_out = rd(gated, w_down)  # (N, h)

    y = jnp.take(sorted_out, dest, axis=0).reshape(B, S, k, h)  # gather combine
    out = jnp.sum(y * gates.reshape(B, S, k, 1).astype(x.dtype), axis=2)
    return out.reshape(B, S, h), aux


def moe_ffn_indexed(x, router_w, w_gate, w_up, w_down, *, k: int, capacity_factor: float = 1.25):
    """Gather-based capacity-slot dispatch — dense expert matmuls without the
    one-hot einsums OR the sorted path's scatter-add.

    The einsum back-end pays two O(B·S·E·C·h) dispatch/combine matmuls
    (~20% extra FLOPs at the bench shape) just to move tokens; the sorted
    back-end avoids them but pays argsort + ragged_dot + a scatter-add.
    This back-end moves tokens with *indices* instead:

    1. scatter the claim ranks into a ``(B, E, C)`` slot→token index map
       (O(S·k) elements — no C-sized one-hot ever exists),
    2. gather tokens into ``(E, B, C, h)`` capacity slots and run the SAME
       dense batched expert einsums as the einsum path (full MXU tiles,
       no ragged group dim),
    3. combine by gathering each claim's output slot and summing the k
       gate-weighted rows — a pure gather, no scatter.

    Routing memory is O(B·S·k·E + B·E·C·h) — subquadratic in S at drop-free
    capacity, like sorted. Drop semantics are identical to both other paths
    (same ``_route`` front-end); unfilled slots default to token 0 and compute
    harmless padding work that the combine never reads (gate 0). Not
    ep-shardable for the same reason as sorted: the gather indices are opaque
    to the partitioner — ``moe_ffn`` keeps einsum under ep.
    """
    B, S, h = x.shape
    E = router_w.shape[-1]
    capacity = router_capacity(S, E, k, capacity_factor)
    router_logits = (x @ router_w.astype(x.dtype)).astype(jnp.float32)
    expert_idx, gate_vals, _onehot, pos, keep, aux = _route(router_logits, k, capacity)

    Sk = S * k
    e_j = expert_idx.reshape(B, Sk)  # chosen expert per claim
    # Rank of each claim within its expert's slots, and whether it was kept.
    p_j = jnp.take_along_axis(pos, e_j[..., None], axis=2)[..., 0].astype(jnp.int32)
    kept_j = jnp.sum(keep, axis=-1)  # (B, Sk) ∈ {0,1}

    # Slot→token map: claim j of row b sits at slot (e_j, p_j); dropped claims
    # aim at row C (out of bounds) and are dropped by the scatter.
    tok_j = jnp.broadcast_to((jnp.arange(Sk, dtype=jnp.int32) // k)[None], (B, Sk))
    b_idx = jnp.broadcast_to(jnp.arange(B, dtype=jnp.int32)[:, None], (B, Sk))
    p_sc = jnp.where(kept_j > 0, p_j, capacity)
    slot_tok = jnp.zeros((B, E, capacity), jnp.int32).at[b_idx, e_j, p_sc].set(
        tok_j, mode="drop"
    )

    expert_in = jnp.take_along_axis(
        x, slot_tok.reshape(B, E * capacity)[..., None], axis=1
    ).reshape(B, E, capacity, h).transpose(1, 0, 2, 3)  # (E, B, C, h)
    expert_in = _constrain_expert_layout(expert_in)
    gated = jax.nn.silu(jnp.einsum("ebch,ehi->ebci", expert_in, w_gate.astype(x.dtype)))
    up = jnp.einsum("ebch,ehi->ebci", expert_in, w_up.astype(x.dtype))
    expert_out = jnp.einsum("ebci,eih->ebch", gated * up, w_down.astype(x.dtype))

    # Combine: gather each claim's output slot, weight by its gate (0 when
    # dropped — the clipped gather row is then never read into the sum).
    eo = expert_out.transpose(1, 0, 2, 3).reshape(B, E * capacity, h)
    flat_ec = e_j * capacity + jnp.clip(p_j, 0, capacity - 1)
    y = jnp.take_along_axis(eo, flat_ec[..., None], axis=1)  # (B, Sk, h)
    g = (gate_vals.reshape(B, Sk) * kept_j).astype(x.dtype)
    out = jnp.sum((y * g[..., None]).reshape(B, S, k, h), axis=2)
    return out, aux


def moe_ffn_einsum(x, router_w, w_gate, w_up, w_down, *, k: int, capacity_factor: float = 1.25):
    """Dense one-hot einsum MoE layer (GShard form) — the ``ep``-sharded path.

    x: (B, S, h); router_w: (h, E); w_gate/w_up: (E, h, i); w_down: (E, i, h).
    Returns (output (B, S, h), aux_loss scalar). Sharding the leading E dim of
    the expert weights on ``ep`` keeps expert compute local; the final combine
    contracts the sharded expert dim — one all-reduce over ``ep`` per layer,
    which is what GSPMD partitions well (ragged_dot's group dim is opaque to
    the partitioner). Memory is O(B·S·E·C): prefer ``moe_ffn_sorted`` whenever
    the mesh has no ep axis.
    """
    B, S, h = x.shape
    E = router_w.shape[-1]
    capacity = router_capacity(S, E, k, capacity_factor)
    router_logits = (x @ router_w.astype(x.dtype)).astype(jnp.float32)
    dispatch, combine, aux = top_k_routing(router_logits, k, capacity, dtype=x.dtype)

    expert_in = jnp.einsum("bsec,bsh->ebch", dispatch, x)
    expert_in = _constrain_expert_layout(expert_in)
    gated = jax.nn.silu(jnp.einsum("ebch,ehi->ebci", expert_in, w_gate.astype(x.dtype)))
    up = jnp.einsum("ebch,ehi->ebci", expert_in, w_up.astype(x.dtype))
    expert_out = jnp.einsum("ebci,eih->ebch", gated * up, w_down.astype(x.dtype))
    expert_out = _constrain_expert_layout(expert_out)
    out = jnp.einsum("ebch,bsec->bsh", expert_out, combine.astype(x.dtype))
    return out, aux


def moe_ffn(x, router_w, w_gate, w_up, w_down, *, k: int, capacity_factor: float = 1.25):
    """Route → expert FFN → combine, auto-selecting the implementation.

    - ep > 1 in the mesh → **einsum** (the ep-shardable form; ragged_dot's
      group dim is opaque to the partitioner).
    - otherwise, short sequences at modest capacity → **einsum** too: the r5
      op-level attribution (PERF.md; benchmarks/moe_op_attribution.py) shows
      ``lax.ragged_dot`` runs 31% below the dense per-expert einsums at the
      bench shape (127 vs 181 TF/s fwd+bwd) and the row gathers cost more
      than einsum's dispatch matmuls — end-to-end einsum 42.6% vs sorted
      27.7% active-MFU at S=1024/cf1.0 on v5e; sorted ties einsum near
      S=4096 (30.8% vs 31.3%).
    - long sequences or drop-free capacity → **sorted** (einsum memory is
      O(S²) at Mixtral's drop-free cf = E/k).

    Override with ``ACCELERATE_MOE_DISPATCH=sorted|einsum|indexed``."""
    import os

    impl = os.environ.get("ACCELERATE_MOE_DISPATCH", "auto")
    if impl == "auto":
        from ..state import PartialState

        try:
            mesh = PartialState().mesh
            ep = mesh.shape.get("ep", 1) if mesh is not None else 1
        except Exception:
            ep = 1
        if ep > 1:
            impl = "einsum"
        else:
            S = x.shape[1]
            impl = "einsum" if (S <= 2048 and capacity_factor <= 2.0) else "sorted"
    fns = {"sorted": moe_ffn_sorted, "einsum": moe_ffn_einsum,
           "indexed": moe_ffn_indexed}
    if impl not in fns:
        raise ValueError(
            f"ACCELERATE_MOE_DISPATCH={impl!r} is not a dispatch back-end "
            f"(valid: auto|{'|'.join(sorted(fns))})"
        )
    return fns[impl](x, router_w, w_gate, w_up, w_down, k=k, capacity_factor=capacity_factor)


def _constrain_expert_layout(t):
    """Pin (E, B, C, ...) intermediates to expert-major sharding: E on ``ep``,
    B on the data axes — guarantees the partitioner keeps expert compute on
    the expert's own shard instead of gathering expert weights to the tokens."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.sharding import data_batch_axes
    from ..state import PartialState

    try:
        mesh = PartialState().mesh
    except Exception:
        return t
    if mesh is None or mesh.shape.get("ep", 1) == 1:
        return t
    axes = data_batch_axes()
    spec = P("ep", axes if axes else None, *([None] * (t.ndim - 2)))
    return jax.lax.with_sharding_constraint(t, NamedSharding(mesh, spec))
