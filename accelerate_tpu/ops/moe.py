"""Mixture-of-experts routing — top-k gating + einsum dispatch/combine.

TPU-first design (the GShard/Switch recipe rather than a torch-style gather
loop): routing produces dense one-hot dispatch/combine tensors and the expert
FFN runs as *batched einsums* over a leading expert dim. Under GSPMD, sharding
that expert dim on the mesh ``ep`` axis partitions the expert FFNs the way
row-parallel TP partitions a matmul: dispatch einsums are device-local (each
ep shard holds its batch rows), expert compute touches only the local experts,
and the combine einsum contracts the sharded expert dim — one all-reduce over
``ep`` per layer, inserted by XLA. No hand-written collectives, and the
einsums stay MXU-shaped. (A token all-to-all materializes instead when ``ep``
is folded into the data axes — the DeepSpeed-MoE topology; with a dedicated
axis the all-reduce form is what's communication-minimal.)

Reference context: the reference has no MoE implementation of its own (only
DeepSpeed-MoE passthrough flags, ``utils/dataclasses.py``); this is a native
capability of the framework (SURVEY.md §2.4 lists EP as a note-only strategy
for the reference).

Shapes (per group = batch row): x (B, S, h); router (h, E); k choices per
token; capacity C per expert per group.

- ``dispatch`` (B, S, E, C) one-hot: token (b, s) occupies slot c of expert e.
- ``combine``  (B, S, E, C) = dispatch · gate: weights for the return trip.
- expert inputs  = einsum('bsec,bsh->ebch', dispatch, x)
- expert outputs = SwiGLU with weights (E, h, i) via 'ebch,ehi->ebci'
- token outputs  = einsum('ebch,bsec->bsh', expert_out, combine)

The auxiliary load-balancing loss is the Switch formulation:
``E · Σ_e  f_e · p̄_e`` (token fraction × mean router prob).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp


def router_capacity(tokens_per_group: int, num_experts: int, k: int, capacity_factor: float) -> int:
    """Slots per expert per group; multiples of 8 keep the lanes happy."""
    cap = int(np.ceil(tokens_per_group * k * capacity_factor / num_experts))
    return max(8, int(np.ceil(cap / 8)) * 8)


def top_k_routing(router_logits, k: int, capacity: int):
    """Build dispatch/combine tensors from router logits.

    router_logits: (B, S, E). Returns (dispatch (B,S,E,C) float, combine
    (B,S,E,C) float, aux_loss scalar). Tokens beyond an expert's capacity are
    dropped (their combine weights are zero → they ride the residual stream
    only, the standard Switch behavior).
    """
    B, S, E = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)  # (B,S,E)
    gate_vals, expert_idx = jax.lax.top_k(probs, k)  # (B,S,k)
    gate_vals = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)

    # One-hot per choice, flattened so earlier tokens (and higher-priority
    # choices) claim capacity first: (B, S·k, E).
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # (B,S,k,E)
    flat = onehot.reshape(B, S * k, E)
    # Position of each claim within its expert's slots (count of prior claims).
    pos = jnp.cumsum(flat, axis=1) - flat  # (B, S·k, E)
    keep = flat * (pos < capacity)
    slot = jnp.einsum(
        "bte,btec->btec",
        keep,
        jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32),
    )
    slot = slot.reshape(B, S, k, E, capacity)

    dispatch = jnp.max(slot, axis=2)  # (B,S,E,C) — a token occupies ≤1 slot per expert
    combine = jnp.einsum("bske,bskec->bsec", onehot * gate_vals[..., None], slot)

    # Switch aux loss: fraction of tokens routed to e (top-1 assignment) times
    # mean router probability of e, scaled by E (≈1 at perfect balance).
    top1 = jax.nn.one_hot(expert_idx[..., 0], E, dtype=jnp.float32)
    frac_tokens = jnp.mean(top1, axis=(0, 1))
    mean_probs = jnp.mean(probs, axis=(0, 1))
    aux_loss = E * jnp.sum(frac_tokens * mean_probs)
    return dispatch, combine, aux_loss


def moe_ffn(x, router_w, w_gate, w_up, w_down, *, k: int, capacity_factor: float = 1.25):
    """Full MoE SwiGLU layer: route → dispatch → expert FFN → combine.

    x: (B, S, h); router_w: (h, E); w_gate/w_up: (E, h, i); w_down: (E, i, h).
    Returns (output (B, S, h), aux_loss scalar). Sharding the leading E dim of
    the expert weights on ``ep`` keeps expert compute local; the final combine
    contracts the sharded expert dim into an all-reduce over ``ep``.
    """
    B, S, h = x.shape
    E = router_w.shape[-1]
    capacity = router_capacity(S, E, k, capacity_factor)
    router_logits = (x @ router_w.astype(x.dtype)).astype(jnp.float32)
    dispatch, combine, aux = top_k_routing(router_logits, k, capacity)

    expert_in = jnp.einsum("bsec,bsh->ebch", dispatch.astype(x.dtype), x)
    expert_in = _constrain_expert_layout(expert_in)
    gated = jax.nn.silu(jnp.einsum("ebch,ehi->ebci", expert_in, w_gate.astype(x.dtype)))
    up = jnp.einsum("ebch,ehi->ebci", expert_in, w_up.astype(x.dtype))
    expert_out = jnp.einsum("ebci,eih->ebch", gated * up, w_down.astype(x.dtype))
    expert_out = _constrain_expert_layout(expert_out)
    out = jnp.einsum("ebch,bsec->bsh", expert_out, combine.astype(x.dtype))
    return out, aux


def _constrain_expert_layout(t):
    """Pin (E, B, C, ...) intermediates to expert-major sharding: E on ``ep``,
    B on the data axes — guarantees the partitioner keeps expert compute on
    the expert's own shard instead of gathering expert weights to the tokens."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..state import PartialState

    try:
        mesh = PartialState().mesh
    except Exception:
        return t
    if mesh is None or mesh.shape.get("ep", 1) == 1:
        return t
    spec = P("ep", ("dp", "fsdp"), *([None] * (t.ndim - 2)))
    return jax.lax.with_sharding_constraint(t, NamedSharding(mesh, spec))
