"""Int8 quantized matmul for training — the TPU analog of fp8 recipes.

TPUs have no fp8 MXU path; the equivalent low-precision speed lever is int8
(v5e: 394 int8 TOPS vs 197 bf16 TFLOPS — exactly 2×). This module provides a
drop-in matmul that:

- dynamically quantizes both operands per-row/per-column (absmax symmetric,
  the AQT recipe) so the contraction runs int8×int8 → int32 on the MXU;
- rescales the int32 accumulator back to the activation dtype;
- backpropagates with a straight-through estimator (gradients flow as if the
  matmul were exact, computed in bf16/fp32) — the standard quantization-aware
  training treatment, so the optimizer state and gradient path stay full
  precision.

Reference context: the reference's fp8 support wires TransformerEngine /
torchao recipes (``utils/transformer_engine.py``, ``utils/ao.py``); there the
recipe swaps Linear modules. Here it swaps the matmul primitive inside the
model's forward (``LlamaConfig(matmul_precision="int8")``), which is the
functional-JAX shape of the same feature (SURVEY.md §2.6).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def _absmax_scale(t, axis):
    """Symmetric per-vector scale: max|t| along `axis` mapped to 127."""
    amax = jnp.max(jnp.abs(t.astype(jnp.float32)), axis=axis, keepdims=True)
    return jnp.where(amax > 0, amax / 127.0, 1.0)


def quantize_rowwise(t, axis):
    """Quantize to int8 with a per-vector scale along ``axis``."""
    scale = _absmax_scale(t, axis)
    q = jnp.clip(jnp.round(t.astype(jnp.float32) / scale), -127, 127).astype(jnp.int8)
    return q, scale


def quantize_kv(t):
    """Per-token KV quantization for the paged pool (``kv_quant="int8"``).

    ``t``: ``(..., H, D)`` K or V rows. The scale is absmax over the trailing
    (heads, head_dim) axes mapped to 127 — ONE scale per token row, so a pool
    block carries a ``(bs,)`` scale vector next to its int8 payload and a
    token written once is never rescaled (blocks fill incrementally at
    scatter time; a per-block running amax would force rewrites of
    already-committed rows). Returns ``(int8 t-shaped, float32 (...,)
    scales)``. Round-trip error is bounded by ``amax/254`` per token row
    (half a quantization step) — tests/test_speculative.py pins it."""
    amax = jnp.max(jnp.abs(t.astype(jnp.float32)), axis=(-2, -1))
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(t.astype(jnp.float32) / scale[..., None, None]), -127, 127)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def dequantize_kv(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv`: ``int8 (..., H, D)`` + ``(...,)``
    scales → ``dtype``. This exact expression (f32 multiply, then cast) is
    the parity seam the Pallas dequant-in-DMA kernels replicate."""
    return (q.astype(jnp.float32) * scale[..., None, None].astype(jnp.float32)).astype(dtype)


@jax.custom_vjp
def int8_matmul(x, w):
    """x @ w with both operands dynamically quantized to int8.

    x: (..., K); w: (K, N). Forward runs int8×int8→int32 on the MXU with
    per-row (x) / per-column (w) rescale; backward is straight-through in the
    original precision. The forward dispatches through the kernel registry
    (op ``int8_matmul``): the fused Pallas quantize+contract+rescale kernel
    (``ops/pallas/int8_mm.py``) when ``ACCELERATE_KERNELS`` selects it, the
    reference lowering below otherwise — bit-identical either way
    (tests/test_kernels.py pins the parity)."""
    return _dispatch_fwd_value(x, w)


def _int8_matmul_fwd_value(x, w):
    """The committed reference lowering — the parity seam the Pallas kernel
    must match bit-for-bit."""
    qx, sx = quantize_rowwise(x, axis=-1)  # per-row of x
    qw, sw = quantize_rowwise(w, axis=0)  # per-column of w
    acc = jax.lax.dot_general(
        qx, qw,
        dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    out = acc.astype(jnp.float32) * sx * sw.reshape((1,) * (acc.ndim - 1) + (-1,))
    return out.astype(x.dtype)


def _dispatch_fwd_value(x, w):
    from .registry import dispatch, resolve_backend

    if resolve_backend("int8_matmul") == "reference":
        return _int8_matmul_fwd_value(x, w)
    return dispatch("int8_matmul", x, w)


def _int8_matmul_fwd(x, w):
    return _dispatch_fwd_value(x, w), (x, w)


def _int8_matmul_bwd(res, g):
    x, w = res
    g32 = g.astype(jnp.float32)
    dx = (g32 @ w.astype(jnp.float32).T).astype(x.dtype)
    dw = jnp.tensordot(
        x.astype(jnp.float32), g32, axes=(tuple(range(x.ndim - 1)), tuple(range(g.ndim - 1)))
    ).astype(w.dtype)
    return dx, dw


int8_matmul.defvjp(_int8_matmul_fwd, _int8_matmul_bwd)


def matmul(x, w, precision: str = "default"):
    """Model-zoo matmul dispatch: ``default`` → ``x @ w``; ``int8`` → the
    quantized MXU path with straight-through backward."""
    if precision == "int8":
        return int8_matmul(x, w)
    if precision != "default":
        raise ValueError(f"matmul precision must be 'default' or 'int8', got {precision!r}")
    return x @ w
