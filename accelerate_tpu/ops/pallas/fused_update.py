"""Fused optimizer-update Pallas kernel — clip + moments + apply + cast, one pass.

``_fused_step_body``'s update region (``_upd_math``) is a chain of small
elementwise passes over every parameter leaf: scale by the clip factor, the
optax moment updates, bias correction, the update rule, weight decay, the
learning-rate scale, and ``apply_updates``'s cast back to the param dtype —
plus the accumulation-buffer zero-reset. On the reference path each is its
own HBM round-trip per leaf; with ZeRO active the chain runs on the 1/dp
shard between the reduce-scatter and the param all-gather, which is exactly
the window ``--xla_preset latency`` must hide (arxiv 2004.13336) — every
pass shortened here widens the overlap budget.

This module fuses the whole per-leaf chain into ONE ``pallas_call`` (param +
moments + grad stream in, param' + moments' + zeroed-buffer stream out):

- :func:`plan_fused_update` inspects an ``optax.GradientTransformation``'s
  closure chain and recovers the exact hyperparameters for the supported
  families — ``sgd`` (with or without classic momentum), ``adam``,
  ``adamw``. Anything else (schedules, nesterov, masks, custom chains)
  returns None and the reference path runs — the registry's clean-fallback
  contract, per optimizer instance.
- :func:`fused_update_apply` runs the kernel per leaf, mirroring optax's op
  order **exactly** (``(1-b)*g + b*m`` moment form, ``1 - decay**count``
  bias correction computed outside the kernel in the same precision,
  ``m / (sqrt(v + eps_root) + eps)``, ``g + wd*p``, ``-lr * u``,
  ``(p + u).astype(p.dtype)``): interpret mode is bit-exact against
  ``tx.update`` + ``optax.apply_updates`` by construction — the windowed
  ZeRO parity drill in tests/test_kernels.py pins it.

The cross-leaf global-norm clip *factor* is computed by the caller (it is a
tree-wide reduction; the kernel is per-leaf) and fused into the first
elementwise pass, identically to the reference's ``g * factor`` pre-scale.
Leaves are flattened and padded to (rows, 128) lanes; padding lanes compute
garbage that is sliced off before reshape (never NaN-propagating into real
lanes — elementwise math only). Under ZeRO the caller invokes this inside
the ``zero_update``-constrained region, so the kernel body lowers on the
dp-sharded values (shard-local math under GSPMD; see
``parallel/sharding.local_leaf_shape`` for the per-device shapes the cost
model uses).
"""

from __future__ import annotations

import inspect
import logging
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..registry import register_op

logger = logging.getLogger(__name__)

_LANES = 128
_MAX_BLOCK_ROWS = 512


# ------------------------------------------------------------------ planning
@dataclass(frozen=True)
class FusedUpdatePlan:
    """The recovered optimizer family + hyperparameters and where its state
    lives in the chain's state tuple. ``kind``: sgd | sgd_momentum | adam
    (adamw = adam with ``weight_decay`` not None)."""

    kind: str
    step_size: float
    b1: float = 0.0
    b2: float = 0.0
    eps: float = 0.0
    eps_root: float = 0.0
    weight_decay: float | None = None
    momentum: float = 0.0
    state_index: int | None = None  # chain position of ScaleByAdamState/TraceState

    def describe(self) -> str:
        wd = self.weight_decay is not None
        return {"adam": "adamw" if wd else "adam"}.get(self.kind, self.kind)


def _inner_update_fns(tx):
    """The chain's inner update fns (unwrapping with_extra_args_support)."""
    try:
        cells = inspect.getclosurevars(tx.update).nonlocals
    except TypeError:
        return None
    fns = cells.get("update_fns")
    if fns is None:
        return None
    out = []
    for f in fns:
        try:
            inner = inspect.getclosurevars(f).nonlocals.get("tx")
        except TypeError:
            inner = None
        out.append(inner.update if inner is not None else f)
    return out


def plan_fused_update(tx) -> FusedUpdatePlan | None:
    """Match ``tx`` against the supported optax constructions; None = run the
    reference path (unsupported chains are a fallback, never an error)."""
    fns = _inner_update_fns(tx)
    if not fns:
        return None
    kind = "sgd"
    hp: dict = {}
    state_index = None
    saw_scale = False
    for i, fn in enumerate(fns):
        qual = getattr(fn, "__qualname__", "")
        try:
            nl = inspect.getclosurevars(fn).nonlocals
        except TypeError:
            return None
        if qual.startswith("identity."):
            continue
        if qual.startswith("scale_by_adam."):
            if kind != "sgd" or saw_scale or nl.get("nesterov") or nl.get("mu_dtype") is not None:
                return None
            kind = "adam"
            state_index = i
            hp.update(b1=float(nl["b1"]), b2=float(nl["b2"]),
                      eps=float(nl["eps"]), eps_root=float(nl["eps_root"]))
            continue
        if qual.startswith("trace."):
            if kind != "sgd" or saw_scale or nl.get("nesterov") or nl.get("accumulator_dtype") is not None:
                return None
            kind = "sgd_momentum"
            state_index = i
            hp.update(momentum=float(nl["decay"]))
            continue
        if qual.startswith("add_decayed_weights."):
            if kind != "adam" or saw_scale or "weight_decay" not in nl:
                return None
            hp.update(weight_decay=float(nl["weight_decay"]))
            continue
        if qual.startswith("scale."):
            if saw_scale or not isinstance(nl.get("step_size"), (int, float)):
                return None
            saw_scale = True
            hp.update(step_size=float(nl["step_size"]))
            continue
        return None  # schedules, masks, anything unrecognized
    if not saw_scale:
        return None
    return FusedUpdatePlan(kind=kind, state_index=state_index, **hp)


# ------------------------------------------------------------------ leaf math
def _leaf_math(plan: FusedUpdatePlan, zero_buffer: bool = True):
    """The per-leaf elementwise chain, mirroring optax op-for-op. Returns a
    function of (p, g, factor, *extras) -> (p'[, zero], *new_extras).
    ``zero_buffer=False`` omits the zeroed accumulation-buffer output — the
    imperative path has no buffer to reset, and an unused pallas output is
    still a full grads-sized HBM write on the compiled path."""

    def _zero_out(g):
        return (jnp.zeros_like(g),) if zero_buffer else ()

    def adam(p, mu, nu, g, factor, bc1, bc2):
        g = g * factor
        new_mu = (1 - plan.b1) * (g ** 1) + plan.b1 * mu
        new_nu = (1 - plan.b2) * (g ** 2) + plan.b2 * nu
        mu_hat = new_mu / bc1.astype(new_mu.dtype)
        nu_hat = new_nu / bc2.astype(new_nu.dtype)
        u = mu_hat / (jnp.sqrt(nu_hat + plan.eps_root) + plan.eps)
        if plan.weight_decay is not None:
            u = u + plan.weight_decay * p
        u = plan.step_size * u
        new_p = (p + u).astype(p.dtype)
        return (new_p,) + _zero_out(g) + (new_mu, new_nu)

    def sgd(p, g, factor):
        g = g * factor
        u = plan.step_size * g
        new_p = (p + u).astype(p.dtype)
        return (new_p,) + _zero_out(g)

    def sgd_momentum(p, trace, g, factor):
        g = g * factor
        new_trace = g + plan.momentum * trace
        u = plan.step_size * new_trace
        new_p = (p + u).astype(p.dtype)
        return (new_p,) + _zero_out(g) + (new_trace,)

    return {"adam": adam, "sgd": sgd, "sgd_momentum": sgd_momentum}[plan.kind]


def _pad_rows(flat, rows, cols):
    pad = rows * cols - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat.reshape(rows, cols)


def _fused_leaf_call(math_fn, arrays, scalars, interpret: bool,
                     name: str = "fused_update_kernel",
                     local_elems: int | None = None):
    """Run the per-leaf chain as ONE pallas_call over (rows, 128) tiles.

    ``arrays`` are the leaf-shaped operands (p[, moments], g); ``scalars``
    broadcast into every tile via SMEM-style (1, 1) blocks. Output avals are
    taken from an eval_shape of the math itself, so dtype promotion follows
    the reference exactly. ``name`` is the audit/fingerprint-visible kernel
    identity (``fused_<family>_update_kernel``)."""
    shape = np.shape(arrays[0])
    size = int(np.prod(shape)) if shape else 1
    # max(1, ...): a zero-size leaf (empty bias, 0-row optional head) still
    # gets one (padded, all-discarded) tile instead of a 0//0 at trace time —
    # the reference path handles empty leaves, so the kernel lever must too.
    rows = max(1, -(-size // _LANES))
    # Tile rows are capped by the SHARD-local element count when a sharding
    # plan is declared (parallel/sharding.local_leaf_shape): under ZeRO the
    # per-leaf pass covers the 1/dp shard, and a grid block must not span
    # shard boundaries or GSPMD re-materializes the leaf to feed it.
    local_rows = rows if local_elems is None else max(1, -(-int(local_elems) // _LANES))
    block_rows = min(rows, local_rows, _MAX_BLOCK_ROWS)
    grid_rows = -(-rows // block_rows)
    padded_rows = grid_rows * block_rows
    tiles = [_pad_rows(jnp.asarray(a).reshape(-1), padded_rows, _LANES)
             for a in arrays]
    scalars = [jnp.asarray(s).reshape(1, 1) for s in scalars]
    out_avals = jax.eval_shape(
        lambda ts, ss: math_fn(*ts, *[s[0, 0] for s in ss]), tiles, scalars
    )

    n_arr = len(tiles)

    def body(*refs):
        ins, outs = refs[: n_arr + len(scalars)], refs[n_arr + len(scalars):]
        tile_vals = [r[:] for r in ins[:n_arr]]
        scalar_vals = [r[0, 0] for r in ins[n_arr:]]
        results = math_fn(*tile_vals, *scalar_vals)
        for o_ref, val in zip(outs, results):
            o_ref[:] = val.astype(o_ref.dtype)

    grid_spec = pl.GridSpec(
        grid=(grid_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))
            for _ in tiles
        ] + [
            pl.BlockSpec((1, 1), lambda i: (0, 0)) for _ in scalars
        ],
        out_specs=tuple(
            pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0))
            for _ in out_avals
        ),
    )
    outs = pl.pallas_call(
        body,
        out_shape=tuple(
            jax.ShapeDtypeStruct((padded_rows, _LANES), o.dtype)
            for o in out_avals
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        name=name,
    )(*tiles, *scalars)
    return tuple(o.reshape(-1)[:size].reshape(shape) for o in outs)


# ------------------------------------------------------------------ front end
def _safe_int32_increment(count):
    max_i32 = jnp.iinfo(jnp.int32).max
    return jnp.where(count < max_i32, count + jnp.array(1, jnp.int32), max_i32)


def fused_update_apply(params, opt_state, grads, *, plan: FusedUpdatePlan,
                       clip_factor, interpret: bool = False, shardings=None,
                       zero_buffer: bool = True):
    """One fused pass per leaf: returns ``(new_params, new_opt_state,
    zeroed_grads)`` matching::

        grads = tree_map(lambda g: g * clip_factor, grads)
        updates, new_opt = tx.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        zero = tree_map(zeros_like, grads)

    (float-equivalent across modules, bit-deterministic within one — see
    docs/kernels.md for the exact parity contract). ``shardings`` is the
    caller's per-leaf plan (the ZeRO update-path shardings) used to size
    tile grids to the shard-local leaf, never to change values.
    ``zero_buffer=False`` skips the zeroed-grads output entirely (returns
    None in its slot) — callers with no accumulation buffer to reset (the
    imperative optimizer) must not pay its HBM write."""
    from ...parallel.sharding import local_leaf_shape

    math_fn = _leaf_math(plan, zero_buffer)
    kname = f"fused_{plan.describe()}_update_kernel"
    treedef = jax.tree_util.tree_structure(params)
    p_leaves = jax.tree_util.tree_leaves(params)
    g_leaves = jax.tree_util.tree_leaves(grads)
    if shardings is not None:
        s_leaves = jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda s: hasattr(s, "spec")
        )
        local_elems = [
            int(np.prod(local_leaf_shape(np.shape(p), s)) or 1)
            for p, s in zip(p_leaves, s_leaves)
        ]
    else:
        local_elems = [None] * len(p_leaves)
    states = list(opt_state) if isinstance(opt_state, (tuple, list)) else [opt_state]

    if plan.kind == "adam":
        st = states[plan.state_index]
        count_inc = _safe_int32_increment(st.count)
        # optax.tree_bias_correction computes 1 - decay**count in full
        # precision BEFORE the per-leaf dtype cast — same here, outside the
        # kernel, broadcast into every tile.
        bc1 = 1 - plan.b1 ** count_inc
        bc2 = 1 - plan.b2 ** count_inc
        mu_leaves = jax.tree_util.tree_leaves(st.mu)
        nu_leaves = jax.tree_util.tree_leaves(st.nu)
        new_p, zeros, new_mu, new_nu = [], [], [], []
        for p, mu, nu, g, le in zip(p_leaves, mu_leaves, nu_leaves, g_leaves,
                                    local_elems):
            out = _fused_leaf_call(
                math_fn, (p, mu, nu, g), (clip_factor, bc1, bc2), interpret,
                name=kname, local_elems=le,
            )
            new_p.append(out[0])
            if zero_buffer:
                zeros.append(out[1])
            new_mu.append(out[-2]); new_nu.append(out[-1])
        states[plan.state_index] = st._replace(
            count=count_inc,
            mu=jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(st.mu), new_mu
            ),
            nu=jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(st.nu), new_nu
            ),
        )
    elif plan.kind == "sgd_momentum":
        st = states[plan.state_index]
        tr_leaves = jax.tree_util.tree_leaves(st.trace)
        new_p, zeros, new_tr = [], [], []
        for p, tr, g, le in zip(p_leaves, tr_leaves, g_leaves, local_elems):
            out = _fused_leaf_call(math_fn, (p, tr, g), (clip_factor,),
                                   interpret, name=kname, local_elems=le)
            new_p.append(out[0])
            if zero_buffer:
                zeros.append(out[1])
            new_tr.append(out[-1])
        states[plan.state_index] = st._replace(
            trace=jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(st.trace), new_tr
            )
        )
    else:  # plain sgd
        new_p, zeros = [], []
        for p, g, le in zip(p_leaves, g_leaves, local_elems):
            out = _fused_leaf_call(math_fn, (p, g), (clip_factor,),
                                   interpret, name=kname, local_elems=le)
            new_p.append(out[0])
            if zero_buffer:
                zeros.append(out[1])

    new_params = jax.tree_util.tree_unflatten(treedef, new_p)
    zero_tree = (
        jax.tree_util.tree_unflatten(jax.tree_util.tree_structure(grads), zeros)
        if zero_buffer else None
    )
    new_state = tuple(states) if isinstance(opt_state, (tuple, list)) else states[0]
    return new_params, new_state, zero_tree


def reference_update_apply(params, opt_state, grads, *, tx, clip_factor):
    """The committed reference seam the kernel must match bit-for-bit: the
    exact op sequence of ``_fused_step_body._upd_math`` after the norm."""
    import optax

    grads = jax.tree_util.tree_map(lambda g: g * clip_factor, grads)
    updates, new_opt = tx.update(grads, opt_state, params)
    new_params = optax.apply_updates(params, updates)
    zero = jax.tree_util.tree_map(jnp.zeros_like, grads)
    return new_params, new_opt, zero


def _kernel_entry(params, opt_state, grads, *, tx=None, plan=None,
                  clip_factor, interpret: bool = False):
    if plan is None:
        plan = plan_fused_update(tx)
    if plan is None:
        return reference_update_apply(
            params, opt_state, grads, tx=tx, clip_factor=clip_factor
        )
    return fused_update_apply(
        params, opt_state, grads, plan=plan, clip_factor=clip_factor,
        interpret=interpret,
    )


def _reference_entry(params, opt_state, grads, *, tx=None, plan=None,
                     clip_factor):
    return reference_update_apply(
        params, opt_state, grads, tx=tx, clip_factor=clip_factor
    )


register_op(
    "fused_update", _reference_entry, _kernel_entry,
    doc="fused clip+moments+apply+cast optimizer update (adam/adamw/sgd)",
)
