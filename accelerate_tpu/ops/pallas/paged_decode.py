"""Paged/ragged decode-attention Pallas kernels — block chains, no gather view.

The reference lowering (``ops/paged_attention.paged_attention_reference``)
materializes every slot's chain as a contiguous HBM view via an XLA gather
over the block tables, then runs ``cached_attention`` on the view — the
explicitly-named slow path (ROADMAP item 3): the gather re-materializes the
whole chain's KV every decode window, and bucket-padded slots pay full price
for garbage.

Two kernels kill it:

- :func:`paged_attention_kernel` — the fused op seam. Grid over batch slots;
  each program walks ITS slot's block chain with per-block async DMA
  (HBM → VMEM scratch), assembles the chain in VMEM only, and computes the
  attention math there. No (B, T, H, D) gather view ever exists in HBM.
  Padded slots (``active == 0``) skip both the DMA walk and the compute.
- :func:`gather_block_view_kernel` — the chain-walk *assembly* kernel behind
  ``gather_block_view``: per-(layer, slot) DMA of pool blocks straight into
  the output view, skipping dead slots. This is the swap the serving
  engine's uniform-write-window design consumes today (the view feeds the
  unmodified model forward); the fused kernel above is the no-view seam the
  model-side paged-cache integration targets.

Bit-exactness: inside the attention kernel the assembled chain is fed to the
SAME ``cached_attention`` math the reference composes (a pure-jnp function —
Pallas traces it into the kernel body), so active-slot outputs are
bit-identical to the reference by construction, not by maintenance. Padded
slots return zeros (the reference computes masked garbage there; the engine
never reads either). Sliding windows, softcap, and GQA ride through
unchanged because the math is shared.

TPU layout note (module docstring of ops/paged_attention.py): ``block_size``
should stay a multiple of 16 (bf16 sublane) so block DMAs stream without
repacking; the engine default is 16. Compiled-Mosaic lowering of the
windowed (valid-slot cumsum) path gathers along the chain axis in-kernel —
validated in interpret mode everywhere, on-chip validation rides the
BENCH_KERNELS round.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..attention import cached_attention
from ..registry import register_op


def _norm_positions(q_positions, batch: int):
    pos = jnp.asarray(q_positions)
    if pos.ndim == 1:
        pos = jnp.broadcast_to(pos[None], (batch, pos.shape[0]))
    return pos


def _norm_active(active, batch: int):
    if active is None:
        return jnp.ones((batch,), jnp.int32)
    return jnp.asarray(active).astype(jnp.int32).reshape(batch)


def paged_attention_kernel(q, k_pool, v_pool, block_tables, *, q_positions,
                           pool_mask=None, window=None, softcap=None,
                           scale=None, active=None, k_scale=None,
                           v_scale=None, interpret: bool = False):
    """Fused paged decode attention: q + pools + block tables → attention out.

    Signature-compatible with ``paged_attention_reference`` plus ``active``:
    a per-slot int/bool vector — slots with ``active == 0`` (bucket padding,
    drained slots) skip the chain walk entirely and return zeros. Shapes:
    q ``(B, S, H, D)``; pools ``(N, bs, Hkv, D)``; tables ``(B, M)``;
    q_positions ``(S,)`` or ``(B, S)``; pool_mask ``(N, bs)``.

    ``k_scale`` / ``v_scale`` (``(N, bs)`` float32) arm the **int8-pool
    dequant-in-DMA path**: the chain walk DMAs each int8 block *and its
    scale row* into VMEM scratch, dequantizes there (``q.astype(f32) *
    scale`` — the exact ``ops/int8.dequantize_kv`` expression the reference
    gather replays), and feeds the shared attention math float32 views. HBM
    traffic halves with the pool; nothing ever rematerializes the bf16
    chain in HBM.
    """
    B, S, H, D = q.shape
    N, bs, Hkv, _ = k_pool.shape
    M = block_tables.shape[-1]
    T = M * bs
    pos = _norm_positions(q_positions, B)
    act = _norm_active(active, B)
    tables = jnp.asarray(block_tables).astype(jnp.int32)
    has_mask = pool_mask is not None
    quant = k_scale is not None
    if quant and v_scale is None:
        raise ValueError("paged_decode: k_scale set without v_scale")
    out_dtype = (jnp.result_type(q.dtype, jnp.float32) if quant
                 else jnp.result_type(q.dtype, v_pool.dtype))

    def body(tbl_ref, act_ref, q_ref, pos_ref, k_ref, v_ref, *rest):
        rest = list(rest)
        m_ref = rest.pop(0) if has_mask else None
        ks_ref = rest.pop(0) if quant else None
        vs_ref = rest.pop(0) if quant else None
        o_ref = rest.pop(0)
        k_scr = rest.pop(0)
        v_scr = rest.pop(0)
        m_scr = rest.pop(0) if has_mask else None
        ks_scr = rest.pop(0) if quant else None
        vs_scr = rest.pop(0) if quant else None
        sems = rest.pop(0)
        b = pl.program_id(0)

        @pl.when(act_ref[b] != 0)
        def _():
            # Walk the slot's chain: per-block DMA from the HBM pools into
            # VMEM scratch. Copies for one chain slot start together (k, v,
            # mask and scales overlap each other); the chain itself is short
            # (M blocks).
            for j in range(M):
                idx = tbl_ref[b, j]
                copies = [
                    pltpu.make_async_copy(k_ref.at[idx], k_scr.at[j], sems.at[0]),
                    pltpu.make_async_copy(v_ref.at[idx], v_scr.at[j], sems.at[1]),
                ]
                if has_mask:
                    copies.append(
                        pltpu.make_async_copy(m_ref.at[idx], m_scr.at[j], sems.at[2])
                    )
                if quant:
                    copies.append(
                        pltpu.make_async_copy(ks_ref.at[idx], ks_scr.at[j], sems.at[3])
                    )
                    copies.append(
                        pltpu.make_async_copy(vs_ref.at[idx], vs_scr.at[j], sems.at[4])
                    )
                for c in copies:
                    c.start()
                for c in copies:
                    c.wait()
            k_view = k_scr[:].reshape(T, Hkv, D)
            v_view = v_scr[:].reshape(T, Hkv, D)
            if quant:
                # Dequant at the VMEM seam: identical expression to the
                # reference's gather_block_view(scales=...) lowering.
                k_view = k_view.astype(jnp.float32) * ks_scr[:].reshape(T)[:, None, None]
                v_view = v_view.astype(jnp.float32) * vs_scr[:].reshape(T)[:, None, None]
            kv_mask = m_scr[:].reshape(1, T) if has_mask else None
            # The reference's exact math on the assembled chain: per-slot
            # attention is independent across B, so the single-slot call is
            # bit-identical to the batched reference row.
            out = cached_attention(
                q_ref[:], k_view[None], v_view[None],
                q_positions=pos_ref[:], kv_mask=kv_mask,
                window=window, softcap=softcap, scale=scale,
            )
            o_ref[:] = out.astype(o_ref.dtype)

        @pl.when(act_ref[b] == 0)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)

    in_specs = [
        pl.BlockSpec((1, S, H, D), lambda b, tbl, act: (b, 0, 0, 0)),
        pl.BlockSpec((1, S), lambda b, tbl, act: (b, 0)),
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    scratch = [
        pltpu.VMEM((M, bs, Hkv, D), k_pool.dtype),
        pltpu.VMEM((M, bs, Hkv, D), v_pool.dtype),
    ]
    operands = [q, pos, k_pool, v_pool]
    n_sems = 2
    if has_mask:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        scratch.append(pltpu.VMEM((M, bs), jnp.asarray(pool_mask).dtype))
        n_sems = 3
        operands.append(jnp.asarray(pool_mask))
    if quant:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        scratch.append(pltpu.VMEM((M, bs), jnp.float32))
        scratch.append(pltpu.VMEM((M, bs), jnp.float32))
        n_sems = 5  # scale sems sit at fixed indices 3/4 past the mask's
        operands.append(jnp.asarray(k_scale).astype(jnp.float32))
        operands.append(jnp.asarray(v_scale).astype(jnp.float32))
    scratch.append(pltpu.SemaphoreType.DMA((n_sems,)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, S, H, D), lambda b, tbl, act: (b, 0, 0, 0)),
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        body,
        out_shape=jax.ShapeDtypeStruct((B, S, H, D), out_dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        name="paged_decode_kernel",
    )(tables, act, *operands)


def gather_block_view_kernel(pool_kv, block_tables, *, active=None,
                             scales=None, out_dtype=None,
                             interpret: bool = False):
    """Chain-walk view assembly: pool + tables → per-slot contiguous views.

    Bit-identical to ``gather_block_view``'s XLA gather for every slot whose
    ``active`` flag is set (pure data movement), zeros for skipped slots.
    ``pool_kv`` is ``(L, N, bs, H, D)`` (the engine's L-stacked pool) or
    ``(N, bs, H, D)`` (a single layer); output matches the reference shape
    ``(..., B, M*bs, H, D)``.

    ``scales`` (``(..., N, bs)`` float32, the quantized pool's per-block
    scale tables) arms the **dequant-in-DMA** path: each int8 block and its
    scale row DMA into VMEM scratch, dequantize there (``q.astype(f32) *
    scale`` — exactly ``gather_block_view``'s lowering), and the view lands
    in ``out_dtype`` (float32 default). The serving engine compiles THIS
    kernel into its decode program when ``kv_quant="int8"`` — the
    fingerprint config ``decode_paged_int8`` pins its presence."""
    squeeze = pool_kv.ndim == 4
    if squeeze:
        pool_kv = pool_kv[None]
        if scales is not None:
            scales = scales[None]
    L, N, bs, Hkv, D = pool_kv.shape
    B, M = block_tables.shape
    T = M * bs
    act = _norm_active(active, B)
    tables = jnp.asarray(block_tables).astype(jnp.int32)
    quant = scales is not None
    # Quant path casts in-kernel (dequant writes o_ref.dtype); the plain path
    # is a pure DMA, so any requested out_dtype applies after the call.
    out_dt = ((out_dtype if out_dtype is not None else jnp.float32)
              if quant else pool_kv.dtype)

    def body(tbl_ref, act_ref, pool_ref, *rest):
        if quant:
            s_ref, o_ref, blk_scr, s_scr, sems = rest
        else:
            (o_ref, sems) = rest
        l = pl.program_id(0)
        b = pl.program_id(1)

        @pl.when(act_ref[b] != 0)
        def _():
            for j in range(M):
                idx = tbl_ref[b, j]
                if quant:
                    copies = [
                        pltpu.make_async_copy(pool_ref.at[l, idx], blk_scr,
                                              sems.at[0]),
                        pltpu.make_async_copy(s_ref.at[l, idx], s_scr.at[0],
                                              sems.at[1]),
                    ]
                    for c in copies:
                        c.start()
                    for c in copies:
                        c.wait()
                    deq = blk_scr[:].astype(jnp.float32) * s_scr[0][:, None, None]
                    o_ref[0, 0, pl.ds(j * bs, bs)] = deq.astype(o_ref.dtype)
                else:
                    dma = pltpu.make_async_copy(
                        pool_ref.at[l, idx],
                        o_ref.at[0, 0, pl.ds(j * bs, bs)],
                        sems.at[0],
                    )
                    dma.start()
                    dma.wait()

        @pl.when(act_ref[b] == 0)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)

    in_specs = [pl.BlockSpec(memory_space=pltpu.ANY)]
    operands = [pool_kv]
    scratch: list = []
    if quant:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        operands.append(jnp.asarray(scales).astype(jnp.float32))
        scratch = [pltpu.VMEM((bs, Hkv, D), pool_kv.dtype),
                   pltpu.VMEM((1, bs), jnp.float32)]
    scratch.append(pltpu.SemaphoreType.DMA((2,)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(L, B),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, T, Hkv, D), lambda l, b, tbl, act: (l, b, 0, 0, 0)
        ),
        scratch_shapes=scratch,
    )
    out = pl.pallas_call(
        body,
        out_shape=jax.ShapeDtypeStruct((L, B, T, Hkv, D), out_dt),
        grid_spec=grid_spec,
        interpret=interpret,
        name="paged_gather_dequant_kernel" if quant else "paged_gather_kernel",
    )(tables, act, *operands)
    if not quant and out_dtype is not None:
        out = out.astype(out_dtype)
    return out[0] if squeeze else out


def _register():
    from ..paged_attention import gather_block_view, paged_attention_reference

    register_op(
        "paged_decode", paged_attention_reference, paged_attention_kernel,
        doc="ragged decode attention over block-table chains (no gather view)",
    )
    register_op(
        "paged_gather", gather_block_view, gather_block_view_kernel,
        doc="chain-walk assembly of per-slot KV views (skips padded slots)",
    )


_register()
