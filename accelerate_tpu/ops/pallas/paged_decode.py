"""Paged/ragged decode-attention Pallas kernels — block chains, no gather view.

The reference lowering (``ops/paged_attention.paged_attention_reference``)
materializes every slot's chain as a contiguous HBM view via an XLA gather
over the block tables, then runs ``cached_attention`` on the view — the
explicitly-named slow path (ROADMAP item 3): the gather re-materializes the
whole chain's KV every decode window, and bucket-padded slots pay full price
for garbage.

Two kernels kill it:

- :func:`paged_attention_kernel` — the fused op seam. Grid over batch slots;
  each program walks ITS slot's block chain with per-block async DMA
  (HBM → VMEM scratch), assembles the chain in VMEM only, and computes the
  attention math there. No (B, T, H, D) gather view ever exists in HBM.
  Padded slots (``active == 0``) skip both the DMA walk and the compute.
- :func:`gather_block_view_kernel` — the chain-walk *assembly* kernel behind
  ``gather_block_view``: per-(layer, slot) DMA of pool blocks straight into
  the output view, skipping dead slots. This is the swap the serving
  engine's uniform-write-window design consumes today (the view feeds the
  unmodified model forward); the fused kernel above is the no-view seam the
  model-side paged-cache integration targets.

Bit-exactness: inside the attention kernel the assembled chain is fed to the
SAME ``cached_attention`` math the reference composes (a pure-jnp function —
Pallas traces it into the kernel body), so active-slot outputs are
bit-identical to the reference by construction, not by maintenance. Padded
slots return zeros (the reference computes masked garbage there; the engine
never reads either). Sliding windows, softcap, and GQA ride through
unchanged because the math is shared.

TPU layout note (module docstring of ops/paged_attention.py): ``block_size``
should stay a multiple of 16 (bf16 sublane) so block DMAs stream without
repacking; the engine default is 16. Compiled-Mosaic lowering of the
windowed (valid-slot cumsum) path gathers along the chain axis in-kernel —
validated in interpret mode everywhere, on-chip validation rides the
BENCH_KERNELS round.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..attention import cached_attention
from ..registry import register_op


def _norm_positions(q_positions, batch: int):
    pos = jnp.asarray(q_positions)
    if pos.ndim == 1:
        pos = jnp.broadcast_to(pos[None], (batch, pos.shape[0]))
    return pos


def _norm_active(active, batch: int):
    if active is None:
        return jnp.ones((batch,), jnp.int32)
    return jnp.asarray(active).astype(jnp.int32).reshape(batch)


def paged_attention_kernel(q, k_pool, v_pool, block_tables, *, q_positions,
                           pool_mask=None, window=None, softcap=None,
                           scale=None, active=None, interpret: bool = False):
    """Fused paged decode attention: q + pools + block tables → attention out.

    Signature-compatible with ``paged_attention_reference`` plus ``active``:
    a per-slot int/bool vector — slots with ``active == 0`` (bucket padding,
    drained slots) skip the chain walk entirely and return zeros. Shapes:
    q ``(B, S, H, D)``; pools ``(N, bs, Hkv, D)``; tables ``(B, M)``;
    q_positions ``(S,)`` or ``(B, S)``; pool_mask ``(N, bs)``.
    """
    B, S, H, D = q.shape
    N, bs, Hkv, _ = k_pool.shape
    M = block_tables.shape[-1]
    T = M * bs
    pos = _norm_positions(q_positions, B)
    act = _norm_active(active, B)
    tables = jnp.asarray(block_tables).astype(jnp.int32)
    has_mask = pool_mask is not None
    out_dtype = jnp.result_type(q.dtype, v_pool.dtype)

    def body(tbl_ref, act_ref, q_ref, pos_ref, k_ref, v_ref, *rest):
        if has_mask:
            m_ref, o_ref, k_scr, v_scr, m_scr, sems = rest
        else:
            o_ref, k_scr, v_scr, sems = rest
            m_ref = m_scr = None
        b = pl.program_id(0)

        @pl.when(act_ref[b] != 0)
        def _():
            # Walk the slot's chain: per-block DMA from the HBM pools into
            # VMEM scratch. Copies for one chain slot start together (k, v,
            # mask overlap each other); the chain itself is short (M blocks).
            for j in range(M):
                idx = tbl_ref[b, j]
                copies = [
                    pltpu.make_async_copy(k_ref.at[idx], k_scr.at[j], sems.at[0]),
                    pltpu.make_async_copy(v_ref.at[idx], v_scr.at[j], sems.at[1]),
                ]
                if has_mask:
                    copies.append(
                        pltpu.make_async_copy(m_ref.at[idx], m_scr.at[j], sems.at[2])
                    )
                for c in copies:
                    c.start()
                for c in copies:
                    c.wait()
            k_view = k_scr[:].reshape(T, Hkv, D)
            v_view = v_scr[:].reshape(T, Hkv, D)
            kv_mask = m_scr[:].reshape(1, T) if has_mask else None
            # The reference's exact math on the assembled chain: per-slot
            # attention is independent across B, so the single-slot call is
            # bit-identical to the batched reference row.
            out = cached_attention(
                q_ref[:], k_view[None], v_view[None],
                q_positions=pos_ref[:], kv_mask=kv_mask,
                window=window, softcap=softcap, scale=scale,
            )
            o_ref[:] = out.astype(o_ref.dtype)

        @pl.when(act_ref[b] == 0)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)

    in_specs = [
        pl.BlockSpec((1, S, H, D), lambda b, tbl, act: (b, 0, 0, 0)),
        pl.BlockSpec((1, S), lambda b, tbl, act: (b, 0)),
        pl.BlockSpec(memory_space=pltpu.ANY),
        pl.BlockSpec(memory_space=pltpu.ANY),
    ]
    scratch = [
        pltpu.VMEM((M, bs, Hkv, D), k_pool.dtype),
        pltpu.VMEM((M, bs, Hkv, D), v_pool.dtype),
    ]
    operands = [q, pos]
    n_sems = 2
    if has_mask:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
        scratch.append(pltpu.VMEM((M, bs), jnp.asarray(pool_mask).dtype))
        n_sems = 3
        operands = [q, pos, k_pool, v_pool, jnp.asarray(pool_mask)]
    else:
        operands = [q, pos, k_pool, v_pool]
    scratch.append(pltpu.SemaphoreType.DMA((n_sems,)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, S, H, D), lambda b, tbl, act: (b, 0, 0, 0)),
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        body,
        out_shape=jax.ShapeDtypeStruct((B, S, H, D), out_dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        name="paged_decode_kernel",
    )(tables, act, *operands)


def gather_block_view_kernel(pool_kv, block_tables, *, active=None,
                             interpret: bool = False):
    """Chain-walk view assembly: pool + tables → per-slot contiguous views.

    Bit-identical to ``gather_block_view``'s XLA gather for every slot whose
    ``active`` flag is set (pure data movement), zeros for skipped slots.
    ``pool_kv`` is ``(L, N, bs, H, D)`` (the engine's L-stacked pool) or
    ``(N, bs, H, D)`` (a single layer); output matches the reference shape
    ``(..., B, M*bs, H, D)``."""
    squeeze = pool_kv.ndim == 4
    if squeeze:
        pool_kv = pool_kv[None]
    L, N, bs, Hkv, D = pool_kv.shape
    B, M = block_tables.shape
    T = M * bs
    act = _norm_active(active, B)
    tables = jnp.asarray(block_tables).astype(jnp.int32)

    def body(tbl_ref, act_ref, pool_ref, o_ref, sem):
        l = pl.program_id(0)
        b = pl.program_id(1)

        @pl.when(act_ref[b] != 0)
        def _():
            for j in range(M):
                idx = tbl_ref[b, j]
                dma = pltpu.make_async_copy(
                    pool_ref.at[l, idx],
                    o_ref.at[0, 0, pl.ds(j * bs, bs)],
                    sem,
                )
                dma.start()
                dma.wait()

        @pl.when(act_ref[b] == 0)
        def _():
            o_ref[:] = jnp.zeros_like(o_ref)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(L, B),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(
            (1, 1, T, Hkv, D), lambda l, b, tbl, act: (l, b, 0, 0, 0)
        ),
        scratch_shapes=[pltpu.SemaphoreType.DMA],
    )
    out = pl.pallas_call(
        body,
        out_shape=jax.ShapeDtypeStruct((L, B, T, Hkv, D), pool_kv.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        name="paged_gather_kernel",
    )(tables, act, pool_kv)
    return out[0] if squeeze else out


def _register():
    from ..paged_attention import gather_block_view, paged_attention_reference

    register_op(
        "paged_decode", paged_attention_reference, paged_attention_kernel,
        doc="ragged decode attention over block-table chains (no gather view)",
    )
    register_op(
        "paged_gather", gather_block_view, gather_block_view_kernel,
        doc="chain-walk assembly of per-slot KV views (skips padded slots)",
    )


_register()
