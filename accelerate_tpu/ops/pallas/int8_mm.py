"""Int8 quantized-matmul Pallas kernel — the serving-path speed lever.

TPUs have no fp8 MXU path; int8 is the low-precision lever (v5e: 394 int8
TOPS vs 197 bf16 TFLOPS). The reference (``ops/int8.py``) quantizes both
operands with XLA ops, runs the int8×int8→int32 contraction, and rescales —
three HBM round-trips over the operands. This kernel fuses
quantize + contract + rescale into one ``pallas_call``:

- per-(TM, TN) output tile, the x row-block and w column-block stream into
  VMEM with the FULL contraction axis (per-row/per-column absmax scales need
  all of K — tile-local scales would change the numerics);
- quantization (absmax symmetric, round, clip — the AQT recipe), the int32
  MXU dot, and the ``acc * sx * sw`` rescale mirror the reference's op order
  exactly, so interpret mode is bit-exact against
  ``ops.int8._int8_matmul_fwd_value`` (integer accumulation is exact in any
  tiling; the float rescale keeps the reference's left-association).

Forward only: the backward stays the reference straight-through estimator
(``ops/int8.py``'s custom VJP — serving is forward-only, and training grads
flow in full precision by design). M/N are padded to tile multiples; padded
rows/columns quantize zeros and are sliced off.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..registry import register_op

_TILE_M = 256
_TILE_N = 256


def int8_matmul_kernel(x, w, *, interpret: bool = False):
    """``x @ w`` with both operands dynamically quantized to int8 in-kernel.

    x: ``(..., K)``; w: ``(K, N)``. Matches ``_int8_matmul_fwd_value``
    bit-for-bit (interpret mode): per-row scales over the full K axis,
    int8×int8→int32 contraction, ``acc.astype(f32) * sx * sw`` rescale, cast
    back to ``x.dtype``."""
    lead = x.shape[:-1]
    K = x.shape[-1]
    N = w.shape[-1]
    x2 = x.reshape(-1, K)
    M = x2.shape[0]

    tm = min(_TILE_M, M)
    tn = min(_TILE_N, N)
    gm = -(-M // tm)
    gn = -(-N // tn)
    pm, pn = gm * tm, gn * tn
    if pm != M:
        x2 = jnp.concatenate([x2, jnp.zeros((pm - M, K), x2.dtype)])
    w2 = w if pn == N else jnp.concatenate(
        [w, jnp.zeros((K, pn - N), w.dtype)], axis=1
    )

    def body(x_ref, w_ref, o_ref):
        from ..int8 import quantize_rowwise

        qx, sx = quantize_rowwise(x_ref[:], axis=-1)   # (tm, K), (tm, 1)
        qw, sw = quantize_rowwise(w_ref[:], axis=0)    # (K, tn), (1, tn)
        acc = jax.lax.dot_general(
            qx, qw,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        out = acc.astype(jnp.float32) * sx * sw.reshape(1, -1)
        o_ref[:] = out.astype(o_ref.dtype)

    grid_spec = pl.GridSpec(
        grid=(gm, gn),
        in_specs=[
            pl.BlockSpec((tm, K), lambda i, j: (i, 0)),
            pl.BlockSpec((K, tn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j: (i, j)),
    )
    out = pl.pallas_call(
        body,
        out_shape=jax.ShapeDtypeStruct((pm, pn), x.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
        name="int8_matmul_kernel",
    )(x2, w2)
    return out[:M, :N].reshape(lead + (N,))


def _register():
    from ..int8 import _int8_matmul_fwd_value

    register_op(
        "int8_matmul", _int8_matmul_fwd_value, int8_matmul_kernel,
        doc="absmax-symmetric int8 quantize + int32 MXU matmul + rescale",
    )


_register()
