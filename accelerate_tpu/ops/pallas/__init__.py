"""Pallas TPU kernels for the remaining hot ops (ROADMAP item 3).

Each module implements one kernel behind the exact signature of its committed
reference seam and self-registers with :mod:`..registry` on import:

- :mod:`.paged_decode` — ``paged_decode`` (ragged decode attention walking
  each slot's block chain in-kernel, no materialized gather view) and
  ``paged_gather`` (the chain-walk view assembly the serving engine's
  uniform-write-window design consumes);
- :mod:`.fused_update` — ``fused_update`` (grad-clip scale + optax
  adam/adamw/sgd moment math + param apply + dtype cast in ONE pass over
  each leaf, the 1/dp ZeRO-shard body of ``_fused_step_body``);
- :mod:`.int8_mm` — ``int8_matmul`` (absmax-symmetric dynamic quantization +
  int8×int8→int32 MXU contraction + rescale, backing ``ops/int8.py``).

Bit-exactness is the contract: every kernel matches its reference lowering
bit-for-bit in interpret mode on the committed test vectors
(tests/test_kernels.py) — which is what lets ``ACCELERATE_KERNELS=pallas``
ship without a numerics review per model family. See docs/kernels.md.
"""

from . import paged_decode  # noqa: F401  (self-registers paged_decode/paged_gather)
from . import fused_update  # noqa: F401  (self-registers fused_update)
from . import int8_mm  # noqa: F401  (self-registers int8_matmul)
