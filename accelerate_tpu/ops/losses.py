"""Loss primitives shared by the model zoo.

TPU notes: cross-entropy is computed from logits in fp32 regardless of the compute
dtype (bf16 logits lose too much precision in the logsumexp), with optional z-loss
regularization and an ignore index for padded positions — the XLA-fused analog of
``torch.nn.functional.cross_entropy(ignore_index=-100)`` the reference examples use.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    ignore_index: int = -100,
    z_loss: float = 0.0,
    label_smoothing: float = 0.0,
):
    """Mean token cross-entropy over non-ignored positions.

    logits: (..., V) float; labels: (...) int. Ignored positions contribute zero
    and are excluded from the mean's denominator.
    """
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = logz - label_logits
    if label_smoothing > 0.0:
        smooth = -jnp.mean(jax.nn.log_softmax(logits, axis=-1), axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
    if z_loss > 0.0:
        nll = nll + z_loss * jnp.square(logz)
    nll = jnp.where(valid, nll, 0.0)
    denom = jnp.maximum(jnp.sum(valid), 1)
    return jnp.sum(nll) / denom


def mse_loss(pred: jax.Array, target: jax.Array):
    return jnp.mean(jnp.square(pred.astype(jnp.float32) - target.astype(jnp.float32)))
