"""Loss primitives shared by the model zoo.

TPU notes: cross-entropy is computed from logits in fp32 regardless of the compute
dtype (bf16 logits lose too much precision in the logsumexp), with optional z-loss
regularization and an ignore index for padded positions — the XLA-fused analog of
``torch.nn.functional.cross_entropy(ignore_index=-100)`` the reference examples use.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    ignore_index: int = -100,
    z_loss: float = 0.0,
    label_smoothing: float = 0.0,
):
    """Mean token cross-entropy over non-ignored positions.

    logits: (..., V) float; labels: (...) int. Ignored positions contribute zero
    and are excluded from the mean's denominator.
    """
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = logz - label_logits
    if label_smoothing > 0.0:
        smooth = -jnp.mean(jax.nn.log_softmax(logits, axis=-1), axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
    if z_loss > 0.0:
        nll = nll + z_loss * jnp.square(logz)
    nll = jnp.where(valid, nll, 0.0)
    denom = jnp.maximum(jnp.sum(valid), 1)
    return jnp.sum(nll) / denom


def fused_cross_entropy_loss(
    hidden: jax.Array,
    head_weight: jax.Array,
    labels: jax.Array,
    *,
    ignore_index: int = -100,
    z_loss: float = 0.0,
    vocab_chunk: int = 8192,
    logit_cap: float | None = None,
):
    """Cross-entropy straight from hidden states — full logits never exist.

    The (B·S, V) fp32 logit tensor is the largest activation of an LM train
    step (1 GB at B2·S4096·V32000, plus its gradient); this computes the same
    loss by scanning the LM head's vocab dimension in chunks, carrying running
    ``(max, sumexp, label_logit)`` streaming-logsumexp statistics — the flash
    trick applied to the classifier. Each chunk's partial logits live only
    transiently (the scan body is rematerialized in the backward), so peak
    memory is O(B·S·vocab_chunk).

    hidden: (B, S, h) — any float dtype, promoted to fp32 per chunk.
    head_weight: (h, V). labels: (B, S) int with ``ignore_index`` holes.
    ``logit_cap`` applies Gemma-2-style tanh softcapping per chunk.
    Returns the mean NLL over non-ignored positions (+ optional z-loss).
    """
    B, S, h = hidden.shape
    V = head_weight.shape[-1]
    T = B * S
    x = hidden.reshape(T, h)
    labels = labels.reshape(T)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)

    def update(carry, w_c, base, width):
        """Fold one vocab slice into the running (max, sumexp, label_logit)."""
        m, se, label_logit = carry
        logits_c = (x @ w_c).astype(jnp.float32)  # (T, width)
        if logit_cap is not None:
            logits_c = jnp.tanh(logits_c / logit_cap) * logit_cap
        m_c = jnp.max(logits_c, axis=-1)
        m_new = jnp.maximum(m, m_c)
        se = se * jnp.exp(m - m_new) + jnp.sum(jnp.exp(logits_c - m_new[:, None]), axis=-1)
        hit = (safe_labels >= base) & (safe_labels < base + width)
        local = jnp.take_along_axis(
            logits_c, jnp.clip(safe_labels - base, 0, width - 1)[:, None], axis=-1
        )[:, 0]
        label_logit = jnp.where(hit, local, label_logit)
        return m_new, se, label_logit

    init = (
        jnp.full((T,), -jnp.inf, jnp.float32),
        jnp.zeros((T,), jnp.float32),
        jnp.zeros((T,), jnp.float32),
    )
    # Full chunks ride a scan; a ragged tail (V % vocab_chunk) is folded by one
    # extra call — never a padded copy of the whole head weight (at 128k-vocab
    # bf16 heads that copy would cost ~1 GB per step).
    n_full = V // vocab_chunk
    carry = init
    if n_full:
        w_chunks = jnp.moveaxis(
            head_weight[:, : n_full * vocab_chunk].reshape(h, n_full, vocab_chunk), 1, 0
        )  # (n_full, h, chunk)

        def body(carry, inp):
            w_c, c_idx = inp
            return update(carry, w_c, c_idx * vocab_chunk, vocab_chunk), None

        body = jax.checkpoint(body)  # recompute chunk logits in the backward
        carry, _ = jax.lax.scan(body, init, (w_chunks, jnp.arange(n_full)))
    tail = V - n_full * vocab_chunk
    if tail:
        tail_fn = jax.checkpoint(
            lambda c, w_t: update(c, w_t, n_full * vocab_chunk, tail)
        )
        carry = tail_fn(carry, head_weight[:, n_full * vocab_chunk :])
    m, se, label_logit = carry
    logz = m + jnp.log(se)
    nll = logz - label_logit
    if z_loss > 0.0:
        nll = nll + z_loss * jnp.square(logz)
    nll = jnp.where(valid, nll, 0.0)
    denom = jnp.maximum(jnp.sum(valid), 1)
    return jnp.sum(nll) / denom


def mse_loss(pred: jax.Array, target: jax.Array):
    return jnp.mean(jnp.square(pred.astype(jnp.float32) - target.astype(jnp.float32)))
