"""Loss primitives shared by the model zoo.

TPU notes: cross-entropy is computed from logits in fp32 regardless of the compute
dtype (bf16 logits lose too much precision in the logsumexp), with optional z-loss
regularization and an ignore index for padded positions — the XLA-fused analog of
``torch.nn.functional.cross_entropy(ignore_index=-100)`` the reference examples use.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    ignore_index: int = -100,
    z_loss: float = 0.0,
    label_smoothing: float = 0.0,
):
    """Mean token cross-entropy over non-ignored positions.

    logits: (..., V) float; labels: (...) int. Ignored positions contribute zero
    and are excluded from the mean's denominator.
    """
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    nll = logz - label_logits
    if label_smoothing > 0.0:
        smooth = -jnp.mean(jax.nn.log_softmax(logits, axis=-1), axis=-1)
        nll = (1.0 - label_smoothing) * nll + label_smoothing * smooth
    if z_loss > 0.0:
        nll = nll + z_loss * jnp.square(logz)
    nll = jnp.where(valid, nll, 0.0)
    denom = jnp.maximum(jnp.sum(valid), 1)
    return jnp.sum(nll) / denom


# --------------------------------------------------------------------- fused CE
#
# The (B·S, V) fp32 logit tensor is the largest activation of an LM train step
# (2.1 GB at B4·S1024·V=128256, plus backward copies); the fused path computes
# the same loss by scanning the LM head's vocab dimension in chunks, carrying
# running (max, sumexp, label_logit) streaming-logsumexp statistics — the flash
# trick applied to the classifier. Peak memory is O(T·vocab_chunk).
#
# Two backward strategies:
#
# - "custom" (default): a hand-written VJP. The forward stores only
#   (x, w, logz, label_logit); the backward makes ONE chunked pass computing
#   dL/dx and dL/dw directly from the recomputed chunk softmax (p = exp(y -
#   logz)), plus a single gather/scatter for the label column. Differentiating
#   the forward scan would instead replay every chunk through the carry chain
#   (max/rescale/sum) and drag its sequential dependency structure into the
#   backward — the custom VJP drops that entirely.
# - "ad": the original jax.checkpoint-over-scan form, kept as the
#   cross-checking reference (tests assert grad equality between the two).


def _chunk_logits(x, w_chunk, *, transposed: bool, cap, dtype):
    """One vocab slice of logits: (T, width) in ``dtype``.

    ``transposed`` means ``w_chunk`` is (width, h) rows of a (V, h) table —
    the tied-embedding layout — contracted via dot_general so no transposed
    copy of the table ever materializes.
    """
    mm = jnp.promote_types(x.dtype, w_chunk.dtype)
    x, w_chunk = x.astype(mm), w_chunk.astype(mm)
    if transposed:
        z = jax.lax.dot_general(x, w_chunk, (((1,), (1,)), ((), ())))
    else:
        z = x @ w_chunk
    z = z.astype(dtype)
    if cap is not None:
        z = jnp.tanh(z / cap) * cap
    return z


def _chunk_starts(V: int, vocab_chunk: int):
    """Static (start, width) pairs covering [0, V): full chunks + ragged tail."""
    n_full = V // vocab_chunk
    spans = [(i * vocab_chunk, vocab_chunk) for i in range(n_full)]
    tail = V - n_full * vocab_chunk
    if tail:
        spans.append((n_full * vocab_chunk, tail))
    return spans


def _slice_w(w, base, width, transposed):
    if transposed:
        return jax.lax.slice_in_dim(w, base, base + width, axis=0)
    return jax.lax.slice_in_dim(w, base, base + width, axis=1)


def _stack_full_chunks(w, n_full, vocab_chunk, transposed):
    """(n_full, ...) stacked full chunks for the scan path. Row-major (V, h)
    tables reshape for free; the (h, V) layout pays one transposed copy."""
    h = w.shape[1] if transposed else w.shape[0]
    if transposed:
        return w[: n_full * vocab_chunk].reshape(n_full, vocab_chunk, h)
    return jnp.moveaxis(
        w[:, : n_full * vocab_chunk].reshape(h, n_full, vocab_chunk), 1, 0
    )


def _fold_stats(carry, z, base, width, safe_labels):
    """Fold one chunk's logits into the running (max, sumexp, label_logit).
    Accumulators stay fp32 regardless of the chunk dtype (the bf16 variant
    computes the exp in bf16 and accumulates the row-sum in fp32)."""
    m, se, label_logit = carry
    m_c = jnp.max(z, axis=-1).astype(jnp.float32)
    m_new = jnp.maximum(m, m_c)
    e = jnp.exp(z - m_new[:, None].astype(z.dtype))
    se = se * jnp.exp(m - m_new) + jnp.sum(e, axis=-1, dtype=jnp.float32)
    hit = (safe_labels >= base) & (safe_labels < base + width)
    local = jnp.take_along_axis(
        z, jnp.clip(safe_labels - base, 0, width - 1)[:, None], axis=-1
    )[:, 0].astype(jnp.float32)
    label_logit = jnp.where(hit, local, label_logit)
    return m_new, se, label_logit


def _streaming_stats_fwd(x, w, safe_labels, *, vocab_chunk, logit_cap, cd,
                         transposed, unroll):
    """Chunked forward pass → (logz, label_logit), both (T,) fp32."""
    T = x.shape[0]
    V = w.shape[0] if transposed else w.shape[-1]
    n_full = V // vocab_chunk
    init = (
        jnp.full((T,), -jnp.inf, jnp.float32),
        jnp.zeros((T,), jnp.float32),
        jnp.zeros((T,), jnp.float32),
    )
    carry = init
    full_unrolled = unroll == 0 or unroll >= n_full
    if n_full and not full_unrolled:
        w_chunks = _stack_full_chunks(w, n_full, vocab_chunk, transposed)

        def body(carry, inp):
            w_c, c_idx = inp
            z = _chunk_logits(x, w_c, transposed=transposed, cap=logit_cap, dtype=cd)
            return _fold_stats(carry, z, c_idx * vocab_chunk, vocab_chunk, safe_labels), None

        # The checkpoint matters only on the AD path; on the custom-VJP path
        # nothing differentiates through this scan, so it costs nothing.
        body = jax.checkpoint(body)
        carry, _ = jax.lax.scan(
            body, init, (w_chunks, jnp.arange(n_full)), unroll=max(unroll, 1)
        )
        spans = _chunk_starts(V, vocab_chunk)[n_full:]
    else:
        spans = _chunk_starts(V, vocab_chunk)
    for base, width in spans:

        def one(carry, w_c, _base=base, _width=width):
            z = _chunk_logits(x, w_c, transposed=transposed, cap=logit_cap, dtype=cd)
            return _fold_stats(carry, z, _base, _width, safe_labels)

        carry = jax.checkpoint(one)(carry, _slice_w(w, base, width, transposed))
    m, se, label_logit = carry
    return m + jnp.log(se), label_logit


def _streaming_stats_bwd(x, w, safe_labels, logz, label_logit, g_logz, g_label,
                         *, vocab_chunk, logit_cap, cd, transposed, unroll):
    """Single-pass backward: recompute each chunk's capped logits, form
    g_y = p·g_logz (softmax term), chain through the softcap, and accumulate
    dx / dw per chunk. The label column contributes once, outside the loop,
    via a (T,)-row gather of w and a (T→V) scatter-add into dw — the
    embedding-gradient pattern, not a per-chunk one-hot."""
    T, h = x.shape
    V = w.shape[0] if transposed else w.shape[-1]
    n_full = V // vocab_chunk
    mm = jnp.promote_types(x.dtype, w.dtype)

    def chunk_grads(w_c, base, width):
        z = _chunk_logits(x, w_c, transposed=transposed, cap=logit_cap, dtype=cd)
        p = jnp.exp(z.astype(jnp.float32) - logz[:, None])
        g_y = p * g_logz[:, None]
        if logit_cap is not None:
            g_y = g_y * (1.0 - jnp.square(z.astype(jnp.float32) / logit_cap))
        # Cast the fp32 cotangent back to the matmul dtype — exactly where the
        # AD path's convert_element_type cotangent lands.
        g_y = g_y.astype(mm)
        w_c, x_mm = w_c.astype(mm), x.astype(mm)
        if transposed:
            dx_c = g_y @ w_c  # (T,c)@(c,h)
            dw_c = jax.lax.dot_general(g_y, x_mm, (((0,), (0,)), ((), ())))  # (c,h)
        else:
            dx_c = jax.lax.dot_general(g_y, w_c, (((1,), (1,)), ((), ())))
            dw_c = jax.lax.dot_general(x_mm, g_y, (((0,), (0,)), ((), ())))  # (h,c)
        return dx_c.astype(jnp.float32), dw_c

    dx = jnp.zeros((T, h), jnp.float32)
    # dw is assembled by PAD + ADD of the chunk grads — the exact structure AD
    # gives a sliced weight (cotangent of slice = pad). Concatenating the
    # chunk dots along the vocab dim instead triggers a GSPMD mis-partition
    # when that dim is tp-sharded (observed on XLA CPU: each shard's concat
    # silently drops the cross-shard reduction of the T-contracted dots).
    dw = jnp.zeros(w.shape, jnp.promote_types(x.dtype, w.dtype))

    def place(dw, dw_c, base, width):
        if transposed:
            return dw + jnp.pad(dw_c, ((base, V - base - width), (0, 0)))
        return dw + jnp.pad(dw_c, ((0, 0), (base, V - base - width)))

    full_unrolled = unroll == 0 or unroll >= n_full
    if n_full and not full_unrolled:
        w_chunks = _stack_full_chunks(w, n_full, vocab_chunk, transposed)

        def body(dx, inp):
            w_c, c_idx = inp
            dx_c, dw_c = chunk_grads(w_c, c_idx * vocab_chunk, vocab_chunk)
            return dx + dx_c, dw_c

        dx, dw_stack = jax.lax.scan(
            body, dx, (w_chunks, jnp.arange(n_full)), unroll=max(unroll, 1)
        )
        for i in range(n_full):
            dw = place(dw, dw_stack[i], i * vocab_chunk, vocab_chunk)
        spans = _chunk_starts(V, vocab_chunk)[n_full:]
    else:
        spans = _chunk_starts(V, vocab_chunk)
    for base, width in spans:
        dx_c, dw_c = chunk_grads(_slice_w(w, base, width, transposed), base, width)
        dx = dx + dx_c
        dw = place(dw, dw_c, base, width)

    # Label-column term: d label_logit / dx = t'(y_label) · w[label];
    # d/dw scatters t'(y_label)·g_label·x into the label rows.
    gl = g_label
    if logit_cap is not None:
        gl = gl * (1.0 - jnp.square(label_logit / logit_cap))
    w_lab = w[safe_labels] if transposed else w[:, safe_labels].T  # (T, h)
    dx = dx + gl[:, None] * w_lab.astype(jnp.float32)
    scatter = (gl[:, None] * x.astype(jnp.float32)).astype(dw.dtype)
    if transposed:
        dw = dw.at[safe_labels].add(scatter)
    else:
        dw = dw.T.at[safe_labels].add(scatter).T
    return dx.astype(x.dtype), dw.astype(w.dtype)


def _streaming_stats(x, w, safe_labels, *, vocab_chunk, logit_cap, cd,
                     transposed, unroll, custom_backward):
    """(logz, label_logit) with the selected backward strategy."""
    kw = dict(vocab_chunk=vocab_chunk, logit_cap=logit_cap, cd=cd,
              transposed=transposed, unroll=unroll)
    if not custom_backward:
        return _streaming_stats_fwd(x, w, safe_labels, **kw)

    @jax.custom_vjp
    def stats(x, w):
        return _streaming_stats_fwd(x, w, safe_labels, **kw)

    def fwd(x, w):
        logz, label_logit = _streaming_stats_fwd(x, w, safe_labels, **kw)
        return (logz, label_logit), (x, w, logz, label_logit)

    def bwd(res, g):
        x, w, logz, label_logit = res
        g_logz, g_label = g
        return _streaming_stats_bwd(
            x, w, safe_labels, logz, label_logit,
            g_logz.astype(jnp.float32), g_label.astype(jnp.float32), **kw
        )

    stats.defvjp(fwd, bwd)
    return stats(x, w)


def fused_cross_entropy_loss(
    hidden: jax.Array,
    head_weight: jax.Array,
    labels: jax.Array,
    *,
    ignore_index: int = -100,
    z_loss: float = 0.0,
    vocab_chunk: int = 8192,
    logit_cap: float | None = None,
    chunk_dtype: str = "fp32",
    unroll: int = 1,
    head_transposed: bool = False,
    custom_backward: bool = True,
):
    """Cross-entropy straight from hidden states — full logits never exist.

    hidden: (B, S, h) — any float dtype. labels: (B, S) int with
    ``ignore_index`` holes. ``head_weight``: (h, V), or (V, h) with
    ``head_transposed=True`` — the tied-embedding layout, chunked by rows so
    the table is never transposed-copied (at 128k-vocab bf16 that copy costs
    ~0.5 GB per step).

    Tuning knobs (swept by ``benchmarks/vocab128k_profile.py``; defaults are
    the winning vocab128k recipe):

    - ``vocab_chunk``: vocab tile per step; peak memory is O(T·vocab_chunk).
    - ``chunk_dtype``: ``"fp32"`` (exact vs the dense path) or ``"bf16"`` —
      chunk logits/exp in bf16, running (max, sumexp) accumulated in fp32;
      halves the bytes of the largest transient at ~1e-2 relative loss error.
    - ``unroll``: scan unroll factor for the full chunks (0 = fully unrolled
      Python loop — no scan machinery at all).
    - ``custom_backward``: single-pass hand-written VJP (default) vs
      differentiating the forward scan (``False``; the reference
      implementation the tests cross-check against).

    ``logit_cap`` applies Gemma-2-style tanh softcapping per chunk.
    Returns the mean NLL over non-ignored positions (+ optional z-loss).
    """
    if chunk_dtype not in ("fp32", "bf16"):
        raise ValueError(f"chunk_dtype must be fp32|bf16, got {chunk_dtype!r}")
    if vocab_chunk <= 0:
        raise ValueError(f"vocab_chunk must be > 0, got {vocab_chunk}")
    B, S, h = hidden.shape
    T = B * S
    x = hidden.reshape(T, h)
    labels = labels.reshape(T)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logz, label_logit = _streaming_stats(
        x, head_weight, safe_labels,
        vocab_chunk=vocab_chunk,
        logit_cap=logit_cap,
        cd=jnp.bfloat16 if chunk_dtype == "bf16" else jnp.float32,
        transposed=head_transposed,
        unroll=unroll,
        custom_backward=custom_backward,
    )
    nll = logz - label_logit
    if z_loss > 0.0:
        nll = nll + z_loss * jnp.square(logz)
    nll = jnp.where(valid, nll, 0.0)
    denom = jnp.maximum(jnp.sum(valid), 1)
    return jnp.sum(nll) / denom


def mse_loss(pred: jax.Array, target: jax.Array):
    return jnp.mean(jnp.square(pred.astype(jnp.float32) - target.astype(jnp.float32)))
