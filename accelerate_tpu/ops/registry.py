"""Kernel registry — per-op backend resolution for the Pallas kernel layer.

PAPER.md's thesis is that custom kernels land in C++-backed Pallas/Mosaic,
not Python stand-ins — but a kernel that cannot fall back is a production
liability. This module is the dispatch seam between the three hot-op
reference lowerings (``ops/paged_attention.py``'s block-table gather,
``accelerator._fused_step_body``'s optax update chain, ``ops/int8.py``'s
quantized matmul) and their ``ops/pallas/`` kernels:

- every op registers a **reference** implementation (plain XLA lowering,
  always available, the committed parity seam) and a **kernel**
  implementation (a ``pallas_call`` accepting ``interpret=``);
- :func:`resolve_backend` maps the operator's spec (call-site override >
  ``ACCELERATE_KERNELS`` env) to one of ``pallas`` / ``interpret`` /
  ``reference`` per op. ``pallas`` resolves to the compiled Mosaic kernel
  only on a TPU backend; elsewhere it degrades to ``interpret`` — the same
  kernel body run by the Pallas interpreter, which is what makes CPU parity
  tests exercise the *kernel's* math, not a stand-in (and is why
  ``ACCELERATE_KERNELS=pallas`` is safe to set fleet-wide);
- specs may be a bare token (applies to every op) or a per-op map
  (``paged_decode=pallas,int8_matmul=off``); unset means ``reference``.

Backend resolution happens at **trace time**: switching the spec after a
program compiled requires a rebuild, exactly like every other compiled-in
lever (train_window, zero_sharding). The resolved per-op map rides in the
builders' ``_audit_meta["kernels"]`` so audits, fingerprints, and bench
lines record which backend actually lowered.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass

logger = logging.getLogger(__name__)

# Canonical backend names (what resolve_backend returns).
PALLAS = "pallas"
INTERPRET = "interpret"
REFERENCE = "reference"
BACKENDS = (PALLAS, INTERPRET, REFERENCE)

# Spellings accepted in specs (env / flag / call-site).
_TOKEN_ALIASES = {
    "pallas": PALLAS,
    "interpret": INTERPRET,
    "reference": REFERENCE,
    "off": REFERENCE,
    "none": REFERENCE,
    "0": REFERENCE,
    "": REFERENCE,
}


@dataclass
class KernelOp:
    """One registered hot op: its reference lowering and its Pallas kernel.

    ``kernel`` must accept the reference's exact signature plus a keyword
    ``interpret: bool`` and match the reference bit-for-bit on the committed
    test vectors (tests/test_kernels.py) — the registry guarantees dispatch,
    the kernel guarantees the seam."""

    name: str
    reference: callable
    kernel: callable
    doc: str = ""


_OPS: dict = {}
_WARNED: set = set()


def register_op(name: str, reference, kernel, doc: str = "") -> None:
    """Register (or re-register, e.g. on module reload) a kernel-backed op."""
    _OPS[name] = KernelOp(name=name, reference=reference, kernel=kernel, doc=doc)


def _ensure_registered() -> None:
    """Import the kernel modules (each self-registers) exactly once; a broken
    pallas import degrades every op to its reference lowering rather than
    taking the framework down — the always-available-fallback contract."""
    if _OPS:
        return
    try:
        from . import pallas  # noqa: F401  (self-registers on import)
    except Exception as exc:  # pragma: no cover - env-specific
        if "import" not in _WARNED:
            _WARNED.add("import")
            logger.warning(
                "Pallas kernel layer unavailable (%s); all ops stay on their "
                "reference lowerings.", exc,
            )


def known_ops() -> tuple:
    _ensure_registered()
    return tuple(sorted(_OPS))


def parse_kernel_spec(spec: str | None) -> dict:
    """Parse a spec string into ``{op_or_default: backend_token}``.

    A bare token (``pallas``) maps under the default key ``""``; a per-op map
    (``paged_decode=pallas,int8_matmul=off``) may mix with a bare default
    token (``pallas,int8_matmul=off``). Unknown tokens AND unknown op names
    raise — the launcher validates the flag with this same function, so a
    typo (either side of the ``=``) dies at launch instead of silently
    running reference."""
    out: dict = {}
    if spec is None:
        return out
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            op, _, token = part.partition("=")
            op, token = op.strip(), token.strip().lower()
        else:
            op, token = "", part.lower()
        if token not in _TOKEN_ALIASES:
            raise ValueError(
                f"unknown kernel backend {token!r} in ACCELERATE_KERNELS spec "
                f"{spec!r}; choose from pallas | interpret | reference | off"
            )
        if op:
            ops = known_ops()
            # Only validate when the registry actually populated (a broken
            # pallas import leaves it empty — everything degrades to
            # reference there, and dying on the spec would be worse).
            if ops and op not in ops:
                raise ValueError(
                    f"unknown kernel op {op!r} in ACCELERATE_KERNELS spec "
                    f"{spec!r}; registered ops: {', '.join(ops)}"
                )
        out[op] = _TOKEN_ALIASES[token]
    return out


def pallas_supported() -> bool:
    """Whether the compiled (Mosaic) kernel path can run: a TPU backend."""
    try:
        import jax

        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - backendless env
        return False


def resolve_backend(op: str, spec: str | dict | None = None) -> str:
    """Resolve ``op``'s backend: call-site spec wins over ``ACCELERATE_KERNELS``.

    Returns one of ``pallas`` / ``interpret`` / ``reference``. The ``pallas``
    token degrades to ``interpret`` off-TPU (logged once per op) so the kernel
    code path stays live everywhere; ``reference`` is only ever chosen
    explicitly or by default."""
    _ensure_registered()
    if not _OPS:
        # The pallas package failed to import: every op degrades to its
        # reference lowering regardless of the requested spec (the warning
        # fired once in _ensure_registered).
        return REFERENCE
    if isinstance(spec, dict):
        tokens = spec
    else:
        if spec is None:
            from ..utils.constants import ENV_KERNELS

            spec = os.environ.get(ENV_KERNELS)
        tokens = parse_kernel_spec(spec)
    token = tokens.get(op, tokens.get("", REFERENCE))
    if token == PALLAS and not pallas_supported():
        if op not in _WARNED:
            _WARNED.add(op)
            logger.info(
                "kernels: %s=pallas requested but the backend is not TPU; "
                "running the kernel in interpret mode.", op,
            )
        return INTERPRET
    return token


def resolved_backends(spec: str | dict | None = None) -> dict:
    """{op: resolved backend} over every registered op — what builder meta,
    bench ``detail.kernels``, and the docs' tri-state examples record."""
    _ensure_registered()
    return {op: resolve_backend(op, spec) for op in sorted(_OPS)}


def dispatch(op: str, *args, backend: str | dict | None = None, **kwargs):
    """Run ``op`` on its resolved backend. ``backend`` may be a raw token, a
    spec string, or a parsed spec dict; None reads ``ACCELERATE_KERNELS``."""
    _ensure_registered()
    entry = _OPS.get(op)
    if entry is None:
        raise KeyError(f"unknown kernel op {op!r}; registered: {known_ops()}")
    if isinstance(backend, str) and backend in BACKENDS:
        resolved = backend
        if resolved == PALLAS and not pallas_supported():
            resolved = INTERPRET
    else:
        resolved = resolve_backend(op, backend)
    if resolved == REFERENCE or entry.kernel is None:
        return entry.reference(*args, **kwargs)
    return entry.kernel(*args, interpret=(resolved == INTERPRET), **kwargs)


def reference_impl(op: str):
    """The committed reference lowering for ``op`` (the parity seam)."""
    _ensure_registered()
    return _OPS[op].reference
