"""Autoregressive generation over the KV-cache decode path.

Reference counterpart: the reference has no generate() of its own — its
big-model-inference story is transformers' ``model.generate`` driven through
dispatched/offloaded models (``benchmarks/big_model_inference/
big_model_inference.py``, BASELINE.md big-model tables measure s/token).
Here generation is part of the framework, built TPU-first:

- **One compiled program per shape**: prefill is one jit; the decode loop is a
  single ``lax.scan`` over steps with a static-shape cache, so the entire
  generation runs as two XLA programs — no per-token Python dispatch.
- **Static shapes everywhere**: the cache is pre-allocated to
  ``prompt + max_new_tokens``; finished rows keep stepping but emit
  ``pad_token_id`` (the standard masked-finish idiom), preserving SPMD-friendly
  control flow (no data-dependent early exit inside jit).
- **Ragged batches are left-aligned internally**: right-padded prompts are
  rolled so every row's last real token sits at index S-1 — all rows then share
  one global cache write offset (SPMD-uniform). Embedding positions are derived
  from the attention mask (``mask_positions``), NOT the cache slot index, so
  absolute-position models (GPT-2's learned wpe) are exact on ragged batches;
  causal masking still runs on slot indices (leading pads masked via kv_mask).
- **Offloaded models stream instead**: for ``StreamedScanModel`` (layer weights
  on host/disk) each token's forward streams layer slices just-in-time — the
  per-token Python loop is the point there, since HBM never holds the model.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp


def sample_logits(logits, rng, temperature: float = 1.0, top_k: int | None = None,
                  top_p: float | None = None):
    """Sample token ids from (B, V) logits. temperature<=0 means greedy."""
    if temperature is None or temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and 0.0 < top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Smallest logit value still inside the nucleus, per row.
        inside = cum - probs < top_p
        cutoff = jnp.min(jnp.where(inside, sorted_logits, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def left_align(input_ids, attention_mask):
    """Roll each right-padded row so its last real token lands at index S-1.

    Decoder-only generation with ragged batches requires left padding: with
    right padding each row's next token would need a per-row write offset and a
    per-row RoPE position. After the roll, one global offset serves every row,
    and the constant per-row position shift cancels in RoPE dot products.
    """
    S = input_ids.shape[1]
    shifts = S - jnp.sum(attention_mask, axis=-1).astype(jnp.int32)  # pad count per row
    roll = jax.vmap(lambda row, s: jnp.roll(row, s, axis=0))
    return roll(input_ids, shifts), roll(attention_mask, shifts)


def mask_positions(attention_mask):
    """Token positions from the attention mask: position = count of real
    tokens before it (cumsum - 1, clipped). Real positions are what
    absolute-position models (GPT-2's learned ``wpe``) must see for ragged
    batches — the cache slot index counts pads (VERDICT r2 #6); for RoPE the
    per-row difference is a constant that cancels, so one code path serves
    both families."""
    return jnp.clip(jnp.cumsum(attention_mask.astype(jnp.int32), axis=-1) - 1, 0)


def beam_search(
    model,
    input_ids,
    *,
    num_beams: int,
    max_new_tokens: int,
    params=None,
    attention_mask=None,
    length_penalty: float = 1.0,
    eos_token_id: int | None = None,
    pad_token_id: int = 0,
    cache_dtype=jnp.float32,
    include_prompt: bool = True,
):
    """Greedy beam search over the KV-cache decode path — one compiled program.

    TPU-shaped like the sampling loop: beams live as a widened batch
    (B·num_beams), every step is one cached forward + a top-k over K·V + a
    gather that reorders the cache and token history along the beam dim, all
    inside ``lax.scan`` (no per-step host round trips).

    Reference parity: the reference defers to transformers'
    ``generate(num_beams=...)``; with ``eos_token_id=None`` this matches it
    token-for-token (tests/test_convert.py::test_beam_search_matches_hf).
    EOS handling mirrors transformers' draw-2K-keep-K-non-eos scheme: eos
    candidates ranked within the top num_beams are banked by normalized
    score (BeamHypotheses' role — lower-ranked eos candidates are skipped,
    HF's is_beam_token_worse_than_top_num_beams), and the best K non-eos
    candidates keep running;
    final selection compares the bank against the best running beam. The
    length penalty divides by the GENERATED length (eos included for
    banked hypotheses; the prompt never enters the denominator) — matching
    transformers' generated_len convention.
    """
    module, mparams = _unwrap(model)
    if params is None:
        params = mparams
    if params is None:
        raise ValueError("Model has no params; pass params= or init the model first.")
    input_ids = jnp.asarray(input_ids, jnp.int32)
    B, S = input_ids.shape
    K = num_beams
    eos = -1 if eos_token_id is None else eos_token_id
    mask = (
        jnp.asarray(attention_mask, jnp.int32)
        if attention_mask is not None
        else jnp.ones((B, S), jnp.int32)
    )

    def beam_select(tree, idx, width):
        """Reorder every cache leaf's beam/batch dim by ``idx`` (the k/v
        stacks carry it at axis 1 under the layer dim, host-side leaves at
        axis 0); one helper serves both the prefill tiling (repeated index)
        and the per-step parent gather."""
        return jax.tree_util.tree_map(
            lambda t: (
                jnp.take(t, idx, axis=1)
                if t.ndim >= 3 and t.shape[1] == width
                else (jnp.take(t, idx, axis=0) if t.ndim >= 1 and t.shape[0] == width else t)
            ),
            tree,
        )

    cache_store = module.__dict__.setdefault("_generate_fns", {})
    key = ("beam", K, max_new_tokens, length_penalty, eos, pad_token_id, str(cache_dtype))
    if key not in cache_store:

        def run(params, input_ids, mask):
            B, S = input_ids.shape
            total = S + max_new_tokens
            input_ids, mask = left_align(input_ids, mask)
            real_len = jnp.sum(mask, axis=-1).astype(jnp.int32)

            # Prefill once per batch row, then tile the cache across beams.
            cache = module.init_cache(B, total, dtype=cache_dtype)
            out = module.apply(params, input_ids=input_ids, attention_mask=mask,
                               cache=cache, positions=mask_positions(mask))
            logp0 = jax.nn.log_softmax(out["logits"][:, -1].astype(jnp.float32))  # (B,V)
            V = logp0.shape[-1]

            bank_score = jnp.full((B,), -jnp.inf, jnp.float32)
            bank_hist = jnp.full((B, max_new_tokens), pad_token_id, jnp.int32)
            if eos >= 0:
                # transformers banks an eos continuation only when it ranks
                # within the top K ("is_beam_token_worse_than_top_num_beams"
                # skip), normalized by the generated length WITHOUT the eos —
                # here just the prompt — and keeps the best K non-eos running.
                topk0, idx0 = jax.lax.top_k(logp0, min(K, V))
                ink = jnp.any((idx0 == eos) & jnp.isfinite(topk0), axis=1)
                # transformers' denominator is the GENERATED length including
                # the eos (generated_len = cur_len+1 - prompt_len) — here 1.
                bank_score = jnp.where(ink, logp0[:, eos], -jnp.inf)
                bank_hist = bank_hist.at[:, 0].set(jnp.where(ink, eos, pad_token_id))
                logp0 = logp0.at[:, eos].set(-jnp.inf)
            scores, tok0 = jax.lax.top_k(logp0, K)  # (B,K)
            cache = beam_select(out["cache"], jnp.repeat(jnp.arange(B), K), B)
            history = jnp.full((B, K, max_new_tokens), pad_token_id, jnp.int32)
            history = history.at[:, :, 0].set(tok0)
            tok = tok0.reshape(B * K)

            def step(carry, s):
                cache, tok, scores, history, bank_score, bank_hist = carry
                out = module.apply(params, input_ids=tok[:, None], cache=cache,
                                   positions=pos_of(s))
                logp = jax.nn.log_softmax(out["logits"][:, -1].astype(jnp.float32))
                cand = scores[..., None] + logp.reshape(B, K, V)  # (B,K,V)
                if eos >= 0:
                    # HF's scheme: an eos candidate is banked only when it
                    # ranks within the top K (HF skips eos candidates 'worse
                    # than top num_beams'), normalized by the length excluding
                    # the eos (= prompt + s generated); the best K non-eos
                    # keep running.
                    topk, idxk = jax.lax.top_k(cand.reshape(B, K * V), K)
                    is_eosk = (idxk % V) == eos
                    eos_scores = jnp.where(is_eosk, topk, -jnp.inf)  # (B,K)
                    b_sel = jnp.argmax(eos_scores, axis=1)
                    b_raw = jnp.take_along_axis(eos_scores, b_sel[:, None], axis=1)[:, 0]
                    b_parent = jnp.take_along_axis(idxk // V, b_sel[:, None], axis=1)[:, 0]
                    b_score = b_raw / ((s + 1.0) ** length_penalty)
                    b_hist = jnp.take_along_axis(
                        history, b_parent[:, None, None], axis=1
                    )[:, 0]
                    b_hist = jnp.where(jnp.arange(max_new_tokens)[None] == s, eos, b_hist)
                    better = b_score > bank_score
                    bank_score = jnp.where(better, b_score, bank_score)
                    bank_hist = jnp.where(better[:, None], b_hist, bank_hist)
                    cand = cand.at[:, :, eos].set(-jnp.inf)
                new_scores, flat_idx = jax.lax.top_k(cand.reshape(B, K * V), K)
                parent = flat_idx // V  # (B,K) beam each winner extends
                token = (flat_idx % V).astype(jnp.int32)

                gidx = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
                new_cache = beam_select(out["cache"], gidx, B * K)
                history = jnp.take_along_axis(history, parent[..., None], axis=1)
                history = jnp.where(
                    jnp.arange(max_new_tokens)[None, None] == s, token[..., None], history
                )
                return (new_cache, token.reshape(B * K), new_scores, history,
                        bank_score, bank_hist), None

            def pos_of(s):
                # The token fed at scan step ``s`` is generation index s-1
                # (tok0 at s=1), so its position is prompt_len + s - 1.
                return (jnp.repeat(real_len, K) + s - 1)[:, None]

            carry = (cache, tok, scores, history, bank_score, bank_hist)
            (cache, tok, scores, history, bank_score, bank_hist), _ = jax.lax.scan(
                step, carry, jnp.arange(1, max_new_tokens)
            )
            # Final selection: best banked (finished) hypothesis vs the best
            # running beam at max length (HF finalize adds running beams with
            # the full generated length in the denominator).
            running = scores / (float(max_new_tokens) ** length_penalty)
            run_best = jnp.argmax(running, axis=1)
            run_score = jnp.take_along_axis(running, run_best[:, None], axis=1)[:, 0]
            run_hist = jnp.take_along_axis(history, run_best[:, None, None], axis=1)[:, 0]
            pick_bank = bank_score >= run_score
            return jnp.where(pick_bank[:, None], bank_hist, run_hist)

        cache_store[key] = jax.jit(run)
    new_tokens = cache_store[key](params, input_ids, mask)
    if include_prompt:
        return jnp.concatenate([input_ids, new_tokens], axis=1)
    return new_tokens


def assisted_generate(
    model,
    draft_model,
    input_ids,
    *,
    max_new_tokens: int,
    num_draft_tokens: int = 5,
    params=None,
    draft_params=None,
    eos_token_id: int | None = None,
    pad_token_id: int = 0,
    cache_dtype=jnp.float32,
    include_prompt: bool = True,
):
    """Speculative (assisted) greedy decoding — transformers'
    ``generate(assistant_model=...)``, TPU-shaped.

    The draft model proposes ``num_draft_tokens`` greedily from its own KV
    cache; the target scores the whole proposal in ONE cached forward and
    accepts the longest matching prefix, emitting one extra corrected token —
    so each target forward yields 1..γ+1 tokens while the output is **exactly
    the target model's greedy decode** (the speculative guarantee, pinned by
    tests). Both caches roll back to the accepted length by rewinding the
    write offset and kv_mask; the whole accept/rollback loop is a
    ``lax.while_loop`` inside one jit (no host round-trips).

    Greedy only, batch size 1 (the transformers restriction as well).
    """
    module, mparams = _unwrap(model)
    dmodule, dmparams = _unwrap(draft_model)
    params = params if params is not None else mparams
    draft_params = draft_params if draft_params is not None else dmparams
    if params is None or draft_params is None:
        raise ValueError("Both target and draft models need params.")
    input_ids = jnp.asarray(input_ids, jnp.int32)
    B, S = input_ids.shape
    if B != 1:
        raise ValueError("assisted generation supports batch_size=1 (as transformers)")
    gamma = num_draft_tokens
    eos = -1 if eos_token_id is None else eos_token_id

    cache_store = module.__dict__.setdefault("_generate_fns", {})
    key = ("assisted", id(dmodule), gamma, max_new_tokens, eos, pad_token_id, str(cache_dtype))
    if key not in cache_store:

        def rollback(cache, new_pos):
            """Rewind a cache's write offset: slots >= new_pos become invalid
            (kv_mask zeroed; stale k/v are masked by causality and later
            overwritten)."""
            total = cache["kv_mask"].shape[1]
            return {
                **cache,
                "pos": new_pos,
                "kv_mask": jnp.where(
                    jnp.arange(total)[None] < new_pos, cache["kv_mask"], 0
                ),
            }

        def run(params, draft_params, input_ids):
            S = input_ids.shape[1]
            total = S + max_new_tokens + gamma + 1  # headroom for the last chunk
            t_cache = module.init_cache(1, total, dtype=cache_dtype)
            d_cache = dmodule.init_cache(1, total + 1, dtype=cache_dtype)

            t_out = module.apply(params, input_ids=input_ids, cache=t_cache)
            d_out = dmodule.apply(draft_params, input_ids=input_ids, cache=d_cache)
            first = jnp.argmax(t_out["logits"][0, -1]).astype(jnp.int32)

            out_buf = jnp.full((max_new_tokens + gamma + 1,), pad_token_id, jnp.int32)
            out_buf = out_buf.at[0].set(first)

            def cond(carry):
                n, finished, *_ = carry
                return (n < max_new_tokens) & ~finished

            def body(carry):
                n, finished, last_tok, out_buf, t_cache, d_cache = carry

                # Draft proposes gamma tokens greedily from its own cache.
                def d_step(c, _):
                    d_cache, tok = c
                    o = dmodule.apply(draft_params, input_ids=tok[None, None], cache=d_cache)
                    nxt = jnp.argmax(o["logits"][0, -1]).astype(jnp.int32)
                    return (o["cache"], nxt), nxt

                # One extra step so the draft cache also holds the LAST
                # proposal's KV — otherwise a fully-accepted round leaves a
                # permanent hole that silently degrades later acceptance.
                (d_cache, _), draft_all = jax.lax.scan(
                    d_step, (d_cache, last_tok), None, length=gamma + 1
                )
                draft = draft_all[:gamma]
                # Target scores [last_tok, d0..d_{g-1}] in one chunk of g+1:
                # t_choice[i] is the target's greedy pick after ...last,d0..d_{i-1},
                # so t_choice[n_acc] is the correction at the first mismatch AND
                # the bonus continuation when everything matched.
                chunk = jnp.concatenate([last_tok[None], draft])[None]  # (1, g+1)
                t_out = module.apply(params, input_ids=chunk, cache=t_cache)
                t_choice = jnp.argmax(t_out["logits"][0], axis=-1).astype(jnp.int32)  # (g+1,)
                match = t_choice[:gamma] == draft
                n_acc = jnp.argmin(
                    jnp.concatenate([match, jnp.zeros((1,), bool)])
                ).astype(jnp.int32)  # accepted prefix length, 0..gamma
                fix = t_choice[n_acc]
                produced = n_acc + 1

                slot = jnp.arange(gamma + 1)
                block = jnp.where(
                    slot < n_acc,
                    jnp.concatenate([draft, jnp.zeros((1,), jnp.int32)]),
                    jnp.where(slot == n_acc, fix, pad_token_id),
                )
                out_buf = jax.lax.dynamic_update_slice(out_buf, block, (n,))
                hit_eos = (
                    jnp.any((slot < produced) & (block == eos))
                    if eos >= 0
                    else jnp.asarray(False)
                )
                # Roll both caches back to the accepted frontier (last_tok +
                # accepted draft tokens; the fix token's KV lands next round).
                t_cache = rollback(t_out["cache"], t_out["cache"]["pos"] - gamma + n_acc)
                d_cache = rollback(d_cache, d_cache["pos"] - gamma + n_acc)
                return (n + produced, hit_eos, fix, out_buf, t_cache, d_cache)

            carry = (jnp.int32(1), jnp.asarray(first == eos), first, out_buf,
                     t_out["cache"], d_out["cache"])
            n, finished, last, out_buf, *_ = jax.lax.while_loop(cond, body, carry)
            out = out_buf[:max_new_tokens]
            if eos >= 0:
                # Pad strictly after the first eos.
                after = jnp.cumsum(jnp.cumsum((out == eos).astype(jnp.int32)))
                out = jnp.where(after > 1, pad_token_id, out)
            out = jnp.where(jnp.arange(max_new_tokens) < n, out, pad_token_id)
            return out[None]

        cache_store[key] = jax.jit(run)
        # Each assisted entry's closure pins its draft module + compiled
        # executables; cap retention so sweeping draft models can't grow
        # host memory without bound.
        assisted_keys = [k for k in cache_store if k[0] == "assisted"]
        for stale in assisted_keys[:-4]:
            del cache_store[stale]
    new_tokens = cache_store[key](params, draft_params, input_ids)
    if include_prompt:
        return jnp.concatenate([input_ids, new_tokens], axis=1)
    return new_tokens


def _unwrap(model):
    """(module, params) from a Module, PreparedModel, or raw (module, params)."""
    handle = getattr(model, "handle", None)
    if handle is not None:  # PreparedModel
        return handle.module, handle.params
    return model, getattr(model, "params", None)


def generate(
    model,
    input_ids,
    *,
    max_new_tokens: int,
    params=None,
    attention_mask=None,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng=None,
    eos_token_id: int | None = None,
    pad_token_id: int = 0,
    cache_dtype=jnp.bfloat16,
    include_prompt: bool = True,
    num_beams: int = 1,
    length_penalty: float = 1.0,
):
    """Generate ``max_new_tokens`` continuations for a batch of prompts.

    ``model`` may be an ``accelerate_tpu.Module`` (with ``init_cache``), a
    ``PreparedModel`` from ``Accelerator.prepare``, or a ``StreamedScanModel``
    from offloaded ``dispatch_model``. Prompts are right-padded; pass
    ``attention_mask`` (1 = real) for ragged batches.

    Returns int32 ids of shape (B, prompt_len + max_new_tokens) when
    ``include_prompt`` else (B, max_new_tokens). Encoder-decoder models (those
    with an ``encode`` method, e.g. T5) always return (B, max_new_tokens): the
    prompt is the encoder input and the decoder stream starts fresh from
    ``decoder_start_token_id``, so there is no prompt to include.
    """
    from .big_modeling import StreamedScanModel

    if num_beams > 1:
        if temperature and temperature > 0.0:
            raise ValueError("beam search is greedy; use temperature<=0 (or num_beams=1)")
        if isinstance(model, StreamedScanModel) or hasattr(_unwrap(model)[0], "encode"):
            raise ValueError("beam search supports decoder-only cached models")
        return beam_search(
            model, input_ids, num_beams=num_beams, max_new_tokens=max_new_tokens,
            params=params, attention_mask=attention_mask,
            length_penalty=length_penalty, eos_token_id=eos_token_id,
            pad_token_id=pad_token_id, cache_dtype=cache_dtype,
            include_prompt=include_prompt,
        )

    input_ids = jnp.asarray(input_ids, jnp.int32)
    B, S = input_ids.shape
    if attention_mask is not None:
        attention_mask = jnp.asarray(attention_mask, jnp.int32)
    if rng is None:
        rng = jax.random.key(0)
    eos = -1 if eos_token_id is None else eos_token_id

    if isinstance(model, StreamedScanModel):
        module, mparams = model, None
    else:
        module, mparams = _unwrap(model)
    if hasattr(module, "encode"):
        # Encoder-decoder (T5-style): the "prompt" is the encoder input; decoding
        # starts fresh from decoder_start_token_id, so the return is always
        # (B, max_new_tokens) — see the docstring.
        if params is None:
            params = mparams
        if params is None:
            raise ValueError("Model has no params; pass params= or init the model first.")
        fn = _compiled_generate_encdec(module, max_new_tokens, temperature, top_k,
                                       top_p, eos, pad_token_id, cache_dtype)
        # None passes through jit as an empty pytree; encode() applies the
        # model's own pad-mask default, keeping one implementation.
        return fn(params, input_ids, attention_mask, rng)

    if isinstance(model, StreamedScanModel):
        new_tokens = _generate_streamed(
            model, input_ids, attention_mask, max_new_tokens,
            temperature, top_k, top_p, rng, eos, pad_token_id, cache_dtype,
        )
    else:
        module, mparams = _unwrap(model)
        if params is None:
            params = mparams
        if params is None:
            raise ValueError("Model has no params; pass params= or init the model first.")
        fn = _compiled_generate(module, max_new_tokens, temperature, top_k, top_p,
                                eos, pad_token_id, cache_dtype)
        mask_arg = (
            attention_mask if attention_mask is not None else jnp.ones((B, S), jnp.int32)
        )
        new_tokens = fn(params, input_ids, mask_arg, rng)
    if include_prompt:
        return jnp.concatenate([input_ids, new_tokens], axis=1)
    return new_tokens


def _scan_decode(first_out, step_apply, rng, max_new_tokens, temperature, top_k,
                 top_p, eos, pad_token_id, positions0=None):
    """Shared sample + finished-mask + lax.scan loop for both decode paths.

    ``first_out`` is the prefill's ModelOutput; ``step_apply(tok, cache, pos)``
    runs one cached decode step (``pos`` (B,) = each row's next token
    position, threaded through the carry; encoder-decoder ignores it)."""
    B = first_out["logits"].shape[0]
    if positions0 is None:
        positions0 = jnp.zeros((B,), jnp.int32)
    rng0, rng_loop = jax.random.split(rng)
    tok = sample_logits(first_out["logits"][:, -1], rng0, temperature, top_k, top_p)
    # HF convention (shared by the beam/assisted paths): the eos itself is
    # emitted; only tokens AFTER it become pad.
    finished = tok == eos

    def step(carry, _):
        cache, tok, pos, finished, rng = carry
        rng, sub = jax.random.split(rng)
        out = step_apply(jnp.where(finished, pad_token_id, tok), cache, pos)
        nxt = sample_logits(out["logits"][:, -1], sub, temperature, top_k, top_p)
        nxt = jnp.where(finished, pad_token_id, nxt)
        return (out["cache"], nxt, pos + 1, finished | (nxt == eos), rng), nxt

    (_, _, _, _, _), rest = jax.lax.scan(
        step, (first_out["cache"], tok, positions0, finished, rng_loop), None,
        length=max_new_tokens - 1,
    )
    return jnp.concatenate([tok[:, None], rest.T], axis=1)


def _compiled_generate(module, max_new_tokens, temperature, top_k, top_p,
                       eos, pad_token_id, cache_dtype):
    """Prefill + scan-decode as one jitted function, cached per module so
    repeated calls with the same shapes reuse the compiled program."""
    cache_store = module.__dict__.setdefault("_generate_fns", {})
    key = (max_new_tokens, temperature, top_k, top_p, eos, pad_token_id, str(cache_dtype))
    if key in cache_store:
        return cache_store[key]

    def run(params, input_ids, attention_mask, rng):
        B, S = input_ids.shape
        total = S + max_new_tokens
        cache = module.init_cache(B, total, dtype=cache_dtype)

        input_ids, attention_mask = left_align(input_ids, attention_mask)
        # Token positions from the mask (not cache slots): exact for GPT-2's
        # learned wpe on ragged batches; a no-op difference under RoPE.
        real_len = jnp.sum(attention_mask, axis=-1).astype(jnp.int32)
        out = module.apply(params, input_ids=input_ids, attention_mask=attention_mask,
                           cache=cache, positions=mask_positions(attention_mask))
        step_apply = lambda tok, cache, pos: module.apply(
            params, input_ids=tok[:, None], cache=cache, positions=pos[:, None]
        )
        return _scan_decode(out, step_apply, rng, max_new_tokens, temperature,
                            top_k, top_p, eos, pad_token_id, positions0=real_len)

    fn = jax.jit(run)
    cache_store[key] = fn
    return fn


def _compiled_generate_encdec(module, max_new_tokens, temperature, top_k, top_p,
                              eos, pad_token_id, cache_dtype):
    """Encoder once + cross-KV precompute + scan-decode, one jitted program
    (cached per module/shape like the decoder-only path)."""
    cache_store = module.__dict__.setdefault("_generate_fns", {})
    key = ("encdec", max_new_tokens, temperature, top_k, top_p, eos, pad_token_id,
           str(cache_dtype))
    if key in cache_store:
        return cache_store[key]

    def run(params, input_ids, attention_mask, rng):
        B = input_ids.shape[0]
        enc_out, enc_mask = module.encode(params, input_ids, attention_mask)
        cross_kv = module.precompute_cross_kv(params, enc_out)
        cache = module.init_cache(B, max_new_tokens, dtype=cache_dtype)

        start = jnp.full((B, 1), module.config.decoder_start_token_id, jnp.int32)
        out = module.decode(params, start, cache, enc_out, enc_mask, cross_kv=cross_kv)
        step_apply = lambda tok, cache, pos: module.decode(
            params, tok[:, None], cache, enc_out, enc_mask, cross_kv=cross_kv
        )
        return _scan_decode(out, step_apply, rng, max_new_tokens, temperature,
                            top_k, top_p, eos, pad_token_id)

    fn = jax.jit(run)
    cache_store[key] = fn
    return fn


def _generate_streamed(model, input_ids, attention_mask, max_new_tokens,
                       temperature, top_k, top_p, rng, eos, pad_token_id, cache_dtype):
    """Per-token Python loop for offloaded models: every forward streams layer
    weights host→HBM just-in-time (the model never fully resides on chip)."""
    B, S = input_ids.shape
    total = S + max_new_tokens
    cache = model.init_cache(B, total, dtype=cache_dtype)
    mask = attention_mask if attention_mask is not None else jnp.ones((B, S), jnp.int32)

    input_ids, mask = left_align(input_ids, mask)
    next_pos = jnp.sum(mask, axis=-1).astype(jnp.int32)
    out = model(input_ids=input_ids, attention_mask=mask, cache=cache,
                positions=mask_positions(mask))
    last_logits = out["logits"][:, -1]
    rng, sub = jax.random.split(rng)
    tok = sample_logits(last_logits, sub, temperature, top_k, top_p)
    # HF convention (shared with the compiled paths): the eos itself is
    # emitted; only tokens AFTER it become pad.
    finished = tok == eos
    cache = out["cache"]

    tokens = [tok]
    for _ in range(max_new_tokens - 1):
        rng, sub = jax.random.split(rng)
        out = model(input_ids=jnp.where(finished, pad_token_id, tok)[:, None],
                    cache=cache, positions=next_pos[:, None])
        next_pos = next_pos + 1
        cache = out["cache"]
        nxt = sample_logits(out["logits"][:, -1], sub, temperature, top_k, top_p)
        nxt = jnp.where(finished, pad_token_id, nxt)
        finished = finished | (nxt == eos)
        tokens.append(nxt)
        tok = nxt
    return jnp.stack(tokens, axis=1)
