"""Autoregressive generation over the KV-cache decode path.

Reference counterpart: the reference has no generate() of its own — its
big-model-inference story is transformers' ``model.generate`` driven through
dispatched/offloaded models (``benchmarks/big_model_inference/
big_model_inference.py``, BASELINE.md big-model tables measure s/token).
Here generation is part of the framework, built TPU-first:

- **One compiled program per shape**: prefill is one jit; the decode loop is a
  single ``lax.scan`` over steps with a static-shape cache, so the entire
  generation runs as two XLA programs — no per-token Python dispatch.
- **Static shapes everywhere**: the cache is pre-allocated to
  ``prompt + max_new_tokens``; finished rows keep stepping but emit
  ``pad_token_id`` (the standard masked-finish idiom), preserving SPMD-friendly
  control flow (no data-dependent early exit inside jit).
- **Ragged batches are left-aligned internally**: right-padded prompts are
  rolled so every row's last real token sits at index S-1 — all rows then share
  one global cache write offset (SPMD-uniform). Embedding positions are derived
  from the attention mask (``mask_positions``), NOT the cache slot index, so
  absolute-position models (GPT-2's learned wpe) are exact on ragged batches;
  causal masking still runs on slot indices (leading pads masked via kv_mask).
- **Offloaded models stream instead**: for ``StreamedScanModel`` (layer weights
  on host/disk) each token's forward streams layer slices just-in-time — the
  per-token Python loop is the point there, since HBM never holds the model.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp


def _warp_scores(scores, temperature: float = 1.0, top_k: int | None = None,
                 top_p: float | None = None):
    """The logits-warper chain (temperature → top-k → nucleus) on (..., V)
    rows — shared by single-sequence sampling and sampled beams so the
    masking semantics can never diverge."""
    scores = scores.astype(jnp.float32)
    if temperature and temperature != 1.0:
        scores = scores / temperature
    if top_k is not None and top_k > 0:
        kth = jnp.sort(scores, axis=-1)[..., -top_k][..., None]
        scores = jnp.where(scores < kth, -jnp.inf, scores)
    if top_p is not None and 0.0 < top_p < 1.0:
        srt = jnp.flip(jnp.sort(scores, axis=-1), axis=-1)
        probs = jax.nn.softmax(srt, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Smallest score value still inside the nucleus, per row.
        inside = cum - probs < top_p
        cutoff = jnp.min(jnp.where(inside, srt, jnp.inf), axis=-1, keepdims=True)
        scores = jnp.where(scores < cutoff, -jnp.inf, scores)
    return scores


def sample_logits(logits, rng, temperature: float = 1.0, top_k: int | None = None,
                  top_p: float | None = None):
    """Sample token ids from (B, V) logits. temperature<=0 means greedy."""
    if temperature is None or temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = _warp_scores(logits, temperature, top_k, top_p)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def left_align(input_ids, attention_mask):
    """Roll each right-padded row so its last real token lands at index S-1.

    Decoder-only generation with ragged batches requires left padding: with
    right padding each row's next token would need a per-row write offset and a
    per-row RoPE position. After the roll, one global offset serves every row,
    and the constant per-row position shift cancels in RoPE dot products.
    """
    S = input_ids.shape[1]
    shifts = S - jnp.sum(attention_mask, axis=-1).astype(jnp.int32)  # pad count per row
    roll = jax.vmap(lambda row, s: jnp.roll(row, s, axis=0))
    return roll(input_ids, shifts), roll(attention_mask, shifts)


def mask_positions(attention_mask):
    """Token positions from the attention mask: position = count of real
    tokens before it (cumsum - 1, clipped). Real positions are what
    absolute-position models (GPT-2's learned ``wpe``) must see for ragged
    batches — the cache slot index counts pads (VERDICT r2 #6); for RoPE the
    per-row difference is a constant that cancels, so one code path serves
    both families."""
    return jnp.clip(jnp.cumsum(attention_mask.astype(jnp.int32), axis=-1) - 1, 0)


def beam_search(
    model,
    input_ids,
    *,
    num_beams: int,
    max_new_tokens: int,
    params=None,
    attention_mask=None,
    length_penalty: float = 1.0,
    eos_token_id: int | None = None,
    pad_token_id: int = 0,
    cache_dtype=jnp.float32,
    include_prompt: bool = True,
    num_return_sequences: int = 1,
    do_sample: bool = False,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng=None,
):
    """Beam search over the KV-cache decode path — one compiled program.

    TPU-shaped like the sampling loop: beams live as a widened batch
    (B·num_beams), every step is one cached forward + a candidate draw over
    K·V + a gather that reorders the cache and token history along the beam
    dim, all inside ``lax.scan`` (no per-step host round trips).

    Reference parity: the reference defers to transformers'
    ``generate(num_beams=...)``; with ``eos_token_id=None`` this matches it
    token-for-token (tests/test_convert.py::test_beam_search_matches_hf).
    Each step draws 2K candidates — transformers' literal scheme — either the
    top-2K by score (greedy) or 2K Gumbel-top-k samples from the warped
    distribution (``do_sample=True`` — temperature/top_k/top_p applied to the
    joint beam+token scores, the logits-warper order of HF ``beam_sample``;
    sampling without replacement via the Gumbel trick, so the draw matches
    ``torch.multinomial(..., 2K)`` in distribution). EOS candidates ranked
    within the top num_beams are banked by normalized score into a K-deep
    hypothesis bank (BeamHypotheses' role — lower-ranked eos candidates are
    skipped, HF's is_beam_token_worse_than_top_num_beams), and the best K
    non-eos candidates keep running. Final selection merges the bank with the
    running beams and returns the best ``num_return_sequences`` per row,
    HF-style as (B·num_return_sequences, T). The length penalty divides by
    the GENERATED length (eos included for banked hypotheses; the prompt
    never enters the denominator) — matching transformers' generated_len
    convention.
    """
    module, mparams = _unwrap(model)
    if params is None:
        params = mparams
    if params is None:
        raise ValueError("Model has no params; pass params= or init the model first.")
    input_ids = jnp.asarray(input_ids, jnp.int32)
    B, S = input_ids.shape
    K = num_beams
    R = num_return_sequences
    if not (1 <= R <= K):
        raise ValueError(f"num_return_sequences must be in [1, num_beams], got {R}")
    eos = -1 if eos_token_id is None else eos_token_id
    if rng is None:
        rng = jax.random.key(0)
    mask = (
        jnp.asarray(attention_mask, jnp.int32)
        if attention_mask is not None
        else jnp.ones((B, S), jnp.int32)
    )

    def beam_select(tree, idx, width):
        """Reorder every cache leaf's beam/batch dim by ``idx`` (the k/v
        stacks carry it at axis 1 under the layer dim, host-side leaves at
        axis 0); one helper serves both the prefill tiling (repeated index)
        and the per-step parent gather."""
        return jax.tree_util.tree_map(
            lambda t: (
                jnp.take(t, idx, axis=1)
                if t.ndim >= 3 and t.shape[1] == width
                else (jnp.take(t, idx, axis=0) if t.ndim >= 1 and t.shape[0] == width else t)
            ),
            tree,
        )

    cache_store = module.__dict__.setdefault("_generate_fns", {})
    key = ("beam", K, R, max_new_tokens, length_penalty, eos, pad_token_id,
           str(cache_dtype), do_sample,
           (temperature, top_k, top_p) if do_sample else None)
    if key not in cache_store:

        def draw(scores, n, rng_s):
            """2K candidates from per-beam (B, beams, V) scores, best-first:
            top-k over the flattened beams·V when greedy; Gumbel-top-k
            (= multinomial without replacement) from the warped distribution
            when sampling. The warpers apply PER BEAM on the V axis —
            transformers' beam_sample order (each beam keeps its own top-k /
            nucleus survivors before the joint draw) — and sampled candidates
            carry their WARPED scores forward as beam scores, HF's
            convention."""
            flat = scores.reshape(scores.shape[0], -1)
            if not do_sample:
                return jax.lax.top_k(flat, n)
            w = _warp_scores(scores, temperature, top_k, top_p).reshape(flat.shape)
            g = jax.random.gumbel(rng_s, w.shape, jnp.float32)
            _, sel = jax.lax.top_k(jnp.where(jnp.isfinite(w), w + g, -jnp.inf), n)
            sel_scores = jnp.take_along_axis(w, sel, axis=1)
            order = jnp.argsort(-sel_scores, axis=1)
            return (
                jnp.take_along_axis(sel_scores, order, axis=1),
                jnp.take_along_axis(sel, order, axis=1),
            )

        def bank_insert(bank_score, bank_hist, cand_score, cand_hist):
            """Merge candidate hypotheses into the K-deep bank, keeping the
            best K (BeamHypotheses.add with worst-pruning)."""
            ms = jnp.concatenate([bank_score, cand_score], axis=1)
            mh = jnp.concatenate([bank_hist, cand_hist], axis=1)
            bank_score, sel = jax.lax.top_k(ms, K)
            return bank_score, jnp.take_along_axis(mh, sel[..., None], axis=1)

        def run(params, input_ids, mask, rng):
            B, S = input_ids.shape
            total = S + max_new_tokens
            input_ids, mask = left_align(input_ids, mask)
            real_len = jnp.sum(mask, axis=-1).astype(jnp.int32)
            rng0, rng_loop = jax.random.split(rng)

            # Prefill once per batch row, then tile the cache across beams.
            cache = module.init_cache(B, total, dtype=cache_dtype)
            out = module.apply(params, input_ids=input_ids, attention_mask=mask,
                               cache=cache, positions=mask_positions(mask))
            logp0 = jax.nn.log_softmax(out["logits"][:, -1].astype(jnp.float32))  # (B,V)
            V = logp0.shape[-1]
            n_draw = min(2 * K, V)

            bank_score = jnp.full((B, K), -jnp.inf, jnp.float32)
            bank_hist = jnp.full((B, K, max_new_tokens), pad_token_id, jnp.int32)
            # First expansion: draw 2K continuations of the single prompt beam
            # (HF starts with one active beam per row), bank eos ones ranked
            # within the top K — the generated length is 1, so the banked
            # denominator is 1**lp — and keep the best K non-eos running.
            sel_scores, sel_tok = draw(logp0[:, None, :], n_draw, rng0)
            if eos >= 0:
                is_eos_c = sel_tok == eos
                bankable = is_eos_c & (jnp.arange(n_draw)[None] < K)
                c_score = jnp.where(bankable, sel_scores, -jnp.inf)
                c_hist = jnp.full((B, n_draw, max_new_tokens), pad_token_id, jnp.int32)
                c_hist = c_hist.at[:, :, 0].set(jnp.where(bankable, eos, pad_token_id))
                bank_score, bank_hist = bank_insert(bank_score, bank_hist, c_score, c_hist)
                sel_scores = jnp.where(is_eos_c, -jnp.inf, sel_scores)
            scores, pick = jax.lax.top_k(sel_scores, K)  # (B,K) best non-eos
            tok0 = jnp.take_along_axis(sel_tok, pick, axis=1).astype(jnp.int32)
            cache = beam_select(out["cache"], jnp.repeat(jnp.arange(B), K), B)
            history = jnp.full((B, K, max_new_tokens), pad_token_id, jnp.int32)
            history = history.at[:, :, 0].set(tok0)
            tok = tok0.reshape(B * K)

            def pos_of(s):
                # The token fed at scan step ``s`` is generation index s-1
                # (tok0 at s=1), so its position is prompt_len + s - 1.
                return (jnp.repeat(real_len, K) + s - 1)[:, None]

            def step(carry, inp):
                s, rng_s = inp
                cache, tok, scores, history, bank_score, bank_hist = carry
                out = module.apply(params, input_ids=tok[:, None], cache=cache,
                                   positions=pos_of(s))
                logp = jax.nn.log_softmax(out["logits"][:, -1].astype(jnp.float32))
                cand = scores[..., None] + logp.reshape(B, K, V)  # (B,K,V)
                n2k = min(2 * K, K * V)
                sel_scores, sel_idx = draw(cand, n2k, rng_s)
                if eos >= 0:
                    # HF's scheme: every eos candidate ranked within the top K
                    # is banked (lower-ranked ones are skipped — HF's
                    # is_beam_token_worse_than_top_num_beams), normalized by
                    # the generated length INCLUDING the eos (= s+1, matching
                    # the (s+1)**lp below); the best K non-eos keep running.
                    is_eos_c = (sel_idx % V) == eos
                    bankable = is_eos_c & (jnp.arange(n2k)[None] < K)
                    c_score = jnp.where(
                        bankable, sel_scores / ((s + 1.0) ** length_penalty), -jnp.inf
                    )
                    c_parent = sel_idx // V
                    c_hist = jnp.take_along_axis(history, c_parent[..., None], axis=1)
                    c_hist = jnp.where(
                        jnp.arange(max_new_tokens)[None, None] == s,
                        jnp.where(bankable[..., None], eos, pad_token_id),
                        c_hist,
                    )
                    bank_score, bank_hist = bank_insert(
                        bank_score, bank_hist, c_score, c_hist
                    )
                    sel_scores = jnp.where(is_eos_c, -jnp.inf, sel_scores)
                new_scores, pick = jax.lax.top_k(sel_scores, K)
                flat_idx = jnp.take_along_axis(sel_idx, pick, axis=1)
                parent = flat_idx // V  # (B,K) beam each winner extends
                token = (flat_idx % V).astype(jnp.int32)

                gidx = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
                new_cache = beam_select(out["cache"], gidx, B * K)
                history = jnp.take_along_axis(history, parent[..., None], axis=1)
                history = jnp.where(
                    jnp.arange(max_new_tokens)[None, None] == s, token[..., None], history
                )
                return (new_cache, token.reshape(B * K), new_scores, history,
                        bank_score, bank_hist), None

            carry = (cache, tok, scores, history, bank_score, bank_hist)
            steps = jnp.arange(1, max_new_tokens)
            (cache, tok, scores, history, bank_score, bank_hist), _ = jax.lax.scan(
                step, carry, (steps, jax.random.split(rng_loop, max_new_tokens - 1))
            )
            # Final selection: merge the bank with the running beams at max
            # length (HF finalize adds running beams with the full generated
            # length in the denominator) and keep the best R per row. Bank
            # entries come first so score ties resolve to the finished
            # hypothesis, as before.
            running = scores / (float(max_new_tokens) ** length_penalty)
            merged_score = jnp.concatenate([bank_score, running], axis=1)  # (B,2K)
            merged_hist = jnp.concatenate([bank_hist, history], axis=1)
            _, best = jax.lax.top_k(merged_score, R)
            picked = jnp.take_along_axis(merged_hist, best[..., None], axis=1)  # (B,R,T)
            return picked.reshape(B * R, max_new_tokens)

        cache_store[key] = jax.jit(run)
    new_tokens = cache_store[key](params, input_ids, mask, rng)
    if include_prompt:
        prompts = jnp.repeat(input_ids, R, axis=0)
        return jnp.concatenate([prompts, new_tokens], axis=1)
    return new_tokens


_ASSIST_UIDS = iter(range(1 << 62))


def _assist_uid(dmodule):
    """Stable compile-cache identity for a draft module. ``id()`` was the
    previous key and could be REUSED after a draft module was GC'd, silently
    hitting a stale compiled closure; this uid is monotone and lives exactly
    as long as the module object (advisor r3 / VERDICT weak #5)."""
    return dmodule.__dict__.setdefault("_assist_uid", next(_ASSIST_UIDS))


def assisted_generate(
    model,
    draft_model,
    input_ids,
    *,
    max_new_tokens: int,
    num_draft_tokens: int = 5,
    params=None,
    draft_params=None,
    attention_mask=None,
    eos_token_id: int | None = None,
    pad_token_id: int = 0,
    cache_dtype=jnp.bfloat16,  # same default as generate(): the two entry
    include_prompt: bool = True,  # points must produce identical tokens
):
    """Speculative (assisted) greedy decoding — transformers'
    ``generate(assistant_model=...)``, TPU-shaped.

    The draft model proposes ``num_draft_tokens`` greedily from its own KV
    cache; the target scores the whole proposal in ONE cached forward and
    accepts the longest matching prefix, emitting one extra corrected token —
    so each target forward yields 1..γ+1 tokens while the output is **exactly
    the target model's greedy decode** (the speculative guarantee, pinned by
    tests). The whole accept/rollback loop is a ``lax.while_loop`` inside one
    jit (no host round-trips).

    Greedy only. Batch size 1 rolls the caches back to the accepted frontier
    (contiguous slots, minimal memory — transformers stops here). Batched
    prompts (``attention_mask`` for ragged ones) EXCEED the reference: rows
    accept independently via per-row kv-mask invalidation — each round writes
    its γ+1-slot block at one global offset and a row's rejected slots become
    permanent masked holes, so the cache is over-allocated to
    ``S + max_new_tokens·(γ+1)`` slots (the worst case of one accepted token
    per round). Rope/wpe positions stay exact per row (they ride the
    ``positions`` channel, not slot indices); sliding-window models are exact
    too — ``cached_attention`` measures windows in valid-slot distance, so
    the rejected-slot holes don't stretch the window (ops/attention.py).
    """
    module, mparams = _unwrap(model)
    dmodule, dmparams = _unwrap(draft_model)
    params = params if params is not None else mparams
    draft_params = draft_params if draft_params is not None else dmparams
    if params is None or draft_params is None:
        raise ValueError("Both target and draft models need params.")
    input_ids = jnp.asarray(input_ids, jnp.int32)
    B, S = input_ids.shape
    gamma = num_draft_tokens
    eos = -1 if eos_token_id is None else eos_token_id
    if B != 1:
        return _assisted_generate_batched(
            module, dmodule, params, draft_params, input_ids, attention_mask,
            max_new_tokens=max_new_tokens, gamma=gamma, eos=eos,
            pad_token_id=pad_token_id, cache_dtype=cache_dtype,
            include_prompt=include_prompt,
        )
    if attention_mask is not None:
        # B == 1: compact the real tokens (host-side boolean take — correct
        # for pads in ANY position, not just trailing) down to a dense prompt.
        m_np = np.asarray(attention_mask).astype(bool).reshape(-1)
        if not m_np.all():
            input_ids = jnp.asarray(np.asarray(input_ids)[0][m_np][None], jnp.int32)
            S = int(m_np.sum())

    cache_store = module.__dict__.setdefault("_generate_fns", {})
    key = ("assisted", _assist_uid(dmodule), gamma, max_new_tokens, eos,
           pad_token_id, str(cache_dtype))
    if key not in cache_store:

        def rollback(cache, new_pos):
            """Rewind a cache's write offset: slots >= new_pos become invalid
            (kv_mask zeroed; stale k/v are masked by causality and later
            overwritten)."""
            total = cache["kv_mask"].shape[1]
            return {
                **cache,
                "pos": new_pos,
                "kv_mask": jnp.where(
                    jnp.arange(total)[None] < new_pos, cache["kv_mask"], 0
                ),
            }

        def run(params, draft_params, input_ids):
            S = input_ids.shape[1]
            total = S + max_new_tokens + gamma + 1  # headroom for the last chunk
            t_cache = module.init_cache(1, total, dtype=cache_dtype)
            d_cache = dmodule.init_cache(1, total + 1, dtype=cache_dtype)

            t_out = module.apply(params, input_ids=input_ids, cache=t_cache)
            d_out = dmodule.apply(draft_params, input_ids=input_ids, cache=d_cache)
            first = jnp.argmax(t_out["logits"][0, -1]).astype(jnp.int32)

            out_buf = jnp.full((max_new_tokens + gamma + 1,), pad_token_id, jnp.int32)
            out_buf = out_buf.at[0].set(first)

            def cond(carry):
                n, finished, *_ = carry
                return (n < max_new_tokens) & ~finished

            def body(carry):
                n, finished, last_tok, out_buf, t_cache, d_cache = carry

                # Draft proposes gamma tokens greedily from its own cache.
                def d_step(c, _):
                    d_cache, tok = c
                    o = dmodule.apply(draft_params, input_ids=tok[None, None], cache=d_cache)
                    nxt = jnp.argmax(o["logits"][0, -1]).astype(jnp.int32)
                    return (o["cache"], nxt), nxt

                # One extra step so the draft cache also holds the LAST
                # proposal's KV — otherwise a fully-accepted round leaves a
                # permanent hole that silently degrades later acceptance.
                (d_cache, _), draft_all = jax.lax.scan(
                    d_step, (d_cache, last_tok), None, length=gamma + 1
                )
                draft = draft_all[:gamma]
                # Target scores [last_tok, d0..d_{g-1}] in one chunk of g+1:
                # t_choice[i] is the target's greedy pick after ...last,d0..d_{i-1},
                # so t_choice[n_acc] is the correction at the first mismatch AND
                # the bonus continuation when everything matched.
                chunk = jnp.concatenate([last_tok[None], draft])[None]  # (1, g+1)
                t_out = module.apply(params, input_ids=chunk, cache=t_cache)
                t_choice = jnp.argmax(t_out["logits"][0], axis=-1).astype(jnp.int32)  # (g+1,)
                match = t_choice[:gamma] == draft
                n_acc = jnp.argmin(
                    jnp.concatenate([match, jnp.zeros((1,), bool)])
                ).astype(jnp.int32)  # accepted prefix length, 0..gamma
                fix = t_choice[n_acc]
                produced = n_acc + 1

                slot = jnp.arange(gamma + 1)
                block = jnp.where(
                    slot < n_acc,
                    jnp.concatenate([draft, jnp.zeros((1,), jnp.int32)]),
                    jnp.where(slot == n_acc, fix, pad_token_id),
                )
                out_buf = jax.lax.dynamic_update_slice(out_buf, block, (n,))
                hit_eos = (
                    jnp.any((slot < produced) & (block == eos))
                    if eos >= 0
                    else jnp.asarray(False)
                )
                # Roll both caches back to the accepted frontier (last_tok +
                # accepted draft tokens; the fix token's KV lands next round).
                t_cache = rollback(t_out["cache"], t_out["cache"]["pos"] - gamma + n_acc)
                d_cache = rollback(d_cache, d_cache["pos"] - gamma + n_acc)
                return (n + produced, hit_eos, fix, out_buf, t_cache, d_cache)

            carry = (jnp.int32(1), jnp.asarray(first == eos), first, out_buf,
                     t_out["cache"], d_out["cache"])
            n, finished, last, out_buf, *_ = jax.lax.while_loop(cond, body, carry)
            out = out_buf[:max_new_tokens]
            if eos >= 0:
                # Pad strictly after the first eos.
                after = jnp.cumsum(jnp.cumsum((out == eos).astype(jnp.int32)))
                out = jnp.where(after > 1, pad_token_id, out)
            out = jnp.where(jnp.arange(max_new_tokens) < n, out, pad_token_id)
            return out[None]

        cache_store[key] = jax.jit(run)
        # Each assisted entry's closure pins its draft module + compiled
        # executables; cap retention so sweeping draft models can't grow
        # host memory without bound.
        assisted_keys = [k for k in cache_store if k[0] == "assisted"]
        for stale in assisted_keys[:-4]:
            del cache_store[stale]
    new_tokens = cache_store[key](params, draft_params, input_ids)
    if include_prompt:
        return jnp.concatenate([input_ids, new_tokens], axis=1)
    return new_tokens


def _assisted_generate_batched(
    module, dmodule, params, draft_params, input_ids, attention_mask, *,
    max_new_tokens, gamma, eos, pad_token_id, cache_dtype, include_prompt,
):
    """Batched speculative decoding — see ``assisted_generate``'s docstring.

    Every round, every row: the draft proposes γ tokens, the target scores
    [last, d0..dγ-1] in one (B, γ+1) cached forward at per-row rope positions,
    and each row accepts its own longest matching prefix + one correction.
    Cache writes stay SPMD-uniform (one global write offset per round); a
    row's rejected slots are invalidated in its kv_mask and never reused —
    attention correctness needs only slot-causality + validity, both of which
    hole-tolerate. Each row's output is exactly that row's greedy decode.
    """
    B, S = input_ids.shape
    mask = (
        jnp.asarray(attention_mask, jnp.int32)
        if attention_mask is not None
        else jnp.ones((B, S), jnp.int32)
    )

    cache_store = module.__dict__.setdefault("_generate_fns", {})
    key = ("assisted_b", _assist_uid(dmodule), gamma, max_new_tokens, eos,
           pad_token_id, str(cache_dtype))
    if key not in cache_store:

        def invalidate(cache, start0, keep_upto):
            """Zero kv_mask slots in [keep_upto+1, start0+width) per row —
            this round's rejected block tail (later slots are still zero)."""
            total = cache["kv_mask"].shape[1]
            slot = jnp.arange(total)[None]
            reject = (slot > keep_upto[:, None]) & (slot >= start0)
            return {**cache, "kv_mask": jnp.where(reject, 0, cache["kv_mask"])}

        def run(params, draft_params, input_ids, mask):
            B, S = input_ids.shape
            # Worst case one accepted token per round: max_new rounds of γ+1
            # slots each (plus prefill) — the documented memory trade.
            total = S + max_new_tokens * (gamma + 1) + gamma + 2
            t_cache = module.init_cache(B, total, dtype=cache_dtype)
            d_cache = dmodule.init_cache(B, total + gamma + 2, dtype=cache_dtype)

            input_ids, mask = left_align(input_ids, mask)
            real_len = jnp.sum(mask, axis=-1).astype(jnp.int32)
            pos0 = mask_positions(mask)
            t_out = module.apply(params, input_ids=input_ids, attention_mask=mask,
                                 cache=t_cache, positions=pos0)
            d_out = dmodule.apply(draft_params, input_ids=input_ids,
                                  attention_mask=mask, cache=d_cache, positions=pos0)
            first = jnp.argmax(t_out["logits"][:, -1], axis=-1).astype(jnp.int32)

            out_buf = jnp.full((B, max_new_tokens + gamma + 1), pad_token_id, jnp.int32)
            out_buf = out_buf.at[:, 0].set(first)
            slot_r = jnp.arange(gamma + 1)

            def cond(carry):
                n, finished, *_ = carry
                return jnp.any(~finished & (n < max_new_tokens))

            def body(carry):
                n, finished, last_tok, p_last, out_buf, t_cache, d_cache = carry
                done = finished | (n >= max_new_tokens)

                # Draft proposes γ tokens greedily; each step writes one slot
                # at the global draft offset, rope positions per row.
                def d_step(c, j):
                    d_cache, tok, p = c
                    o = dmodule.apply(draft_params, input_ids=tok[:, None],
                                      cache=d_cache, positions=p[:, None])
                    nxt = jnp.argmax(o["logits"][:, -1], axis=-1).astype(jnp.int32)
                    return (o["cache"], nxt, p + 1), nxt

                d_start = d_cache["pos"]
                (d_cache, _, _), draft_all = jax.lax.scan(
                    d_step, (d_cache, last_tok, p_last), jnp.arange(gamma + 1)
                )
                draft = draft_all[:gamma].T  # (B, γ)

                # Target scores [last, d0..dγ-1] in one chunk per row.
                chunk = jnp.concatenate([last_tok[:, None], draft], axis=1)
                chunk_pos = p_last[:, None] + slot_r[None]
                t_start = t_cache["pos"]
                t_out = module.apply(params, input_ids=chunk, cache=t_cache,
                                     positions=chunk_pos)
                t_choice = jnp.argmax(t_out["logits"], axis=-1).astype(jnp.int32)  # (B,γ+1)
                match = t_choice[:, :gamma] == draft
                n_acc = jnp.argmin(
                    jnp.concatenate([match, jnp.zeros((B, 1), bool)], axis=1), axis=1
                ).astype(jnp.int32)  # (B,) accepted prefix length
                fix = jnp.take_along_axis(t_choice, n_acc[:, None], axis=1)[:, 0]
                produced = jnp.where(done, 0, n_acc + 1)

                block = jnp.where(
                    slot_r[None] < n_acc[:, None],
                    jnp.concatenate([draft, jnp.zeros((B, 1), jnp.int32)], axis=1),
                    jnp.where(slot_r[None] == n_acc[:, None], fix[:, None], pad_token_id),
                )
                block = jnp.where(done[:, None], pad_token_id, block)
                # Done rows write pads AT n: their slots >= n are already pads
                # (n >= max_new clamps into the trimmed headroom), so the
                # write is a no-op for them — SPMD-uniform, no special case.
                write = jax.vmap(
                    lambda buf, blk, start: jax.lax.dynamic_update_slice(buf, blk, (start,))
                )
                out_buf = write(out_buf, block, n)
                hit_eos = (
                    jnp.any((slot_r[None] < produced[:, None]) & (block == eos), axis=1)
                    if eos >= 0
                    else jnp.zeros((B,), bool)
                )
                # Per-row invalidation: keep last_tok + accepted drafts of this
                # round's block, hole out the rest (done rows hole the whole
                # block — their writes are garbage). Offsets never rewind.
                keep = jnp.where(done, -1, n_acc)
                t_cache = invalidate(t_out["cache"], t_start, t_start + keep)
                d_cache = invalidate(d_cache, d_start, d_start + keep)
                return (
                    n + produced, finished | hit_eos,
                    jnp.where(done, last_tok, fix),
                    jnp.where(done, p_last, p_last + produced),
                    out_buf, t_cache, d_cache,
                )

            carry = (
                jnp.ones((B,), jnp.int32),
                first == eos if eos >= 0 else jnp.zeros((B,), bool),
                first,
                real_len,  # position of the token AFTER the prompt's last = first's position
                out_buf, t_out["cache"], d_out["cache"],
            )
            n, finished, _, _, out_buf, *_ = jax.lax.while_loop(cond, body, carry)
            out = out_buf[:, :max_new_tokens]
            if eos >= 0:
                after = jnp.cumsum(jnp.cumsum((out == eos).astype(jnp.int32), axis=1), axis=1)
                out = jnp.where(after > 1, pad_token_id, out)
            out = jnp.where(jnp.arange(max_new_tokens)[None] < n[:, None], out, pad_token_id)
            return out

        cache_store[key] = jax.jit(run)
        stale = [k for k in cache_store if k[0] == "assisted_b"]
        for k in stale[:-4]:
            del cache_store[k]
    new_tokens = cache_store[key](params, draft_params, input_ids, mask)
    if include_prompt:
        return jnp.concatenate([input_ids, new_tokens], axis=1)
    return new_tokens


def _unwrap(model):
    """(module, params) from a Module, PreparedModel, or raw (module, params)."""
    handle = getattr(model, "handle", None)
    if handle is not None:  # PreparedModel
        return handle.module, handle.params
    return model, getattr(model, "params", None)


def _precision_variant(module, precision: str):
    """A shallow config variant of ``module`` with ``matmul_precision`` set —
    the serving-side int8 weight-quantization switch (ops/int8.py): the model
    zoo routes every projection through ``ops.int8.matmul(a, b,
    precision=config.matmul_precision)``, so flipping the config field is the
    whole plumb and the params (dynamically quantized inside the matmul) are
    shared bit-for-bit with the full-precision module. Variants are memoized
    ON the original module: each one keeps its own ``_generate_fns`` compile
    cache, so repeated ``generate(..., matmul_precision='int8')`` calls reuse
    one compiled program instead of re-tracing per call."""
    import copy
    import dataclasses

    cfg = getattr(module, "config", None)
    if cfg is None or not hasattr(cfg, "matmul_precision"):
        raise ValueError(
            f"model {type(module).__name__} has no matmul_precision config "
            "field; int8 serving needs a zoo model routed through ops.int8.matmul"
        )
    if precision == cfg.matmul_precision:
        return module
    variants = module.__dict__.setdefault("_precision_variants", {})
    if precision not in variants:
        clone = copy.copy(module)
        clone.config = dataclasses.replace(cfg, matmul_precision=precision)
        # A fresh compile/variant cache: the clone must never share compiled
        # programs (or further variants) with the original module.
        clone.__dict__.pop("_generate_fns", None)
        clone.__dict__.pop("_precision_variants", None)
        variants[precision] = clone
    return variants[precision]


def generate(
    model,
    input_ids,
    *,
    max_new_tokens: int,
    params=None,
    attention_mask=None,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng=None,
    eos_token_id: int | None = None,
    pad_token_id: int = 0,
    cache_dtype=jnp.bfloat16,
    include_prompt: bool = True,
    num_beams: int = 1,
    length_penalty: float = 1.0,
    num_return_sequences: int = 1,
    do_sample: bool = False,
    assistant_model=None,
    num_draft_tokens: int = 5,
    matmul_precision: str | None = None,
):
    """Generate ``max_new_tokens`` continuations for a batch of prompts.

    ``model`` may be an ``accelerate_tpu.Module`` (with ``init_cache``), a
    ``PreparedModel`` from ``Accelerator.prepare``, or a ``StreamedScanModel``
    from offloaded ``dispatch_model``. Prompts are right-padded; pass
    ``attention_mask`` (1 = real) for ragged batches.

    Returns int32 ids of shape (B, prompt_len + max_new_tokens) when
    ``include_prompt`` else (B, max_new_tokens). Encoder-decoder models (those
    with an ``encode`` method, e.g. T5) always return (B, max_new_tokens): the
    prompt is the encoder input and the decoder stream starts fresh from
    ``decoder_start_token_id``, so there is no prompt to include.
    """
    from .big_modeling import StreamedScanModel

    # Opt-in serving dtype policy (ISSUE 20 lever c): run the forward's
    # matmuls through the kernel-backed int8 path. Applied via a memoized
    # module variant so compiled programs are still cached per (module,
    # precision) — see _precision_variant.
    if matmul_precision in ("", "default"):
        matmul_precision = None
    if matmul_precision is not None and (
        assistant_model is not None or num_beams > 1
        or isinstance(model, StreamedScanModel)
    ):
        raise ValueError(
            "matmul_precision supports the plain decoder-only generate path "
            "(no assistant_model/num_beams/StreamedScanModel)"
        )

    if assistant_model is not None:
        # transformers' generate(assistant_model=...) entry point: route to
        # speculative decoding (greedy only, like HF's assisted path).
        if isinstance(model, StreamedScanModel) or hasattr(_unwrap(model)[0], "encode"):
            raise ValueError(
                "assisted generation supports decoder-only cached models "
                "(not StreamedScanModel or encoder-decoder)"
            )
        if num_beams > 1 or do_sample or (temperature and temperature > 0.0):
            raise ValueError(
                "assistant_model (speculative decoding) is greedy-only; drop "
                "num_beams/do_sample/temperature or call assisted_generate directly."
            )
        if num_return_sequences != 1:
            raise ValueError("assistant_model does not support num_return_sequences > 1")
        return assisted_generate(
            model, assistant_model, input_ids, max_new_tokens=max_new_tokens,
            num_draft_tokens=num_draft_tokens, params=params,
            attention_mask=attention_mask, eos_token_id=eos_token_id,
            pad_token_id=pad_token_id, cache_dtype=cache_dtype,
            include_prompt=include_prompt,
        )
    if num_beams > 1:
        if temperature and temperature > 0.0 and not do_sample:
            raise ValueError(
                "beam search is greedy unless do_sample=True (HF beam_sample); "
                "set do_sample=True to use temperature/top_k/top_p with beams"
            )
        if isinstance(model, StreamedScanModel) or hasattr(_unwrap(model)[0], "encode"):
            raise ValueError("beam search supports decoder-only cached models")
        return beam_search(
            model, input_ids, num_beams=num_beams, max_new_tokens=max_new_tokens,
            params=params, attention_mask=attention_mask,
            length_penalty=length_penalty, eos_token_id=eos_token_id,
            pad_token_id=pad_token_id, cache_dtype=cache_dtype,
            include_prompt=include_prompt,
            num_return_sequences=num_return_sequences,
            do_sample=do_sample,
            temperature=temperature if (do_sample and temperature) else 1.0,
            top_k=top_k, top_p=top_p, rng=rng,
        )
    if do_sample and not (temperature and temperature > 0.0):
        temperature = 1.0  # HF do_sample semantics: sample at T=1 by default
    if num_return_sequences != 1:
        # HF semantics for sampling: n independent draws per prompt, returned
        # as (B*n, T) with each prompt's draws adjacent. Implemented by
        # row-expanding the batch; each expanded row samples its own stream.
        if not (temperature and temperature > 0.0):
            raise ValueError(
                "num_return_sequences > 1 needs sampling (do_sample/temperature"
                " > 0) or beam search (num_beams > 1) — greedy returns one "
                "sequence."
            )
        n = num_return_sequences
        input_ids = jnp.repeat(jnp.asarray(input_ids), n, axis=0)
        if attention_mask is not None:
            attention_mask = jnp.repeat(jnp.asarray(attention_mask, jnp.int32), n, axis=0)
        num_return_sequences = 1

    if isinstance(model, StreamedScanModel):
        module, mparams = model, None
    else:
        module, mparams = _unwrap(model)
        if matmul_precision is not None:
            module = _precision_variant(module, matmul_precision)

    # Token prompts cast to int32. Float arrays pass through unchanged ONLY
    # for encoder-decoders, whose "prompt" may be continuous encoder input
    # (Whisper's (B, n_mels, T) log-mel features); decoder-only models keep
    # the unconditional cast (the pre-Whisper behavior — float token ids
    # truncate, they don't error deep inside the jitted embedding lookup).
    input_ids = jnp.asarray(input_ids)
    if jnp.issubdtype(input_ids.dtype, jnp.integer) or not hasattr(module, "encode"):
        input_ids = input_ids.astype(jnp.int32)
    if attention_mask is not None:
        attention_mask = jnp.asarray(attention_mask, jnp.int32)
    if rng is None:
        rng = jax.random.key(0)
    eos = -1 if eos_token_id is None else eos_token_id
    if hasattr(module, "encode"):
        # Encoder-decoder (T5-style): the "prompt" is the encoder input; decoding
        # starts fresh from decoder_start_token_id, so the return is always
        # (B, max_new_tokens) — see the docstring.
        if params is None:
            params = mparams
        if params is None:
            raise ValueError("Model has no params; pass params= or init the model first.")
        fn = _compiled_generate_encdec(module, max_new_tokens, temperature, top_k,
                                       top_p, eos, pad_token_id, cache_dtype)
        # None passes through jit as an empty pytree; encode() applies the
        # model's own pad-mask default, keeping one implementation.
        return fn(params, input_ids, attention_mask, rng)

    B, S = input_ids.shape
    if isinstance(model, StreamedScanModel):
        new_tokens = _generate_streamed(
            model, input_ids, attention_mask, max_new_tokens,
            temperature, top_k, top_p, rng, eos, pad_token_id, cache_dtype,
        )
    else:
        module, mparams = _unwrap(model)
        if params is None:
            params = mparams
        if params is None:
            raise ValueError("Model has no params; pass params= or init the model first.")
        fn = _compiled_generate(module, max_new_tokens, temperature, top_k, top_p,
                                eos, pad_token_id, cache_dtype)
        mask_arg = (
            attention_mask if attention_mask is not None else jnp.ones((B, S), jnp.int32)
        )
        new_tokens = fn(params, input_ids, mask_arg, rng)
    if include_prompt:
        return jnp.concatenate([input_ids, new_tokens], axis=1)
    return new_tokens


def _scan_decode(first_out, step_apply, rng, max_new_tokens, temperature, top_k,
                 top_p, eos, pad_token_id, positions0=None):
    """Shared sample + finished-mask + lax.scan loop for both decode paths.

    ``first_out`` is the prefill's ModelOutput; ``step_apply(tok, cache, pos)``
    runs one cached decode step (``pos`` (B,) = each row's next token
    position, threaded through the carry; encoder-decoder ignores it)."""
    B = first_out["logits"].shape[0]
    if positions0 is None:
        positions0 = jnp.zeros((B,), jnp.int32)
    rng0, rng_loop = jax.random.split(rng)
    tok = sample_logits(first_out["logits"][:, -1], rng0, temperature, top_k, top_p)
    # HF convention (shared by the beam/assisted paths): the eos itself is
    # emitted; only tokens AFTER it become pad.
    finished = tok == eos

    def step(carry, _):
        cache, tok, pos, finished, rng = carry
        rng, sub = jax.random.split(rng)
        out = step_apply(jnp.where(finished, pad_token_id, tok), cache, pos)
        nxt = sample_logits(out["logits"][:, -1], sub, temperature, top_k, top_p)
        nxt = jnp.where(finished, pad_token_id, nxt)
        return (out["cache"], nxt, pos + 1, finished | (nxt == eos), rng), nxt

    (_, _, _, _, _), rest = jax.lax.scan(
        step, (first_out["cache"], tok, positions0, finished, rng_loop), None,
        length=max_new_tokens - 1,
    )
    return jnp.concatenate([tok[:, None], rest.T], axis=1)


def _compiled_generate(module, max_new_tokens, temperature, top_k, top_p,
                       eos, pad_token_id, cache_dtype):
    """Prefill + scan-decode as one jitted function, cached per module so
    repeated calls with the same shapes reuse the compiled program."""
    cache_store = module.__dict__.setdefault("_generate_fns", {})
    key = (max_new_tokens, temperature, top_k, top_p, eos, pad_token_id, str(cache_dtype))
    if key in cache_store:
        return cache_store[key]

    def run(params, input_ids, attention_mask, rng):
        B, S = input_ids.shape
        total = S + max_new_tokens
        cache = module.init_cache(B, total, dtype=cache_dtype)

        input_ids, attention_mask = left_align(input_ids, attention_mask)
        # Token positions from the mask (not cache slots): exact for GPT-2's
        # learned wpe on ragged batches; a no-op difference under RoPE.
        real_len = jnp.sum(attention_mask, axis=-1).astype(jnp.int32)
        out = module.apply(params, input_ids=input_ids, attention_mask=attention_mask,
                           cache=cache, positions=mask_positions(attention_mask))
        step_apply = lambda tok, cache, pos: module.apply(
            params, input_ids=tok[:, None], cache=cache, positions=pos[:, None]
        )
        return _scan_decode(out, step_apply, rng, max_new_tokens, temperature,
                            top_k, top_p, eos, pad_token_id, positions0=real_len)

    fn = jax.jit(run)
    cache_store[key] = fn
    return fn


def _compiled_generate_encdec(module, max_new_tokens, temperature, top_k, top_p,
                              eos, pad_token_id, cache_dtype):
    """Encoder once + cross-KV precompute + scan-decode, one jitted program
    (cached per module/shape like the decoder-only path)."""
    cache_store = module.__dict__.setdefault("_generate_fns", {})
    key = ("encdec", max_new_tokens, temperature, top_k, top_p, eos, pad_token_id,
           str(cache_dtype))
    if key in cache_store:
        return cache_store[key]

    def run(params, input_ids, attention_mask, rng):
        B = input_ids.shape[0]
        enc_out, enc_mask = module.encode(params, input_ids, attention_mask)
        cross_kv = module.precompute_cross_kv(params, enc_out)
        cache = module.init_cache(B, max_new_tokens, dtype=cache_dtype)

        start = jnp.full((B, 1), module.config.decoder_start_token_id, jnp.int32)
        out = module.decode(params, start, cache, enc_out, enc_mask, cross_kv=cross_kv)
        step_apply = lambda tok, cache, pos: module.decode(
            params, tok[:, None], cache, enc_out, enc_mask, cross_kv=cross_kv
        )
        return _scan_decode(out, step_apply, rng, max_new_tokens, temperature,
                            top_k, top_p, eos, pad_token_id)

    fn = jax.jit(run)
    cache_store[key] = fn
    return fn


def _generate_streamed(model, input_ids, attention_mask, max_new_tokens,
                       temperature, top_k, top_p, rng, eos, pad_token_id, cache_dtype):
    """Per-token Python loop for offloaded models: every forward streams layer
    weights host→HBM just-in-time (the model never fully resides on chip)."""
    B, S = input_ids.shape
    total = S + max_new_tokens
    cache = model.init_cache(B, total, dtype=cache_dtype)
    mask = attention_mask if attention_mask is not None else jnp.ones((B, S), jnp.int32)

    input_ids, mask = left_align(input_ids, mask)
    next_pos = jnp.sum(mask, axis=-1).astype(jnp.int32)
    out = model(input_ids=input_ids, attention_mask=mask, cache=cache,
                positions=mask_positions(mask))
    last_logits = out["logits"][:, -1]
    rng, sub = jax.random.split(rng)
    tok = sample_logits(last_logits, sub, temperature, top_k, top_p)
    # HF convention (shared with the compiled paths): the eos itself is
    # emitted; only tokens AFTER it become pad.
    finished = tok == eos
    cache = out["cache"]

    tokens = [tok]
    for _ in range(max_new_tokens - 1):
        rng, sub = jax.random.split(rng)
        out = model(input_ids=jnp.where(finished, pad_token_id, tok)[:, None],
                    cache=cache, positions=next_pos[:, None])
        next_pos = next_pos + 1
        cache = out["cache"]
        nxt = sample_logits(out["logits"][:, -1], sub, temperature, top_k, top_p)
        nxt = jnp.where(finished, pad_token_id, nxt)
        finished = finished | (nxt == eos)
        tokens.append(nxt)
        tok = nxt
    return jnp.stack(tokens, axis=1)
