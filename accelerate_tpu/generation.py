"""Autoregressive generation over the KV-cache decode path.

Reference counterpart: the reference has no generate() of its own — its
big-model-inference story is transformers' ``model.generate`` driven through
dispatched/offloaded models (``benchmarks/big_model_inference/
big_model_inference.py``, BASELINE.md big-model tables measure s/token).
Here generation is part of the framework, built TPU-first:

- **One compiled program per shape**: prefill is one jit; the decode loop is a
  single ``lax.scan`` over steps with a static-shape cache, so the entire
  generation runs as two XLA programs — no per-token Python dispatch.
- **Static shapes everywhere**: the cache is pre-allocated to
  ``prompt + max_new_tokens``; finished rows keep stepping but emit
  ``pad_token_id`` (the standard masked-finish idiom), preserving SPMD-friendly
  control flow (no data-dependent early exit inside jit).
- **Ragged batches are left-aligned internally**: right-padded prompts are
  rolled so every row's last real token sits at index S-1 — all rows then share
  one global cache write offset (SPMD-uniform). Embedding positions are derived
  from the attention mask (``mask_positions``), NOT the cache slot index, so
  absolute-position models (GPT-2's learned wpe) are exact on ragged batches;
  causal masking still runs on slot indices (leading pads masked via kv_mask).
- **Offloaded models stream instead**: for ``StreamedScanModel`` (layer weights
  on host/disk) each token's forward streams layer slices just-in-time — the
  per-token Python loop is the point there, since HBM never holds the model.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp


def sample_logits(logits, rng, temperature: float = 1.0, top_k: int | None = None,
                  top_p: float | None = None):
    """Sample token ids from (B, V) logits. temperature<=0 means greedy."""
    if temperature is None or temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and 0.0 < top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Smallest logit value still inside the nucleus, per row.
        inside = cum - probs < top_p
        cutoff = jnp.min(jnp.where(inside, sorted_logits, jnp.inf), axis=-1, keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def left_align(input_ids, attention_mask):
    """Roll each right-padded row so its last real token lands at index S-1.

    Decoder-only generation with ragged batches requires left padding: with
    right padding each row's next token would need a per-row write offset and a
    per-row RoPE position. After the roll, one global offset serves every row,
    and the constant per-row position shift cancels in RoPE dot products.
    """
    S = input_ids.shape[1]
    shifts = S - jnp.sum(attention_mask, axis=-1).astype(jnp.int32)  # pad count per row
    roll = jax.vmap(lambda row, s: jnp.roll(row, s, axis=0))
    return roll(input_ids, shifts), roll(attention_mask, shifts)


def mask_positions(attention_mask):
    """Token positions from the attention mask: position = count of real
    tokens before it (cumsum - 1, clipped). Real positions are what
    absolute-position models (GPT-2's learned ``wpe``) must see for ragged
    batches — the cache slot index counts pads (VERDICT r2 #6); for RoPE the
    per-row difference is a constant that cancels, so one code path serves
    both families."""
    return jnp.clip(jnp.cumsum(attention_mask.astype(jnp.int32), axis=-1) - 1, 0)


def beam_search(
    model,
    input_ids,
    *,
    num_beams: int,
    max_new_tokens: int,
    params=None,
    attention_mask=None,
    length_penalty: float = 1.0,
    eos_token_id: int | None = None,
    pad_token_id: int = 0,
    cache_dtype=jnp.float32,
    include_prompt: bool = True,
):
    """Greedy beam search over the KV-cache decode path — one compiled program.

    TPU-shaped like the sampling loop: beams live as a widened batch
    (B·num_beams), every step is one cached forward + a top-k over K·V + a
    gather that reorders the cache and token history along the beam dim, all
    inside ``lax.scan`` (no per-step host round trips). Finished beams (EOS)
    freeze their score and emit pad. Final selection applies HF's length
    penalty ``score / len**penalty`` over finished-or-running beams.

    Reference parity: the reference defers to transformers'
    ``generate(num_beams=...)``; with ``eos_token_id=None`` this matches it
    token-for-token (tests/test_convert.py::test_beam_search_matches_hf).
    Finished hypotheses are banked by normalized score (transformers'
    BeamHypotheses role) so a finished beam can never be evicted by running
    beams and then lost; the length penalty divides by the FULL sequence
    length (prompt + generated), matching transformers.
    """
    module, mparams = _unwrap(model)
    if params is None:
        params = mparams
    if params is None:
        raise ValueError("Model has no params; pass params= or init the model first.")
    input_ids = jnp.asarray(input_ids, jnp.int32)
    B, S = input_ids.shape
    K = num_beams
    eos = -1 if eos_token_id is None else eos_token_id
    mask = (
        jnp.asarray(attention_mask, jnp.int32)
        if attention_mask is not None
        else jnp.ones((B, S), jnp.int32)
    )

    def beam_select(tree, idx, width):
        """Reorder every cache leaf's beam/batch dim by ``idx`` (the k/v
        stacks carry it at axis 1 under the layer dim, host-side leaves at
        axis 0); one helper serves both the prefill tiling (repeated index)
        and the per-step parent gather."""
        return jax.tree_util.tree_map(
            lambda t: (
                jnp.take(t, idx, axis=1)
                if t.ndim >= 3 and t.shape[1] == width
                else (jnp.take(t, idx, axis=0) if t.ndim >= 1 and t.shape[0] == width else t)
            ),
            tree,
        )

    cache_store = module.__dict__.setdefault("_generate_fns", {})
    key = ("beam", K, max_new_tokens, length_penalty, eos, pad_token_id, str(cache_dtype))
    if key not in cache_store:

        def run(params, input_ids, mask):
            B, S = input_ids.shape
            total = S + max_new_tokens
            input_ids, mask = left_align(input_ids, mask)
            real_len = jnp.sum(mask, axis=-1).astype(jnp.int32)

            # Prefill once per batch row, then tile the cache across beams.
            cache = module.init_cache(B, total, dtype=cache_dtype)
            out = module.apply(params, input_ids=input_ids, attention_mask=mask,
                               cache=cache, positions=mask_positions(mask))
            logp0 = jax.nn.log_softmax(out["logits"][:, -1].astype(jnp.float32))  # (B,V)
            V = logp0.shape[-1]
            scores0, tok0 = jax.lax.top_k(logp0, K)  # (B,K)
            cache = beam_select(out["cache"], jnp.repeat(jnp.arange(B), K), B)

            finished0 = (tok0 == eos).reshape(B, K)
            # History records the raw token (an immediate eos included, as HF
            # does); only the NEXT model input becomes pad for finished beams.
            history = jnp.full((B, K, max_new_tokens), pad_token_id, jnp.int32)
            history = history.at[:, :, 0].set(tok0)
            tok = jnp.where(finished0, pad_token_id, tok0).reshape(B * K)
            lengths = jnp.ones((B, K), jnp.int32)  # generated tokens incl. eos
            pos = jnp.repeat(real_len, K)  # next-token position per beam
            full_len = real_len[:, None].astype(jnp.float32)  # prompt part

            def norm_scores(scores, lengths):
                # transformers divides by the FULL hypothesis length.
                return scores / ((full_len + lengths.astype(jnp.float32)) ** length_penalty)

            bank_score = jnp.where(
                finished0, norm_scores(scores0, lengths), -jnp.inf
            ).max(axis=1)
            bank_hist = jnp.take_along_axis(
                history,
                jnp.argmax(jnp.where(finished0, norm_scores(scores0, lengths), -jnp.inf),
                           axis=1)[:, None, None],
                axis=1,
            )[:, 0]

            def step(carry, _):
                cache, tok, scores, finished, lengths, history, pos, bank_score, bank_hist = carry
                out = module.apply(params, input_ids=tok[:, None], cache=cache,
                                   positions=pos[:, None])
                logp = jax.nn.log_softmax(out["logits"][:, -1].astype(jnp.float32))
                logp = logp.reshape(B, K, V)
                # Finished beams may only extend with pad at zero cost.
                pad_only = jnp.full((V,), -jnp.inf).at[pad_token_id].set(0.0)
                logp = jnp.where(finished.reshape(B, K)[..., None], pad_only[None, None], logp)
                cand = scores[..., None] + logp  # (B,K,V)
                new_scores, flat_idx = jax.lax.top_k(cand.reshape(B, K * V), K)
                parent = flat_idx // V  # (B,K) beam each winner extends
                token = (flat_idx % V).astype(jnp.int32)

                gidx = (jnp.arange(B)[:, None] * K + parent).reshape(-1)
                new_cache = beam_select(out["cache"], gidx, B * K)
                finished = jnp.take_along_axis(finished.reshape(B, K), parent, axis=1)
                lengths = jnp.take_along_axis(lengths, parent, axis=1)
                history = jnp.take_along_axis(history, parent[..., None], axis=1)
                pos = jnp.take_along_axis(pos.reshape(B, K), parent, axis=1).reshape(-1)

                newly = finished | (token == eos)
                # Unfinished beams append their token (including the eos
                # itself) at index `lengths`; finished beams write nothing.
                lengths = lengths + (~finished).astype(jnp.int32)
                idx = jnp.minimum(lengths - 1, max_new_tokens - 1)
                history = jnp.where(
                    (~finished)[..., None]
                    & (jnp.arange(max_new_tokens)[None, None] == idx[..., None]),
                    token[..., None],
                    history,
                )
                next_tok = jnp.where(newly, pad_token_id, token).reshape(B * K)
                pos = pos + 1
                # Bank beams that finished THIS step (transformers'
                # BeamHypotheses role): a banked hypothesis can never be
                # evicted from the running top-k and lost.
                just = newly & ~finished
                cand_norm = jnp.where(just, norm_scores(new_scores, lengths), -jnp.inf)
                step_best = jnp.argmax(cand_norm, axis=1)
                step_score = jnp.take_along_axis(cand_norm, step_best[:, None], axis=1)[:, 0]
                step_hist = jnp.take_along_axis(
                    history, step_best[:, None, None], axis=1
                )[:, 0]
                better = step_score > bank_score
                bank_score = jnp.where(better, step_score, bank_score)
                bank_hist = jnp.where(better[:, None], step_hist, bank_hist)
                return (new_cache, next_tok, new_scores, newly, lengths, history, pos,
                        bank_score, bank_hist), None

            carry = (cache, tok, scores0, finished0, lengths, history, pos,
                     bank_score, bank_hist)
            (cache, tok, scores, finished, lengths, history, pos,
             bank_score, bank_hist), _ = jax.lax.scan(
                step, carry, None, length=max_new_tokens - 1
            )
            # Final selection: best banked (finished) hypothesis vs the best
            # still-running beam, both under the full-length penalty.
            running = jnp.where(finished, -jnp.inf, norm_scores(scores, lengths))
            run_best = jnp.argmax(running, axis=1)
            run_score = jnp.take_along_axis(running, run_best[:, None], axis=1)[:, 0]
            run_hist = jnp.take_along_axis(history, run_best[:, None, None], axis=1)[:, 0]
            # If nothing is running (all finished) run_score is -inf → bank wins;
            # if nothing ever finished the bank is -inf → running wins.
            pick_bank = bank_score >= run_score
            return jnp.where(pick_bank[:, None], bank_hist, run_hist)

        cache_store[key] = jax.jit(run)
    new_tokens = cache_store[key](params, input_ids, mask)
    if include_prompt:
        return jnp.concatenate([input_ids, new_tokens], axis=1)
    return new_tokens


def _unwrap(model):
    """(module, params) from a Module, PreparedModel, or raw (module, params)."""
    handle = getattr(model, "handle", None)
    if handle is not None:  # PreparedModel
        return handle.module, handle.params
    return model, getattr(model, "params", None)


def generate(
    model,
    input_ids,
    *,
    max_new_tokens: int,
    params=None,
    attention_mask=None,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng=None,
    eos_token_id: int | None = None,
    pad_token_id: int = 0,
    cache_dtype=jnp.bfloat16,
    include_prompt: bool = True,
    num_beams: int = 1,
    length_penalty: float = 1.0,
):
    """Generate ``max_new_tokens`` continuations for a batch of prompts.

    ``model`` may be an ``accelerate_tpu.Module`` (with ``init_cache``), a
    ``PreparedModel`` from ``Accelerator.prepare``, or a ``StreamedScanModel``
    from offloaded ``dispatch_model``. Prompts are right-padded; pass
    ``attention_mask`` (1 = real) for ragged batches.

    Returns int32 ids of shape (B, prompt_len + max_new_tokens) when
    ``include_prompt`` else (B, max_new_tokens). Encoder-decoder models (those
    with an ``encode`` method, e.g. T5) always return (B, max_new_tokens): the
    prompt is the encoder input and the decoder stream starts fresh from
    ``decoder_start_token_id``, so there is no prompt to include.
    """
    from .big_modeling import StreamedScanModel

    if num_beams > 1:
        if temperature and temperature > 0.0:
            raise ValueError("beam search is greedy; use temperature<=0 (or num_beams=1)")
        if isinstance(model, StreamedScanModel) or hasattr(_unwrap(model)[0], "encode"):
            raise ValueError("beam search supports decoder-only cached models")
        return beam_search(
            model, input_ids, num_beams=num_beams, max_new_tokens=max_new_tokens,
            params=params, attention_mask=attention_mask,
            length_penalty=length_penalty, eos_token_id=eos_token_id,
            pad_token_id=pad_token_id, cache_dtype=cache_dtype,
            include_prompt=include_prompt,
        )

    input_ids = jnp.asarray(input_ids, jnp.int32)
    B, S = input_ids.shape
    if attention_mask is not None:
        attention_mask = jnp.asarray(attention_mask, jnp.int32)
    if rng is None:
        rng = jax.random.key(0)
    eos = -1 if eos_token_id is None else eos_token_id

    if isinstance(model, StreamedScanModel):
        module, mparams = model, None
    else:
        module, mparams = _unwrap(model)
    if hasattr(module, "encode"):
        # Encoder-decoder (T5-style): the "prompt" is the encoder input; decoding
        # starts fresh from decoder_start_token_id, so the return is always
        # (B, max_new_tokens) — see the docstring.
        if params is None:
            params = mparams
        if params is None:
            raise ValueError("Model has no params; pass params= or init the model first.")
        fn = _compiled_generate_encdec(module, max_new_tokens, temperature, top_k,
                                       top_p, eos, pad_token_id, cache_dtype)
        # None passes through jit as an empty pytree; encode() applies the
        # model's own pad-mask default, keeping one implementation.
        return fn(params, input_ids, attention_mask, rng)

    if isinstance(model, StreamedScanModel):
        new_tokens = _generate_streamed(
            model, input_ids, attention_mask, max_new_tokens,
            temperature, top_k, top_p, rng, eos, pad_token_id, cache_dtype,
        )
    else:
        module, mparams = _unwrap(model)
        if params is None:
            params = mparams
        if params is None:
            raise ValueError("Model has no params; pass params= or init the model first.")
        fn = _compiled_generate(module, max_new_tokens, temperature, top_k, top_p,
                                eos, pad_token_id, cache_dtype)
        mask_arg = (
            attention_mask if attention_mask is not None else jnp.ones((B, S), jnp.int32)
        )
        new_tokens = fn(params, input_ids, mask_arg, rng)
    if include_prompt:
        return jnp.concatenate([input_ids, new_tokens], axis=1)
    return new_tokens


def _scan_decode(first_out, step_apply, rng, max_new_tokens, temperature, top_k,
                 top_p, eos, pad_token_id, positions0=None):
    """Shared sample + finished-mask + lax.scan loop for both decode paths.

    ``first_out`` is the prefill's ModelOutput; ``step_apply(tok, cache, pos)``
    runs one cached decode step (``pos`` (B,) = each row's next token
    position, threaded through the carry; encoder-decoder ignores it)."""
    B = first_out["logits"].shape[0]
    if positions0 is None:
        positions0 = jnp.zeros((B,), jnp.int32)
    rng0, rng_loop = jax.random.split(rng)
    tok = sample_logits(first_out["logits"][:, -1], rng0, temperature, top_k, top_p)
    finished = tok == eos
    tok = jnp.where(finished, pad_token_id, tok)

    def step(carry, _):
        cache, tok, pos, finished, rng = carry
        rng, sub = jax.random.split(rng)
        out = step_apply(tok, cache, pos)
        nxt = sample_logits(out["logits"][:, -1], sub, temperature, top_k, top_p)
        newly = finished | (nxt == eos)
        nxt = jnp.where(newly, pad_token_id, nxt)
        return (out["cache"], nxt, pos + 1, newly, rng), nxt

    (_, _, _, _, _), rest = jax.lax.scan(
        step, (first_out["cache"], tok, positions0, finished, rng_loop), None,
        length=max_new_tokens - 1,
    )
    return jnp.concatenate([tok[:, None], rest.T], axis=1)


def _compiled_generate(module, max_new_tokens, temperature, top_k, top_p,
                       eos, pad_token_id, cache_dtype):
    """Prefill + scan-decode as one jitted function, cached per module so
    repeated calls with the same shapes reuse the compiled program."""
    cache_store = module.__dict__.setdefault("_generate_fns", {})
    key = (max_new_tokens, temperature, top_k, top_p, eos, pad_token_id, str(cache_dtype))
    if key in cache_store:
        return cache_store[key]

    def run(params, input_ids, attention_mask, rng):
        B, S = input_ids.shape
        total = S + max_new_tokens
        cache = module.init_cache(B, total, dtype=cache_dtype)

        input_ids, attention_mask = left_align(input_ids, attention_mask)
        # Token positions from the mask (not cache slots): exact for GPT-2's
        # learned wpe on ragged batches; a no-op difference under RoPE.
        real_len = jnp.sum(attention_mask, axis=-1).astype(jnp.int32)
        out = module.apply(params, input_ids=input_ids, attention_mask=attention_mask,
                           cache=cache, positions=mask_positions(attention_mask))
        step_apply = lambda tok, cache, pos: module.apply(
            params, input_ids=tok[:, None], cache=cache, positions=pos[:, None]
        )
        return _scan_decode(out, step_apply, rng, max_new_tokens, temperature,
                            top_k, top_p, eos, pad_token_id, positions0=real_len)

    fn = jax.jit(run)
    cache_store[key] = fn
    return fn


def _compiled_generate_encdec(module, max_new_tokens, temperature, top_k, top_p,
                              eos, pad_token_id, cache_dtype):
    """Encoder once + cross-KV precompute + scan-decode, one jitted program
    (cached per module/shape like the decoder-only path)."""
    cache_store = module.__dict__.setdefault("_generate_fns", {})
    key = ("encdec", max_new_tokens, temperature, top_k, top_p, eos, pad_token_id,
           str(cache_dtype))
    if key in cache_store:
        return cache_store[key]

    def run(params, input_ids, attention_mask, rng):
        B = input_ids.shape[0]
        enc_out, enc_mask = module.encode(params, input_ids, attention_mask)
        cross_kv = module.precompute_cross_kv(params, enc_out)
        cache = module.init_cache(B, max_new_tokens, dtype=cache_dtype)

        start = jnp.full((B, 1), module.config.decoder_start_token_id, jnp.int32)
        out = module.decode(params, start, cache, enc_out, enc_mask, cross_kv=cross_kv)
        step_apply = lambda tok, cache, pos: module.decode(
            params, tok[:, None], cache, enc_out, enc_mask, cross_kv=cross_kv
        )
        return _scan_decode(out, step_apply, rng, max_new_tokens, temperature,
                            top_k, top_p, eos, pad_token_id)

    fn = jax.jit(run)
    cache_store[key] = fn
    return fn


def _generate_streamed(model, input_ids, attention_mask, max_new_tokens,
                       temperature, top_k, top_p, rng, eos, pad_token_id, cache_dtype):
    """Per-token Python loop for offloaded models: every forward streams layer
    weights host→HBM just-in-time (the model never fully resides on chip)."""
    B, S = input_ids.shape
    total = S + max_new_tokens
    cache = model.init_cache(B, total, dtype=cache_dtype)
    mask = attention_mask if attention_mask is not None else jnp.ones((B, S), jnp.int32)

    input_ids, mask = left_align(input_ids, mask)
    next_pos = jnp.sum(mask, axis=-1).astype(jnp.int32)
    out = model(input_ids=input_ids, attention_mask=mask, cache=cache,
                positions=mask_positions(mask))
    last_logits = out["logits"][:, -1]
    rng, sub = jax.random.split(rng)
    tok = sample_logits(last_logits, sub, temperature, top_k, top_p)
    finished = tok == eos
    tok = jnp.where(finished, pad_token_id, tok)
    cache = out["cache"]

    tokens = [tok]
    for _ in range(max_new_tokens - 1):
        rng, sub = jax.random.split(rng)
        out = model(input_ids=tok[:, None], cache=cache, positions=next_pos[:, None])
        next_pos = next_pos + 1
        cache = out["cache"]
        nxt = sample_logits(out["logits"][:, -1], sub, temperature, top_k, top_p)
        newly = finished | (nxt == eos)
        nxt = jnp.where(finished | (nxt == eos), pad_token_id, nxt)
        finished = newly
        tokens.append(nxt)
        tok = nxt
    return jnp.stack(tokens, axis=1)
