"""Data layer: cross-process sharded loading + device-mesh feed.

Reference parity: ``src/accelerate/data_loader.py`` (1,435 LoC). The sharding
*semantics* are ported 1:1 (they are pure index logic, SURVEY.md §2.2):

- ``SeedableRandomSampler``  (reference :72-107) — per-epoch reseeded shuffle
- ``BatchSamplerShard``      (:109-263) — split-within-batch vs stride-across-
  batches, ``even_batches`` wraparound duplication
- ``IterableDatasetShard``   (:265-362) — chunk ``batch_size*n`` items, emit this
  process's slice, pad the final chunk from the stream's first items
- ``DataLoaderShard``        (:499-649) — RNG sync at epoch start, prefetch-one-
  ahead end-of-iteration flagging, device placement
- ``DataLoaderDispatcher``   (:702-973) — process 0 reads, others receive
- ``skip_first_batches``     (:1296-1416) — mid-epoch resume

What changes TPU-side is the *feed*: the reference moves each rank's batch to its
GPU (``send_to_device``); here every step consumes one **global** ``jax.Array``
sharded over the mesh's data axes — built with ``device_put`` single-host or
``jax.make_array_from_process_local_data`` on a pod, so the global batch never
materializes on any single host. Uneven final batches are padded by wraparound
(the reference's ``even_batches`` trick) because XLA wants static shapes; the true
tail length is recorded in ``remainder`` and ``gather_for_metrics`` trims it —
this is the static-shape answer to DDP's ``join_uneven_inputs``
(``accelerator.py:1167``).
"""

from __future__ import annotations

import logging
import math
from typing import Any, Callable, Iterable

import numpy as np

import jax

from .parallel.sharding import make_global_batch
from .state import AcceleratorState, GradientState, PartialState
from .utils.dataclasses import RNGType
from .utils.operations import broadcast, broadcast_object_list, recursively_apply
from .utils.transfer import host_view
from .utils.random import synchronize_rng_states

logger = logging.getLogger(__name__)

_PYTORCH_DATALOADER_KWARGS = (
    "num_workers collate_fn pin_memory timeout worker_init_fn multiprocessing_context "
    "generator prefetch_factor persistent_workers pin_memory_device"
).split()


_BATCHES_COUNTER = None  # telemetry.metrics.cached_handles accessor


def _batches_counter():
    """The telemetry batch counter — the yield loops pay only the .inc()
    (cached_handles hoists the registry lookup, keyed on reset generation)."""
    global _BATCHES_COUNTER
    if _BATCHES_COUNTER is None:
        from .telemetry.metrics import cached_handles

        _BATCHES_COUNTER = cached_handles(lambda registry: registry.counter(
            "accelerate_dataloader_batches_total",
            "Batches yielded by prepared data loaders",
        ))
    return _BATCHES_COUNTER()


def _is_torch_loader(obj) -> bool:
    try:
        import torch.utils.data as tud

        return isinstance(obj, tud.DataLoader)
    except ImportError:
        return False


def _find_order_generator(loader):
    """Find the torch.Generator that drives the loader's sample order, walking
    the sampler/batch_sampler chain (a prepared torch loader nests the real
    RandomSampler inside BatchSamplerShard → torch BatchSampler, and torch
    sets the outer ``loader.sampler`` to a SequentialSampler)."""
    seen, frontier = set(), [loader]
    for _ in range(4):  # loader → shard → batch_sampler → sampler is depth 3
        nxt = []
        for obj in frontier:
            if id(obj) in seen or obj is None:
                continue
            seen.add(id(obj))
            gen = getattr(obj, "generator", None)
            if gen is not None and hasattr(gen, "get_state"):
                return gen
            nxt.extend([getattr(obj, "sampler", None), getattr(obj, "batch_sampler", None)])
        frontier = nxt
    return None


def _to_numpy(batch):
    """Convert torch tensors / lists in a fetched batch to numpy leaves."""

    def _one(x):
        if hasattr(x, "detach") and hasattr(x, "cpu"):  # torch tensor
            return x.detach().cpu().numpy()
        return x

    return recursively_apply(_one, batch, test_type=lambda x: hasattr(x, "detach") or hasattr(x, "__array__"))


class SeedableRandomSampler:
    """Deterministic cross-process shuffle, reseeded ``seed + epoch`` each epoch
    (reference ``data_loader.py:72-107``). Yields indices of ``data_source``."""

    def __init__(self, data_source, seed: int | None = None, epoch: int = 0, generator=None):
        self.data_source = data_source
        self.seed = seed if seed is not None else 42
        self.epoch = epoch
        self.generator = generator

    def __len__(self):
        return len(self.data_source)

    def __iter__(self):
        rng = np.random.default_rng(self.seed + self.epoch)
        yield from rng.permutation(len(self.data_source)).tolist()
        self.set_epoch(self.epoch + 1)

    def set_epoch(self, epoch: int):
        self.epoch = epoch


class BatchSamplerShard:
    """Shard an underlying batch sampler across ``num_processes`` (reference :109-263).

    split_batches=True: each global batch is sliced within; requires batch_size
    divisible by num_processes. split_batches=False: batches are dealt out
    round-robin (process p takes batches p, p+n, ...). ``even_batches`` completes
    the tail by wrapping around to the epoch's first samples/batches so every
    process sees the same number of batches.
    """

    def __init__(
        self,
        batch_sampler,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        if split_batches and getattr(batch_sampler, "batch_size", None) is not None:
            if batch_sampler.batch_size % num_processes != 0:
                raise ValueError(
                    f"batch_size {batch_sampler.batch_size} must be divisible by "
                    f"num_processes {num_processes} when split_batches=True"
                )
        self.batch_sampler = batch_sampler
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)

    def reassign(self, num_processes: int, process_index: int):
        """Elastic world-size change (resilience/elastic.py): deal the same
        underlying sampler out across a different process count. The wrapped
        sampler — and therefore the shuffle-RNG stream ordering the epoch —
        is untouched; only which slice this process draws changes."""
        if self.split_batches and self.batch_size is not None and self.batch_size % num_processes != 0:
            raise ValueError(
                f"batch_size {self.batch_size} must be divisible by the new "
                f"num_processes {num_processes} when split_batches=True"
            )
        self.num_processes = int(num_processes)
        self.process_index = int(process_index)

    @property
    def total_length(self):
        return len(self.batch_sampler)

    def __len__(self):
        if self.split_batches:
            return len(self.batch_sampler)
        length = len(self.batch_sampler) // self.num_processes
        rem = len(self.batch_sampler) % self.num_processes
        if rem == 0:
            return length
        if self.even_batches:
            return length + 1
        return length + 1 if self.process_index < rem else length

    def __iter__(self):
        return self._iter_with_split() if self.split_batches else self._iter_with_no_split()

    def _iter_with_split(self):
        initial_data = []
        full_size = self.batch_size
        for idx, batch in enumerate(self.batch_sampler):
            if idx == 0:
                initial_data = list(batch)
                if full_size is None:
                    full_size = len(batch)
            if len(batch) == full_size:
                batch_length = len(batch) // self.num_processes
                start = batch_length * self.process_index
                yield batch[start : start + batch_length]
            else:
                # Final partial batch.
                if not self.even_batches:
                    # Ragged split: proportional slice of what's there.
                    sizes = [len(batch) // self.num_processes] * self.num_processes
                    for i in range(len(batch) % self.num_processes):
                        sizes[i] += 1
                    start = sum(sizes[: self.process_index])
                    shard = batch[start : start + sizes[self.process_index]]
                    if len(shard):
                        yield shard
                else:
                    # Complete from the epoch's first samples, then slice evenly.
                    while len(batch) < full_size:
                        batch = list(batch) + initial_data[: full_size - len(batch)]
                    per = full_size // self.num_processes
                    start = per * self.process_index
                    yield batch[start : start + per]

    def _iter_with_no_split(self):
        initial_batches = []
        group = []
        n_yielded = 0
        for idx, batch in enumerate(self.batch_sampler):
            if idx < self.num_processes:
                initial_batches.append(list(batch))
            group.append(batch)
            if len(group) == self.num_processes:
                yield group[self.process_index]
                n_yielded += 1
                group = []
        if len(group) > 0:
            if not self.even_batches:
                if self.process_index < len(group):
                    yield group[self.process_index]
            else:
                # Wrap around: complete the group from the epoch's first batches.
                # The final real batch may be short; when it is *this* process's,
                # also complete it from the first batch's samples (reference
                # behavior so all shards stay rectangular).
                fill_idx = 0
                while len(group) < self.num_processes:
                    group.append(initial_batches[fill_idx % max(len(initial_batches), 1)])
                    fill_idx += 1
                batch = list(group[self.process_index])
                if self.batch_size is not None and len(batch) < self.batch_size and not self.drop_last:
                    fill = initial_batches[0] if initial_batches else batch
                    while len(batch) < self.batch_size and len(fill):
                        batch += fill[: self.batch_size - len(batch)]
                yield batch

    def set_epoch(self, epoch):
        if hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)
        sampler = getattr(self.batch_sampler, "sampler", None)
        if sampler is not None and hasattr(sampler, "set_epoch"):
            sampler.set_epoch(epoch)


class IterableDatasetShard:
    """Shard an iterable dataset (reference :265-362): buffer
    ``batch_size * num_processes`` items (or ``batch_size`` when split_batches),
    emit this process's slice; final short buffer is padded from the first items.
    """

    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        drop_last: bool = False,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches

    def reassign(self, num_processes: int, process_index: int):
        """Elastic world-size change: re-slice the stream across a different
        process count (see ``BatchSamplerShard.reassign``)."""
        if self.split_batches and self.batch_size % num_processes != 0:
            # __iter__ floors per_process = batch_size // num_processes: a
            # non-dividing count would silently drop the remainder of every
            # buffer — refuse like the map-style shard does.
            raise ValueError(
                f"batch_size {self.batch_size} must be divisible by the new "
                f"num_processes {num_processes} when split_batches=True"
            )
        self.num_processes = int(num_processes)
        self.process_index = int(process_index)

    def set_epoch(self, epoch):
        self.epoch = epoch
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __len__(self):
        n = len(self.dataset)
        real = self.batch_size if self.split_batches else self.batch_size * self.num_processes
        per = real // self.num_processes
        if self.drop_last:
            return (n // real) * per
        return math.ceil(n / real) * per

    def __iter__(self):
        real_batch_size = self.batch_size if self.split_batches else self.batch_size * self.num_processes
        per_process = real_batch_size // self.num_processes
        start = per_process * self.process_index
        first_batch = None
        buffer = []
        for item in self.dataset:
            buffer.append(item)
            if len(buffer) == real_batch_size:
                yield from buffer[start : start + per_process]
                if first_batch is None:
                    first_batch = buffer.copy()
                buffer = []
        if len(buffer) > 0 and not self.drop_last:
            if first_batch is None:
                first_batch = buffer.copy()
            while len(buffer) < real_batch_size:
                buffer += first_batch[: real_batch_size - len(buffer)]
            yield from buffer[start : start + per_process]


class DataLoaderStateMixin:
    """end-of-iteration flags shared with ``GradientState`` (reference :365-404)."""

    def __init_subclass__(cls, **kwargs):
        cls.end_of_dataloader = False
        cls.remainder = -1

    def reset(self):
        self.end_of_dataloader = False
        self.remainder = -1

    def begin(self):
        self.reset()
        if self.batch_size is not None:
            # Only meaningful when the batch size is known (torch-loader path);
            # generic iterables discover their tail while iterating.
            with suppress_exception():
                length = getattr(self.dataset, "total_dataset_length", len(self.dataset))
                self.remainder = length % self.total_batch_size
        self.gradient_state._add_dataloader(self)

    def end(self):
        self.gradient_state._remove_dataloader(self)


class suppress_exception:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return True


def _reassign_shard_objects(root, num_processes: int, process_index: int) -> int:
    """Walk a wrapped loader chain (loader → batch_sampler/sampler/dataset)
    and ``reassign`` every shard wrapper found; returns how many were
    repointed. Shared by the prepared loaders' ``reassign_shards``."""
    seen: set = set()
    stack = [root]
    updated = 0
    while stack:
        obj = stack.pop()
        if obj is None or id(obj) in seen:
            continue
        seen.add(id(obj))
        if isinstance(obj, (BatchSamplerShard, IterableDatasetShard)):
            obj.reassign(num_processes, process_index)
            updated += 1
        for attr in ("base_loader", "batch_sampler", "sampler", "dataset"):
            nxt = getattr(obj, attr, None)
            if nxt is not None and not isinstance(nxt, (int, float, str, bytes)):
                stack.append(nxt)
    return updated


class DataLoaderShard(DataLoaderStateMixin):
    """Per-process loader feeding **global sharded arrays** (reference :499-649).

    Wraps any iterable of batches (a torch DataLoader rebuilt with a sharded
    sampler, or a plain python iterable). Each yielded batch is the *global*
    logical batch as a mesh-sharded ``jax.Array`` pytree.
    """

    def __init__(
        self,
        base_loader,
        device=None,
        rng_types=None,
        synchronized_generator=None,
        skip_batches: int = 0,
        use_stateful_dataloader: bool = False,
        _drop_last: bool = False,
        _non_blocking: bool = False,
        slice_fn=None,
        put_on_device: bool = True,
        **kwargs,
    ):
        self.base_loader = base_loader
        self.device = device
        self.rng_types = rng_types
        self.synchronized_generator = synchronized_generator
        self.skip_batches = skip_batches
        self.gradient_state = GradientState()
        self.put_on_device = put_on_device
        self._drop_last = _drop_last
        self.iteration = 0
        self._num_batches_fetched = 0
        self._resume_batches = 0
        # True sampler-state resume (VERDICT r2 #7): the RNG snapshot that
        # generated the current epoch's shuffle order, a snapshot pending
        # restoration from load_state_dict, and a pending base-loader state
        # for stateful bases (torchdata StatefulDataLoader protocol).
        self._epoch_rng = None
        self._pending_rng = None
        self._pending_base_state = None
        self._base_state_live = None
        try:
            self.state = AcceleratorState()
        except Exception:
            self.state = PartialState()

    # ------------------------------------------------- sampler-state capture
    def _capture_sampler_rng(self):
        """Snapshot the RNG that will generate THIS epoch's sample order:
        the torch sampler's dedicated generator when it has one, else the
        torch global stream (RandomSampler's fallback source). Captured
        *before* ``iter()`` consumes it, so restoring the snapshot and
        re-iterating replays the interrupted epoch's exact order — no
        seedable sampler required."""
        try:
            import torch
        except ImportError:
            return None
        gen = _find_order_generator(self.base_loader)
        if gen is not None and hasattr(gen, "get_state"):
            return ("generator", gen.get_state().numpy().tobytes())
        if _is_torch_loader(self.base_loader):
            return ("torch_global", torch.random.get_rng_state().numpy().tobytes())
        return None

    def _restore_sampler_rng(self, snapshot):
        if snapshot is None:
            return
        import torch

        kind, raw = snapshot
        state = torch.from_numpy(np.frombuffer(raw, dtype=np.uint8).copy())
        if kind == "generator":
            gen = _find_order_generator(self.base_loader)
            if gen is not None:
                gen.set_state(state)
        else:
            torch.random.set_rng_state(state)

    # -------------------------------------------------------------- delegation
    @property
    def dataset(self):
        return getattr(self.base_loader, "dataset", self.base_loader)

    @property
    def batch_sampler(self):
        return getattr(self.base_loader, "batch_sampler", None)

    @property
    def batch_size(self):
        bs = getattr(self.base_loader, "batch_size", None)
        if bs is None and self.batch_sampler is not None:
            bs = getattr(self.batch_sampler, "batch_size", None)
        return bs

    @property
    def total_batch_size(self):
        """Global batch size across all processes (reference :620-633)."""
        sampler = self.batch_sampler
        if isinstance(sampler, BatchSamplerShard):
            return (
                sampler.batch_size
                if sampler.split_batches
                else (sampler.batch_size or 1) * sampler.num_processes
            )
        n = jax.process_count()
        return (self.batch_size or 1) * n

    @property
    def total_dataset_length(self):
        return getattr(self.dataset, "total_dataset_length", None) or len(self.dataset)

    def set_epoch(self, epoch: int):
        if self.iteration != epoch:
            # A restored mid-epoch position belongs to epoch `iteration`;
            # switching to a different epoch invalidates ALL of it — the skip
            # counter, the shuffle-RNG snapshot, and any pending base-loader
            # state (otherwise they'd silently reposition the wrong epoch).
            self._resume_batches = 0
            self._pending_rng = None
            self._pending_base_state = None
            self.iteration = epoch
        if hasattr(self.base_loader, "set_epoch"):
            self.base_loader.set_epoch(epoch)
        if self.batch_sampler is not None and hasattr(self.batch_sampler, "set_epoch"):
            self.batch_sampler.set_epoch(epoch)
        sampler = getattr(self.base_loader, "sampler", None)
        if sampler is not None and hasattr(sampler, "set_epoch"):
            sampler.set_epoch(epoch)
        ds = self.dataset
        if hasattr(ds, "set_epoch"):
            ds.set_epoch(epoch)

    def reassign_shards(self, num_processes: int, process_index: int):
        """Elastic world-size change (resilience/elastic.py): point every
        shard wrapper under this loader at the new world. The sampler-RNG
        contract stays intact — the shuffle stream (and its
        ``state_dict``/``load_state_dict`` snapshots) is untouched; only
        which slice this process draws changes."""
        _reassign_shard_objects(self.base_loader, num_processes, process_index)

    def __len__(self):
        n = len(self.base_loader)
        return max(n - self.skip_batches, 0)

    # ------------------------------------------------------------------- feed
    def _device_feed(self, np_batch, pad_info):
        """host batch (this process's shard) → global sharded jax.Array pytree."""
        if not self.put_on_device:
            return np_batch
        mesh = self.state.mesh
        return make_global_batch(np_batch, mesh)

    def _pad_batch_to(self, np_batch, target: int):
        """Pad a short final batch to ``target`` rows by wrapping its own rows."""

        def _one(x):
            x = host_view(x)
            if x.ndim == 0 or x.shape[0] >= target:
                return x
            reps = math.ceil((target - x.shape[0]) / max(x.shape[0], 1))
            fill = np.concatenate([x] * reps, axis=0)[: target - x.shape[0]]
            return np.concatenate([x, fill], axis=0)

        return recursively_apply(_one, np_batch)

    def __iter__(self):
        self.begin()
        if self.rng_types is not None:
            synchronize_rng_states(self.rng_types, self.synchronized_generator)
        self.set_epoch(self.iteration)
        resume = self._resume_batches
        self._resume_batches = 0
        self._num_batches_fetched = resume
        if self._pending_base_state is not None:
            # Stateful base (torchdata StatefulDataLoader protocol): the base
            # restores its own sampler/iterator position — no skip replay.
            self.base_loader.load_state_dict(self._pending_base_state)
            self._pending_base_state = None
            resume = 0
        if self._pending_rng is not None:
            # Replay the interrupted epoch's exact shuffle order by restoring
            # the RNG snapshot taken before that epoch's iterator was built.
            self._restore_sampler_rng(self._pending_rng)
            self._pending_rng = None
        self._epoch_rng = self._capture_sampler_rng()
        effective_skip = self.skip_batches + resume
        base_is_stateful = hasattr(self.base_loader, "state_dict") and callable(
            getattr(self.base_loader, "state_dict")
        )
        # Indexable bases skip by *indexing*, not by loading-and-discarding —
        # O(1) positioning instead of the O(epoch) counter replay. Stateful
        # bases are excluded: the index path bypasses their own iterator, so
        # their reported state would go stale.
        if (
            effective_skip > 0
            and not base_is_stateful
            and hasattr(self.base_loader, "__getitem__")
            and hasattr(self.base_loader, "__len__")
            and not _is_torch_loader(self.base_loader)
        ):
            n = len(self.base_loader)
            iterator = (self.base_loader[i] for i in range(min(effective_skip, n), n))
            effective_skip = 0
        else:
            iterator = iter(self.base_loader)
        skipped = 0
        # Prefetch-one-ahead so the flag flips *on* the final batch, not after it
        # (reference :563-587) — grad accumulation must sync on the last batch.
        current = None
        have_current = False
        batches_yielded = 0
        expected_local = None
        while True:
            if base_is_stateful:
                # Snapshot BEFORE the fetch: with the one-ahead prefetch, the
                # state at any yield point must say "next fetch returns the
                # buffered batch" — a post-fetch snapshot would drop it.
                try:
                    self._base_state_live = self.base_loader.state_dict()
                except Exception:
                    self._base_state_live = None
            try:
                nxt = _to_numpy(next(iterator))
            except StopIteration:
                nxt = None
                if not have_current:
                    break
            if have_current:
                if skipped < effective_skip:
                    skipped += 1
                else:
                    is_last = nxt is None
                    if is_last:
                        self.end_of_dataloader = True
                    batch = current
                    if expected_local is None:
                        leaves = [l for l in jax.tree_util.tree_leaves(batch) if hasattr(l, "shape") and np.ndim(l) > 0]
                        if leaves:
                            expected_local = leaves[0].shape[0]
                    if is_last and expected_local is not None and not self._drop_last:
                        # Record the true tail, pad to static shape.
                        leaves = [l for l in jax.tree_util.tree_leaves(batch) if hasattr(l, "shape") and np.ndim(l) > 0]
                        actual = leaves[0].shape[0] if leaves else expected_local
                        if actual < expected_local:
                            if self.remainder < 0:
                                # Global real tail = this process's tail × feeders.
                                self.remainder = actual * jax.process_count()
                            batch = self._pad_batch_to(batch, expected_local)
                    self._num_batches_fetched += 1
                    _batches_counter().inc()
                    yield self._device_feed(batch, None)
                    batches_yielded += 1
            if nxt is None:
                break
            current = nxt
            have_current = True
        self.iteration += 1
        # Natural exhaustion: the epoch is over, position resets (torchdata
        # StatefulDataLoader semantics — a checkpoint taken *between* epochs
        # resumes at the top of the next epoch, not mid-stream).
        self._num_batches_fetched = 0
        self._base_state_live = None
        # A between-epoch checkpoint must NOT replay the finished epoch's
        # shuffle into the next epoch — drop the consumed snapshot.
        self._epoch_rng = None
        self.end()

    # -------------------------------------------------- resume (stateful) API
    def state_dict(self):
        """Mid-epoch resume state (reference StatefulDataLoader passthrough
        ``data_loader.py:444-497``). Three layers, best available wins at load:

        - ``base_state``: the wrapped loader's own ``state_dict()`` when it is
          stateful (torchdata StatefulDataLoader) — true pass-through, the
          base repositions itself without any skip replay;
        - ``sampler_rng``: the RNG snapshot that generated the current epoch's
          shuffle order, so plain torch ``RandomSampler`` (no seedable
          sampler) replays the interrupted order exactly on resume;
        - position counters, replayed by skipping (indexable bases skip by
          index, O(1)).

        A just-restored, not-yet-iterated loader reports its pending state so
        load→save round-trips are idempotent."""
        sd = {
            "num_batches_fetched": max(self._num_batches_fetched, self._resume_batches),
            "iteration": self.iteration,
        }
        # A pending (loaded, not yet consumed) snapshot is the authoritative
        # resume state; the live epoch snapshot only applies mid-iteration.
        rng = self._pending_rng if self._pending_rng is not None else self._epoch_rng
        if rng is not None:
            sd["sampler_rng"] = rng
        if self._pending_base_state is not None:
            sd["base_state"] = self._pending_base_state
        elif getattr(self, "_base_state_live", None) is not None:
            # The pre-fetch snapshot from the live iterator (accounts for the
            # one-ahead prefetch buffer; see __iter__).
            sd["base_state"] = self._base_state_live
        return sd

    def load_state_dict(self, sd):
        self._resume_batches = sd.get("num_batches_fetched", 0)
        self.iteration = sd.get("iteration", 0)
        self._pending_rng = sd.get("sampler_rng")
        self._epoch_rng = None  # any live-epoch snapshot is now stale
        self._base_state_live = None
        base_state = sd.get("base_state")
        if base_state is not None and hasattr(self.base_loader, "load_state_dict"):
            self._pending_base_state = base_state


class DataLoaderDispatcher(DataLoaderStateMixin):
    """Process 0 reads every batch; others receive their shard (reference :702-973).

    Used for iterable datasets that can't be sharded by index (e.g. streaming). On
    one host this degrades gracefully to DataLoaderShard behavior.
    """

    def __init__(self, base_loader, split_batches: bool = False, put_on_device: bool = True,
                 skip_batches: int = 0, _drop_last: bool = False, slice_fn=None, **kwargs):
        self.base_loader = base_loader
        self.split_batches = split_batches
        self.put_on_device = put_on_device
        self.skip_batches = skip_batches
        self._drop_last = _drop_last
        self.gradient_state = GradientState()
        self.iteration = 0
        self._num_batches_fetched = 0
        self._resume_batches = 0
        try:
            self.state = AcceleratorState()
        except Exception:
            self.state = PartialState()
        self.slice_fn = slice_fn

    @property
    def dataset(self):
        return getattr(self.base_loader, "dataset", self.base_loader)

    @property
    def batch_size(self):
        return getattr(self.base_loader, "batch_size", None)

    @property
    def total_batch_size(self):
        return (self.batch_size or 1) * (1 if self.split_batches else self.state.num_processes)

    @property
    def total_dataset_length(self):
        return len(self.dataset)

    def __len__(self):
        return max(len(self.base_loader) - self.skip_batches, 0)

    def set_epoch(self, epoch):
        if self.iteration != epoch:
            self._resume_batches = 0  # see DataLoaderShard.set_epoch
        self.iteration = epoch
        if hasattr(self.base_loader, "set_epoch"):
            self.base_loader.set_epoch(epoch)

    def reassign_shards(self, num_processes: int, process_index: int):
        """See ``DataLoaderShard.reassign_shards`` — the dispatcher's own
        slicing follows ``self.state`` live, but a wrapped shard sampler
        still needs repointing."""
        _reassign_shard_objects(self.base_loader, num_processes, process_index)

    def _fetch_and_scatter(self, iterator):
        """Process 0 fetches; batch is broadcast; each process keeps its slice
        (reference ``_fetch_batches`` :784-848)."""
        state = self.state
        if state.is_main_process:
            try:
                batch = _to_numpy(next(iterator))
                info = [True]
            except StopIteration:
                batch, info = None, [False]
        else:
            batch, info = None, [None]
        if state.num_processes > 1:
            broadcast_object_list(info, from_process=0)
        if not info[0]:
            return None
        if state.num_processes > 1:
            payload = [batch]
            broadcast_object_list(payload, from_process=0)
            batch = payload[0]
        return batch

    def __iter__(self):
        self.begin()
        iterator = iter(self.base_loader)
        state = self.state
        resume = self._resume_batches
        self._resume_batches = 0
        self._num_batches_fetched = resume
        effective_skip = self.skip_batches + resume
        skipped = 0
        prev = None
        have_prev = False
        while True:
            batch = self._fetch_and_scatter(iterator)
            if batch is None:
                if have_prev and skipped >= effective_skip:
                    self.end_of_dataloader = True
                    self._num_batches_fetched += 1
                    _batches_counter().inc()
                    yield self._emit(prev)
                break
            if have_prev:
                if skipped < effective_skip:
                    skipped += 1
                else:
                    self._num_batches_fetched += 1
                    _batches_counter().inc()
                    yield self._emit(prev)
            prev = batch
            have_prev = True
        self.iteration += 1
        self._num_batches_fetched = 0
        self.end()

    # -------------------------------------------------- resume (stateful) API
    def state_dict(self):
        return {
            "num_batches_fetched": max(self._num_batches_fetched, self._resume_batches),
            "iteration": self.iteration,
        }

    def load_state_dict(self, sd):
        self._resume_batches = sd.get("num_batches_fetched", 0)
        self.iteration = sd.get("iteration", 0)

    def _emit(self, global_np_batch):
        """Each process slices its rows, then the global array is assembled."""
        state = self.state
        n = state.num_processes
        if self.put_on_device:
            mesh = state.mesh

            def _slice(x):
                x = host_view(x)
                if n == 1:
                    return x
                per = x.shape[0] // n
                return x[state.process_index * per : (state.process_index + 1) * per]

            local = recursively_apply(_slice, global_np_batch) if n > 1 else global_np_batch
            return make_global_batch(local, mesh)
        return global_np_batch


class DeviceBatchPrefetcher:
    """Async host→device input feed — the train loop must never wait on an
    upload.

    A background thread pulls host batches from any iterable (typically a
    prepared :class:`DataLoaderShard`), ``device_put``s each one onto the
    mesh's data-axis sharding ``prefetch`` batches ahead of the consumer
    (single-host ``device_put`` and multi-host
    ``make_array_from_process_local_data`` global-batch forms, via
    ``parallel/sharding.py``), and — with ``window=K`` — stacks K consecutive
    batches into one K-leading-axis window buffer shaped for
    ``Accelerator.build_train_window`` (window axis replicated, batch axis
    sharded).

    Every upload is counted through :func:`~.utils.transfer.host_put`; when
    the *training* thread has to wait for a batch that is not staged yet the
    wait is recorded via :func:`~.utils.transfer.record_input_wait` as a
    blocking input transfer plus its wall-clock — so "the loop never blocks
    on input" is a measured property (``StepTimeline.summary()['transfers']``,
    ``bench.py`` ``detail.input_wait_s``), not an assertion. The FIRST batch
    of an iteration is pipeline fill (nothing could have been staged yet) and
    is excluded, the same way the timeline's first boundary is baseline-only.

    Resume contract: ``state_dict()``/``load_state_dict()`` delegate to the
    wrapped loader (sampler-RNG snapshot included) but report the CONSUMER
    position — whole windows handed to the train loop — not the producer's
    read-ahead, so a checkpoint taken at a window boundary resumes bit-exact:
    staged-but-unconsumed batches are re-read from the replayed epoch order.

    Note: a wrapped shard loader's own ``end_of_dataloader`` flag flips when
    the *producer* reaches the tail (up to ``prefetch×window`` batches early);
    windowed loops should drive accumulation boundaries off step counts, not
    the dataloader flag.
    """

    _SENTINEL = object()

    def __init__(self, loader, mesh=None, prefetch: int = 2, window: int = 1):
        if prefetch < 1:
            raise ValueError(f"prefetch must be >= 1, got {prefetch}")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.loader = loader
        self.prefetch = int(prefetch)
        self.window = int(window)
        self._mesh = mesh
        self._consumed = 0  # base batches handed to the train loop this epoch
        self._resume_consumed = 0
        # Epoch-identity snapshot (iteration + sampler RNG) taken at the
        # epoch's FIRST batch: the producer's read-ahead can exhaust the
        # wrapped loader — whose epilogue advances iteration and drops the
        # epoch RNG — while staged windows are still unconsumed, so the live
        # state_dict() near an epoch tail describes the NEXT epoch.
        self._epoch_identity = None

    @property
    def mesh(self):
        if self._mesh is not None:
            return self._mesh
        state = getattr(self.loader, "state", None)
        if state is not None:
            return state.mesh
        return PartialState().mesh

    def __len__(self):
        n = len(self.loader)
        return n // self.window if self.window > 1 else n

    # ------------------------------------------------------------------ feed
    def _stage(self, batches, mesh):
        """window host batches → ONE device-resident buffer (counted upload).
        Already-placed device leaves pass through (stacked on device for
        windows) without an h2d count — their upload happened elsewhere; in a
        MIXED batch only the host leaves are uploaded, so a device-resident
        leaf never round-trips through ``np.asarray`` (a blocking, uncounted
        device→host readback plus a redundant re-upload)."""
        if self.window == 1:
            batch = batches[0]
            placer = lambda b: make_global_batch(b, mesh)
        else:
            def _stack(*xs):
                # A device leaf in ANY slot routes through jnp.stack (mixed
                # host/device inputs accepted) — np.asarray on a jax.Array
                # would be a blocking, uncounted device→host readback.
                if any(isinstance(x, jax.Array) for x in xs):
                    import jax.numpy as jnp

                    return jnp.stack(xs, axis=0)
                return np.stack([host_view(x) for x in xs], axis=0)

            from .parallel.sharding import make_global_window_batch

            batch = jax.tree_util.tree_map(_stack, *batches)
            placer = lambda b: make_global_window_batch(b, mesh)

        leaves = [l for l in jax.tree_util.tree_leaves(batch) if hasattr(l, "shape")]
        if leaves and all(isinstance(l, jax.Array) for l in leaves):
            return batch
        from .utils.transfer import host_put as _put

        if leaves and any(isinstance(l, jax.Array) for l in leaves):
            return _put(batch, lambda b: jax.tree_util.tree_map(
                lambda l: l if isinstance(l, jax.Array) else placer(l), b))
        return _put(batch, placer)

    def __iter__(self):
        import queue
        import threading
        import time

        from .utils.transfer import record_input_wait

        resume = self._resume_consumed
        self._resume_consumed = 0
        self._consumed = resume
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        box = {"error": None}
        mesh = self.mesh  # resolved on the consumer thread (singletons)
        loader = self.loader

        def _offer(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            # The wrapped shard loader would otherwise device-feed on its own;
            # the prefetcher owns placement (counted, window-shaped), so its
            # put is suspended for the duration of this iteration.
            restore_put = None
            if getattr(loader, "put_on_device", None) is True:
                restore_put = True
                loader.put_on_device = False
            try:
                stack = []
                first = True
                for batch in loader:
                    if first:
                        # The shard's iterator has started: its state_dict now
                        # names THIS epoch (iteration, sampler RNG). Snapshot
                        # the identity before read-ahead can cross the epoch
                        # boundary and advance it under the consumer.
                        first = False
                        if hasattr(loader, "state_dict"):
                            try:
                                ident = dict(loader.state_dict())
                            except Exception:
                                ident = None
                            if ident is not None:
                                ident.pop("base_state", None)
                                ident.pop("num_batches_fetched", None)
                                self._epoch_identity = ident
                    if stop.is_set():
                        return
                    stack.append(batch)
                    if len(stack) < self.window:
                        continue
                    staged = self._stage(stack, mesh)
                    stack = []
                    if not _offer(staged):
                        return
                if stack:
                    logger.info(
                        "DeviceBatchPrefetcher: dropping %d tail batch(es) that "
                        "do not fill a window of %d", len(stack), self.window,
                    )
            except BaseException as exc:  # surfaced on the consumer thread
                box["error"] = exc
            finally:
                if restore_put:
                    loader.put_on_device = True
                _offer(self._SENTINEL)

        thread = threading.Thread(
            target=produce, name="accelerate-device-prefetch", daemon=True
        )

        # An ABANDONED iterator (consumer broke out and never exhausted or
        # closed it) leaves the producer alive into interpreter teardown,
        # where a daemon thread woken mid-XLA/queue C++ frames aborts the
        # process ("terminate called without an active exception"). Stop it
        # at atexit — before daemon threads are frozen — and let the
        # generator's own finally unregister on every normal path.
        import atexit

        def _shutdown():
            stop.set()
            thread.join(timeout=1.0)

        atexit.register(_shutdown)
        thread.start()
        delivered = False
        try:
            while True:
                waited = 0.0
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    t0 = time.perf_counter()
                    item = q.get()
                    waited = time.perf_counter() - t0
                if item is self._SENTINEL:
                    if box["error"] is not None:
                        raise box["error"]
                    # Natural exhaustion: the epoch is over, position resets
                    # (mirrors DataLoaderShard's between-epoch semantics) and
                    # the identity snapshot retires — a between-epoch
                    # checkpoint must name the NEXT epoch, not replay this one.
                    self._consumed = 0
                    self._epoch_identity = None
                    break
                if delivered and waited > 1e-3:
                    # Steady-state stall: the batch was not staged when the
                    # train loop asked — the blocking-input event the prefetch
                    # depth exists to prevent. Sub-millisecond waits are
                    # get_nowait-vs-get scheduler jitter (the producer enqueued
                    # between the two calls), not an input stall.
                    record_input_wait(waited)
                delivered = True
                self._consumed += self.window
                yield item
        finally:
            stop.set()
            try:
                atexit.unregister(_shutdown)
            except Exception:
                pass  # interpreter teardown: atexit module may be gone
            # Pre-bound: an abandoned generator finalized at interpreter
            # shutdown has lost the local `queue` module reference, and
            # `except queue.Empty` would itself raise.
            empty = queue.Empty
            try:
                while True:
                    q.get_nowait()
            except empty:
                pass
            thread.join(timeout=5.0)

    # -------------------------------------------------- resume (stateful) API
    def state_dict(self):
        """The wrapped loader's resume state with the position rewritten to
        the CONSUMER's (whole windows yielded), so staged-but-unconsumed
        read-ahead is replayed after a resume instead of lost."""
        sd = dict(self.loader.state_dict()) if hasattr(self.loader, "state_dict") else {}
        if self._epoch_identity is not None:
            # Mid-epoch for the CONSUMER: read-ahead may have crossed the
            # epoch boundary, advancing the live iteration and dropping the
            # epoch RNG — the snapshot taken at this epoch's first batch is
            # the consumer's truth.
            sd.update(self._epoch_identity)
        sd["num_batches_fetched"] = max(self._consumed, self._resume_consumed)
        # A stateful base's own snapshot was taken at the PRODUCER's read-ahead
        # position (up to prefetch×window batches past the consumer) and would
        # take precedence on resume, silently dropping staged-but-unconsumed
        # batches — force the consumer-count skip-replay path instead.
        sd.pop("base_state", None)
        return sd

    def load_state_dict(self, sd):
        self._resume_consumed = sd.get("num_batches_fetched", 0)
        self._consumed = 0
        # The restored checkpoint may be from a different epoch than the one
        # a prior partial iteration snapshotted; a stale identity would be
        # overlaid onto the restored state by the next state_dict().
        self._epoch_identity = None
        if hasattr(self.loader, "load_state_dict"):
            self.loader.load_state_dict(sd)


class SkipBatchSampler:
    """Batch sampler skipping the first ``skip_batches`` batches (reference :1296)."""

    def __init__(self, batch_sampler, skip_batches: int = 0):
        self.batch_sampler = batch_sampler
        self.skip_batches = skip_batches

    def __iter__(self):
        for idx, batch in enumerate(self.batch_sampler):
            if idx >= self.skip_batches:
                yield batch

    @property
    def total_length(self):
        return len(self.batch_sampler)

    def __len__(self):
        return len(self.batch_sampler) - self.skip_batches


class SkipDataLoader:
    """Iterable skipping first N batches (reference :1318-1356)."""

    def __init__(self, dataset_or_loader, skip_batches: int = 0):
        self.base = dataset_or_loader
        self.skip_batches = skip_batches

    def __iter__(self):
        for idx, batch in enumerate(self.base):
            if idx >= self.skip_batches:
                yield batch

    def __len__(self):
        return len(self.base) - self.skip_batches


def skip_first_batches(dataloader, num_batches: int = 0):
    """Mid-epoch resume: a loader that starts ``num_batches`` in (reference :1359).

    For our shard/dispatcher wrappers the skip happens *before* device feed; for
    raw iterables a SkipDataLoader is returned.
    """
    if isinstance(dataloader, (DataLoaderShard, DataLoaderDispatcher)):
        import copy

        new_loader = copy.copy(dataloader)
        new_loader.skip_batches = dataloader.skip_batches + num_batches
        # Explicit skip wins: don't compound with ANY pending stateful-resume
        # position — the counter, the shuffle-RNG snapshot, or a stateful
        # base's saved position (load_state + skip_first_batches would
        # otherwise double-skip this epoch, and the leftover pending state
        # would silently truncate the source loader's next epoch).
        for obj in (new_loader, dataloader):
            obj._resume_batches = 0
            if hasattr(obj, "_pending_rng"):
                obj._pending_rng = None
            if hasattr(obj, "_pending_base_state"):
                obj._pending_base_state = None
        return new_loader
    return SkipDataLoader(dataloader, skip_batches=num_batches)


# ------------------------------------------------------------------ preparation
def prepare_data_loader(
    dataloader,
    device=None,
    num_processes: int | None = None,
    process_index: int | None = None,
    split_batches: bool = False,
    put_on_device: bool = True,
    rng_types=None,
    dispatch_batches: bool | None = None,
    even_batches: bool = True,
    slice_fn_for_dispatch=None,
    use_seedable_sampler: bool = False,
    data_seed: int | None = None,
    non_blocking: bool = False,
    use_stateful_dataloader: bool = False,
):
    """Shard a dataloader across processes and route it onto the mesh
    (reference ``data_loader.py:994-1293``).

    Accepts a ``torch.utils.data.DataLoader`` (rebuilt with a sharded sampler, its
    dataset/collate/workers preserved) or any iterable of batches.
    """
    state = PartialState()
    num_processes = num_processes if num_processes is not None else state.num_processes
    process_index = process_index if process_index is not None else state.process_index

    if _is_torch_loader(dataloader):
        import torch.utils.data as tud

        dataset = dataloader.dataset
        is_iterable = isinstance(dataset, tud.IterableDataset)
        if dispatch_batches is None:
            dispatch_batches = is_iterable and put_on_device and num_processes > 1

        synchronized_generator = None
        if is_iterable:
            if dispatch_batches:
                return DataLoaderDispatcher(
                    dataloader,
                    split_batches=split_batches,
                    put_on_device=put_on_device,
                    slice_fn=slice_fn_for_dispatch,
                    _drop_last=dataloader.drop_last,
                )
            new_dataset = IterableDatasetShard(
                dataset,
                batch_size=dataloader.batch_size,
                drop_last=dataloader.drop_last,
                num_processes=num_processes,
                process_index=process_index,
                split_batches=split_batches,
            )
            kwargs = {k: getattr(dataloader, k) for k in _PYTORCH_DATALOADER_KWARGS if hasattr(dataloader, k)}
            kwargs.pop("prefetch_factor", None)
            new_bs = dataloader.batch_size // num_processes if split_batches else dataloader.batch_size
            inner = tud.DataLoader(new_dataset, batch_size=new_bs, **kwargs)
        else:
            batch_sampler = dataloader.batch_sampler
            sampler = getattr(batch_sampler, "sampler", None)
            if use_seedable_sampler and isinstance(sampler, tud.RandomSampler):
                seedable = SeedableRandomSampler(
                    dataset, seed=data_seed if data_seed is not None else 42
                )
                batch_sampler = tud.BatchSampler(
                    seedable, batch_size=dataloader.batch_size, drop_last=dataloader.drop_last
                )
                synchronized_generator = seedable
            sharded_sampler = BatchSamplerShard(
                batch_sampler,
                num_processes=num_processes,
                process_index=process_index,
                split_batches=split_batches,
                even_batches=even_batches,
            )
            kwargs = {k: getattr(dataloader, k) for k in _PYTORCH_DATALOADER_KWARGS if hasattr(dataloader, k)}
            if kwargs.get("prefetch_factor", None) is None:
                kwargs.pop("prefetch_factor", None)
            inner = tud.DataLoader(dataset, batch_sampler=sharded_sampler, **kwargs)
        return DataLoaderShard(
            inner,
            device=device,
            rng_types=rng_types,
            synchronized_generator=synchronized_generator,
            put_on_device=put_on_device,
            _drop_last=dataloader.drop_last,
            _non_blocking=non_blocking,
        )

    # Generic iterable of ready-made batches.
    if dispatch_batches:
        return DataLoaderDispatcher(dataloader, split_batches=split_batches, put_on_device=put_on_device)
    return DataLoaderShard(dataloader, device=device, rng_types=rng_types, put_on_device=put_on_device)
