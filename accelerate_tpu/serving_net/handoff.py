"""Prefill/decode disaggregation — ship a finished KV chain between hosts.

Why this is possible at all: rope/wpe rotations are baked into K at write
time from the per-row position channel, so a chain's K/V blocks are a pure
function of (params, token prefix) — the same property that makes blocks
shareable across requests (serving.py) makes them TRANSFERABLE across
processes. A prefill host runs chunked prefill to completion
(:func:`run_prefill_only`), :func:`export_chain` lifts the written blocks
plus the slot's armed decode state into a JSON-safe payload, and
:func:`import_chain` splices both into a decode host's pool via block-table
surgery. Greedy decode then continues bit-identically to a single host that
ran the whole request (pinned by test_utils/disagg_script.py): the decode
program only ever sees (pool contents, table, state), never who wrote them.

The transfer is bounded: only the ``ceil(slot_len / block_size)`` blocks the
chain actually WROTE travel (the worst-case reservation's unwritten decode
tail is re-reserved from the importer's free list, so admission stays the
only capacity decision point on both hosts). Stale bits in the written
blocks' bucket-padding holes ride along mask-invalid, exactly as they sit in
the exporter's pool.

Clock discipline: ``time.monotonic`` is per-process, so the payload carries
WALL-clock submit/export times; the importer rebases them onto its own
monotonic clock. The router-assigned rid rides every leg, so the per-tier
tracer records (prefill: submit→chunks→handoff out; decode: handoff
in→windows→finish) join into one cross-host trace by rid.
"""

from __future__ import annotations

import base64
import time

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.paged_attention import export_chain_blocks, import_chain_blocks
from ..utils.transfer import host_fetch, host_view

PAYLOAD_VERSION = 1

_HANDOFF_COUNTERS = None  # telemetry.metrics.cached_handles accessor


def _handoff_counters():
    """(bytes, chains, blocks) counters, labeled by transfer direction — the
    series /fleet rolls up into per-tier handoff traffic and the
    BENCH_SERVING_DISAGG lever snapshots into ``detail.serving.routing``."""
    global _HANDOFF_COUNTERS
    if _HANDOFF_COUNTERS is None:
        from ..telemetry.metrics import cached_handles

        _HANDOFF_COUNTERS = cached_handles(lambda registry: (
            registry.counter(
                "accelerate_serving_handoff_bytes_total",
                "KV chain bytes transferred between serving tiers",
                labelnames=("direction",),
            ),
            registry.counter(
                "accelerate_serving_handoff_chains_total",
                "KV chains transferred between serving tiers",
                labelnames=("direction",),
            ),
            registry.counter(
                "accelerate_serving_handoff_blocks_total",
                "KV blocks transferred between serving tiers",
                labelnames=("direction",),
            ),
        ))
    return _HANDOFF_COUNTERS()


def _book_handoff(direction: str, nbytes: int, blocks: int,
                  rid: int | None = None):
    counter_bytes, counter_chains, counter_blocks = _handoff_counters()
    counter_bytes.inc(int(nbytes), direction=direction)
    counter_chains.inc(direction=direction)
    counter_blocks.inc(int(blocks), direction=direction)
    # Durable wire-level leg (telemetry/journal.py): tracer-less engines
    # (relay tiers) still land their handoff legs in the per-host journal,
    # so a fleet timeline shows chain movement even where no RequestTracer
    # is attached. No-op when journaling is off.
    from ..telemetry.journal import journal_event

    journal_event("handoff_wire", rid=rid, direction=str(direction),
                  bytes=int(nbytes), blocks=int(blocks))


# ------------------------------------------------------------ wire encoding
def _encode(arr) -> dict:
    # host_view: a device-resident chain fetches counted; host data passes.
    arr = host_view(arr)
    return {
        "dtype": str(arr.dtype),
        "shape": list(arr.shape),
        "data": base64.b64encode(arr.tobytes()).decode("ascii"),
    }


def _decode(enc) -> np.ndarray:
    raw = base64.b64decode(enc["data"])
    # bfloat16 round-trips through ml_dtypes' registered numpy dtype (jax
    # registers it at import, so np.dtype("bfloat16") resolves here).
    arr = np.frombuffer(raw, dtype=np.dtype(enc["dtype"]))
    return arr.reshape(enc["shape"]).copy()


def _chain_nbytes(chain: dict) -> int:
    return sum(len(base64.b64decode(enc["data"])) for enc in chain.values())


# ----------------------------------------------------------------- prefill
def run_prefill_only(engine, rid: int) -> None:
    """Drive the paged engine's admission + chunk dispatch until request
    ``rid``'s prefill completes (its slot arms for decode) — WITHOUT ever
    dispatching a decode window. The prefill tier's engine loop: other
    admitted requests' chunks interleave in submit order exactly as the
    unified loop would run them, so prefill-host chunk traces match the
    single-host dispatch discipline."""
    if not engine.paged:
        raise ValueError("disaggregated prefill requires a paged engine")
    state = engine._state_tuple()
    while True:
        target = next(
            (s for s in range(engine.B)
             if engine._slot_req[s] is not None
             and engine._slot_req[s].rid == rid),
            None,
        )
        if target is not None and engine._slot_mode[target] == "decode":
            return
        now = time.monotonic()
        engine._admit_paged(now)
        # window_pace=None: no decode runs here, so TPOT pacing (which would
        # defer chunks in decode's favor) has nothing to protect.
        s = engine._pick_chunk_slot(now, None)
        if s is None:
            if target is None and not any(
                q.rid == rid for q in engine._queue
            ):
                raise KeyError(f"request {rid} is not queued or in flight")
            if target is None:
                # Queued but unadmittable and no chunks left to dispatch:
                # every in-flight slot is armed-for-decode deadweight this
                # loop will never retire. The caller must export those
                # chains (freeing their blocks) before retrying.
                raise RuntimeError(
                    f"prefill tier stalled: request {rid} cannot admit "
                    f"({len(engine._free_blocks)} of {engine.num_blocks} "
                    "blocks free) and no prefill work remains; export "
                    "finished chains to free capacity."
                )
            continue
        state = engine._dispatch_chunk(s, state)


# ------------------------------------------------------------------ export
def export_chain(engine, rid: int, endpoint: str | None = None,
                 free: bool = True) -> dict:
    """Lift request ``rid``'s finished prefill off ``engine``: the written
    chain blocks' contents, the slot's armed decode state, and the request's
    identity/controls, as one JSON-safe payload. With ``free=True`` the
    chain is refcount-freed here (blocks return to the exporter's pool the
    moment they're copied out); the relay path passes ``free=False`` and
    frees only once the importer ACKS the shipped chain
    (:func:`release_chain`) — free-on-ack, so an import that fails mid-wire
    leaves the chain intact for re-handoff to a surviving decode host. The
    tracer books the ``out`` leg either way, closing this tier's record as
    ``handed_off``."""
    if not engine.paged:
        raise ValueError("chain export requires a paged engine")
    s = next(
        (s for s in range(engine.B)
         if engine._slot_req[s] is not None and engine._slot_req[s].rid == rid),
        None,
    )
    if s is None:
        raise KeyError(f"request {rid} holds no slot (not prefilled yet?)")
    if engine._slot_mode[s] != "decode" or engine._slot_chunks[s]:
        raise RuntimeError(
            f"request {rid} has prefill chunks outstanding; "
            "run_prefill_only() it to completion first"
        )
    req = engine._slot_req[s]
    bs = engine.block_size
    slot_len = int(engine._slot_len[s])
    n_data = -(-slot_len // bs)
    data_ids = engine._slot_blocks[s][:n_data]
    chain = export_chain_blocks(engine._pool, data_ids)
    chain_enc = {name: _encode(host_fetch(chain[name])) for name in ("k", "v", "mask")}
    pool_k = engine._pool["k"]
    # One blocking fetch per field is fine here: export is a per-request
    # boundary event, not the steady-state decode loop.
    slot = {
        "tok": int(host_fetch(engine._tok[s])),
        "pos": int(host_fetch(engine._pos[s])),
        "n_out": int(host_fetch(engine._n_out[s])),
        "active": bool(host_fetch(engine._active[s])),
        "out_row": _encode(host_fetch(engine._out_buf[s])),
        "key_data": _encode(host_fetch(jax.random.key_data(engine._keys)[s])),
        "max": int(host_fetch(engine._slot_max[s])),
        "temp": float(host_fetch(engine._slot_temp[s])),
        "eos": int(host_fetch(engine._slot_eos[s])),
        "len": slot_len,
        "base": int(engine._slot_base[s]),
    }
    mono_now, wall_now = time.monotonic(), time.time()
    payload = {
        "version": PAYLOAD_VERSION,
        "rid": int(rid),
        "model": {
            "layers": int(pool_k.shape[0]),
            "kv_heads": int(pool_k.shape[3]),
            "head_dim": int(pool_k.shape[4]),
            "block_size": bs,
            "dtype": str(np.dtype(pool_k.dtype).name),
        },
        "chain": chain_enc,
        "data_blocks": n_data,
        "reserved_blocks": len(engine._slot_blocks[s]),
        "slot": slot,
        "tokens": _encode(engine._slot_tokens[s]),
        "request": {
            "max_new": int(req.max_new),
            "temperature": float(req.temperature),
            "eos": int(req.eos),
            "stop": [_encode(stop) for stop in req.stop],
        },
        # Wall-clock rebasing: monotonic clocks don't cross processes, so
        # the importer reconstructs submit age from wall time.
        "clock": {
            "wall_submit": wall_now - (mono_now - req.submit_t),
            "wall_export": wall_now,
        },
    }
    nbytes = _chain_nbytes(chain_enc)
    if engine.tracer is not None:
        engine.tracer.handoff(rid, "out", bytes=nbytes, blocks=n_data,
                              endpoint=endpoint)
    _book_handoff("out", nbytes, n_data, rid=rid)
    if free:
        engine.release_request(rid)
    return payload


def release_chain(engine, rid: int) -> bool:
    """Free an exported-but-retained chain (``export_chain(...,
    free=False)``): the importer acked — or every handoff target failed and
    the chain is being abandoned. Idempotent (False when ``rid`` holds no
    slot), so relay error paths can release unconditionally without
    double-free risk."""
    return bool(engine.release_request(rid))


# ------------------------------------------------------------------ import
def import_chain(engine, payload: dict, endpoint: str | None = None) -> int:
    """Splice an exported chain into ``engine``'s pool: re-reserve the full
    worst-case chain from the local free list, write the transferred blocks'
    contents (``ops.paged_attention.import_chain_blocks``), and arm the slot
    with the shipped decode state. After this, ``engine.run()`` decodes the
    request exactly as if the prefill had happened locally. Returns the rid
    (unchanged — router-assigned ids survive every hop)."""
    if not engine.paged:
        raise ValueError("chain import requires a paged engine")
    if payload.get("version") != PAYLOAD_VERSION:
        raise ValueError(
            f"handoff payload version {payload.get('version')!r} != "
            f"{PAYLOAD_VERSION}; tiers must run the same serving build"
        )
    pool_k = engine._pool["k"]
    model = payload["model"]
    local = {
        "layers": int(pool_k.shape[0]), "kv_heads": int(pool_k.shape[3]),
        "head_dim": int(pool_k.shape[4]), "block_size": engine.block_size,
        "dtype": str(np.dtype(pool_k.dtype).name),
    }
    if model != local:
        raise ValueError(
            f"handoff layout mismatch: exporter {model} vs importer {local} "
            "(tiers must share model config, block_size, and cache dtype)"
        )
    rid = int(payload["rid"])
    req_spec = payload["request"]
    if req_spec["max_new"] > engine.max_new:
        raise ValueError(
            f"request max_new {req_spec['max_new']} exceeds the decode "
            f"engine's output buffer ({engine.max_new})"
        )
    reserved = int(payload["reserved_blocks"])
    n_data = int(payload["data_blocks"])
    if reserved > engine.max_blocks_per_slot:
        raise ValueError(
            f"chain reservation {reserved} blocks exceeds the decode "
            f"engine's static table ({engine.max_blocks_per_slot}); raise "
            "max_tokens_per_request to match the prefill tier"
        )
    s = next((s for s in range(engine.B) if engine._slot_mode[s] == "free"), None)
    if s is None:
        raise RuntimeError("no free slot to import into; drain a wave first")
    if reserved > len(engine._free_blocks):
        raise RuntimeError(
            f"KV pool capacity exhausted ({len(engine._free_blocks)} of "
            f"{engine.num_blocks} blocks free; the imported chain needs "
            f"{reserved})"
        )
    fresh = [engine._free_blocks.pop(0) for _ in range(reserved)]
    for blk in fresh:
        engine._block_ref[blk] += 1
    chain = {name: jnp.asarray(_decode(payload["chain"][name]))
             for name in ("k", "v", "mask")}
    engine._pool = import_chain_blocks(engine._pool, fresh[:n_data], chain)
    slot = payload["slot"]
    prompt = _decode(payload["tokens"])
    engine._tables_np[s, :] = 0
    engine._tables_np[s, :reserved] = fresh
    engine._slot_blocks[s] = fresh
    engine._slot_len[s] = int(slot["len"])
    engine._slot_base[s] = int(slot["base"])
    engine._slot_chunks[s] = []
    engine._slot_tokens[s] = prompt
    engine._slot_mode[s] = "decode"
    # Rebase the exporter's wall-clock submit onto this process's monotonic
    # clock, so queue-wait/TTFT attribution spans the whole cross-tier
    # journey (transfer latency included) instead of restarting at import.
    mono_now, wall_now = time.monotonic(), time.time()
    submit_t = mono_now - max(0.0, wall_now - payload["clock"]["wall_submit"])
    from ..serving import _Request

    req = _Request(
        rid, prompt, int(req_spec["max_new"]), float(req_spec["temperature"]),
        int(req_spec["eos"]),
        tuple(_decode(stop) for stop in req_spec["stop"]),
        submit_t,
    )
    engine._slot_req[s] = req
    engine._next_rid = max(engine._next_rid, rid + 1)
    engine._req_times[rid] = {"submit": submit_t}
    out_row = _decode(slot["out_row"])
    if out_row.size < engine.max_new:
        out_row = np.concatenate([
            out_row,
            np.full((engine.max_new - out_row.size,), engine.pad, np.int32),
        ])
    key = jax.random.wrap_key_data(jnp.asarray(_decode(slot["key_data"])))
    engine._tok = engine._tok.at[s].set(slot["tok"])
    engine._pos = engine._pos.at[s].set(slot["pos"])
    engine._n_out = engine._n_out.at[s].set(slot["n_out"])
    engine._active = engine._active.at[s].set(slot["active"])
    engine._out_buf = engine._out_buf.at[s].set(jnp.asarray(out_row[: engine.max_new]))
    engine._keys = engine._keys.at[s].set(key)
    engine._slot_max = engine._slot_max.at[s].set(slot["max"])
    engine._slot_temp = engine._slot_temp.at[s].set(slot["temp"])
    engine._slot_eos = engine._slot_eos.at[s].set(slot["eos"])
    nbytes = _chain_nbytes(payload["chain"])
    if engine.tracer is not None:
        engine.tracer.submit(rid, int(prompt.size), submit_t=submit_t,
                             tier="decode")
        engine.tracer.handoff(rid, "in", bytes=nbytes, blocks=n_data,
                              endpoint=endpoint)
    _book_handoff("in", nbytes, n_data, rid=rid)
    engine._peak_consumed_slots = max(
        engine._peak_consumed_slots, engine.blocks_in_use * engine.block_size
    )
    engine._publish_pool_gauges()
    return rid
