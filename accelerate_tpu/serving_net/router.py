"""The serving fleet's front door — prefix-affinity routing over /v1 workers.

The router tier runs no engine, no pool, no model: it discovers decode and
prefill workers through the fleet KV namespace (the registration transport
``telemetry/fleet.py`` already rides), assigns each request its fleet-wide
rid, decides which tier the request ENTERS (the SLO sentinel's
:func:`~..telemetry.slo.arbitrate_serving_tier`), and relays the chosen
worker's SSE stream back to the client — prepending its own tracer record to
the final event's trace, so one rid spans router admission → prefill chunks
→ chain handoff → first decode token.

Routing policy (per request, all host-side lookups):

- **Prefix-cache affinity first**: every decode-capable worker answers
  ``POST /v1/prefixes`` with how many leading prompt tokens its refcounted
  share index already holds resident (a dict lookup against the engine's
  ``_share_index`` — never a device touch). The longest match wins: decoding
  where the prefix lives aliases those blocks instead of re-prefilling them.
- **Least-loaded fallback**: on a tie (including the common all-zero case),
  the worker with the fewest in-flight requests wins — the prefixes answer
  carries the load signal, so routing costs one round per worker.
- **Tier arbitration**: multi-chunk prompts enter the prefill tier when one
  exists (the decode tier's TPOT is protected from long prefills); the
  chosen decode worker rides along as the chain's handoff target, so
  affinity still decides where the request ultimately DECODES.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

from ..logging import get_logger
from ..telemetry.fleet import _kv_client, metrics_endpoint
from ..telemetry.slo import arbitrate_serving_tier
from .frontend import relay_generate, sse_event

logger = get_logger(__name__)

# Coordination-service KV namespace for serving-role registration — one key
# per rank holding "role|host:port", the same persistent-fact discipline as
# the metrics registry (telemetry/fleet.py KV_NAMESPACE).
SERVING_KV_NAMESPACE = "at_fleet/serving"

# How long one worker gets to answer an affinity/stats probe before routing
# falls back without it — a dead worker must not stall admission.
PROBE_TIMEOUT_S = 3.0

_LOCK = threading.Lock()
_LOCAL_WORKERS: dict[int, dict] = {}  # rank -> {"role", "endpoint"} (in-process)

_ROUTER_COUNTERS = None  # telemetry.metrics.cached_handles accessor


def _router_counters():
    """(routed{tier=}, affinity_hits) — the routing decisions /fleet and the
    BENCH_SERVING_DISAGG lever read back as the affinity hit rate."""
    global _ROUTER_COUNTERS
    if _ROUTER_COUNTERS is None:
        from ..telemetry.metrics import cached_handles

        _ROUTER_COUNTERS = cached_handles(lambda registry: (
            registry.counter(
                "accelerate_serving_router_requests_total",
                "Requests admitted by the router, by entry tier",
                labelnames=("tier",),
            ),
            registry.counter(
                "accelerate_serving_router_affinity_hits_total",
                "Requests routed to a worker holding a resident prompt prefix",
            ),
        ))
    return _ROUTER_COUNTERS()


def publish_serving_endpoint(role: str, process_index: int = 0,
                             endpoint: str | None = None) -> str | None:
    """Register this worker's serving role + endpoint in the fleet KV
    namespace (``ServingFrontend.install`` calls this). ``endpoint``
    defaults to the already-published metrics endpoint — the /v1 API lives
    on the same port. Returns the published ``role|host:port``."""
    endpoint = endpoint or metrics_endpoint()
    if endpoint is None:
        return None
    value = f"{role}|{endpoint}"
    with _LOCK:
        _LOCAL_WORKERS[int(process_index)] = {"role": role, "endpoint": endpoint}
    client = _kv_client()
    if client is not None:
        key = f"{SERVING_KV_NAMESPACE}/{int(process_index)}"
        try:
            client.key_value_set(key, value)
        except Exception:
            try:  # a stale key from a prior incarnation: replace it
                client.key_value_delete(key)
                client.key_value_set(key, value)
            except Exception:
                pass
    return value


def discover_serving_workers(num_processes: int,
                             timeout_ms: int = 10_000) -> list[dict]:
    """``[{"rank", "role", "endpoint"}]`` for every rank that has registered
    a serving role — the fair-total-budget read discipline of
    :func:`~..telemetry.fleet.discover_endpoints`; an unregistered rank is
    absent, never an exception. Without a distributed client returns the
    in-process registrations."""
    client = _kv_client()
    if client is None or num_processes <= 1:
        with _LOCK:
            return [
                {"rank": rank, **spec}
                for rank, spec in sorted(_LOCAL_WORKERS.items())
            ]
    workers = []
    ranks = list(range(int(num_processes)))
    deadline = time.monotonic() + timeout_ms / 1000.0
    for i, rank in enumerate(ranks):
        remaining_ms = int((deadline - time.monotonic()) * 1000)
        if remaining_ms <= 0:
            break
        slice_ms = max(50, remaining_ms // (len(ranks) - i))
        try:
            value = client.blocking_key_value_get(
                f"{SERVING_KV_NAMESPACE}/{rank}", slice_ms
            )
        except Exception:
            continue  # not registered (yet) — degradation, not failure
        role, _, endpoint = value.partition("|")
        if endpoint:
            workers.append({"rank": rank, "role": role, "endpoint": endpoint})
    return workers


def reset_serving_registry():
    """Drop in-process serving registrations — tests."""
    with _LOCK:
        _LOCAL_WORKERS.clear()


def _post_json(url: str, payload: dict, timeout_s: float = PROBE_TIMEOUT_S) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as response:
        return json.loads(response.read().decode("utf-8", "replace"))


def _get_json(url: str, timeout_s: float = PROBE_TIMEOUT_S) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as response:
        return json.loads(response.read().decode("utf-8", "replace"))


class Router:
    """The /v1 provider for the router role; see module docstring.

    ``workers`` pins the fleet explicitly (``[{"role", "endpoint"}]`` —
    tests, ad-hoc operator use); otherwise every routing decision re-reads
    the KV registry through a short cache, so workers that register late (or
    re-register after an elastic restart) are picked up live. ``slo`` is the
    fleet's :class:`~..serving.SLOTargets` for tier arbitration."""

    def __init__(self, workers=None, num_processes: int = 1, slo=None,
                 cache_s: float = 2.0, trace_requests: bool = True):
        self._static = workers is not None
        self._workers = [dict(w) for w in workers] if workers else []
        self.num_processes = int(num_processes)
        if slo is None:
            from ..telemetry.slo import serving_slo_from_env

            slo = serving_slo_from_env()
        self.slo = slo
        self.cache_s = float(cache_s)
        self._cached_at = 0.0
        self._prefill_chunk: int | None = None
        self._next_rid = 0
        self._lock = threading.Lock()
        if trace_requests:
            from ..telemetry.requests import RequestTracer

            self.tracer = RequestTracer(slo=slo)
        else:
            self.tracer = None

    def install(self, process_index: int = 0, server=None,
                endpoint: str | None = None):
        """Become this process's serving provider and register the router
        role in the fleet KV namespace (clients discover the front door the
        same way the router discovers workers). ``server`` attaches to one
        specific MetricsServer instead of the process-global route."""
        from ..telemetry.metrics import get_registry, set_serving_provider

        if server is not None:
            server.set_serving(self)
            if endpoint is None and server.port is not None:
                endpoint = f"127.0.0.1:{server.port}"
        else:
            set_serving_provider(self)
        get_registry().gauge(
            "accelerate_serving_role",
            "Serving tier this process runs (1 = the labeled role)",
            labelnames=("role",),
        ).set(1, role="router")
        publish_serving_endpoint("router", process_index=process_index,
                                 endpoint=endpoint)
        return self

    # ------------------------------------------------------------- discovery
    def workers(self) -> list[dict]:
        if self._static:
            return self._workers
        now = time.monotonic()
        with self._lock:
            if self._workers and now - self._cached_at < self.cache_s:
                return self._workers
        found = discover_serving_workers(self.num_processes)
        with self._lock:
            if found:
                self._workers = found
                self._cached_at = now
            return self._workers

    def _prefill_chunk_of(self, endpoint: str) -> int:
        """The prefill tier's chunk size (what tier arbitration counts
        chunks with) — fetched once from the worker's /v1/stats and cached;
        0 (unknown) degrades arbitration to single-chunk behavior."""
        if self._prefill_chunk is None:
            try:
                stats = _get_json(f"http://{endpoint}/v1/stats")
                self._prefill_chunk = int(stats.get("prefill_chunk") or 0)
            except Exception:
                return 0
        return self._prefill_chunk

    # --------------------------------------------------------------- routing
    def _pick_decode(self, prompt: list, candidates: list[dict]):
        """Affinity first, least-loaded on ties; a worker that fails its
        probe drops out of this decision, not out of the fleet."""
        probed = []
        for worker in candidates:
            try:
                answer = _post_json(
                    f"http://{worker['endpoint']}/v1/prefixes",
                    {"prompt": prompt},
                )
                probed.append((worker, int(answer.get("match_tokens", 0)),
                               int(answer.get("in_flight", 0))))
            except Exception as exc:
                logger.warning(
                    f"serving worker {worker['endpoint']} failed its affinity "
                    f"probe ({exc!r}); routing around it"
                )
        if not probed:
            return None, 0
        best_match = max(match for _, match, _ in probed)
        tied = [(w, m, load) for w, m, load in probed if m == best_match]
        worker = min(tied, key=lambda t: t[2])[0]
        return worker, best_match

    def route(self, request: dict):
        """One admission decision: assign the fleet rid, arbitrate the entry
        tier, pick workers, and return ``(rid, url, outbound_request)`` —
        the relay target. Raises RuntimeError when no worker can serve."""
        prompt = list(request.get("prompt") or [])
        if not prompt:
            raise ValueError("empty or missing 'prompt'")
        workers = self.workers()
        decode_candidates = [w for w in workers
                             if w["role"] in ("decode", "unified")]
        prefill_candidates = [w for w in workers if w["role"] == "prefill"]
        if not decode_candidates:
            raise RuntimeError(
                "no decode-capable serving worker registered "
                f"({len(workers)} workers known)"
            )
        decode_worker, match = self._pick_decode(prompt, decode_candidates)
        if decode_worker is None:
            raise RuntimeError("every decode-capable worker failed its probe")
        prefill_chunk = (
            self._prefill_chunk_of(prefill_candidates[0]["endpoint"])
            if prefill_candidates else 0
        )
        tier = arbitrate_serving_tier(
            len(prompt), self.slo, prefill_chunk=prefill_chunk,
            have_prefill_tier=bool(prefill_candidates),
        )
        with self._lock:
            rid = self._next_rid
            self._next_rid += 1
        if self.tracer is not None:
            self.tracer.submit(rid, len(prompt), tier="router")
            self.tracer.admit(rid, decision=f"route_{tier}",
                              aliased_blocks=0, chunks=1)
        routed, affinity_hits = _router_counters()
        routed.inc(tier=tier)
        if match > 0:
            affinity_hits.inc()
        outbound = {key: value for key, value in request.items()
                    if key != "request_id"}
        outbound["request_id"] = rid
        if tier == "prefill":
            prefill_worker = min(
                prefill_candidates,
                key=lambda w: self._in_flight_of(w["endpoint"]),
            )
            outbound["decode_endpoint"] = decode_worker["endpoint"]
            return rid, f"http://{prefill_worker['endpoint']}/v1/generate", outbound
        return rid, f"http://{decode_worker['endpoint']}/v1/generate", outbound

    def _in_flight_of(self, endpoint: str) -> int:
        try:
            return int(_get_json(f"http://{endpoint}/v1/stats")["in_flight"])
        except Exception:
            return 1 << 30  # unprobeable: route around it when possible

    # ------------------------------------------------------------- provider
    def handle_get(self, path: str, query: dict):
        if path == "/v1/stats":
            body = json.dumps(self.stats()).encode()
            return (200, "application/json", body)
        return None

    def handle_post(self, path: str, query: dict, body: bytes):
        if path != "/v1/generate":
            return None
        request = json.loads(body or b"{}")
        try:
            rid, url, outbound = self.route(request)
        except ValueError as exc:
            return ("json", 400, {"error": str(exc)})
        except RuntimeError as exc:
            return ("json", 503, {"error": str(exc)})

        def finalize(done: dict) -> dict:
            if self.tracer is not None:
                self.tracer.finish(rid, len(done.get("tokens", [])),
                                   tpot_s=done.get("tpot_s"))
                record = next(
                    (r for r in self.tracer.records() if r["rid"] == rid),
                    None,
                )
                if record is not None:
                    done["trace"] = [record] + done.get("trace", [])
            return done

        return ("sse", relay_generate(url, outbound, finalize=finalize))

    def stats(self) -> dict:
        routed, affinity_hits = _router_counters()
        by_tier = {key[0]: int(v)
                   for key, v in routed.series_values().items()}
        total = sum(by_tier.values())
        hits = int(affinity_hits.value())
        return {
            "role": "router",
            "workers": self.workers(),
            "routed": by_tier,
            "affinity_hits": hits,
            "affinity_hit_rate": round(hits / total, 6) if total else None,
        }
