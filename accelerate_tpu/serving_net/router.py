"""The serving fleet's front door — prefix-affinity routing over /v1 workers.

The router tier runs no engine, no pool, no model: it discovers decode and
prefill workers through the fleet KV namespace (the registration transport
``telemetry/fleet.py`` already rides), assigns each request its fleet-wide
rid, decides which tier the request ENTERS (the SLO sentinel's
:func:`~..telemetry.slo.arbitrate_serving_tier`), and relays the chosen
worker's SSE stream back to the client — prepending its own tracer record to
the final event's trace, so one rid spans router admission → prefill chunks
→ chain handoff → first decode token.

Routing policy (per request, all host-side lookups):

- **Prefix-cache affinity first**: every decode-capable worker answers
  ``POST /v1/prefixes`` with how many leading prompt tokens its refcounted
  share index already holds resident (a dict lookup against the engine's
  ``_share_index`` — never a device touch). The longest match wins: decoding
  where the prefix lives aliases those blocks instead of re-prefilling them.
- **Least-loaded fallback**: on a tie (including the common all-zero case),
  the worker with the fewest in-flight requests wins — the prefixes answer
  carries the load signal, so routing costs one round per worker.
- **Tier arbitration**: multi-chunk prompts enter the prefill tier when one
  exists (the decode tier's TPOT is protected from long prefills); the
  chosen decode worker rides along as the chain's handoff target, so
  affinity still decides where the request ultimately DECODES.

Fault tolerance (docs/serving.md "Failure semantics"):

- **Lease eviction**: registrations are TTL leases (:mod:`.lease`); a worker
  whose lease expires (its heartbeat stopped) is evicted — dropped from the
  candidate set, its affinity/load caches invalidated so a retry can never
  re-pick it.
- **Circuit breakers**: per-worker closed → open (after N consecutive failed
  probes/dispatches) → half-open (one trial after a cooldown), so a flapping
  host absorbs no live traffic while it flaps.
- **Retry under the same rid**: a failed dispatch (connect error, retryable
  worker error, or a stream that dies without a terminal frame) re-routes to
  a surviving worker with exponential backoff inside a bounded budget; token
  deltas already streamed to the client are de-duplicated, so the client
  sees ONE contiguous stream. Deadlines (``deadline_wall``) propagate on
  every dispatch so no client ever hangs.
- **Degradation ladder**: prefill tier lost → multi-chunk prompts route to
  decode-as-unified (booked ``accelerate_serving_degraded_total``); every
  decode-capable worker lost → a fast 503 with ``retry_after_s``, the shed
  booked through the SLO sentinel (``availability`` breach target).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request

from ..logging import get_logger
from ..telemetry.fleet import _kv_client, metrics_endpoint
from ..telemetry.slo import arbitrate_serving_tier
from .frontend import STREAM_TIMEOUT_S, sse_event
from .lease import encode_lease, lease_expired, parse_lease, retry_budget_from_env

logger = get_logger(__name__)

# Coordination-service KV namespace for serving-role registration — one key
# per rank holding "role|host:port|expires=<unix>", the same persistent-fact
# discipline as the metrics registry (telemetry/fleet.py KV_NAMESPACE) with
# the lease expiry layered on top (lease.py).
SERVING_KV_NAMESPACE = "at_fleet/serving"

# How long one worker gets to answer an affinity/stats probe before routing
# falls back without it — a dead worker must not stall admission.
PROBE_TIMEOUT_S = 3.0

# Circuit-breaker defaults: consecutive probe/dispatch failures before a
# worker opens, and how long it stays open before one half-open trial.
BREAKER_FAILURES = 3
BREAKER_COOLDOWN_S = 5.0

# Retry backoff: base * 2^(attempt-1), capped — small enough that a retried
# request still beats its deadline, large enough to ride out a GC pause.
BACKOFF_BASE_S = 0.1
BACKOFF_CAP_S = 2.0

_LOCK = threading.Lock()
_LOCAL_WORKERS: dict[int, dict] = {}  # rank -> {"role", "endpoint", "expires"}

_ROUTER_COUNTERS = None  # telemetry.metrics.cached_handles accessor
_FAULT_COUNTERS = None   # retry/eviction/degradation handles


def _router_counters():
    """(routed{tier=}, affinity_hits) — the routing decisions /fleet and the
    BENCH_SERVING_DISAGG lever read back as the affinity hit rate."""
    global _ROUTER_COUNTERS
    if _ROUTER_COUNTERS is None:
        from ..telemetry.metrics import cached_handles

        _ROUTER_COUNTERS = cached_handles(lambda registry: (
            registry.counter(
                "accelerate_serving_router_requests_total",
                "Requests admitted by the router, by entry tier",
                labelnames=("tier",),
            ),
            registry.counter(
                "accelerate_serving_router_affinity_hits_total",
                "Requests routed to a worker holding a resident prompt prefix",
            ),
        ))
    return _ROUTER_COUNTERS()


def _fault_counters():
    """(retries{reason=}, evictions{reason=}, degraded{mode=},
    breaker_state{endpoint=}) — the fault-tolerance series /fleet rolls up
    and the BENCH_SERVING_CHAOS lever snapshots."""
    global _FAULT_COUNTERS
    if _FAULT_COUNTERS is None:
        from ..telemetry.metrics import cached_handles

        _FAULT_COUNTERS = cached_handles(lambda registry: (
            registry.counter(
                "accelerate_serving_retries_total",
                "Request dispatches retried on a surviving worker, by reason",
                labelnames=("reason",),
            ),
            registry.counter(
                "accelerate_serving_evictions_total",
                "Serving workers evicted from the router's candidate set",
                labelnames=("reason",),
            ),
            registry.counter(
                "accelerate_serving_degraded_total",
                "Requests served in an explicitly degraded mode",
                labelnames=("mode",),
            ),
            registry.gauge(
                "accelerate_serving_breaker_state",
                "Per-worker circuit breaker (0 closed, 1 half-open, 2 open)",
                labelnames=("endpoint",),
            ),
        ))
    return _FAULT_COUNTERS()


def publish_serving_endpoint(role: str, process_index: int = 0,
                             endpoint: str | None = None,
                             ttl_s: float | None = None) -> str | None:
    """Register this worker's serving role + endpoint in the fleet KV
    namespace as a TTL lease (``ServingFrontend.install`` calls this once,
    then a :class:`~.lease.LeaseHeartbeat` refreshes it). ``endpoint``
    defaults to the already-published metrics endpoint — the /v1 API lives
    on the same port; ``ttl_s`` defaults to the launcher env contract
    (``ACCELERATE_SERVING_LEASE_TTL``). Returns the published value."""
    endpoint = endpoint or metrics_endpoint()
    if endpoint is None:
        return None
    if ttl_s is None:
        from .lease import lease_ttl_from_env

        ttl_s = lease_ttl_from_env()
    now = time.time()
    value = encode_lease(role, endpoint, ttl_s, now=now)
    with _LOCK:
        _LOCAL_WORKERS[int(process_index)] = {
            "role": role, "endpoint": endpoint,
            "expires": (now + ttl_s) if ttl_s and ttl_s > 0 else None,
        }
    client = _kv_client()
    if client is not None:
        key = f"{SERVING_KV_NAMESPACE}/{int(process_index)}"
        try:
            client.key_value_set(key, value)
        except Exception:
            try:  # a stale key from a prior incarnation: replace it
                client.key_value_delete(key)
                client.key_value_set(key, value)
            except Exception:
                pass
    return value


def revoke_serving_endpoint(process_index: int = 0):
    """Delete this worker's serving registration outright — the graceful
    path (drain, uninstall): the router sees the worker gone on its next
    discovery instead of waiting out the lease TTL."""
    with _LOCK:
        _LOCAL_WORKERS.pop(int(process_index), None)
    client = _kv_client()
    if client is not None:
        try:
            client.key_value_delete(
                f"{SERVING_KV_NAMESPACE}/{int(process_index)}")
        except Exception:
            pass


def discover_serving_workers(num_processes: int,
                             timeout_ms: int = 10_000) -> list[dict]:
    """``[{"rank", "role", "endpoint", "expires"}]`` for every rank holding a
    LIVE serving lease — the fair-total-budget read discipline of
    :func:`~..telemetry.fleet.discover_endpoints`; an unregistered rank is
    absent, never an exception, and an expired lease is absent too (the
    dead-worker case leases exist for: coordination-service keys outlive
    their writers). Without a distributed client returns the in-process
    registrations, same expiry rule."""
    now = time.time()
    client = _kv_client()
    if client is None or num_processes <= 1:
        with _LOCK:
            return [
                {"rank": rank, **spec}
                for rank, spec in sorted(_LOCAL_WORKERS.items())
                if not lease_expired(spec, now)
            ]
    workers = []
    ranks = list(range(int(num_processes)))
    deadline = time.monotonic() + timeout_ms / 1000.0
    for i, rank in enumerate(ranks):
        remaining_ms = int((deadline - time.monotonic()) * 1000)
        if remaining_ms <= 0:
            break
        slice_ms = max(50, remaining_ms // (len(ranks) - i))
        try:
            value = client.blocking_key_value_get(
                f"{SERVING_KV_NAMESPACE}/{rank}", slice_ms
            )
        except Exception:
            continue  # not registered (yet) — degradation, not failure
        lease = parse_lease(value)
        if lease is not None and not lease_expired(lease, now):
            workers.append({"rank": rank, **lease})
    return workers


def reset_serving_registry():
    """Drop in-process serving registrations — tests."""
    with _LOCK:
        _LOCAL_WORKERS.clear()


def _post_json(url: str, payload: dict, timeout_s: float = PROBE_TIMEOUT_S) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as response:
        return json.loads(response.read().decode("utf-8", "replace"))


def _get_json(url: str, timeout_s: float = PROBE_TIMEOUT_S) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as response:
        return json.loads(response.read().decode("utf-8", "replace"))


class _Breaker:
    """One worker's circuit breaker: ``closed`` (healthy) → ``open`` after
    ``failures`` consecutive probe/dispatch failures (no traffic) →
    ``half_open`` after ``cooldown_s`` (exactly one trial request; success
    closes, failure re-opens). Host-side state only."""

    STATES = ("closed", "half_open", "open")

    def __init__(self, failures: int = BREAKER_FAILURES,
                 cooldown_s: float = BREAKER_COOLDOWN_S):
        self.failure_threshold = int(failures)
        self.cooldown_s = float(cooldown_s)
        self.state = "closed"
        self.consecutive = 0
        self.opened_at = 0.0
        self._trial_out = False

    def allows(self, now: float) -> bool:
        if self.state == "closed":
            return True
        if self.state == "open":
            if now - self.opened_at >= self.cooldown_s:
                self.state = "half_open"
                self._trial_out = True
                return True
            return False
        # half_open: one trial in flight at a time
        if self._trial_out:
            return False
        self._trial_out = True
        return True

    def ok(self):
        self.state = "closed"
        self.consecutive = 0
        self._trial_out = False

    def fail(self, now: float) -> bool:
        """Record one failure; returns True when this failure TRIPPED the
        breaker open (closed/half-open → open transition)."""
        self.consecutive += 1
        trip = (self.state == "half_open"
                or (self.state == "closed"
                    and self.consecutive >= self.failure_threshold))
        if trip or self.state == "open":
            self.state = "open"
            self.opened_at = now
            self._trial_out = False
        return trip

    def permit_trial(self):
        """Skip the remaining cooldown — the next ``allows`` grants a trial
        (a re-registered worker re-earns trust instead of waiting it out)."""
        if self.state == "open":
            self.opened_at = -float("inf")


class Router:
    """The /v1 provider for the router role; see module docstring.

    ``workers`` pins the fleet explicitly (``[{"role", "endpoint"}]`` —
    tests, ad-hoc operator use); otherwise every routing decision re-reads
    the KV registry through a short cache, so workers that register late (or
    re-register after an elastic restart) are picked up live. ``slo`` is the
    fleet's :class:`~..serving.SLOTargets` for tier arbitration.
    ``retry_budget`` bounds re-dispatches per request (None = the launcher
    env contract, ``ACCELERATE_SERVING_RETRY_BUDGET``); the breaker/backoff
    knobs exist for drills — the defaults are the production contract."""

    def __init__(self, workers=None, num_processes: int = 1, slo=None,
                 cache_s: float = 2.0, trace_requests: bool = True,
                 retry_budget: int | None = None,
                 breaker_failures: int = BREAKER_FAILURES,
                 breaker_cooldown_s: float = BREAKER_COOLDOWN_S,
                 backoff_base_s: float = BACKOFF_BASE_S,
                 backoff_cap_s: float = BACKOFF_CAP_S,
                 retry_after_s: float = 2.0):
        self._static = workers is not None
        self._workers = [dict(w) for w in workers] if workers else []
        self.num_processes = int(num_processes)
        if slo is None:
            from ..telemetry.slo import serving_slo_from_env

            slo = serving_slo_from_env()
        self.slo = slo
        self.cache_s = float(cache_s)
        self.retry_budget = (int(retry_budget) if retry_budget is not None
                             else retry_budget_from_env())
        self.breaker_failures = int(breaker_failures)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.retry_after_s = float(retry_after_s)
        self._cached_at = 0.0
        self._prefill_chunk: int | None = None
        self._prefill_chunk_ep: str | None = None
        self._had_prefill_tier = False
        self._breakers: dict[str, _Breaker] = {}
        self._evicted: dict[str, str] = {}  # endpoint -> eviction reason
        self._next_rid = 0
        self._lock = threading.Lock()
        self._heartbeat = None
        if trace_requests:
            from ..telemetry.requests import RequestTracer

            self.tracer = RequestTracer(slo=slo)
        else:
            self.tracer = None

    def install(self, process_index: int = 0, server=None,
                endpoint: str | None = None):
        """Become this process's serving provider and register the router
        role in the fleet KV namespace (clients discover the front door the
        same way the router discovers workers — heartbeat-leased like any
        worker). ``server`` attaches to one specific MetricsServer instead
        of the process-global route."""
        from ..telemetry.metrics import get_registry, set_serving_provider

        if server is not None:
            server.set_serving(self)
            if endpoint is None and server.port is not None:
                endpoint = f"127.0.0.1:{server.port}"
        else:
            set_serving_provider(self)
        get_registry().gauge(
            "accelerate_serving_role",
            "Serving tier this process runs (1 = the labeled role)",
            labelnames=("role",),
        ).set(1, role="router")
        if endpoint is not None or metrics_endpoint() is not None:
            from .lease import LeaseHeartbeat

            self._heartbeat = LeaseHeartbeat(
                "router", process_index,
                endpoint or metrics_endpoint(),
            ).start()
        return self

    def shutdown(self):
        """Stop the lease heartbeat and revoke the router's registration
        (graceful exit — the drill's teardown path)."""
        if self._heartbeat is not None:
            self._heartbeat.stop(revoke=True)
            self._heartbeat = None

    # ------------------------------------------------------------- discovery
    def workers(self) -> list[dict]:
        if self._static:
            return self._workers
        now = time.monotonic()
        with self._lock:
            if self._workers and now - self._cached_at < self.cache_s:
                return list(self._workers)
        found = discover_serving_workers(self.num_processes)
        with self._lock:
            known = {w["endpoint"] for w in self._workers}
            self._workers = found
            self._cached_at = now
        fresh = {w["endpoint"] for w in found}
        # A worker that vanished from discovery lost its lease (expired or
        # revoked): evict it so retries and affinity can never re-pick it.
        for endpoint in known - fresh:
            self._evict(endpoint, "lease_expired")
        # A previously lease-evicted worker whose heartbeat resumed re-earns
        # trust through one half-open trial instead of a full cooldown.
        for worker in found:
            if self._evicted.get(worker["endpoint"]) == "lease_expired":
                self._evicted.pop(worker["endpoint"], None)
                breaker = self._breakers.get(worker["endpoint"])
                if breaker is not None:
                    breaker.permit_trial()
        return found

    def _breaker(self, endpoint: str) -> _Breaker:
        breaker = self._breakers.get(endpoint)
        if breaker is None:
            breaker = self._breakers[endpoint] = _Breaker(
                self.breaker_failures, self.breaker_cooldown_s)
        return breaker

    def _publish_breaker(self, endpoint: str):
        state = self._breakers[endpoint].state
        _, _, _, breaker_gauge = _fault_counters()
        breaker_gauge.set(float(_Breaker.STATES.index(state)),
                          endpoint=endpoint)

    def _probe_ok(self, endpoint: str):
        breaker = self._breakers.get(endpoint)
        if breaker is not None and breaker.state != "closed":
            breaker.ok()
            self._publish_breaker(endpoint)
        elif breaker is not None:
            breaker.ok()

    def _probe_failed(self, endpoint: str):
        """One failed probe/dispatch against ``endpoint``; trips the breaker
        (and books an eviction) after the consecutive-failure threshold."""
        breaker = self._breaker(endpoint)
        tripped = breaker.fail(time.monotonic())
        self._publish_breaker(endpoint)
        if tripped:
            self._evict(endpoint, "probe_failures")

    def _evict(self, endpoint: str, reason: str):
        """Drop ``endpoint`` from the candidate set: open its breaker, book
        the eviction, and invalidate every cache that could hand it back —
        the worker cache (so re-routing re-discovers) and the prefill-chunk
        cache when this endpoint supplied it (a dead tier must not keep
        shaping arbitration)."""
        if self._evicted.get(endpoint) == reason:
            return
        self._evicted[endpoint] = reason
        breaker = self._breaker(endpoint)
        breaker.state = "open"
        breaker.opened_at = time.monotonic()
        self._publish_breaker(endpoint)
        _, evictions, _, _ = _fault_counters()
        evictions.inc(reason=reason)
        with self._lock:
            self._workers = [w for w in self._workers
                             if w["endpoint"] != endpoint]
            if not self._static:
                self._cached_at = 0.0
            if self._prefill_chunk_ep == endpoint:
                self._prefill_chunk_ep = None
        logger.warning(f"serving worker {endpoint} evicted ({reason})")
        from ..telemetry.flight import get_flight_recorder

        get_flight_recorder().record("serving_eviction", endpoint=endpoint,
                                     reason=reason)

    def _available(self, workers: list[dict]) -> list[dict]:
        """Candidates whose breaker admits traffic right now (closed, or one
        half-open trial after cooldown)."""
        now = time.monotonic()
        out = []
        for worker in workers:
            breaker = self._breakers.get(worker["endpoint"])
            if breaker is None or breaker.allows(now):
                out.append(worker)
        return out

    def _prefill_chunk_of(self, endpoint: str) -> int:
        """The prefill tier's chunk size (what tier arbitration counts
        chunks with) — fetched from the worker's /v1/stats and cached per
        endpoint (an eviction invalidates the binding, so a replacement
        prefill tier is re-probed); 0 (unknown) degrades arbitration to
        single-chunk behavior."""
        if self._prefill_chunk is None or self._prefill_chunk_ep != endpoint:
            try:
                stats = _get_json(f"http://{endpoint}/v1/stats")
                self._prefill_chunk = int(stats.get("prefill_chunk") or 0)
                self._prefill_chunk_ep = endpoint
            except Exception:
                return self._prefill_chunk or 0
        return self._prefill_chunk

    # --------------------------------------------------------------- routing
    def _pick_decode(self, prompt: list, candidates: list[dict]):
        """Affinity first, least-loaded on ties; a worker that fails its
        probe drops out of this decision AND feeds its circuit breaker —
        enough consecutive failures evict it from the fleet."""
        probed = []
        for worker in candidates:
            try:
                answer = _post_json(
                    f"http://{worker['endpoint']}/v1/prefixes",
                    {"prompt": prompt},
                )
                probed.append((worker, int(answer.get("match_tokens", 0)),
                               int(answer.get("in_flight", 0))))
                self._probe_ok(worker["endpoint"])
            except Exception as exc:
                logger.warning(
                    f"serving worker {worker['endpoint']} failed its affinity "
                    f"probe ({exc!r}); routing around it"
                )
                self._probe_failed(worker["endpoint"])
        if not probed:
            return None, 0
        best_match = max(match for _, match, _ in probed)
        tied = [(w, m, load) for w, m, load in probed if m == best_match]
        worker = min(tied, key=lambda t: t[2])[0]
        return worker, best_match

    def route(self, request: dict, rid: int | None = None, exclude=()):
        """One admission (or re-dispatch) decision: assign the fleet rid,
        arbitrate the entry tier, pick workers, and return ``(rid, url,
        outbound_request)`` — the relay target. ``rid`` not-None marks a
        retry leg (same rid, no re-admission bookkeeping); ``exclude`` drops
        endpoints that already failed this request. Raises RuntimeError when
        no worker can serve (the 503 shed path)."""
        prompt = list(request.get("prompt") or [])
        if not prompt:
            raise ValueError("empty or missing 'prompt'")
        workers = self._available(self.workers())
        workers = [w for w in workers if w["endpoint"] not in exclude]
        decode_candidates = [w for w in workers
                             if w["role"] in ("decode", "unified")]
        prefill_candidates = [w for w in workers if w["role"] == "prefill"]
        _, _, degraded, _ = _fault_counters()
        if not decode_candidates:
            # The ladder's floor: nothing can decode — shed fast, explicitly.
            degraded.inc(mode="no_decode")
            raise RuntimeError(
                "no decode-capable serving worker available "
                f"({len(workers)} workers known)"
            )
        decode_worker, match = self._pick_decode(prompt, decode_candidates)
        if decode_worker is None:
            degraded.inc(mode="no_decode")
            raise RuntimeError("every decode-capable worker failed its probe")
        prefill_chunk = (
            self._prefill_chunk_of(prefill_candidates[0]["endpoint"])
            if prefill_candidates else (self._prefill_chunk or 0)
        )
        tier = arbitrate_serving_tier(
            len(prompt), self.slo, prefill_chunk=prefill_chunk,
            have_prefill_tier=bool(prefill_candidates),
        )
        if prefill_candidates:
            self._had_prefill_tier = True
        elif (self._had_prefill_tier and prefill_chunk > 0
                and len(prompt) > prefill_chunk):
            # Rung one of the ladder: this prompt would have entered the
            # prefill tier, but that tier is gone — decode-as-unified, booked
            # so the degradation is explicit, not silent.
            degraded.inc(mode="prefill_lost")
            from ..telemetry.flight import get_flight_recorder

            get_flight_recorder().record(
                "serving_degraded", mode="prefill_lost",
                prompt_tokens=len(prompt),
            )
        first_leg = rid is None
        if first_leg:
            with self._lock:
                rid = self._next_rid
                self._next_rid += 1
            if self.tracer is not None:
                self.tracer.submit(rid, len(prompt), tier="router")
                self.tracer.admit(rid, decision=f"route_{tier}",
                                  aliased_blocks=0, chunks=1)
            routed, affinity_hits = _router_counters()
            routed.inc(tier=tier)
            if match > 0:
                affinity_hits.inc()
        outbound = {key: value for key, value in request.items()
                    if key != "request_id"}
        outbound["request_id"] = rid
        if tier == "prefill":
            prefill_worker = min(
                prefill_candidates,
                key=lambda w: self._in_flight_of(w["endpoint"]),
            )
            outbound["decode_endpoint"] = decode_worker["endpoint"]
            # Re-handoff targets, preference order: a failed import tries the
            # next surviving decode worker without re-prefilling.
            outbound["decode_endpoints"] = (
                [decode_worker["endpoint"]]
                + [w["endpoint"] for w in decode_candidates
                   if w["endpoint"] != decode_worker["endpoint"]]
            )
            return rid, f"http://{prefill_worker['endpoint']}/v1/generate", outbound
        return rid, f"http://{decode_worker['endpoint']}/v1/generate", outbound

    def _in_flight_of(self, endpoint: str) -> int:
        try:
            return int(_get_json(f"http://{endpoint}/v1/stats")["in_flight"])
        except Exception:
            return 1 << 30  # unprobeable: route around it when possible

    # ------------------------------------------------------------- provider
    def handle_get(self, path: str, query: dict):
        if path == "/v1/stats":
            body = json.dumps(self.stats()).encode()
            return (200, "application/json", body)
        return None

    def handle_post(self, path: str, query: dict, body: bytes):
        if path != "/v1/generate":
            return None
        request = json.loads(body or b"{}")
        # End-to-end deadline: the client's timeout_s (or the stream-timeout
        # default) becomes a wall-clock deadline every downstream dispatch
        # carries — a retried request never outlives what the client waits.
        if request.get("deadline_wall") is None:
            timeout_s = float(request.get("timeout_s") or STREAM_TIMEOUT_S)
            request["deadline_wall"] = time.time() + timeout_s
        try:
            rid, url, outbound = self.route(request)
        except ValueError as exc:
            return ("json", 400, {"error": str(exc), "retryable": False})
        except RuntimeError as exc:
            return self._shed(exc)
        return ("sse", self._relay_with_retry(rid, request, url, outbound))

    def _shed(self, exc, rid=None):
        """The ladder's floor: a fast, explicit 503 with a retry hint — and
        the shed booked through the SLO sentinel, so availability loss lands
        in the same counter/flight/warning path as every other breach."""
        from ..telemetry.slo import record_breach

        record_breach("availability", 1.0, 0.0, rid=rid)
        return ("json", 503, {
            "error": str(exc),
            "retryable": True,
            "retry_after_s": self.retry_after_s,
        })

    def _finalize(self, rid: int, done: dict) -> dict:
        if self.tracer is not None:
            self.tracer.finish(rid, len(done.get("tokens", [])),
                               tpot_s=done.get("tpot_s"))
            record = next(
                (r for r in self.tracer.records() if r["rid"] == rid),
                None,
            )
            if record is not None:
                done["trace"] = [record] + done.get("trace", [])
        return done

    def _relay_with_retry(self, rid: int, request: dict, url: str,
                          outbound: dict):
        """The relay generator behind every routed request: stream the
        chosen worker's SSE frames through, and on a retryable failure
        (connect error, retryable error frame, or a stream that ends without
        ``done``/``error``) re-route to a surviving worker under the SAME
        rid with exponential backoff, inside the retry budget and deadline.

        Token deltas are de-duplicated across legs: a retried worker replays
        the whole generation (greedy decode is deterministic, so the replay
        is bit-identical), and only the not-yet-delivered tail is forwarded —
        the client sees ONE contiguous stream. A terminal frame (``done`` or
        ``error``) is guaranteed on every path."""
        deadline_wall = float(outbound.get("deadline_wall")
                              or time.time() + STREAM_TIMEOUT_S)
        retries, _, _, _ = _fault_counters()
        delivered = 0   # token deltas already forwarded to the client
        attempt = 0
        failed: set[str] = set()
        while True:
            endpoint = url.split("/")[2]
            leg_seen = 0
            failure = None
            timeout_s = max(0.05, deadline_wall - time.time())
            req = urllib.request.Request(
                url, data=json.dumps(outbound).encode(),
                headers={"Content-Type": "application/json"},
            )
            response = None
            try:
                response = urllib.request.urlopen(req, timeout=timeout_s)
            except urllib.error.HTTPError as exc:
                try:
                    detail = json.loads(exc.read().decode("utf-8", "replace"))
                except Exception:
                    detail = {}
                if detail.get("retryable") is False:
                    yield sse_event("error", {
                        "rid": rid, "retryable": False,
                        "error": detail.get("error", str(exc)),
                    })
                    if self.tracer is not None:
                        self.tracer.cancel(rid)
                    return
                failure = "dispatch_failed"
            except Exception:
                failure = "dispatch_failed"
            if response is not None:
                from .frontend import iter_sse

                try:
                    with response:
                        for kind, data in iter_sse(response):
                            if kind == "tokens":
                                payload = json.loads(data)
                                tokens = payload.get("tokens", [])
                                start = leg_seen
                                leg_seen += len(tokens)
                                if leg_seen <= delivered:
                                    continue  # replayed prefix: already sent
                                fresh = tokens[max(0, delivered - start):]
                                delivered = leg_seen
                                yield sse_event("tokens",
                                                {"rid": rid, "tokens": fresh})
                            elif kind == "done":
                                try:
                                    payload = self._finalize(rid,
                                                             json.loads(data))
                                except (ValueError, TypeError):
                                    yield f"event: done\ndata: {data}\n\n"
                                    return
                                yield sse_event("done", payload)
                                return
                            elif kind == "error":
                                payload = json.loads(data)
                                if payload.get("retryable", True):
                                    failure = "worker_error"
                                    break
                                payload.setdefault("rid", rid)
                                yield sse_event("error", payload)
                                if self.tracer is not None:
                                    self.tracer.cancel(rid)
                                return
                            else:
                                yield f"event: {kind}\ndata: {data}\n\n"
                except Exception:
                    failure = "stream_broken"
                if failure is None:
                    # EOF without a terminal frame: the worker died mid-stream.
                    failure = "stream_broken"
            # ---------------------------------------------------- retry leg
            self._probe_failed(endpoint)
            failed.add(endpoint)
            attempt += 1
            remaining = deadline_wall - time.time()
            if attempt > self.retry_budget or remaining <= 0:
                reason = ("deadline exceeded" if remaining <= 0
                          else f"retry budget ({self.retry_budget}) exhausted")
                yield sse_event("error", {
                    "rid": rid, "retryable": False,
                    "error": f"request failed after {attempt} dispatch(es): "
                             f"{failure}; {reason}",
                })
                if self.tracer is not None:
                    self.tracer.cancel(rid)
                return
            retries.inc(reason=failure)
            if self.tracer is not None:
                self.tracer.retry(rid, attempt, failure, endpoint=endpoint)
            backoff = min(self.backoff_cap_s,
                          self.backoff_base_s * (2 ** (attempt - 1)))
            time.sleep(min(backoff, max(0.0, remaining)))
            try:
                _, url, outbound = self.route(request, rid=rid, exclude=failed)
            except (ValueError, RuntimeError) as exc:
                # No surviving worker for the retry: shed explicitly (booked
                # like any availability loss), terminal error to the client.
                from ..telemetry.slo import record_breach

                record_breach("availability", 1.0, 0.0, rid=rid)
                yield sse_event("error", {
                    "rid": rid, "retryable": True,
                    "retry_after_s": self.retry_after_s,
                    "error": f"no surviving worker for retry: {exc}",
                })
                if self.tracer is not None:
                    self.tracer.cancel(rid)
                return

    def stats(self) -> dict:
        routed, affinity_hits = _router_counters()
        retries, evictions, degraded, _ = _fault_counters()
        by_tier = {key[0]: int(v)
                   for key, v in routed.series_values().items()}
        total = sum(by_tier.values())
        hits = int(affinity_hits.value())
        return {
            "role": "router",
            "workers": self.workers(),
            "routed": by_tier,
            "affinity_hits": hits,
            "affinity_hit_rate": round(hits / total, 6) if total else None,
            "retries": {key[0]: int(v)
                        for key, v in retries.series_values().items()},
            "evictions": dict(self._evicted),
            "evictions_total": {key[0]: int(v)
                                for key, v in evictions.series_values().items()},
            "degraded": {key[0]: int(v)
                         for key, v in degraded.series_values().items()},
            "breakers": {endpoint: breaker.state
                         for endpoint, breaker in self._breakers.items()},
            "retry_budget": self.retry_budget,
        }
