"""Streaming HTTP front end over one serving engine — the /v1/* worker API.

One :class:`ServingFrontend` wraps one ``ContinuousBatcher`` and installs
itself as the serving provider on the SAME HTTP server the process already
runs for ``/metrics`` (telemetry/metrics.py routes ``/v1/*`` here), so a
serving worker exposes generation, prefix-affinity answers, and load stats
on the one port the fleet registry already publishes:

- ``POST /v1/generate`` — submit a prompt, stream its tokens back as SSE
  events (``tokens`` deltas at the engine's sync cadence, then ONE ``done``
  event carrying the authoritative output plus the request's tracer record —
  TTFT/TPOT ride every stream's final event). On a ``prefill`` worker this
  instead runs prefill to completion, ships the chain to the request's
  decode host (:mod:`.handoff`), and RELAYS that host's stream, prepending
  its own tier record to the final event's trace.
- ``POST /v1/import`` — decode tier: splice a shipped chain in and stream
  the request's decode exactly as if it had prefilled locally.
- ``POST /v1/prefixes`` / ``GET /v1/stats`` — the router's affinity and
  least-loaded routing feeds (both pure host lookups; a routing decision
  never touches a device).

Threading: HTTP handler threads only QUEUE work (``submit`` appends to the
engine's deque; imports land in a staging queue) and then block on per-rid
subscriber queues; one background loop thread owns every engine dispatch —
it drains staged imports between waves and calls ``engine.run()`` whenever
work is in flight. The engine's one-window-lookahead loop keeps its
zero-blocking-transfer discipline; streaming rides the report it already
fetches (serving.py ``_process_report``).

Failure semantics (docs/serving.md "Failure semantics"):

- Every ``error`` frame carries a ``retryable`` flag (can the router/client
  re-dispatch this request and expect a different outcome?), and a terminal
  frame (``done`` or ``error``) is guaranteed on every path — a mid-stream
  engine exception, a timed-out subscriber, and a dead downstream tier all
  close the stream explicitly, never silently.
- The worker's registration is a heartbeat-refreshed TTL lease
  (:mod:`.lease`); SIGTERM rides the preemption watcher into
  :meth:`ServingFrontend.drain` — stop admission (503 ``retryable`` with a
  retry hint), finish in-flight requests inside the grace window, revoke the
  lease, then shut down.
- A failed prefill→decode handoff re-enters on the next surviving decode
  endpoint WITHOUT re-prefilling: the export keeps the chain
  (``free=False``) until the importer acks (first non-error frame), then
  frees it — free-on-ack, so a dropped handoff never leaks pool blocks.
- The serving chaos grammar (``resilience/faults.py`` ``req:N=...``) is
  consumed here: ``worker_kill`` dies after the request's first streamed
  delta (``kill_mode`` picks a real ``os._exit`` for launcher drills or a
  soft in-process death for tests/bench), ``stall`` sleeps before admission,
  ``slow_worker`` stretches every stream event, ``handoff_drop`` loses the
  first export POST.
"""

from __future__ import annotations

import json
import os
import queue
import threading
import time
import urllib.request

import numpy as np

from ..logging import get_logger
from ..utils.transfer import host_view
from .handoff import export_chain, import_chain, release_chain, run_prefill_only
from .lease import LeaseHeartbeat, drain_grace_from_env
from .roles import ServingRole, resolve_serving_role

logger = get_logger(__name__)

# How long a subscriber waits for the next stream event before the stream
# closes with an error event — a wedged engine must not hold client
# connections (and their handler threads) forever.
STREAM_TIMEOUT_S = 300.0

# How long the drain-admission 503 tells clients/routers to back off before
# retrying AGAINST THE FLEET (the router re-routes immediately; this hint is
# for direct clients).
DRAIN_RETRY_AFTER_S = 1.0

# Per-event delay unit for the slow_worker chaos action: the injected delay
# is <mult> × this per stream event.
SLOW_WORKER_UNIT_S = 0.05

_DRAIN_COUNTER = None  # telemetry.metrics.cached_handles accessor


def _drain_counter():
    global _DRAIN_COUNTER
    if _DRAIN_COUNTER is None:
        from ..telemetry.metrics import cached_handles

        _DRAIN_COUNTER = cached_handles(lambda registry: registry.counter(
            "accelerate_serving_drained_inflight_total",
            "In-flight requests finished inside a graceful-drain grace window",
        ))
    return _DRAIN_COUNTER()


class ServingStreamError(RuntimeError):
    """An ``error`` SSE frame surfaced client-side (``read_sse_response``).
    ``retryable`` mirrors the frame's flag: True means re-submitting the
    request may succeed (worker died, stream broke, fleet draining); False
    means the request itself is unservable (bad input, deadline exceeded)."""

    def __init__(self, message: str, retryable: bool = True):
        super().__init__(message)
        self.retryable = bool(retryable)


def sse_event(kind: str, data: dict) -> str:
    """One Server-Sent Event frame (the wire contract docs/serving.md pins):
    ``event:`` names the kind, ``data:`` carries one JSON object."""
    return f"event: {kind}\ndata: {json.dumps(data)}\n\n"


def iter_sse(fp):
    """Parse an SSE byte stream into ``(kind, data_str)`` frames — the relay
    tiers' client side (router ← worker, prefill ← decode)."""
    kind, data_lines = None, []
    for raw in fp:
        line = raw.decode("utf-8", "replace").rstrip("\r\n")
        if not line:
            if data_lines:
                yield (kind or "message", "\n".join(data_lines))
            kind, data_lines = None, []
        elif line.startswith("event:"):
            kind = line[len("event:"):].strip()
        elif line.startswith("data:"):
            data_lines.append(line[len("data:"):].strip())
    if data_lines:
        yield (kind or "message", "\n".join(data_lines))


class ServingFrontend:
    """The /v1/* provider for one engine + role; see module docstring.

    ``engine`` is a paged-or-contiguous ``ContinuousBatcher`` (paged required
    for ``prefill``/``decode`` roles — disaggregation is chain surgery);
    ``role`` defaults to the launcher env contract
    (:func:`~.roles.resolve_serving_role`)."""

    # How a worker_kill chaos fault dies: "process" is the real thing
    # (os._exit mid-stream — launcher drills; exit code 0 so the gang
    # launcher doesn't take the survivors down), "stream" is the in-process
    # soft death (tests, the bench chaos lever): the stream breaks without a
    # terminal frame, the heartbeat stops so the lease expires, and every
    # subsequent handler answers 503 so health probes fail like a corpse's.
    kill_mode = "process"

    def __init__(self, engine, role: str | ServingRole | None = None,
                 stream_timeout_s: float = STREAM_TIMEOUT_S):
        if isinstance(role, ServingRole):
            self.role = role
        else:
            self.role = resolve_serving_role(role)
        if not self.role.runs_engine:
            raise ValueError(
                "the router role runs no engine; use serving_net.Router"
            )
        if self.role.name in ("prefill", "decode") and not engine.paged:
            raise ValueError(
                f"serving role {self.role.name!r} requires a paged engine "
                "(disaggregation is block-chain surgery)"
            )
        self.engine = engine
        self.stream_timeout_s = float(stream_timeout_s)
        self._lock = threading.Lock()          # engine submission/surgery
        self._streams: dict[int, queue.Queue] = {}
        self._deadlines: dict[int, float] = {}  # rid -> deadline (wall clock)
        self._imports: queue.Queue = queue.Queue()
        self._wake = threading.Condition()
        self._shutdown = threading.Event()
        self._draining = threading.Event()
        self._thread: threading.Thread | None = None
        self._watch_thread: threading.Thread | None = None
        self._heartbeat: LeaseHeartbeat | None = None
        self._watcher = None
        self._server = None
        self._process_index = 0
        # Serving chaos state (resilience/faults.py req: grammar): the
        # frontend counts ITS OWN admission events (/v1/generate +
        # /v1/import, in arrival order) and handoff exports, so req:N
        # indexes are deterministic per worker.
        self._req_seq = 0
        self._handoff_seq = 0
        self._kill_rids: set[int] = set()
        self._slow: dict[int, float] = {}  # rid -> injected per-event delay
        self._killed = False
        engine.stream = self._on_stream

    # ------------------------------------------------------------ lifecycle
    def install(self, process_index: int = 0, start_loop: bool | None = None,
                server=None, endpoint: str | None = None):
        """Become the process's serving provider: route ``/v1/*`` here,
        publish the role gauge (``accelerate_serving_role{role=}`` — what
        /fleet tier rollups group hosts by), start the lease heartbeat that
        keeps the worker's role+endpoint registration alive in the serving
        KV namespace (what the router discovers — :mod:`.lease`), arm the
        preemption watcher so SIGTERM drains instead of dropping streams,
        and start the engine loop thread (decoding roles; a prefill worker
        dispatches synchronously per request, so it needs no loop).
        ``server`` attaches to one specific
        :class:`~..telemetry.metrics.MetricsServer` instead of the
        process-global route (multi-role single-process rigs)."""
        from ..telemetry.metrics import get_registry, set_serving_provider

        self._process_index = int(process_index)
        self._server = server
        if server is not None:
            server.set_serving(self)
            if endpoint is None and server.port is not None:
                endpoint = f"127.0.0.1:{server.port}"
        else:
            set_serving_provider(self)
        get_registry().gauge(
            "accelerate_serving_role",
            "Serving tier this process runs (1 = the labeled role)",
            labelnames=("role",),
        ).set(1, role=self.role.name)
        from ..telemetry.fleet import metrics_endpoint

        lease_endpoint = endpoint or metrics_endpoint()
        if lease_endpoint is not None:
            self._heartbeat = LeaseHeartbeat(
                self.role.name, process_index, lease_endpoint
            ).start()
        try:
            # Signal handlers are main-thread-only; a frontend installed off
            # the main thread still drains when something else (PartialState)
            # installed the watcher, or when drain() is called directly.
            from ..resilience.preemption import get_default_watcher

            self._watcher = get_default_watcher(install=True)
        except Exception:
            self._watcher = None
        if start_loop is None:
            start_loop = self.role.decodes
        if start_loop and self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="at-serving-loop", daemon=True
            )
            self._thread.start()
        if self._watcher is not None and self._watch_thread is None:
            self._watch_thread = threading.Thread(
                target=self._watch_preemption, name="at-serving-drain",
                daemon=True,
            )
            self._watch_thread.start()
        return self

    def uninstall(self):
        if self._heartbeat is not None:
            self._heartbeat.stop(revoke=True)
            self._heartbeat = None
        if self._server is not None:
            self._server.set_serving(None)
            self._server = None
        else:
            from ..telemetry.metrics import set_serving_provider

            set_serving_provider(None)
        self._shutdown.set()
        with self._wake:
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ----------------------------------------------------------------- drain
    def _watch_preemption(self):
        """Poll the preemption watcher's sticky flag; SIGTERM → drain. Runs
        on its own daemon thread so prefill workers (no engine loop) drain
        too."""
        while not self._shutdown.is_set():
            try:
                if self._watcher.poll():
                    self.drain()
                    return
            except Exception:
                return
            self._shutdown.wait(timeout=0.2)

    def drain(self, grace_s: float | None = None):
        """Graceful shutdown, in contract order (docs/serving.md "Failure
        semantics"): (1) stop admission — new ``/v1/*`` work answers 503
        ``retryable`` with a retry hint while in-flight streams keep
        flowing; (2) wait up to ``grace_s`` (default
        ``ACCELERATE_DRAIN_GRACE_S``) for in-flight requests to finish,
        booking how many did into
        ``accelerate_serving_drained_inflight_total``; (3) revoke the lease
        (the router sees the worker gone on its next discovery, not a TTL
        later) and shut the loop down. Idempotent; callable from any
        thread."""
        if self._draining.is_set():
            return
        self._draining.set()
        grace = float(grace_s if grace_s is not None else drain_grace_from_env())
        in_flight_at_start = self.in_flight()
        logger.warning(
            f"serving worker draining ({self.role.name}): admission stopped, "
            f"{in_flight_at_start} in flight, grace {grace:.1f}s"
        )
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline and self.in_flight() > 0:
            self._notify()
            time.sleep(0.05)
        still_in_flight = self.in_flight()
        drained = max(0, in_flight_at_start - still_in_flight)
        if drained:
            _drain_counter().inc(drained)
        from ..telemetry.flight import get_flight_recorder

        get_flight_recorder().record(
            "serving_drain", role=self.role.name,
            in_flight_at_sigterm=int(in_flight_at_start),
            drained=int(drained), abandoned=int(still_in_flight),
        )
        self.uninstall()

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    # ----------------------------------------------------------------- chaos
    def _next_req_seq(self) -> int:
        with self._lock:
            seq = self._req_seq
            self._req_seq += 1
        return seq

    def _take_admission_fault(self):
        """Consume an admission-indexed serving fault for this worker's next
        request; ``stall`` sleeps here (pre-admission, before any lock), the
        other actions are applied per-rid by :meth:`_arm_request_fault`."""
        from ..resilience.faults import serving_fault

        fault = serving_fault(self._next_req_seq(),
                              "worker_kill", "stall", "slow_worker")
        if fault is not None and fault.action == "stall":
            time.sleep(fault.stall_s)
        return fault

    def _arm_request_fault(self, fault, rid: int):
        """``worker_kill`` arms death after the rid's first streamed delta;
        ``slow_worker`` stretches its stream events."""
        if fault is None:
            return
        if fault.action == "worker_kill":
            self._kill_rids.add(rid)
        elif fault.action == "slow_worker":
            self._slow[rid] = fault.slow_factor * SLOW_WORKER_UNIT_S

    def _die(self):
        """The worker_kill chaos action fires: a hard ``os._exit(0)`` under
        the real launcher (exit 0 so the gang supervisor leaves the
        survivors up — the point is proving THEIR recovery), or the soft
        in-process death (see ``kill_mode``)."""
        logger.warning(f"chaos worker_kill firing ({self.kill_mode} mode)")
        if self.kill_mode == "process":
            os._exit(0)
        self._killed = True
        if self._heartbeat is not None:
            self._heartbeat.stop(revoke=False)  # a crash revokes nothing
            self._heartbeat = None

    def _refuse(self, why: str, retry_after_s: float | None = None):
        detail = {"error": why, "retryable": True}
        if retry_after_s is not None:
            detail["retry_after_s"] = retry_after_s
        return ("json", 503, detail)

    # ---------------------------------------------------------- engine loop
    def _loop(self):
        """The one thread that dispatches engine work: drain staged imports
        (chain surgery must not race a live wave's donated state tuple),
        then run the wave whenever anything is in flight."""
        while not self._shutdown.is_set():
            did_work = False
            while True:
                try:
                    payload, endpoint = self._imports.get_nowait()
                except queue.Empty:
                    break
                did_work = True
                try:
                    import_chain(self.engine, payload, endpoint=endpoint)
                except Exception as exc:
                    logger.warning(f"chain import failed: {exc!r}")
                    self._push(int(payload.get("rid", -1)),
                               ("error", f"import failed: {exc}"))
            if self.engine.in_flight() > 0:
                did_work = True
                try:
                    self.engine.run()
                except Exception as exc:
                    logger.warning(f"serving engine wave failed: {exc!r}")
                    for rid in list(self._streams):
                        self._push(rid, ("error", f"engine error: {exc}"))
            if not did_work:
                with self._wake:
                    self._wake.wait(timeout=0.05)

    def _notify(self):
        with self._wake:
            self._wake.notify_all()

    # ------------------------------------------------------------- streaming
    def _on_stream(self, rid: int, tokens: np.ndarray, final: bool):
        """The engine's streaming sink (runs on the loop thread, fed from
        the report the loop already fetches)."""
        kind = "final" if final else "tokens"
        self._push(rid, (kind, [int(t) for t in host_view(tokens).reshape(-1)]))

    def _push(self, rid: int, item):
        subscriber = self._streams.get(rid)
        if subscriber is not None:
            subscriber.put(item)

    def _trace_record(self, rid: int) -> dict | None:
        """This tier's tracer record for ``rid`` — what rides the final SSE
        event so the client (and each relay tier) assembles the cross-tier
        trace without scraping anything."""
        tracer = self.engine.tracer
        if tracer is None:
            return None
        for record in tracer.records():
            if record["rid"] == rid:
                return record
        return None

    def _stream_response(self, rid: int):
        """The SSE generator behind a local (non-relayed) request: token
        deltas as they land, then the ``done`` frame with the authoritative
        output + this tier's trace record (TTFT/TPOT inside). A terminal
        frame is GUARANTEED on every path — timeout, engine error, deadline,
        and unexpected exception all close with an ``error`` frame carrying
        ``retryable``."""
        subscriber = self._streams[rid]
        slow_s = self._slow.get(rid)
        streamed_any = False
        try:
            while True:
                deadline_wall = self._deadlines.get(rid)
                wait_s = self.stream_timeout_s
                if deadline_wall is not None:
                    wait_s = min(wait_s, max(0.01, deadline_wall - time.time()))
                try:
                    kind, payload = subscriber.get(timeout=wait_s)
                except queue.Empty:
                    if deadline_wall is not None and time.time() >= deadline_wall:
                        yield sse_event("error", {
                            "rid": rid, "retryable": False,
                            "error": "request deadline exceeded",
                        })
                    else:
                        yield sse_event("error", {
                            "rid": rid, "retryable": True,
                            "error": f"stream timed out after "
                                     f"{self.stream_timeout_s}s",
                        })
                    return
                if slow_s:
                    time.sleep(slow_s)
                if kind == "error":
                    yield sse_event("error", {"rid": rid, "error": payload,
                                              "retryable": True})
                    return
                if kind == "final":
                    record = self._trace_record(rid)
                    yield sse_event("done", {
                        "rid": rid,
                        "tokens": payload,
                        "ttft_s": (record or {}).get("ttft_s"),
                        "tpot_s": (record or {}).get("tpot_s"),
                        "trace": [record] if record else [],
                    })
                    return
                yield sse_event("tokens", {"rid": rid, "tokens": payload})
                streamed_any = True
                if rid in self._kill_rids and streamed_any:
                    # worker_kill: die AFTER the client saw a delta, so the
                    # drill proves retry de-duplication, not just re-dispatch.
                    self._kill_rids.discard(rid)
                    self._die()
                    return  # soft mode: stream breaks, no terminal frame
        except GeneratorExit:
            raise
        except Exception as exc:  # the terminal-frame guarantee
            logger.warning(f"serving stream for rid {rid} failed: {exc!r}")
            yield sse_event("error", {"rid": rid, "retryable": True,
                                      "error": f"stream failed: {exc}"})
        finally:
            self._streams.pop(rid, None)
            self._deadlines.pop(rid, None)
            self._slow.pop(rid, None)

    # ------------------------------------------------------------- handlers
    def handle_get(self, path: str, query: dict):
        if self._killed:
            return (503, "application/json",
                    json.dumps({"error": "worker killed (chaos)"}).encode())
        if path == "/v1/stats":
            body = json.dumps(self.stats()).encode()
            return (200, "application/json", body)
        return None

    def handle_post(self, path: str, query: dict, body: bytes):
        if self._killed:
            return self._refuse("worker killed (chaos)")
        if path == "/v1/prefixes":
            if self._draining.is_set():
                # A draining worker must drop out of routing decisions too.
                return self._refuse("worker draining",
                                    retry_after_s=DRAIN_RETRY_AFTER_S)
            request = json.loads(body or b"{}")
            prompt = np.asarray(request.get("prompt", []), np.int32)
            return ("json", 200, {
                "match_tokens": self.engine.prefix_match_tokens(prompt),
                "in_flight": self.in_flight(),
                "role": self.role.name,
            })
        if path == "/v1/generate":
            if self._draining.is_set():
                return self._refuse("worker draining: admission stopped",
                                    retry_after_s=DRAIN_RETRY_AFTER_S)
            return self._handle_generate(json.loads(body or b"{}"))
        if path == "/v1/import":
            if not self.role.decodes:
                return ("json", 409, {
                    "error": f"role {self.role.name!r} does not decode",
                    "retryable": False,
                })
            if self._draining.is_set():
                return self._refuse("worker draining: admission stopped",
                                    retry_after_s=DRAIN_RETRY_AFTER_S)
            payload = json.loads(body or b"{}")
            rid = int(payload["rid"])
            self._arm_request_fault(self._take_admission_fault(), rid)
            self._streams[rid] = queue.Queue()
            deadline_wall = payload.get("deadline_wall")
            if deadline_wall is not None:
                self._deadlines[rid] = float(deadline_wall)
            self._imports.put((payload, None))
            self._notify()
            return ("sse", self._stream_response(rid))
        return None

    def in_flight(self) -> int:
        """Client-visible in-flight count: requests admitted whose stream
        has not yet delivered its terminal frame. Strictly ≥ the engine's
        own count — a slow subscriber keeps a request in flight after the
        engine freed its slot, and drain must wait for delivery, not just
        for compute."""
        return max(self.engine.in_flight(), len(self._streams))

    def stats(self) -> dict:
        """The least-loaded routing feed (host bookkeeping only)."""
        return {
            "role": self.role.name,
            "in_flight": self.in_flight(),
            "prefill_chunk": getattr(self.engine, "prefill_chunk", None),
            "pool": self.engine.pool_stats(),
            "draining": self._draining.is_set(),
        }

    def _handle_generate(self, request: dict):
        prompt = np.asarray(request.get("prompt", []), np.int32).reshape(-1)
        if prompt.size == 0:
            return ("json", 400, {"error": "empty or missing 'prompt'",
                                  "retryable": False})
        deadline_wall = request.get("deadline_wall")
        if deadline_wall is not None and time.time() >= float(deadline_wall):
            # Deadlines propagate end-to-end; admitting dead-on-arrival work
            # would only burn decode slots the survivors need.
            return ("json", 400, {"error": "request deadline exceeded",
                                  "retryable": False})
        kwargs = {}
        for key in ("max_new_tokens", "eos_token_id"):
            if request.get(key) is not None:
                kwargs[key] = int(request[key])
        if request.get("temperature") is not None:
            kwargs["temperature"] = float(request["temperature"])
        if request.get("stop_sequences"):
            kwargs["stop_sequences"] = [
                np.asarray(s, np.int32) for s in request["stop_sequences"]
            ]
        fault = self._take_admission_fault()
        with self._lock:
            # The rid is reserved BEFORE submit so the subscriber queue
            # exists when the loop thread emits the first delta — a
            # router-assigned request_id threads through unchanged (one rid
            # across every tier it crosses).
            rid = (int(request["request_id"])
                   if request.get("request_id") is not None
                   else self.engine._next_rid)
            self._arm_request_fault(fault, rid)
            if self.role.name == "prefill":
                decode_endpoint = request.get("decode_endpoint")
                if not decode_endpoint:
                    return ("json", 400, {
                        "error": "prefill tier needs 'decode_endpoint' "
                                 "(where the finished chain ships)",
                        "retryable": False,
                    })
                self.engine.submit(prompt, request_id=rid,
                                   tier=self.role.name, **kwargs)
                return ("sse", self._relay_prefill(
                    rid, decode_endpoint,
                    alternates=request.get("decode_endpoints") or (),
                    deadline_wall=deadline_wall,
                ))
            self._streams[rid] = queue.Queue()
            if deadline_wall is not None:
                self._deadlines[rid] = float(deadline_wall)
            self.engine.submit(prompt, request_id=rid, tier=self.role.name,
                               **kwargs)
        self._notify()
        return ("sse", self._stream_response(rid))

    # ---------------------------------------------------------------- relay
    def _relay_prefill(self, rid: int, decode_endpoint: str,
                       alternates=(), deadline_wall: float | None = None):
        """The prefill tier's generate path: run this request's chunked
        prefill to completion (no decode window ever dispatches here), ship
        the chain, then relay the decode host's stream — prepending this
        tier's record to the final event's trace, so the client's one trace
        spans prefill chunks AND the handoff leg.

        Free-on-ack re-handoff: the export keeps the chain resident
        (``free=False``); the first non-error frame from a decode import is
        the ack that frees it. A failed import (dead host, dropped POST —
        the ``handoff_drop`` chaos action) moves to the next surviving
        decode endpoint in ``alternates`` WITHOUT re-prefilling; exhausting
        them surfaces a retryable error (the router's retry re-enters
        prefill), and the chain is released on every exit path — a failed
        handoff never leaks pool blocks."""
        try:
            with self._lock:
                run_prefill_only(self.engine, rid)
                payload = export_chain(self.engine, rid,
                                       endpoint=decode_endpoint, free=False)
        except Exception as exc:
            logger.warning(f"prefill for request {rid} failed: {exc!r}")
            yield sse_event("error", {"rid": rid, "error": str(exc),
                                      "retryable": True})
            return
        if deadline_wall is not None:
            payload["deadline_wall"] = float(deadline_wall)

        def finalize(done: dict) -> dict:
            record = self._trace_record(rid)
            if record is not None:
                done["trace"] = [record] + done.get("trace", [])
            return done

        from ..resilience.faults import serving_fault

        with self._lock:
            handoff_seq = self._handoff_seq
            self._handoff_seq += 1
        dropped = serving_fault(handoff_seq, "handoff_drop")
        targets = [decode_endpoint] + [ep for ep in alternates
                                       if ep != decode_endpoint]
        acked = False
        try:
            for attempt, endpoint in enumerate(targets):
                if dropped is not None and attempt == 0:
                    # The chaos action: this POST never happens — exactly a
                    # payload lost on the wire before the importer saw it.
                    logger.warning(
                        f"chaos handoff_drop: dropping export of rid {rid} "
                        f"to {endpoint}"
                    )
                    self._book_handoff_retry(rid, attempt + 1, endpoint)
                    continue
                url = f"http://{endpoint}/v1/import"
                try:
                    req = urllib.request.Request(
                        url, data=json.dumps(payload).encode(),
                        headers={"Content-Type": "application/json"},
                    )
                    response = urllib.request.urlopen(
                        req, timeout=self.stream_timeout_s)
                except Exception as exc:
                    logger.warning(
                        f"handoff of rid {rid} to {endpoint} failed: {exc!r}"
                    )
                    self._book_handoff_retry(rid, attempt + 1, endpoint)
                    continue
                leg_failed = False
                with response:
                    for kind, data in iter_sse(response):
                        if not acked:
                            if kind == "error":
                                detail = json.loads(data)
                                if detail.get("retryable") is False:
                                    # Unservable anywhere: surface as-is.
                                    with self._lock:
                                        release_chain(self.engine, rid)
                                    acked = True  # chain handled
                                    detail.setdefault("rid", rid)
                                    yield sse_event("error", detail)
                                    return
                                logger.warning(
                                    f"decode import of rid {rid} on "
                                    f"{endpoint} refused: {detail.get('error')}"
                                )
                                self._book_handoff_retry(rid, attempt + 1,
                                                         endpoint)
                                leg_failed = True
                                break
                            # First non-error frame: the importer owns the
                            # chain now — free our copy (free-on-ack).
                            acked = True
                            with self._lock:
                                release_chain(self.engine, rid)
                        if kind == "done" and finalize is not None:
                            try:
                                done = finalize(json.loads(data))
                                yield sse_event("done", done)
                                continue
                            except (ValueError, TypeError):
                                pass
                        yield f"event: {kind}\ndata: {data}\n\n"
                if acked:
                    return
                if not leg_failed:
                    # Stream ended before any frame: the importer died
                    # between accepting the POST and streaming.
                    self._book_handoff_retry(rid, attempt + 1, endpoint)
            yield sse_event("error", {
                "rid": rid, "retryable": True,
                "error": f"handoff failed on all {len(targets)} decode "
                         "endpoint(s)",
            })
        finally:
            if not acked:
                with self._lock:
                    release_chain(self.engine, rid)

    def _book_handoff_retry(self, rid: int, attempt: int, endpoint: str):
        """One failed handoff leg: the shared retries counter (reason
        ``handoff_failed``), this tier's tracer retry leg, and the flight
        recorder (via the tracer)."""
        from .router import _fault_counters

        retries, _, _, _ = _fault_counters()
        retries.inc(reason="handoff_failed")
        if self.engine.tracer is not None:
            self.engine.tracer.retry(rid, attempt, "handoff_failed",
                                     endpoint=endpoint)


def relay_generate(url: str, request: dict, finalize=None,
                   timeout_s: float = STREAM_TIMEOUT_S):
    """POST ``request`` to a downstream tier's SSE endpoint and re-yield its
    stream. ``finalize(done_payload) -> done_payload`` rewrites the final
    event as it passes through — each relay tier prepends its own tracer
    record to the ``trace`` list there, which is how the client's one trace
    comes to span router admission → prefill chunks → chain handoff → decode
    — the one relay primitive the prefill tier and the router share."""
    req = urllib.request.Request(
        url, data=json.dumps(request).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        response = urllib.request.urlopen(req, timeout=timeout_s)
    except Exception as exc:
        yield sse_event("error", {
            "error": f"downstream tier {url} unreachable: {exc}",
            "retryable": True,
        })
        return
    with response:
        for kind, data in iter_sse(response):
            if kind == "done" and finalize is not None:
                try:
                    payload = finalize(json.loads(data))
                    yield sse_event("done", payload)
                    continue
                except (ValueError, TypeError):
                    pass
            yield f"event: {kind}\ndata: {data}\n\n"


def read_sse_response(fp) -> dict:
    """Drain one generate stream client-side: returns ``{"tokens": [...],
    "deltas": [...], "done": {...}}`` — the drill's and the tests' client
    helper, so they consume the REAL wire format, not a shortcut. An
    ``error`` frame (or a stream that dies without a terminal frame) raises
    :class:`ServingStreamError`, whose ``retryable`` mirrors the frame's
    flag so callers know whether re-submitting can help."""
    deltas, done = [], None
    for kind, data in iter_sse(fp):
        payload = json.loads(data)
        if kind == "error":
            raise ServingStreamError(
                f"serving stream error: {payload.get('error')}",
                retryable=payload.get("retryable", True),
            )
        if kind == "tokens":
            deltas.append(payload["tokens"])
        elif kind == "done":
            done = payload
    if done is None:
        raise ServingStreamError("serving stream closed without a done event",
                                 retryable=True)
    return {"tokens": done["tokens"], "deltas": deltas, "done": done}
