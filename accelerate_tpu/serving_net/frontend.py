"""Streaming HTTP front end over one serving engine — the /v1/* worker API.

One :class:`ServingFrontend` wraps one ``ContinuousBatcher`` and installs
itself as the serving provider on the SAME HTTP server the process already
runs for ``/metrics`` (telemetry/metrics.py routes ``/v1/*`` here), so a
serving worker exposes generation, prefix-affinity answers, and load stats
on the one port the fleet registry already publishes:

- ``POST /v1/generate`` — submit a prompt, stream its tokens back as SSE
  events (``tokens`` deltas at the engine's sync cadence, then ONE ``done``
  event carrying the authoritative output plus the request's tracer record —
  TTFT/TPOT ride every stream's final event). On a ``prefill`` worker this
  instead runs prefill to completion, ships the chain to the request's
  decode host (:mod:`.handoff`), and RELAYS that host's stream, prepending
  its own tier record to the final event's trace.
- ``POST /v1/import`` — decode tier: splice a shipped chain in and stream
  the request's decode exactly as if it had prefilled locally.
- ``POST /v1/prefixes`` / ``GET /v1/stats`` — the router's affinity and
  least-loaded routing feeds (both pure host lookups; a routing decision
  never touches a device).

Threading: HTTP handler threads only QUEUE work (``submit`` appends to the
engine's deque; imports land in a staging queue) and then block on per-rid
subscriber queues; one background loop thread owns every engine dispatch —
it drains staged imports between waves and calls ``engine.run()`` whenever
work is in flight. The engine's one-window-lookahead loop keeps its
zero-blocking-transfer discipline; streaming rides the report it already
fetches (serving.py ``_process_report``).
"""

from __future__ import annotations

import json
import queue
import threading
import time
import urllib.request

import numpy as np

from ..logging import get_logger
from .handoff import export_chain, import_chain, run_prefill_only
from .roles import ServingRole, resolve_serving_role

logger = get_logger(__name__)

# How long a subscriber waits for the next stream event before the stream
# closes with an error event — a wedged engine must not hold client
# connections (and their handler threads) forever.
STREAM_TIMEOUT_S = 300.0


def sse_event(kind: str, data: dict) -> str:
    """One Server-Sent Event frame (the wire contract docs/serving.md pins):
    ``event:`` names the kind, ``data:`` carries one JSON object."""
    return f"event: {kind}\ndata: {json.dumps(data)}\n\n"


def iter_sse(fp):
    """Parse an SSE byte stream into ``(kind, data_str)`` frames — the relay
    tiers' client side (router ← worker, prefill ← decode)."""
    kind, data_lines = None, []
    for raw in fp:
        line = raw.decode("utf-8", "replace").rstrip("\r\n")
        if not line:
            if data_lines:
                yield (kind or "message", "\n".join(data_lines))
            kind, data_lines = None, []
        elif line.startswith("event:"):
            kind = line[len("event:"):].strip()
        elif line.startswith("data:"):
            data_lines.append(line[len("data:"):].strip())
    if data_lines:
        yield (kind or "message", "\n".join(data_lines))


class ServingFrontend:
    """The /v1/* provider for one engine + role; see module docstring.

    ``engine`` is a paged-or-contiguous ``ContinuousBatcher`` (paged required
    for ``prefill``/``decode`` roles — disaggregation is chain surgery);
    ``role`` defaults to the launcher env contract
    (:func:`~.roles.resolve_serving_role`)."""

    def __init__(self, engine, role: str | ServingRole | None = None,
                 stream_timeout_s: float = STREAM_TIMEOUT_S):
        if isinstance(role, ServingRole):
            self.role = role
        else:
            self.role = resolve_serving_role(role)
        if not self.role.runs_engine:
            raise ValueError(
                "the router role runs no engine; use serving_net.Router"
            )
        if self.role.name in ("prefill", "decode") and not engine.paged:
            raise ValueError(
                f"serving role {self.role.name!r} requires a paged engine "
                "(disaggregation is block-chain surgery)"
            )
        self.engine = engine
        self.stream_timeout_s = float(stream_timeout_s)
        self._lock = threading.Lock()          # engine submission/surgery
        self._streams: dict[int, queue.Queue] = {}
        self._imports: queue.Queue = queue.Queue()
        self._wake = threading.Condition()
        self._shutdown = threading.Event()
        self._thread: threading.Thread | None = None
        engine.stream = self._on_stream

    # ------------------------------------------------------------ lifecycle
    def install(self, process_index: int = 0, start_loop: bool | None = None,
                server=None, endpoint: str | None = None):
        """Become the process's serving provider: route ``/v1/*`` here,
        publish the role gauge (``accelerate_serving_role{role=}`` — what
        /fleet tier rollups group hosts by) and the worker's role+endpoint
        into the serving KV namespace (what the router discovers), and start
        the engine loop thread (decoding roles; a prefill worker dispatches
        synchronously per request, so it needs no loop). ``server`` attaches
        to one specific :class:`~..telemetry.metrics.MetricsServer` instead
        of the process-global route (multi-role single-process rigs)."""
        from ..telemetry.metrics import get_registry, set_serving_provider

        if server is not None:
            server.set_serving(self)
            if endpoint is None and server.port is not None:
                endpoint = f"127.0.0.1:{server.port}"
        else:
            set_serving_provider(self)
        get_registry().gauge(
            "accelerate_serving_role",
            "Serving tier this process runs (1 = the labeled role)",
            labelnames=("role",),
        ).set(1, role=self.role.name)
        from .router import publish_serving_endpoint

        publish_serving_endpoint(self.role.name, process_index=process_index,
                                 endpoint=endpoint)
        if start_loop is None:
            start_loop = self.role.decodes
        if start_loop and self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="at-serving-loop", daemon=True
            )
            self._thread.start()
        return self

    def uninstall(self):
        from ..telemetry.metrics import set_serving_provider

        set_serving_provider(None)
        self._shutdown.set()
        with self._wake:
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ---------------------------------------------------------- engine loop
    def _loop(self):
        """The one thread that dispatches engine work: drain staged imports
        (chain surgery must not race a live wave's donated state tuple),
        then run the wave whenever anything is in flight."""
        while not self._shutdown.is_set():
            did_work = False
            while True:
                try:
                    payload, endpoint = self._imports.get_nowait()
                except queue.Empty:
                    break
                did_work = True
                try:
                    import_chain(self.engine, payload, endpoint=endpoint)
                except Exception as exc:
                    logger.warning(f"chain import failed: {exc!r}")
                    self._push(int(payload.get("rid", -1)),
                               ("error", f"import failed: {exc}"))
            if self.engine.in_flight() > 0:
                did_work = True
                try:
                    self.engine.run()
                except Exception as exc:
                    logger.warning(f"serving engine wave failed: {exc!r}")
                    for rid in list(self._streams):
                        self._push(rid, ("error", f"engine error: {exc}"))
            if not did_work:
                with self._wake:
                    self._wake.wait(timeout=0.05)

    def _notify(self):
        with self._wake:
            self._wake.notify_all()

    # ------------------------------------------------------------- streaming
    def _on_stream(self, rid: int, tokens: np.ndarray, final: bool):
        """The engine's streaming sink (runs on the loop thread, fed from
        the report the loop already fetches)."""
        kind = "final" if final else "tokens"
        self._push(rid, (kind, [int(t) for t in np.asarray(tokens).reshape(-1)]))

    def _push(self, rid: int, item):
        subscriber = self._streams.get(rid)
        if subscriber is not None:
            subscriber.put(item)

    def _trace_record(self, rid: int) -> dict | None:
        """This tier's tracer record for ``rid`` — what rides the final SSE
        event so the client (and each relay tier) assembles the cross-tier
        trace without scraping anything."""
        tracer = self.engine.tracer
        if tracer is None:
            return None
        for record in tracer.records():
            if record["rid"] == rid:
                return record
        return None

    def _stream_response(self, rid: int):
        """The SSE generator behind a local (non-relayed) request: token
        deltas as they land, then the ``done`` frame with the authoritative
        output + this tier's trace record (TTFT/TPOT inside)."""
        subscriber = self._streams[rid]
        try:
            while True:
                try:
                    kind, payload = subscriber.get(timeout=self.stream_timeout_s)
                except queue.Empty:
                    yield sse_event("error", {
                        "rid": rid,
                        "error": f"stream timed out after {self.stream_timeout_s}s",
                    })
                    return
                if kind == "error":
                    yield sse_event("error", {"rid": rid, "error": payload})
                    return
                if kind == "final":
                    record = self._trace_record(rid)
                    yield sse_event("done", {
                        "rid": rid,
                        "tokens": payload,
                        "ttft_s": (record or {}).get("ttft_s"),
                        "tpot_s": (record or {}).get("tpot_s"),
                        "trace": [record] if record else [],
                    })
                    return
                yield sse_event("tokens", {"rid": rid, "tokens": payload})
        finally:
            self._streams.pop(rid, None)

    # ------------------------------------------------------------- handlers
    def handle_get(self, path: str, query: dict):
        if path == "/v1/stats":
            body = json.dumps(self.stats()).encode()
            return (200, "application/json", body)
        return None

    def handle_post(self, path: str, query: dict, body: bytes):
        if path == "/v1/prefixes":
            request = json.loads(body or b"{}")
            prompt = np.asarray(request.get("prompt", []), np.int32)
            return ("json", 200, {
                "match_tokens": self.engine.prefix_match_tokens(prompt),
                "in_flight": self.engine.in_flight(),
                "role": self.role.name,
            })
        if path == "/v1/generate":
            return self._handle_generate(json.loads(body or b"{}"))
        if path == "/v1/import":
            if not self.role.decodes:
                return ("json", 409, {
                    "error": f"role {self.role.name!r} does not decode"
                })
            payload = json.loads(body or b"{}")
            rid = int(payload["rid"])
            self._streams[rid] = queue.Queue()
            self._imports.put((payload, None))
            self._notify()
            return ("sse", self._stream_response(rid))
        return None

    def stats(self) -> dict:
        """The least-loaded routing feed (host bookkeeping only)."""
        return {
            "role": self.role.name,
            "in_flight": self.engine.in_flight(),
            "prefill_chunk": getattr(self.engine, "prefill_chunk", None),
            "pool": self.engine.pool_stats(),
        }

    def _handle_generate(self, request: dict):
        prompt = np.asarray(request.get("prompt", []), np.int32).reshape(-1)
        if prompt.size == 0:
            return ("json", 400, {"error": "empty or missing 'prompt'"})
        kwargs = {}
        for key in ("max_new_tokens", "eos_token_id"):
            if request.get(key) is not None:
                kwargs[key] = int(request[key])
        if request.get("temperature") is not None:
            kwargs["temperature"] = float(request["temperature"])
        if request.get("stop_sequences"):
            kwargs["stop_sequences"] = [
                np.asarray(s, np.int32) for s in request["stop_sequences"]
            ]
        with self._lock:
            # The rid is reserved BEFORE submit so the subscriber queue
            # exists when the loop thread emits the first delta — a
            # router-assigned request_id threads through unchanged (one rid
            # across every tier it crosses).
            rid = (int(request["request_id"])
                   if request.get("request_id") is not None
                   else self.engine._next_rid)
            if self.role.name == "prefill":
                decode_endpoint = request.get("decode_endpoint")
                if not decode_endpoint:
                    return ("json", 400, {
                        "error": "prefill tier needs 'decode_endpoint' "
                                 "(where the finished chain ships)"
                    })
                self.engine.submit(prompt, request_id=rid,
                                   tier=self.role.name, **kwargs)
                return ("sse", self._relay_prefill(rid, decode_endpoint))
            self._streams[rid] = queue.Queue()
            self.engine.submit(prompt, request_id=rid, tier=self.role.name,
                               **kwargs)
        self._notify()
        return ("sse", self._stream_response(rid))

    # ---------------------------------------------------------------- relay
    def _relay_prefill(self, rid: int, decode_endpoint: str):
        """The prefill tier's generate path: run this request's chunked
        prefill to completion (no decode window ever dispatches here), ship
        the chain, then relay the decode host's stream — prepending this
        tier's record to the final event's trace, so the client's one trace
        spans prefill chunks AND the handoff leg."""
        try:
            with self._lock:
                run_prefill_only(self.engine, rid)
                payload = export_chain(self.engine, rid,
                                       endpoint=decode_endpoint)
        except Exception as exc:
            logger.warning(f"prefill for request {rid} failed: {exc!r}")
            yield sse_event("error", {"rid": rid, "error": str(exc)})
            return

        def finalize(done: dict) -> dict:
            record = self._trace_record(rid)
            if record is not None:
                done["trace"] = [record] + done.get("trace", [])
            return done

        yield from relay_generate(
            f"http://{decode_endpoint}/v1/import", payload, finalize=finalize
        )


def relay_generate(url: str, request: dict, finalize=None,
                   timeout_s: float = STREAM_TIMEOUT_S):
    """POST ``request`` to a downstream tier's SSE endpoint and re-yield its
    stream. ``finalize(done_payload) -> done_payload`` rewrites the final
    event as it passes through — each relay tier prepends its own tracer
    record to the ``trace`` list there, which is how the client's one trace
    comes to span router admission → prefill chunks → chain handoff → decode
    — the one relay primitive the prefill tier and the router share."""
    req = urllib.request.Request(
        url, data=json.dumps(request).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        response = urllib.request.urlopen(req, timeout=timeout_s)
    except Exception as exc:
        yield sse_event("error", {
            "error": f"downstream tier {url} unreachable: {exc}"
        })
        return
    with response:
        for kind, data in iter_sse(response):
            if kind == "done" and finalize is not None:
                try:
                    payload = finalize(json.loads(data))
                    yield sse_event("done", payload)
                    continue
                except (ValueError, TypeError):
                    pass
            yield f"event: {kind}\ndata: {data}\n\n"


def read_sse_response(fp) -> dict:
    """Drain one generate stream client-side: returns ``{"tokens": [...],
    "deltas": [...], "done": {...}}`` (raises on an ``error`` frame) — the
    drill's and the tests' client helper, so they consume the REAL wire
    format, not a shortcut."""
    deltas, done = [], None
    for kind, data in iter_sse(fp):
        payload = json.loads(data)
        if kind == "error":
            raise RuntimeError(f"serving stream error: {payload.get('error')}")
        if kind == "tokens":
            deltas.append(payload["tokens"])
        elif kind == "done":
            done = payload
    if done is None:
        raise RuntimeError("serving stream closed without a done event")
    return {"tokens": done["tokens"], "deltas": deltas, "done": done}
