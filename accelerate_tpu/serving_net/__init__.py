"""Disaggregated serving tier — the network front end over the paged engine.

The serving engine (``serving.ContinuousBatcher``) is a single-process
library; this package is what spreads one serving workload across hosts
(ROADMAP item 1, the "millions of users" half of the north star), split
along the who-runs-what-where vs how-it-lowers seam:

- :mod:`.roles` — which role a process plays (``unified`` / ``prefill`` /
  ``decode`` / ``router``), resolved from the launcher env contract.
- :mod:`.frontend` — the streaming HTTP/SSE endpoint colocated with the
  metrics server: POST /v1/generate feeds ``ContinuousBatcher.submit`` and
  streams tokens per request as SSE events, TTFT/TPOT in the final event.
- :mod:`.router` — the front door: discovers workers through the fleet KV
  namespace, routes by prefix-cache affinity (each worker's /v1/prefixes is
  a host-side lookup into its refcounted share index), falls back to
  least-loaded, and lets the SLO sentinel arbitrate which tier a request
  enters.
- :mod:`.handoff` — prefill/decode disaggregation: a dedicated prefill host
  runs chunked prefill and ships the finished KV block chain to a decode
  host via block-table surgery plus a bounded chain transfer
  (``ops.paged_attention.export_chain_blocks`` / ``import_chain_blocks``).
- :mod:`.lease` — fault tolerance's discovery substrate: worker
  registrations are heartbeat-refreshed TTL leases, so the router evicts a
  dead worker (circuit breaker + retry on a survivor under the same rid)
  instead of routing at a corpse forever.

See docs/serving.md "Disaggregated serving" for roles, the handoff
contract, affinity routing, and the SSE wire format — and "Failure
semantics" for leases, retries, drain, and the serving chaos grammar.
"""

from __future__ import annotations

from .frontend import ServingFrontend, ServingStreamError
from .handoff import export_chain, import_chain, release_chain, run_prefill_only
from .lease import (
    LeaseHeartbeat,
    drain_grace_from_env,
    lease_ttl_from_env,
    retry_budget_from_env,
)
from .roles import (
    SERVING_ROLES,
    ServingRole,
    resolve_serving_role,
    router_endpoint_from_env,
)
from .router import Router

__all__ = [
    "LeaseHeartbeat",
    "Router",
    "SERVING_ROLES",
    "ServingFrontend",
    "ServingRole",
    "ServingStreamError",
    "drain_grace_from_env",
    "export_chain",
    "import_chain",
    "lease_ttl_from_env",
    "release_chain",
    "resolve_serving_role",
    "retry_budget_from_env",
    "router_endpoint_from_env",
    "run_prefill_only",
]
