"""Serving roles — who runs what where in a disaggregated serving fleet.

One launch flag (``--serving_role`` → ``ACCELERATE_SERVING_ROLE``) decides
which piece of the serving pipeline a process runs:

- ``unified`` (default): the single-host shape — one engine does chunked
  prefill AND decode; the front end streams straight from it.
- ``prefill``: chunked prefill only. Finished KV block chains ship to a
  decode host (:mod:`.handoff`); this host never builds the decode program,
  which is exactly why memcheck prices its pool differently per role.
- ``decode``: imports chains and decodes; also serves direct (short-prompt)
  requests the router's SLO arbitration keeps out of the prefill tier.
- ``router``: the front door — no engine, no pool, no model; discovers
  workers through the fleet KV namespace and proxies token streams.

The resolution is deliberately a plain env read (no backend touch): the
launcher exports the contract, ``PartialState`` publishes the resolved role
into the fleet registry, and every serving_net module asks this one place.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from ..utils.constants import ENV_ROUTER_ENDPOINT, ENV_SERVING_ROLE

SERVING_ROLES = ("unified", "prefill", "decode", "router")


@dataclass(frozen=True)
class ServingRole:
    """A validated role value with the capability predicates the rest of
    serving_net branches on — so role logic reads as ``role.prefills``
    instead of string comparisons scattered over four modules."""

    name: str

    def __post_init__(self):
        if self.name not in SERVING_ROLES:
            raise ValueError(
                f"unknown serving role {self.name!r}; expected one of "
                f"{SERVING_ROLES} ({ENV_SERVING_ROLE})"
            )

    @property
    def prefills(self) -> bool:
        return self.name in ("unified", "prefill")

    @property
    def decodes(self) -> bool:
        return self.name in ("unified", "decode")

    @property
    def runs_engine(self) -> bool:
        return self.name != "router"

    def __str__(self) -> str:
        return self.name


def resolve_serving_role(explicit: str | None = None) -> ServingRole:
    """The process's serving role: an explicit value wins, else the launcher
    env contract (``ACCELERATE_SERVING_ROLE``), else ``unified`` — unset
    means the single-host default, per the tri-state launch precedent (an
    explicit ``--serving_role unified`` scrubs an inherited value rather
    than exporting one)."""
    value = explicit if explicit is not None else os.environ.get(ENV_SERVING_ROLE)
    value = (value or "unified").strip().lower() or "unified"
    return ServingRole(value)


def router_endpoint_from_env(explicit: str | None = None) -> str | None:
    """The fleet's router endpoint (``host:port``), if one is configured
    (``ACCELERATE_ROUTER_ENDPOINT`` / ``launch --router_endpoint``) — where
    clients point and where non-router workers name their front door. None
    when unset/empty (a scrubbed value is an explicit "no router")."""
    value = explicit if explicit is not None else os.environ.get(ENV_ROUTER_ENDPOINT)
    value = (value or "").strip()
    return value or None
