"""TTL leases over the serving registry — liveness as a first-class fact.

The serving KV namespace (``router.SERVING_KV_NAMESPACE``) records
*registration*, and coordination-service keys outlive their writers: a
worker that dies mid-stream stays in the registry forever, and the router
keeps routing live traffic at a corpse. This module turns each registration
into a **lease**: the published value carries a wall-clock expiry
(``role|endpoint|expires=<unix>``), a :class:`LeaseHeartbeat` thread
re-publishes it every ``ttl/3`` (so one missed beat never evicts), and the
router treats an expired lease as an eviction — no distributed deletes, no
failure detector beyond the clock. Wall clocks cross processes (the handoff
payload's rebasing discipline); the TTL is chosen coarse enough (seconds)
that NTP-grade skew is noise.

Graceful exits don't wait for expiry: :func:`revoke_serving_endpoint`
deletes the key outright (the drain sequence's "revoke its lease" step —
docs/serving.md "Failure semantics").

Launcher contract (tri-state per the SLO precedent): ``launch
--serving_lease_ttl / --serving_retry_budget / --drain_grace_s`` export
``ACCELERATE_SERVING_LEASE_TTL`` / ``ACCELERATE_SERVING_RETRY_BUDGET`` /
``ACCELERATE_DRAIN_GRACE_S``; an explicit 0 scrubs an inherited value back
to the library default. Everything here is host-side bookkeeping — leases,
heartbeats, and expiry checks never touch a device.
"""

from __future__ import annotations

import os
import threading
import time

from ..logging import get_logger
from ..utils.constants import (
    ENV_DRAIN_GRACE_S,
    ENV_SERVING_LEASE_TTL,
    ENV_SERVING_RETRY_BUDGET,
)

logger = get_logger(__name__)

# How long a published serving lease stays valid without a heartbeat refresh.
DEFAULT_LEASE_TTL_S = 15.0
# How many times the router re-dispatches a failed request on a surviving
# worker (under the same rid) before surfacing the error to the client.
DEFAULT_RETRY_BUDGET = 2
# How long a SIGTERM'd serving worker waits for in-flight requests to finish
# before it exits anyway.
DEFAULT_DRAIN_GRACE_S = 30.0
# Refresh cadence as a fraction of the TTL: a lease gets ~3 beats per TTL,
# so one dropped beat (GC pause, network blip) never reads as death.
HEARTBEAT_FRACTION = 1.0 / 3.0


def _positive_env(env_name: str, default, cast):
    raw = os.environ.get(env_name, "").strip()
    if not raw:
        return default
    try:
        value = cast(float(raw)) if cast is int else cast(raw)
    except ValueError:
        raise ValueError(
            f"{env_name}={raw!r} must be a number (0/unset = library default "
            f"{default})"
        ) from None
    return value if value > 0 else default


def lease_ttl_from_env() -> float:
    """The fleet's serving-lease TTL in seconds (``ACCELERATE_SERVING_LEASE_TTL``)."""
    return _positive_env(ENV_SERVING_LEASE_TTL, DEFAULT_LEASE_TTL_S, float)


def retry_budget_from_env() -> int:
    """The router's per-request retry budget (``ACCELERATE_SERVING_RETRY_BUDGET``)."""
    return _positive_env(ENV_SERVING_RETRY_BUDGET, DEFAULT_RETRY_BUDGET, int)


def drain_grace_from_env() -> float:
    """The drain grace window in seconds (``ACCELERATE_DRAIN_GRACE_S``)."""
    return _positive_env(ENV_DRAIN_GRACE_S, DEFAULT_DRAIN_GRACE_S, float)


# ------------------------------------------------------------ wire encoding
def encode_lease(role: str, endpoint: str, ttl_s: float | None,
                 now: float | None = None) -> str:
    """The registry value: ``role|endpoint|expires=<unix wall clock>``.
    ``ttl_s`` None/0 publishes a non-expiring registration (the pre-lease
    wire format stays parseable — see :func:`parse_lease`)."""
    if not ttl_s or ttl_s <= 0:
        return f"{role}|{endpoint}"
    expires = (now if now is not None else time.time()) + float(ttl_s)
    return f"{role}|{endpoint}|expires={expires:.3f}"


def parse_lease(value: str) -> dict | None:
    """``{"role", "endpoint", "expires"}`` from a registry value — tolerant
    of the pre-lease ``role|endpoint`` format (``expires`` None = never).
    Returns None for values with no endpoint (unparseable)."""
    role, _, rest = value.partition("|")
    endpoint, _, tail = rest.partition("|")
    if not endpoint:
        return None
    expires = None
    if tail.startswith("expires="):
        try:
            expires = float(tail[len("expires="):])
        except ValueError:
            expires = None
    return {"role": role, "endpoint": endpoint, "expires": expires}


def lease_expired(lease: dict, now: float | None = None) -> bool:
    expires = lease.get("expires")
    if expires is None:
        return False
    return (now if now is not None else time.time()) > expires


# --------------------------------------------------------------- heartbeat
class LeaseHeartbeat:
    """Re-publish one worker's serving lease every ``ttl * HEARTBEAT_FRACTION``
    seconds until stopped — started by ``ServingFrontend.install`` /
    ``Router.install``, stopped (and the lease revoked) by drain/uninstall.
    Pure host work on its own daemon thread: a beat is one KV write."""

    def __init__(self, role: str, process_index: int, endpoint: str,
                 ttl_s: float | None = None):
        self.role = str(role)
        self.process_index = int(process_index)
        self.endpoint = str(endpoint)
        self.ttl_s = float(ttl_s if ttl_s is not None else lease_ttl_from_env())
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def beat(self):
        """Publish one lease refresh (also the initial registration)."""
        from .router import publish_serving_endpoint

        publish_serving_endpoint(self.role, process_index=self.process_index,
                                 endpoint=self.endpoint, ttl_s=self.ttl_s)

    def start(self) -> "LeaseHeartbeat":
        if self._thread is None:
            self.beat()
            self._thread = threading.Thread(
                target=self._run, name="at-serving-lease", daemon=True
            )
            self._thread.start()
        return self

    def _run(self):
        interval = max(0.05, self.ttl_s * HEARTBEAT_FRACTION)
        while not self._stop.wait(interval):
            try:
                self.beat()
            except Exception as exc:  # a flaky KV write must not kill the beat
                logger.warning(f"serving lease refresh failed: {exc!r}")

    def stop(self, revoke: bool = False):
        """Stop refreshing; ``revoke`` also deletes the registration outright
        (graceful exit — the router sees the worker gone on its next
        discovery instead of waiting out the TTL)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if revoke:
            from .router import revoke_serving_endpoint

            revoke_serving_endpoint(self.process_index)
