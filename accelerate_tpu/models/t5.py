"""T5 encoder-decoder (seq2seq) model.

Completes the reference's Megatron-parity arch set — the reference ships
Bert/GPT/T5 train steps (``utils/megatron_lm.py:445/587/~700``) but imports the
models from transformers; here the model is framework-native.

Architecture follows the public T5 recipe: RMSNorm pre-norm (no biases
anywhere), relative-position-bucket attention bias shared across a stack's
layers, un-scaled dot-product attention (the 1/sqrt(d) is folded into init),
ReLU MLP, shared input embedding with the tied LM head scaled by
``1/sqrt(d_model)``.

TPU-first as with the other zoo models: both stacks scan over stacked layer
weights (one compiled block each), bf16 matmuls with fp32 norms/softmax,
Megatron-style tp sharding rules, optional remat.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..modules import ModelOutput, Module
from ..ops.losses import cross_entropy_loss


@dataclass
class T5Config:
    vocab_size: int = 32128
    d_model: int = 512
    d_kv: int = 64
    d_ff: int = 2048
    num_layers: int = 6  # encoder layers
    num_decoder_layers: int = 6
    num_heads: int = 8
    relative_attention_num_buckets: int = 32
    relative_attention_max_distance: int = 128
    layer_norm_epsilon: float = 1e-6
    pad_token_id: int = 0
    decoder_start_token_id: int = 0
    remat: bool = False
    # T5-v1.1 recipe: gated FFN (wi_0 gate * wi_1) with tanh-gelu, untied head.
    gated_act: bool = False
    dense_act: str = "relu"  # 'relu' | 'gelu_tanh'
    tie_word_embeddings: bool = True

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(
            vocab_size=256, d_model=32, d_kv=8, d_ff=64,
            num_layers=2, num_decoder_layers=2, num_heads=4,
            relative_attention_num_buckets=8, relative_attention_max_distance=16,
        )
        defaults.update(kw)
        return cls(**defaults)


def rms_norm(x, scale, eps):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps) * scale).astype(dtype)


def relative_position_bucket(relative_position, bidirectional: bool, num_buckets: int, max_distance: int):
    """T5's log-bucketed relative positions (public T5 recipe)."""
    ret = 0
    n = -relative_position
    if bidirectional:
        num_buckets //= 2
        ret += (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_if_large = max_exact + (
        jnp.log(n.astype(jnp.float32) / max_exact + 1e-6)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    val_if_large = jnp.minimum(val_if_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_if_large)


class T5ForConditionalGeneration(Module):
    # Encoder-decoder pipeline-parallel training (VERDICT r4 ask #4; reference
    # parity: Megatron's T5TrainStep pipelines T5 under pp_degree,
    # /root/reference/src/accelerate/utils/megatron_lm.py ~:700). Design: pp
    # stages split the DECODER stack, the encoder stays pp-replicated and runs
    # once per batch outside the pipeline. Why this split: the decoder carries
    # self-attn + cross-attn + FFN per layer (the deeper/wider side of every
    # seq2seq training step, and the side whose depth grows in practice), and
    # the encoder's output is read-only per microbatch — it rides the
    # pipeline's microbatched context, so the generic GPipe schedule
    # (parallel/pipeline.py) applies unchanged. Splitting encoder stages then
    # decoder stages across one ring would double the wavefront latency and
    # need a second context channel for the encoder-side activations.
    pipeline_capable = True

    def __init__(self, config: T5Config):
        self.config = config
        self.params = None

    def pipeline_layer_params(self, params):
        """The pipelined stack (decoder layers) for resolve_pipeline_spec."""
        return params["decoder"]["layers"]

    def _stack_params(self, keys, L, cross: bool):
        cfg = self.config
        h, kv, ff, nh = cfg.d_model, cfg.d_kv, cfg.d_ff, cfg.num_heads
        inner = nh * kv

        def dense(shape, fan_in):
            return jax.random.normal(next(keys), shape, jnp.float32) * (fan_in ** -0.5)

        block = {
            "self_attn": {
                "wq": dense((L, h, inner), h),
                "wk": dense((L, h, inner), h),
                "wv": dense((L, h, inner), h),
                "wo": dense((L, inner, h), inner),
            },
            "self_norm": {"scale": jnp.ones((L, h), jnp.float32)},
            "mlp": (
                {
                    "wi_0": dense((L, h, ff), h),
                    "wi_1": dense((L, h, ff), h),
                    "wo": dense((L, ff, h), ff),
                }
                if self.config.gated_act
                else {
                    "wi": dense((L, h, ff), h),
                    "wo": dense((L, ff, h), ff),
                }
            ),
            "mlp_norm": {"scale": jnp.ones((L, h), jnp.float32)},
        }
        if cross:
            block["cross_attn"] = {
                "wq": dense((L, h, inner), h),
                "wk": dense((L, h, inner), h),
                "wv": dense((L, h, inner), h),
                "wo": dense((L, inner, h), inner),
            }
            block["cross_norm"] = {"scale": jnp.ones((L, h), jnp.float32)}
        return block

    def init(self, rng, *example_inputs, **kwargs):
        cfg = self.config
        keys = iter(jax.random.split(rng, 64))
        params = {
            "shared": jax.random.normal(next(keys), (cfg.vocab_size, cfg.d_model), jnp.float32),
            "encoder": {
                "layers": self._stack_params(keys, cfg.num_layers, cross=False),
                "rel_bias": jax.random.normal(
                    next(keys), (cfg.relative_attention_num_buckets, cfg.num_heads), jnp.float32
                ) * 0.1,
                "final_norm": {"scale": jnp.ones((cfg.d_model,), jnp.float32)},
            },
            "decoder": {
                "layers": self._stack_params(keys, cfg.num_decoder_layers, cross=True),
                "rel_bias": jax.random.normal(
                    next(keys), (cfg.relative_attention_num_buckets, cfg.num_heads), jnp.float32
                ) * 0.1,
                "final_norm": {"scale": jnp.ones((cfg.d_model,), jnp.float32)},
            },
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = jax.random.normal(
                next(keys), (cfg.d_model, cfg.vocab_size), jnp.float32
            ) * (cfg.d_model ** -0.5)
        return params

    def _ffn(self, layer, y):
        """Position-wise FFN: original-T5 ReLU or the v1.1 gated tanh-gelu
        (``gelu(y @ wi_0) * (y @ wi_1)``), selected by config."""
        cfg = self.config
        act = (
            jax.nn.relu
            if cfg.dense_act == "relu"
            else (lambda t: jax.nn.gelu(t, approximate=True))
        )
        m = layer["mlp"]
        if cfg.gated_act:
            return (act(y @ m["wi_0"]) * (y @ m["wi_1"])) @ m["wo"]
        return act(y @ m["wi"]) @ m["wo"]

    def sharding_rules(self):
        """tp/fsdp Megatron rules on both stacks; the DECODER layer stack's
        leading dim additionally shards on ``pp`` (pipeline stages own
        contiguous decoder blocks — see the class docstring), while the
        encoder stays pp-replicated (it runs once, outside the pipeline)."""
        return [
            (r"shared", P("tp", "fsdp")),
            (r"decoder/layers/.*attn/w[qkv]", P("pp", "fsdp", "tp")),
            (r"decoder/layers/.*attn/wo", P("pp", "tp", "fsdp")),
            (r"decoder/layers/mlp/wi", P("pp", "fsdp", "tp")),
            (r"decoder/layers/mlp/wo", P("pp", "tp", "fsdp")),
            (r"decoder/layers/.*norm", P("pp")),
            (r"attn/w[qkv]", P(None, "fsdp", "tp")),
            (r"attn/wo", P(None, "tp", "fsdp")),
            (r"mlp/wi", P(None, "fsdp", "tp")),
            (r"mlp/wo", P(None, "tp", "fsdp")),
            (r"lm_head", P("fsdp", "tp")),
            (r"norm|rel_bias", P()),
        ]

    def _rel_bias(self, rel_emb, qlen, klen, bidirectional):
        cfg = self.config
        ctx = jnp.arange(qlen)[:, None]
        mem = jnp.arange(klen)[None, :]
        buckets = relative_position_bucket(
            mem - ctx, bidirectional, cfg.relative_attention_num_buckets,
            cfg.relative_attention_max_distance,
        )
        return jnp.take(rel_emb, buckets, axis=0).transpose(2, 0, 1)[None]  # [1, nh, q, k]

    def _attend(self, x, kv_x, w, bias):
        cfg = self.config
        B, S, _ = x.shape
        Skv = kv_x.shape[1]
        nh, dkv = cfg.num_heads, cfg.d_kv
        q = (x @ w["wq"]).reshape(B, S, nh, dkv)
        k = (kv_x @ w["wk"]).reshape(B, Skv, nh, dkv)
        v = (kv_x @ w["wv"]).reshape(B, Skv, nh, dkv)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) + bias
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, nh * dkv)
        return out @ w["wo"]

    def block(self, layer, x, ctx):
        """One decoder block for the pipeline stage protocol
        (``parallel/pipeline.py`` ``_stage_body``): the encoder output and
        attention biases arrive through the read-only per-microbatch context.
        Same math as ``_run_stack``'s scan body with ``cross=True``."""
        cfg = self.config
        y = rms_norm(x, layer["self_norm"]["scale"], cfg.layer_norm_epsilon)
        x = x + self._attend(y, y, layer["self_attn"], ctx["dec_bias"])
        y = rms_norm(x, layer["cross_norm"]["scale"], cfg.layer_norm_epsilon)
        x = x + self._attend(y, ctx["enc_out"], layer["cross_attn"], ctx["enc_pad"])
        y = rms_norm(x, layer["mlp_norm"]["scale"], cfg.layer_norm_epsilon)
        return x + self._ffn(layer, y)

    def _run_stack(self, stack, x, enc_out, self_bias, cross_bias, cross: bool):
        cfg = self.config

        def block(h, layer):
            y = rms_norm(h, layer["self_norm"]["scale"], cfg.layer_norm_epsilon)
            h = h + self._attend(y, y, layer["self_attn"], self_bias)
            if cross:
                y = rms_norm(h, layer["cross_norm"]["scale"], cfg.layer_norm_epsilon)
                h = h + self._attend(y, enc_out, layer["cross_attn"], cross_bias)
            y = rms_norm(h, layer["mlp_norm"]["scale"], cfg.layer_norm_epsilon)
            h = h + self._ffn(layer, y)
            return h, None

        body = block
        if cfg.remat:
            body = jax.checkpoint(block)
        x, _ = jax.lax.scan(body, x, stack["layers"])
        return rms_norm(x, stack["final_norm"]["scale"], cfg.layer_norm_epsilon)

    def _shift_right(self, labels):
        cfg = self.config
        start = jnp.full((labels.shape[0], 1), cfg.decoder_start_token_id, labels.dtype)
        shifted = jnp.concatenate([start, labels[:, :-1]], axis=1)
        return jnp.where(shifted == -100, cfg.pad_token_id, shifted)

    def apply(
        self,
        params,
        input_ids=None,
        attention_mask=None,
        decoder_input_ids=None,
        decoder_attention_mask=None,
        labels=None,
        train: bool = False,
        rngs=None,
        pipeline=None,
        **kwargs,
    ):
        cfg = self.config
        if decoder_input_ids is None:
            if labels is None:
                raise ValueError("Need decoder_input_ids or labels")
            decoder_input_ids = self._shift_right(labels)
        T = decoder_input_ids.shape[1]
        emb = params["shared"]
        compute_dtype = emb.dtype

        # Encoder (shared with the generation path — one implementation).
        enc_out, attention_mask = self.encode(params, input_ids, attention_mask)
        enc_pad = jnp.where(attention_mask[:, None, None, :].astype(bool), 0.0, -1e30).astype(jnp.float32)

        # Decoder: causal self-attn bias + cross-attn encoder padding bias.
        causal = jnp.where(
            jnp.tril(jnp.ones((T, T), bool))[None, None], 0.0, -1e30
        ).astype(jnp.float32)
        dec_bias = self._rel_bias(params["decoder"]["rel_bias"], T, T, bidirectional=False) + causal
        if decoder_attention_mask is not None:
            dec_bias = dec_bias + jnp.where(
                decoder_attention_mask[:, None, None, :].astype(bool), 0.0, -1e30
            ).astype(jnp.float32)
        y = jnp.take(emb, decoder_input_ids, axis=0).astype(compute_dtype)
        if pipeline is not None:
            # GPipe over the decoder stack (encoder replicated — see the
            # class docstring). dec_bias without a per-row mask is (1, nh,
            # T, T) and replicates across microbatches; enc_out/enc_pad
            # carry the batch dim and microbatch with the residual stream.
            ctx = {"enc_out": enc_out, "enc_pad": enc_pad, "dec_bias": dec_bias}
            y, _ = pipeline.run(self, params["decoder"]["layers"], y, ctx)
            dec_out = rms_norm(
                y, params["decoder"]["final_norm"]["scale"], cfg.layer_norm_epsilon
            )
        else:
            dec_out = self._run_stack(params["decoder"], y, enc_out, dec_bias, enc_pad, cross=True)

        # Tied head carries T5's 1/sqrt(d) rescale; the untied v1.1 head
        # projects directly (HF applies the rescale only when tied).
        if cfg.tie_word_embeddings:
            logits = (dec_out * (cfg.d_model ** -0.5)) @ emb.T.astype(compute_dtype)
        else:
            logits = dec_out @ params["lm_head"].astype(compute_dtype)
        logits = logits.astype(jnp.float32)
        out = ModelOutput(logits=logits, encoder_last_hidden_state=enc_out)
        if labels is not None:
            masked = jnp.where(labels == cfg.pad_token_id, -100, labels)
            out["loss"] = cross_entropy_loss(logits, masked)
        return out

    # ------------------------------------------------------------- generation
    # Cached incremental decoding (the seq2seq analog of Llama's decode cache;
    # reference workload: the big_model_inference benchmark's T0pp s/token
    # table, BASELINE.md). The encoder runs once; decoder self-attention K/V
    # accumulate in a static-shape cache and cross-attention K/V are
    # precomputed per layer from the encoder output.
    def encode(self, params, input_ids, attention_mask=None):
        """Run the encoder once. Returns (enc_out, attention_mask)."""
        cfg = self.config
        if attention_mask is None:
            attention_mask = (input_ids != cfg.pad_token_id).astype(jnp.int32)
        S = input_ids.shape[1]
        emb = params["shared"]
        enc_pad = jnp.where(
            attention_mask[:, None, None, :].astype(bool), 0.0, -1e30
        ).astype(jnp.float32)
        x = jnp.take(emb, input_ids, axis=0).astype(emb.dtype)
        enc_bias = self._rel_bias(params["encoder"]["rel_bias"], S, S, bidirectional=True) + enc_pad
        enc_out = self._run_stack(params["encoder"], x, None, enc_bias, None, cross=False)
        return enc_out, attention_mask

    def init_cache(self, batch_size: int, max_len: int, dtype=jnp.bfloat16):
        """Decoder self-attention K/V cache, stacked over layers."""
        cfg = self.config
        shape = (cfg.num_decoder_layers, batch_size, max_len, cfg.num_heads, cfg.d_kv)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
                "pos": jnp.zeros((), jnp.int32)}

    def precompute_cross_kv(self, params, enc_out):
        """Per-layer cross-attention K/V from the encoder output (computed once
        per generation): (L, B, S, nh, dkv) each."""
        cfg = self.config
        nh, dkv = cfg.num_heads, cfg.d_kv
        B, S, _ = enc_out.shape
        wk = params["decoder"]["layers"]["cross_attn"]["wk"]  # (L, h, inner)
        wv = params["decoder"]["layers"]["cross_attn"]["wv"]
        ck = jnp.einsum("bsh,lhi->lbsi", enc_out, wk).reshape(-1, B, S, nh, dkv)
        cv = jnp.einsum("bsh,lhi->lbsi", enc_out, wv).reshape(-1, B, S, nh, dkv)
        return ck, cv

    def decode(self, params, decoder_input_ids, cache, enc_out, enc_attention_mask,
               cross_kv=None):
        """One cached decoder chunk (prefill or single decode step).

        Returns ``ModelOutput(logits=..., cache=...)``; positions are implicit
        (``cache['pos']`` + offset) — T5 decoding always starts at position 0
        with ``decoder_start_token_id``, so there is no left-padding to handle.
        """
        cfg = self.config
        B, Tc = decoder_input_ids.shape
        T_max = cache["k"].shape[2]
        nh, dkv = cfg.num_heads, cfg.d_kv
        pos = cache["pos"]
        emb = params["shared"]
        y = jnp.take(emb, decoder_input_ids, axis=0).astype(emb.dtype)

        if cross_kv is None:
            cross_kv = self.precompute_cross_kv(params, enc_out)
        enc_pad = jnp.where(
            enc_attention_mask[:, None, None, :].astype(bool), 0.0, -1e30
        ).astype(jnp.float32)

        # Relative bias between this chunk's query positions and every cache
        # slot; slots after the query are causally masked (never written yet).
        q_pos = pos + jnp.arange(Tc)
        k_pos = jnp.arange(T_max)
        buckets = relative_position_bucket(
            k_pos[None, :] - q_pos[:, None], False,
            cfg.relative_attention_num_buckets, cfg.relative_attention_max_distance,
        )
        rel = jnp.take(params["decoder"]["rel_bias"], buckets, axis=0)  # (Tc,Tmax,nh)
        self_bias = rel.transpose(2, 0, 1)[None].astype(jnp.float32)
        causal = jnp.where(k_pos[None, :] <= q_pos[:, None], 0.0, -1e30)
        self_bias = self_bias + causal[None, None].astype(jnp.float32)

        def block(carry, inp):
            h = carry
            layer, k_cache, v_cache, ck, cv = inp
            # Cached self-attention.
            z = rms_norm(h, layer["self_norm"]["scale"], cfg.layer_norm_epsilon)
            q = (z @ layer["self_attn"]["wq"]).reshape(B, Tc, nh, dkv)
            k = (z @ layer["self_attn"]["wk"]).reshape(B, Tc, nh, dkv)
            v = (z @ layer["self_attn"]["wv"]).reshape(B, Tc, nh, dkv)
            k_cache = jax.lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype), (0, pos, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype), (0, pos, 0, 0))
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache.astype(q.dtype)).astype(jnp.float32)
            probs = jax.nn.softmax(scores + self_bias, axis=-1).astype(h.dtype)
            attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v_cache.astype(h.dtype))
            h = h + attn.reshape(B, Tc, nh * dkv) @ layer["self_attn"]["wo"]
            # Cross-attention against precomputed encoder K/V.
            z = rms_norm(h, layer["cross_norm"]["scale"], cfg.layer_norm_epsilon)
            q = (z @ layer["cross_attn"]["wq"]).reshape(B, Tc, nh, dkv)
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, ck.astype(q.dtype)).astype(jnp.float32)
            probs = jax.nn.softmax(scores + enc_pad, axis=-1).astype(h.dtype)
            attn = jnp.einsum("bhqk,bkhd->bqhd", probs, cv.astype(h.dtype))
            h = h + attn.reshape(B, Tc, nh * dkv) @ layer["cross_attn"]["wo"]
            # MLP.
            z = rms_norm(h, layer["mlp_norm"]["scale"], cfg.layer_norm_epsilon)
            h = h + self._ffn(layer, z)
            return h, (k_cache, v_cache)

        ck, cv = cross_kv
        y, (nk, nv) = jax.lax.scan(
            block, y, (params["decoder"]["layers"], cache["k"], cache["v"], ck, cv)
        )
        y = rms_norm(y, params["decoder"]["final_norm"]["scale"], cfg.layer_norm_epsilon)
        if cfg.tie_word_embeddings:
            logits = ((y * (cfg.d_model ** -0.5)) @ emb.T.astype(y.dtype)).astype(jnp.float32)
        else:
            logits = (y @ params["lm_head"].astype(y.dtype)).astype(jnp.float32)
        return ModelOutput(
            logits=logits,
            cache={"k": nk, "v": nv, "pos": pos + Tc},
        )
