"""GPT-2-family decoder — learned positions, pre-LN blocks, tied LM head.

Fills the GPT slot of the reference's Megatron model trio (Bert/GPT/T5 train
steps, ``utils/megatron_lm.py:587``); the reference never defines the
architecture itself (it comes from transformers/Megatron). Same TPU-first
skeleton as ``Llama``: stacked-layer scan, stage protocol (embed/block/head)
for pipelined and layer-streamed execution, Megatron-style tp sharding rules,
remat, and the ``matmul_precision`` dispatch.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..modules import ModelOutput, Module
from ..ops.attention import attention as _attention
from ..ops.losses import cross_entropy_loss


from ..ops.norms import layer_norm as _layer_norm


@dataclass
class GPT2Config:
    vocab_size: int = 50257
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 1024
    layer_norm_eps: float = 1e-5
    remat: bool = False
    remat_policy: str = "nothing_saveable"
    attention_impl: str = "auto"
    matmul_precision: str = "default"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            max_position_embeddings=128,
        )
        defaults.update(kw)
        return cls(**defaults)


class GPT2(Module):
    # embed/block/head stage protocol — GPipe-eligible (parallel/pipeline.py).
    # (No scan_aux_keys: the GPT-2 block sows nothing; models that do must also
    # collect aux in their scan path as Llama does.)
    pipeline_capable = True

    def __init__(self, config: GPT2Config):
        self.config = config
        self.params = None

    # ------------------------------------------------------------------- init
    def init(self, rng, *example_inputs, **kwargs):
        cfg = self.config
        h, inter, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
        keys = jax.random.split(rng, 8)

        def dense(key, shape, scale_dim=None):
            # Stacked-layer weights are (L, fan_in, fan_out): the fan-in is the
            # second-to-last dim, not the layer count.
            fan_in = scale_dim if scale_dim is not None else (shape[-2] if len(shape) >= 3 else shape[0])
            scale = 1.0 / np.sqrt(fan_in)
            return (jax.random.normal(key, shape, jnp.float32) * scale).astype(jnp.float32)

        return {
            "embed": {
                "wte": dense(keys[0], (cfg.vocab_size, h), h),
                "wpe": dense(keys[1], (cfg.max_position_embeddings, h), h),
            },
            "layers": {
                "attn": {
                    "w_qkv": dense(keys[2], (L, h, 3 * h)),
                    "b_qkv": jnp.zeros((L, 3 * h), jnp.float32),
                    "wo": dense(keys[3], (L, h, h)),
                    "bo": jnp.zeros((L, h), jnp.float32),
                },
                "mlp": {
                    "w_in": dense(keys[4], (L, h, inter)),
                    "b_in": jnp.zeros((L, inter), jnp.float32),
                    "w_out": dense(keys[5], (L, inter, h)),
                    "b_out": jnp.zeros((L, h), jnp.float32),
                },
                "ln_1": {"scale": jnp.ones((L, h), jnp.float32), "bias": jnp.zeros((L, h), jnp.float32)},
                "ln_2": {"scale": jnp.ones((L, h), jnp.float32), "bias": jnp.zeros((L, h), jnp.float32)},
            },
            "ln_f": {"scale": jnp.ones((h,), jnp.float32), "bias": jnp.zeros((h,), jnp.float32)},
        }  # LM head tied to wte (GPT-2 convention)

    # --------------------------------------------------------------- sharding
    def sharding_rules(self):
        """Fused QKV is column-split on tp; under GSPMD the downstream
        ``jnp.split``/head reshape stays correct for any layout (the partitioner
        inserts any needed resharding — unlike Megatron's manual fused-QKV
        interleave requirement). wo/w_out are row-parallel; layer stack on pp."""
        return [
            (r"embed/wte", P("tp", "fsdp")),
            (r"embed/wpe", P(None, "fsdp")),
            (r"attn/w_qkv", P("pp", "fsdp", "tp")),
            (r"attn/b_qkv", P("pp", "tp")),
            (r"attn/wo", P("pp", "tp", "fsdp")),
            (r"attn/bo", P("pp")),
            (r"mlp/w_in", P("pp", "fsdp", "tp")),
            (r"mlp/b_in", P("pp", "tp")),
            (r"mlp/w_out", P("pp", "tp", "fsdp")),
            (r"mlp/b_out", P("pp")),
            (r"layers/ln_", P("pp")),
            (r"ln_f", P()),
        ]

    # ---------------------------------------------------------------- forward
    def embed(self, params, input_ids, positions=None, attention_mask=None):
        B, S = input_ids.shape
        if S > self.config.max_position_embeddings:
            # Learned positions have a hard table limit; jnp.take would silently
            # clamp out-of-range rows to the last position otherwise.
            raise ValueError(
                f"sequence length {S} exceeds max_position_embeddings "
                f"{self.config.max_position_embeddings}"
            )
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
        from ..parallel.sharding import embedding_lookup

        x = embedding_lookup(params["embed"]["wte"], input_ids) + embedding_lookup(
            params["embed"]["wpe"], positions
        )
        return x.astype(params["embed"]["wte"].dtype), {"attention_mask": attention_mask}

    def _mm(self, a, b):
        from ..ops.int8 import matmul

        return matmul(a, b, precision=self.config.matmul_precision)

    def block(self, layer, x, ctx, cache_layer=None):
        cfg = self.config
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        B, S, h = x.shape
        ln1 = _layer_norm(x, layer["ln_1"]["scale"], layer["ln_1"]["bias"], cfg.layer_norm_eps)
        qkv = self._mm(ln1, layer["attn"]["w_qkv"]) + layer["attn"]["b_qkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, nh, hd)
        k = k.reshape(B, S, nh, hd)
        v = v.reshape(B, S, nh, hd)
        new_cache = None
        if cache_layer is not None:
            from ..ops.attention import cached_attention

            pos = ctx["cache_pos"]
            k_cache = jax.lax.dynamic_update_slice(
                cache_layer["k"], k.astype(cache_layer["k"].dtype), (0, pos, 0, 0)
            )
            v_cache = jax.lax.dynamic_update_slice(
                cache_layer["v"], v.astype(cache_layer["v"].dtype), (0, pos, 0, 0)
            )
            attn = cached_attention(
                q, k_cache, v_cache,
                q_positions=ctx["positions"],
                kv_mask=ctx.get("kv_mask"),
            )
            new_cache = {"k": k_cache, "v": v_cache}
        else:
            attn = _attention(
                q, k, v, causal=True, mask=ctx["attention_mask"], impl=cfg.attention_impl
            )
        x = x + self._mm(attn.reshape(B, S, h), layer["attn"]["wo"]) + layer["attn"]["bo"]
        ln2 = _layer_norm(x, layer["ln_2"]["scale"], layer["ln_2"]["bias"], cfg.layer_norm_eps)
        mid = jax.nn.gelu(self._mm(ln2, layer["mlp"]["w_in"]) + layer["mlp"]["b_in"], approximate=True)
        x = x + self._mm(mid, layer["mlp"]["w_out"]) + layer["mlp"]["b_out"]
        return x if new_cache is None else (x, new_cache)

    @staticmethod
    def _shift_labels(labels, attention_mask):
        """Next-token targets with the padding guards — same contract as
        ``Llama._shift_labels`` (the 1F1B pipeline reads this to renormalize
        per-microbatch losses, so head and schedule share one definition)."""
        B = labels.shape[0]
        shifted = jnp.concatenate(
            [labels[:, 1:], jnp.full((B, 1), -100, labels.dtype)], axis=1
        )
        if attention_mask is not None:
            # A position trains only if it is itself real (left-padding
            # guard) AND its target token t+1 is real (right-padding guard).
            target_valid = jnp.concatenate(
                [attention_mask[:, 1:], jnp.zeros((B, 1), attention_mask.dtype)], axis=1
            )
            valid = target_valid.astype(bool) & attention_mask.astype(bool)
            shifted = jnp.where(valid, shifted, -100)
        return shifted

    def head(self, params, x, labels=None, attention_mask=None):
        cfg = self.config
        x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"], cfg.layer_norm_eps)
        logits = (x @ params["embed"]["wte"].T.astype(x.dtype)).astype(jnp.float32)
        out = ModelOutput(logits=logits)
        if labels is not None:
            out["loss"] = cross_entropy_loss(logits, self._shift_labels(labels, attention_mask))
        return out

    def init_cache(self, batch_size: int, max_len: int, dtype=jnp.bfloat16):
        """Pre-allocated decode cache (same layout/contract as Llama's)."""
        cfg = self.config
        if max_len > cfg.max_position_embeddings:
            raise ValueError(
                f"cache length {max_len} exceeds max_position_embeddings "
                f"{cfg.max_position_embeddings}: learned positions cannot extend "
                "past the table (decode steps would silently reuse the last row)"
            )
        shape = (cfg.num_hidden_layers, batch_size, max_len, cfg.num_attention_heads, cfg.head_dim)
        return {
            "k": jnp.zeros(shape, dtype),
            "v": jnp.zeros(shape, dtype),
            "pos": jnp.zeros((), jnp.int32),
            "kv_mask": jnp.zeros((batch_size, max_len), jnp.int32),
        }

    def _apply_cached(self, params, input_ids, attention_mask, cache, labels=None,
                      positions=None):
        """``positions`` (optional) are the *token* positions for the learned
        ``wpe`` lookup — essential for ragged batches, where the cache slot
        index ≠ the token's real position (VERDICT r2 #6). Causal masking
        always uses slot indices."""
        B, S = input_ids.shape
        pos = cache["pos"]
        slot_positions = pos + jnp.arange(S, dtype=jnp.int32)[None]
        slot_positions = jnp.broadcast_to(slot_positions, (B, S))
        wpe_positions = slot_positions if positions is None else positions
        chunk_mask = (
            attention_mask.astype(jnp.int32)
            if attention_mask is not None
            else jnp.ones((B, S), jnp.int32)
        )
        kv_mask = jax.lax.dynamic_update_slice(cache["kv_mask"], chunk_mask, (0, pos))
        x, ctx = self.embed(params, input_ids, wpe_positions, attention_mask)
        ctx["positions"] = slot_positions
        ctx["kv_mask"] = kv_mask
        ctx["cache_pos"] = pos

        def scan_step(x, inp):
            layer, ck, cv = inp
            x, new = self.block(layer, x, ctx, cache_layer={"k": ck, "v": cv})
            return x, (new["k"], new["v"])

        x, (nk, nv) = jax.lax.scan(scan_step, x, (params["layers"], cache["k"], cache["v"]))
        out = self.head(params, x, labels=labels, attention_mask=attention_mask)
        out["cache"] = {"k": nk, "v": nv, "pos": pos + S, "kv_mask": kv_mask}
        return out

    def apply(
        self,
        params,
        input_ids=None,
        labels=None,
        attention_mask=None,
        positions=None,
        cache=None,
        train: bool = False,
        rngs=None,
        pipeline=None,
        **kwargs,
    ):
        cfg = self.config
        if cache is not None:
            return self._apply_cached(
                params, input_ids, attention_mask, cache, labels=labels, positions=positions
            )
        x, ctx = self.embed(params, input_ids, positions, attention_mask)

        if pipeline is not None:
            x, _aux = pipeline.run(self, params["layers"], x, ctx)
        else:
            body = lambda x, layer: self.block(layer, x, ctx)
            if cfg.remat:
                from ..utils.dataclasses import resolve_remat_policy

                policy = resolve_remat_policy(cfg.remat_policy, getattr(cfg, "remat_save_names", ()))
                body = jax.checkpoint(body, policy=policy)

            def scan_step(x, layer):
                return body(x, layer), None

            x, _ = jax.lax.scan(scan_step, x, params["layers"])
        return self.head(params, x, labels=labels, attention_mask=attention_mask)

    # -------------------------------------------------------------- estimation
    def num_params(self) -> int:
        cfg = self.config
        h, inter, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
        layer = 3 * h * h + 3 * h + h * h + h + 2 * h * inter + inter + h + 4 * h
        return L * layer + cfg.vocab_size * h + cfg.max_position_embeddings * h + 2 * h
