"""HF/torch checkpoint conversion into the TPU-native model zoo.

The reference never defines model architectures — users bring transformers
``nn.Module``s and their checkpoints. For a reference user switching here, the
weights are the moat: this module maps HuggingFace state dicts (torch tensors,
numpy arrays, or safetensors files) onto the zoo's stacked-layer param pytrees
so existing Llama/GPT-2 checkpoints run on the TPU engine unchanged.

Layout differences handled:
- torch ``nn.Linear`` stores (out, in); zoo matmuls are ``x @ W`` with
  (in, out) → transpose. GPT-2's ``Conv1D`` already stores (in, out) → direct.
- per-layer tensors are stacked into one leading-``L``-dim array (the scan
  layout; one XLA program per block instead of L inlined copies).
- RoPE: HF-Llama's rotate_half and the zoo's split-halves convention are the
  same math — verified by the logits-parity tests (tests/test_convert.py).

Entry points::

    model, params = from_hf(hf_model)            # a transformers PreTrainedModel
    params = llama_params_from_hf(sd, config)    # raw state dict → pytree
    cfg = llama_config_from_hf(hf_config)
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .bert import BertConfig, BertForSequenceClassification
from .gpt2 import GPT2, GPT2Config
from .gptx import GPTX, GPTXConfig
from .llama import Llama, LlamaConfig
from .moe import MoELlama, MoELlamaConfig
from .t5 import T5Config, T5ForConditionalGeneration
from .vit import ViTConfig, ViTForImageClassification
from .whisper import WhisperConfig, WhisperForConditionalGeneration


def _to_numpy(t, dtype=None) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor (may be bf16: go through float32)
        arr = t.detach().cpu().float().numpy()
    else:
        arr = np.asarray(t)
    # Cast per-tensor so a large checkpoint never stages fully in fp32.
    return arr.astype(dtype) if dtype is not None else arr


def _normalize_keys(state_dict, prefixes=("model.", "transformer.", "bert.")) -> dict:
    """Strip the wrapper prefix transformers adds (``model.`` for Llama,
    ``transformer.`` for GPT-2) so bare-backbone and LMHead checkpoints both map.
    First matching prefix wins; converters with nested wrappers pass their own
    list (OPT: ``model.decoder.``)."""
    out = {}
    for k, v in state_dict.items():
        for prefix in prefixes:
            if k.startswith(prefix):
                k = k[len(prefix):]
                break
        out[k] = v
    return out


def _stack(sd, pattern: str, num_layers: int, transpose: bool = False, dtype=None) -> jnp.ndarray:
    mats = []
    for i in range(num_layers):
        m = _to_numpy(sd[pattern.format(i=i)], dtype)
        mats.append(m.T if transpose else m)
    return jnp.asarray(np.stack(mats))


def _getter(hf_config):
    """Uniform field access for transformers config objects and plain dicts."""
    if isinstance(hf_config, dict):
        return lambda k, d=None: hf_config.get(k, d)
    return lambda k, d=None: getattr(hf_config, k, d)


def _get_converter(model_type):
    if model_type not in _CONVERTERS:
        raise ValueError(
            f"No converter for model_type={model_type!r}; supported: {sorted(_CONVERTERS)}"
        )
    return _CONVERTERS[model_type]


# --------------------------------------------------------------------- llama
def llama_config_from_hf(hf_config, check_act: bool = True) -> LlamaConfig:
    """Map a ``transformers.LlamaConfig`` (attributes or dict) onto the zoo config.

    Raises on config features the zoo model does not implement (unsupported
    rope_type values, attention/mlp biases, decoupled head_dim) — silently
    dropping them would convert cleanly and then generate garbage at
    depth/length. linear and llama3 rope scaling are supported."""
    get = _getter(hf_config)
    rope_scaling = get("rope_scaling")
    if rope_scaling:
        rope_scaling = dict(rope_scaling)
        from .llama import SUPPORTED_ROPE_TYPES

        rope_type = rope_scaling.get("rope_type", rope_scaling.get("type", "default"))
        if rope_type not in SUPPORTED_ROPE_TYPES:
            raise ValueError(
                f"rope_type={rope_type!r} is not supported by the zoo Llama "
                f"(supported: {SUPPORTED_ROPE_TYPES}); converting would "
                "silently mis-position long contexts."
            )
    if get("mlp_bias"):
        raise ValueError("mlp_bias checkpoints are not supported (zoo Llama's FFN is bias-free)")
    if check_act:
        act = get("hidden_act") or "silu"
        if act != "silu":
            raise ValueError(
                f"hidden_act={act!r} is not supported for llama-type checkpoints "
                "(the zoo converts SwiGLU here; Gemma's GeGLU has its own converter)"
            )
    return LlamaConfig(
        head_dim=get("head_dim"),
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        intermediate_size=get("intermediate_size"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        num_key_value_heads=get("num_key_value_heads") or get("num_attention_heads"),
        max_position_embeddings=get("max_position_embeddings", 2048),
        rms_norm_eps=get("rms_norm_eps", 1e-5),
        rope_theta=get("rope_theta", 10000.0),
        tie_word_embeddings=bool(get("tie_word_embeddings", False)),
        rope_scaling=rope_scaling,
        attention_bias=bool(get("attention_bias", False)),
        sliding_window=get("sliding_window"),
    )


def _llama_backbone_params(sd, config, dtype) -> dict:
    """Embed + attention + norms + head — shared by the dense-Llama and
    Mixtral converters (Mixtral swaps only the FFN)."""
    L = config.num_hidden_layers
    params = {
        "embed": {"weight": jnp.asarray(_to_numpy(sd["embed_tokens.weight"], dtype))},
        "layers": {
            "attn": {
                "wq": _stack(sd, "layers.{i}.self_attn.q_proj.weight", L, transpose=True, dtype=dtype),
                "wk": _stack(sd, "layers.{i}.self_attn.k_proj.weight", L, transpose=True, dtype=dtype),
                "wv": _stack(sd, "layers.{i}.self_attn.v_proj.weight", L, transpose=True, dtype=dtype),
                "wo": _stack(sd, "layers.{i}.self_attn.o_proj.weight", L, transpose=True, dtype=dtype),
            },
            "input_norm": {"weight": _stack(sd, "layers.{i}.input_layernorm.weight", L, dtype=dtype)},
            "post_attn_norm": {
                "weight": _stack(sd, "layers.{i}.post_attention_layernorm.weight", L, dtype=dtype)
            },
        },
        "final_norm": {"weight": jnp.asarray(_to_numpy(sd["norm.weight"], dtype))},
    }
    if not config.tie_word_embeddings:
        head = sd.get("lm_head.weight")
        if head is None:  # backbone-only checkpoint: fall back to tying
            head = sd["embed_tokens.weight"]
        params["lm_head"] = {"weight": jnp.asarray(_to_numpy(head, dtype).T)}
    return params


def llama_params_from_hf(state_dict, config: LlamaConfig, dtype=jnp.float32) -> dict:
    sd = _normalize_keys(state_dict)
    L = config.num_hidden_layers
    params = _llama_backbone_params(sd, config, dtype)
    if config.attention_bias:
        params["layers"]["attn"].update({
            "bq": _stack(sd, "layers.{i}.self_attn.q_proj.bias", L, dtype=dtype),
            "bk": _stack(sd, "layers.{i}.self_attn.k_proj.bias", L, dtype=dtype),
            "bv": _stack(sd, "layers.{i}.self_attn.v_proj.bias", L, dtype=dtype),
        })
    params["layers"]["mlp"] = {
        "w_gate": _stack(sd, "layers.{i}.mlp.gate_proj.weight", L, transpose=True, dtype=dtype),
        "w_up": _stack(sd, "layers.{i}.mlp.up_proj.weight", L, transpose=True, dtype=dtype),
        "w_down": _stack(sd, "layers.{i}.mlp.down_proj.weight", L, transpose=True, dtype=dtype),
    }
    return params


# --------------------------------------------------------------------- gemma
def gemma_config_from_hf(hf_config) -> LlamaConfig:
    """Gemma = the Llama skeleton with GeGLU FFN, sqrt(hidden)-scaled
    embeddings, decoupled head_dim, and (1 + weight) RMSNorm — the norm offset
    is baked into the stored weights at conversion (rms_norm is linear in its
    scale), so only the first three need config knobs."""
    get = _getter(hf_config)
    # GemmaMLP reads hidden_activation (defaulting to tanh-gelu) and ignores
    # hidden_act; mirror that precedence and only accept the activation the
    # zoo reproduces exactly.
    act = get("hidden_activation") or "gelu_pytorch_tanh"
    if act != "gelu_pytorch_tanh":
        raise ValueError(
            f"hidden_activation={act!r} is not supported for Gemma (tanh-gelu only)"
        )
    cfg = llama_config_from_hf(hf_config, check_act=False)  # Gemma validated above
    import dataclasses

    return dataclasses.replace(
        cfg,
        hidden_act="gelu_tanh",
        embedding_multiplier=float(get("hidden_size")) ** 0.5,
        tie_word_embeddings=True,  # Gemma always ties
    )


def gemma_params_from_hf(state_dict, config: LlamaConfig, dtype=jnp.float32) -> dict:
    params = llama_params_from_hf(state_dict, config, dtype=dtype)
    # Gemma's RMSNorm computes x * (1 + weight): fold the offset in once.
    for tree in (params["layers"]["input_norm"], params["layers"]["post_attn_norm"],
                 params["final_norm"]):
        tree["weight"] = tree["weight"] + 1.0
    return params


# -------------------------------------------------------------------- gemma2
def gemma2_config_from_hf(hf_config) -> LlamaConfig:
    """Gemma-2 = Gemma (GeGLU, scaled embeddings, decoupled heads, (1+w)
    norms) + sandwich norms, tanh softcapping on attention scores and final
    logits, query_pre_attn_scalar scaling, and alternating local/global
    attention (``layer_types``) — all expressible on the zoo's LlamaConfig
    (the per-layer windows drive the segmented layer scan; VERDICT r2 #5)."""
    get = _getter(hf_config)
    act = get("hidden_activation") or "gelu_pytorch_tanh"
    if act != "gelu_pytorch_tanh":
        raise ValueError(
            f"hidden_activation={act!r} is not supported for Gemma-2 (tanh-gelu only)"
        )
    cfg = llama_config_from_hf(hf_config, check_act=False)
    import dataclasses

    L = get("num_hidden_layers")
    window = get("sliding_window", 4096)
    layer_types = get("layer_types")
    if layer_types is None:  # HF default: odd-numbered (1-based) layers slide
        layer_types = [
            "sliding_attention" if (i + 1) % 2 else "full_attention" for i in range(L)
        ]
    layer_windows = tuple(
        window if t == "sliding_attention" else None for t in layer_types
    )
    return dataclasses.replace(
        cfg,
        hidden_act="gelu_tanh",
        embedding_multiplier=float(get("hidden_size")) ** 0.5,
        tie_word_embeddings=True,
        sliding_window=None,
        layer_windows=layer_windows,
        sandwich_norms=True,
        attn_logit_softcap=get("attn_logit_softcapping", 50.0),
        final_logit_softcap=get("final_logit_softcapping", 30.0),
        query_pre_attn_scalar=float(get("query_pre_attn_scalar", 256)),
    )


def gemma2_params_from_hf(state_dict, config: LlamaConfig, dtype=jnp.float32) -> dict:
    # Shared trees (incl. the (1+weight) fold on input/post-attn/final norms)
    # come from the Gemma-1 converter; only the two sandwich norms are new.
    params = gemma_params_from_hf(state_dict, config, dtype=dtype)
    sd = _normalize_keys(state_dict)
    L = config.num_hidden_layers
    params["layers"]["pre_ffw_norm"] = {
        "weight": _stack(sd, "layers.{i}.pre_feedforward_layernorm.weight", L, dtype=dtype) + 1.0
    }
    params["layers"]["post_ffw_norm"] = {
        "weight": _stack(sd, "layers.{i}.post_feedforward_layernorm.weight", L, dtype=dtype) + 1.0
    }
    return params


# --------------------------------------------------------------------- qwen2
def _qwen_windows(get):
    """Qwen2/Qwen3 window rule: layer i is windowed iff use_sliding_window and
    i >= max_window_layers (HF layer_types default). Uniform cases map onto
    sliding_window; mixed cases drive the segmented layer scan via
    layer_windows (two runs: full then windowed; VERDICT r2 #5)."""
    window, layer_windows = None, None
    if get("use_sliding_window"):
        L = get("num_hidden_layers")
        mwl = get("max_window_layers", 0) or 0
        w = get("sliding_window")
        if mwl >= L or w is None:
            window = None  # no layer windowed
        elif mwl == 0:
            window = w  # every layer windowed
        else:
            layer_windows = (None,) * mwl + (w,) * (L - mwl)
    return window, layer_windows


def qwen2_config_from_hf(hf_config) -> LlamaConfig:
    """Qwen2 = the Llama recipe + QKV biases; map onto LlamaConfig with
    ``attention_bias=True``."""
    get = _getter(hf_config)
    cfg = llama_config_from_hf(hf_config)
    import dataclasses

    window, layer_windows = _qwen_windows(get)
    return dataclasses.replace(
        cfg, attention_bias=True, sliding_window=window, layer_windows=layer_windows
    )


# Qwen2's QKV-bias loading rides the generalized Llama converter (the config
# forces attention_bias=True above).
qwen2_params_from_hf = llama_params_from_hf


def qwen3_config_from_hf(hf_config) -> LlamaConfig:
    """Qwen3 = the Llama recipe + per-head QK RMSNorm (``qk_norm``), bias-free
    projections, decoupled head_dim."""
    get = _getter(hf_config)
    cfg = llama_config_from_hf(hf_config)
    import dataclasses

    window, layer_windows = _qwen_windows(get)
    return dataclasses.replace(
        cfg, qk_norm=True, sliding_window=window, layer_windows=layer_windows
    )


def qwen3_params_from_hf(state_dict, config: LlamaConfig, dtype=jnp.float32) -> dict:
    params = llama_params_from_hf(state_dict, config, dtype=dtype)
    sd = _normalize_keys(state_dict)
    L = config.num_hidden_layers
    params["layers"]["attn"].update({
        "q_norm": _stack(sd, "layers.{i}.self_attn.q_norm.weight", L, dtype=dtype),
        "k_norm": _stack(sd, "layers.{i}.self_attn.k_norm.weight", L, dtype=dtype),
    })
    return params


def phi3_config_from_hf(hf_config) -> LlamaConfig:
    """Phi-3 = the Llama recipe with FUSED qkv/gate_up projections (split at
    conversion). Longrope-scaled long-context variants are rejected by the
    shared rope validation (llama_config_from_hf)."""
    get = _getter(hf_config)
    prf = get("partial_rotary_factor", 1.0) or 1.0
    if prf != 1.0:
        # Phi-4-mini ships model_type 'phi3' with partial rotary; the zoo
        # Llama rotates the full head — converting would silently mis-rotate
        # (measured 7.9e-3 logit error at 2 layers, compounding with depth).
        raise ValueError(
            f"partial_rotary_factor={prf} is not supported for phi3-type "
            "checkpoints (the zoo Llama applies full-width rotary)"
        )
    return llama_config_from_hf(hf_config)


def phi3_params_from_hf(state_dict, config: LlamaConfig, dtype=jnp.float32) -> dict:
    sd = _normalize_keys(state_dict)
    L = config.num_hidden_layers
    nh, nkv, hd = config.num_attention_heads, config.num_key_value_heads, config.head_dim
    inter = config.intermediate_size

    wq, wk, wv, wg, wu = [], [], [], [], []
    for i in range(L):
        qkv = _to_numpy(sd[f"layers.{i}.self_attn.qkv_proj.weight"], dtype)  # (q+k+v, h)
        wq.append(qkv[: nh * hd].T)
        wk.append(qkv[nh * hd: nh * hd + nkv * hd].T)
        wv.append(qkv[nh * hd + nkv * hd:].T)
        gu = _to_numpy(sd[f"layers.{i}.mlp.gate_up_proj.weight"], dtype)  # (2i, h)
        wg.append(gu[:inter].T)
        wu.append(gu[inter:].T)

    params = {
        "embed": {"weight": jnp.asarray(_to_numpy(sd["embed_tokens.weight"], dtype))},
        "layers": {
            "attn": {
                "wq": jnp.asarray(np.stack(wq)),
                "wk": jnp.asarray(np.stack(wk)),
                "wv": jnp.asarray(np.stack(wv)),
                "wo": _stack(sd, "layers.{i}.self_attn.o_proj.weight", L, transpose=True, dtype=dtype),
            },
            "mlp": {
                "w_gate": jnp.asarray(np.stack(wg)),
                "w_up": jnp.asarray(np.stack(wu)),
                "w_down": _stack(sd, "layers.{i}.mlp.down_proj.weight", L, transpose=True, dtype=dtype),
            },
            "input_norm": {"weight": _stack(sd, "layers.{i}.input_layernorm.weight", L, dtype=dtype)},
            "post_attn_norm": {
                "weight": _stack(sd, "layers.{i}.post_attention_layernorm.weight", L, dtype=dtype)
            },
        },
        "final_norm": {"weight": jnp.asarray(_to_numpy(sd["norm.weight"], dtype))},
    }
    if not config.tie_word_embeddings:
        head = sd.get("lm_head.weight", sd["embed_tokens.weight"])
        params["lm_head"] = {"weight": jnp.asarray(_to_numpy(head, dtype).T)}
    return params


# ---------------------------------------------------------------------- gpt2
def gpt2_config_from_hf(hf_config) -> GPT2Config:
    get = _getter(hf_config)
    act = get("activation_function", "gelu_new")
    if act not in ("gelu_new", "gelu_pytorch_tanh"):
        raise ValueError(f"activation_function={act!r} is not supported (zoo GPT-2 uses tanh-gelu)")
    if get("scale_attn_weights") is False:
        raise ValueError(
            "scale_attn_weights=False checkpoints are not supported "
            "(zoo GPT-2 always scales by 1/sqrt(head_dim))"
        )
    if get("scale_attn_by_inverse_layer_idx") or get("reorder_and_upcast_attn"):
        raise ValueError(
            "scale_attn_by_inverse_layer_idx / reorder_and_upcast_attn checkpoints "
            "are not supported (zoo GPT-2 uses uniform 1/sqrt(head_dim) scaling)"
        )
    n_embd = get("n_embd") or get("hidden_size")
    return GPT2Config(
        vocab_size=get("vocab_size"),
        hidden_size=n_embd,
        intermediate_size=get("n_inner") or 4 * n_embd,
        num_hidden_layers=get("n_layer") or get("num_hidden_layers"),
        num_attention_heads=get("n_head") or get("num_attention_heads"),
        max_position_embeddings=get("n_positions") or get("max_position_embeddings", 1024),
        layer_norm_eps=get("layer_norm_epsilon", 1e-5),
    )


def gpt2_params_from_hf(state_dict, config: GPT2Config, dtype=jnp.float32) -> dict:
    sd = _normalize_keys(state_dict)
    L = config.num_hidden_layers

    def ln(i_pattern):
        return {
            "scale": _stack(sd, f"h.{{i}}.{i_pattern}.weight", L, dtype=dtype),
            "bias": _stack(sd, f"h.{{i}}.{i_pattern}.bias", L, dtype=dtype),
        }

    params = {
        "embed": {
            "wte": jnp.asarray(_to_numpy(sd["wte.weight"], dtype)),
            "wpe": jnp.asarray(_to_numpy(sd["wpe.weight"], dtype)),
        },
        "layers": {
            # transformers GPT-2 uses Conv1D: weights already (in, out).
            "attn": {
                "w_qkv": _stack(sd, "h.{i}.attn.c_attn.weight", L, dtype=dtype),
                "b_qkv": _stack(sd, "h.{i}.attn.c_attn.bias", L, dtype=dtype),
                "wo": _stack(sd, "h.{i}.attn.c_proj.weight", L, dtype=dtype),
                "bo": _stack(sd, "h.{i}.attn.c_proj.bias", L, dtype=dtype),
            },
            "mlp": {
                "w_in": _stack(sd, "h.{i}.mlp.c_fc.weight", L, dtype=dtype),
                "b_in": _stack(sd, "h.{i}.mlp.c_fc.bias", L, dtype=dtype),
                "w_out": _stack(sd, "h.{i}.mlp.c_proj.weight", L, dtype=dtype),
                "b_out": _stack(sd, "h.{i}.mlp.c_proj.bias", L, dtype=dtype),
            },
            "ln_1": ln("ln_1"),
            "ln_2": ln("ln_2"),
        },
        "ln_f": {
            "scale": jnp.asarray(_to_numpy(sd["ln_f.weight"], dtype)),
            "bias": jnp.asarray(_to_numpy(sd["ln_f.bias"], dtype)),
        },
    }
    return params


# ---------------------------------------------------------------------- bert
def bert_config_from_hf(hf_config) -> BertConfig:
    get = _getter(hf_config)
    act = get("hidden_act", "gelu")
    if act not in ("gelu", "gelu_python"):
        raise ValueError(f"hidden_act={act!r} is not supported (zoo BERT uses exact gelu)")
    pos_type = get("position_embedding_type", "absolute")
    if pos_type != "absolute":
        raise ValueError(
            f"position_embedding_type={pos_type!r} is not supported (zoo BERT uses "
            "absolute learned positions; relative distance_embedding weights would be dropped)"
        )
    return BertConfig(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        intermediate_size=get("intermediate_size"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        max_position_embeddings=get("max_position_embeddings", 512),
        type_vocab_size=get("type_vocab_size", 2),
        layer_norm_eps=get("layer_norm_eps", 1e-12),
        num_labels=get("num_labels", 2) or 2,
        hidden_dropout_prob=get("hidden_dropout_prob", 0.1),
    )


def bert_params_from_hf(state_dict, config: BertConfig, dtype=jnp.float32) -> dict:
    """BertForSequenceClassification layout; a backbone-only checkpoint gets a
    fresh pooler/classifier (the standard fine-tuning setup)."""
    sd = _normalize_keys(state_dict)
    L = config.num_hidden_layers
    h = config.hidden_size

    def ln_pair(pattern):
        return {
            "scale": _stack(sd, f"{pattern}.weight", L, dtype=dtype),
            "bias": _stack(sd, f"{pattern}.bias", L, dtype=dtype),
        }

    fresh_head_rng = np.random.default_rng(0)

    def head_linear(key_w, key_b, out_dim, transpose=True):
        if key_w in sd:
            w = _to_numpy(sd[key_w], dtype)
            return {
                "w": jnp.asarray(w.T if transpose else w),
                "b": jnp.asarray(_to_numpy(sd[key_b], dtype)),
            }
        rng = fresh_head_rng  # one stream: fresh pooler/classifier stay independent
        return {
            "w": jnp.asarray(rng.normal(scale=0.02, size=(h, out_dim)).astype(dtype or np.float32)),
            "b": jnp.zeros((out_dim,), dtype or jnp.float32),
        }

    params = {
        "embeddings": {
            "word": jnp.asarray(_to_numpy(sd["embeddings.word_embeddings.weight"], dtype)),
            "position": jnp.asarray(_to_numpy(sd["embeddings.position_embeddings.weight"], dtype)),
            "token_type": jnp.asarray(_to_numpy(sd["embeddings.token_type_embeddings.weight"], dtype)),
            "norm": {
                "scale": jnp.asarray(_to_numpy(sd["embeddings.LayerNorm.weight"], dtype)),
                "bias": jnp.asarray(_to_numpy(sd["embeddings.LayerNorm.bias"], dtype)),
            },
        },
        "layers": {
            "attn": {
                "wq": _stack(sd, "encoder.layer.{i}.attention.self.query.weight", L, transpose=True, dtype=dtype),
                "bq": _stack(sd, "encoder.layer.{i}.attention.self.query.bias", L, dtype=dtype),
                "wk": _stack(sd, "encoder.layer.{i}.attention.self.key.weight", L, transpose=True, dtype=dtype),
                "bk": _stack(sd, "encoder.layer.{i}.attention.self.key.bias", L, dtype=dtype),
                "wv": _stack(sd, "encoder.layer.{i}.attention.self.value.weight", L, transpose=True, dtype=dtype),
                "bv": _stack(sd, "encoder.layer.{i}.attention.self.value.bias", L, dtype=dtype),
                "wo": _stack(sd, "encoder.layer.{i}.attention.output.dense.weight", L, transpose=True, dtype=dtype),
                "bo": _stack(sd, "encoder.layer.{i}.attention.output.dense.bias", L, dtype=dtype),
            },
            "attn_norm": ln_pair("encoder.layer.{i}.attention.output.LayerNorm"),
            "mlp": {
                "w_in": _stack(sd, "encoder.layer.{i}.intermediate.dense.weight", L, transpose=True, dtype=dtype),
                "b_in": _stack(sd, "encoder.layer.{i}.intermediate.dense.bias", L, dtype=dtype),
                "w_out": _stack(sd, "encoder.layer.{i}.output.dense.weight", L, transpose=True, dtype=dtype),
                "b_out": _stack(sd, "encoder.layer.{i}.output.dense.bias", L, dtype=dtype),
            },
            "mlp_norm": ln_pair("encoder.layer.{i}.output.LayerNorm"),
        },
        "pooler": head_linear("pooler.dense.weight", "pooler.dense.bias", h),
        "classifier": head_linear("classifier.weight", "classifier.bias", config.num_labels),
    }
    return params


# -------------------------------------------------------------------- mixtral
def mixtral_config_from_hf(hf_config):
    """Mixtral = Llama attention/norms + top-k sparse MoE FFN. Our renormalized
    top-k gate is mathematically identical to Mixtral's softmax-over-top-k-
    logits; ``capacity_factor = num_experts/top_k`` guarantees no token is ever
    dropped, so converted inference is exact (tests/test_convert.py)."""
    get = _getter(hf_config)
    from .llama import SUPPORTED_ROPE_TYPES

    rope_scaling = get("rope_scaling")
    if rope_scaling:
        rope_scaling = dict(rope_scaling)
        rope_type = rope_scaling.get("rope_type", rope_scaling.get("type", "default"))
        if rope_type not in SUPPORTED_ROPE_TYPES:
            raise ValueError(
                f"rope_type={rope_type!r} is not supported by the zoo MoE Llama "
                f"(supported: {SUPPORTED_ROPE_TYPES})"
            )
    E = get("num_local_experts", 8)
    k = get("num_experts_per_tok", 2)
    return MoELlamaConfig(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        intermediate_size=get("intermediate_size"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        num_key_value_heads=get("num_key_value_heads") or get("num_attention_heads"),
        max_position_embeddings=get("max_position_embeddings", 2048),
        rms_norm_eps=get("rms_norm_eps", 1e-5),
        rope_theta=get("rope_theta", 10000.0),
        tie_word_embeddings=bool(get("tie_word_embeddings", False)),
        num_experts=E,
        moe_top_k=k,
        capacity_factor=float(E) / k,  # drop-free: exact Mixtral routing
        router_aux_coef=coef if (coef := get("router_aux_loss_coef")) is not None else 0.001,
        rope_scaling=rope_scaling,
        sliding_window=get("sliding_window"),
    )


def mixtral_params_from_hf(state_dict, config, dtype=jnp.float32) -> dict:
    sd = _normalize_keys(state_dict)
    L, E = config.num_hidden_layers, config.num_experts
    params = _llama_backbone_params(sd, config, dtype)

    def expert_stack(w_name, transpose=True):
        mats = []
        for i in range(L):
            per_layer = []
            for e in range(E):
                m = _to_numpy(
                    sd[f"layers.{i}.block_sparse_moe.experts.{e}.{w_name}.weight"], dtype
                )
                per_layer.append(m.T if transpose else m)
            mats.append(np.stack(per_layer))
        return jnp.asarray(np.stack(mats))  # (L, E, in, out)

    params["layers"]["mlp"] = {
        "router": _stack(sd, "layers.{i}.block_sparse_moe.gate.weight", L,
                         transpose=True, dtype=dtype),
        "w_gate": expert_stack("w1"),
        "w_up": expert_stack("w3"),
        "w_down": expert_stack("w2"),
    }
    return params


# ------------------------------------------------------------------------ t5
def t5_config_from_hf(hf_config) -> T5Config:
    get = _getter(hf_config)
    # HF encodes the FFN recipe in feed_forward_proj: 'relu' (original T5) or
    # 'gated-gelu' (t5-v1.1: wi_0 gate * wi_1, tanh-gelu, untied head).
    ff_proj = get("feed_forward_proj", "relu")
    if ff_proj not in ("relu", "gated-gelu"):
        raise ValueError(
            f"feed_forward_proj={ff_proj!r} is not supported "
            "(zoo T5 implements the original relu recipe and v1.1's gated-gelu)"
        )
    gated = ff_proj == "gated-gelu"
    pad = get("pad_token_id", 0)
    pad = 0 if pad is None else pad
    start = get("decoder_start_token_id")
    # transformers leaves this None and falls back to pad at generate time.
    start = pad if start is None else start
    return T5Config(
        vocab_size=get("vocab_size"),
        d_model=get("d_model"),
        d_kv=get("d_kv"),
        d_ff=get("d_ff"),
        num_layers=get("num_layers"),
        num_decoder_layers=get("num_decoder_layers") or get("num_layers"),
        num_heads=get("num_heads"),
        relative_attention_num_buckets=get("relative_attention_num_buckets", 32),
        relative_attention_max_distance=get("relative_attention_max_distance", 128),
        layer_norm_epsilon=get("layer_norm_epsilon", 1e-6),
        pad_token_id=pad,
        decoder_start_token_id=start,
        gated_act=gated,
        dense_act="gelu_tanh" if gated else "relu",
        tie_word_embeddings=bool(get("tie_word_embeddings", True)),
    )


def t5_params_from_hf(state_dict, config: T5Config, dtype=jnp.float32) -> dict:
    """HF T5 blocks are layer.0=self-attn, layer.1=cross-attn (decoder) or MLP
    (encoder), layer.2=MLP (decoder); the relative bias lives only in block 0."""
    sd = dict(state_dict)  # T5 keys carry no strippable prefix

    def attn(side, L, li, name):
        base = f"{side}.block.{{i}}.layer.{li}.{name}"
        return {
            "wq": _stack(sd, f"{base}.q.weight", L, transpose=True, dtype=dtype),
            "wk": _stack(sd, f"{base}.k.weight", L, transpose=True, dtype=dtype),
            "wv": _stack(sd, f"{base}.v.weight", L, transpose=True, dtype=dtype),
            "wo": _stack(sd, f"{base}.o.weight", L, transpose=True, dtype=dtype),
        }

    def norm(side, L, li):
        return {
            "scale": _stack(sd, f"{side}.block.{{i}}.layer.{li}.layer_norm.weight", L, dtype=dtype)
        }

    def mlp(side, L, li):
        base = f"{side}.block.{{i}}.layer.{li}.DenseReluDense"
        if config.gated_act:  # v1.1: wi_0 (gated) + wi_1
            return {
                "wi_0": _stack(sd, f"{base}.wi_0.weight", L, transpose=True, dtype=dtype),
                "wi_1": _stack(sd, f"{base}.wi_1.weight", L, transpose=True, dtype=dtype),
                "wo": _stack(sd, f"{base}.wo.weight", L, transpose=True, dtype=dtype),
            }
        return {
            "wi": _stack(sd, f"{base}.wi.weight", L, transpose=True, dtype=dtype),
            "wo": _stack(sd, f"{base}.wo.weight", L, transpose=True, dtype=dtype),
        }

    def side_params(side, L, cross):
        layers = {
            "self_attn": attn(side, L, 0, "SelfAttention"),
            "self_norm": norm(side, L, 0),
        }
        if cross:
            layers["cross_attn"] = attn(side, L, 1, "EncDecAttention")
            layers["cross_norm"] = norm(side, L, 1)
        mlp_idx = 2 if cross else 1
        layers["mlp"] = mlp(side, L, mlp_idx)
        layers["mlp_norm"] = norm(side, L, mlp_idx)
        return {
            "layers": layers,
            "rel_bias": jnp.asarray(_to_numpy(
                sd[f"{side}.block.0.layer.0.SelfAttention.relative_attention_bias.weight"], dtype
            )),
            "final_norm": {
                "scale": jnp.asarray(_to_numpy(sd[f"{side}.final_layer_norm.weight"], dtype))
            },
        }

    params = {
        "shared": jnp.asarray(_to_numpy(sd["shared.weight"], dtype)),
        "encoder": side_params("encoder", config.num_layers, cross=False),
        "decoder": side_params("decoder", config.num_decoder_layers, cross=True),
    }
    if not config.tie_word_embeddings:  # v1.1 untied head: (V, d) -> (d, V)
        params["lm_head"] = jnp.asarray(_to_numpy(sd["lm_head.weight"], dtype).T)
    return params


# --------------------------------------------------- classic GPTs (gptx.py)
def _map_act(act: str) -> str:
    """HF activation_function names → the zoo's three classic-GPT activations."""
    if act in ("gelu", "gelu_python"):
        return "gelu"
    if act in ("gelu_new", "gelu_fast", "gelu_pytorch_tanh"):
        return "gelu_tanh"
    if act == "relu":
        return "relu"
    raise ValueError(f"activation {act!r} is not supported by the classic-GPT zoo model")


def gpt_neox_config_from_hf(hf_config) -> GPTXConfig:
    """GPT-NeoX (reference baseline model family: GPT-NeoX-20B, BASELINE.md).
    Partial half-split rotary (``rotary_pct``), parallel residual with two
    norms, fused per-head-interleaved QKV (de-interleaved at conversion)."""
    get = _getter(hf_config)
    head_dim = get("hidden_size") // get("num_attention_heads")
    rotary_dim = int(head_dim * get("rotary_pct", 0.25))
    if rotary_dim % 2:
        raise ValueError(f"rotary_pct yields odd rotary_dim {rotary_dim} at head_dim {head_dim}")
    rope_scaling = get("rope_scaling")
    if rope_scaling:
        rope_scaling = dict(rope_scaling)
        rope_type = rope_scaling.get("rope_type", rope_scaling.get("type", "default"))
        if rope_type not in ("linear", "llama3", "yarn", "default"):
            # Mirrors llama_config_from_hf: converting would silently
            # mis-position long contexts ('dynamic' needs cache-capacity
            # pinning the classic-GPT skeleton doesn't carry).
            raise ValueError(
                f"rope_type={rope_type!r} is not supported for GPT-NeoX checkpoints "
                "(supported: linear, llama3, yarn)"
            )
    # Sequential NeoX checkpoints (use_parallel_residual=False) reuse the same
    # params with OPT's residual topology.
    parallel = bool(get("use_parallel_residual", True))
    return GPTXConfig(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        intermediate_size=get("intermediate_size"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        max_position_embeddings=get("max_position_embeddings", 2048),
        layer_norm_eps=get("layer_norm_eps", 1e-5),
        position_style="rotary_neox",
        rotary_dim=rotary_dim,
        rope_theta=get("rotary_emb_base", get("rope_theta", 10000.0)),
        rope_scaling=rope_scaling,
        parallel_residual=parallel,
        hidden_act=_map_act(get("hidden_act", "gelu")),
        attention_bias=bool(get("attention_bias", True)),
        tie_word_embeddings=bool(get("tie_word_embeddings", False)),
    )


def gpt_neox_params_from_hf(state_dict, config: GPTXConfig, dtype=jnp.float32) -> dict:
    sd = _normalize_keys(state_dict, prefixes=("gpt_neox.",))
    L = config.num_hidden_layers
    nh, hd, h = config.num_attention_heads, config.head_dim, config.hidden_size

    def deinterleave(i):
        # HF NeoX fuses QKV per head: rows are [q_h, k_h, v_h] blocks for each
        # head h. Split to the zoo's contiguous [Q | K | V] column layout.
        w = _to_numpy(sd[f"layers.{i}.attention.query_key_value.weight"], dtype)
        w = w.reshape(nh, 3, hd, h)
        wq, wk, wv = (w[:, j].reshape(nh * hd, h) for j in range(3))
        out = {"w": np.concatenate([wq, wk, wv], axis=0).T}
        bkey = f"layers.{i}.attention.query_key_value.bias"
        if bkey in sd:
            b = _to_numpy(sd[bkey], dtype).reshape(nh, 3, hd)
            out["b"] = np.concatenate([b[:, j].reshape(nh * hd) for j in range(3)])
        return out

    qkv = [deinterleave(i) for i in range(L)]
    attn = {
        "w_qkv": jnp.asarray(np.stack([q["w"] for q in qkv])),
        "wo": _stack(sd, "layers.{i}.attention.dense.weight", L, transpose=True, dtype=dtype),
    }
    if config.attention_bias:
        attn["b_qkv"] = jnp.asarray(np.stack([q["b"] for q in qkv]))
        attn["bo"] = _stack(sd, "layers.{i}.attention.dense.bias", L, dtype=dtype)

    def ln(name):
        return {
            "scale": _stack(sd, f"layers.{{i}}.{name}.weight", L, dtype=dtype),
            "bias": _stack(sd, f"layers.{{i}}.{name}.bias", L, dtype=dtype),
        }

    params = {
        "embed": {"wte": jnp.asarray(_to_numpy(sd["embed_in.weight"], dtype))},
        "layers": {
            "attn": attn,
            "mlp": {
                "w_in": _stack(sd, "layers.{i}.mlp.dense_h_to_4h.weight", L, transpose=True, dtype=dtype),
                "b_in": _stack(sd, "layers.{i}.mlp.dense_h_to_4h.bias", L, dtype=dtype),
                "w_out": _stack(sd, "layers.{i}.mlp.dense_4h_to_h.weight", L, transpose=True, dtype=dtype),
                "b_out": _stack(sd, "layers.{i}.mlp.dense_4h_to_h.bias", L, dtype=dtype),
            },
            "ln_1": ln("input_layernorm"),
            "ln_2": ln("post_attention_layernorm"),
        },
        "ln_f": {
            "scale": jnp.asarray(_to_numpy(sd["final_layer_norm.weight"], dtype)),
            "bias": jnp.asarray(_to_numpy(sd["final_layer_norm.bias"], dtype)),
        },
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = {"weight": jnp.asarray(_to_numpy(sd["embed_out.weight"], dtype).T)}
    return params


def gptj_config_from_hf(hf_config) -> GPTXConfig:
    """GPT-J (reference baseline model family: GPT-J-6B, BASELINE.md).
    Interleaved-pair rotary on ``rotary_dim`` lanes, parallel residual sharing
    ONE layernorm, bias-free attention, untied LM head with bias."""
    get = _getter(hf_config)
    n_embd = get("n_embd") or get("hidden_size")
    rotary_dim = get("rotary_dim")
    if rotary_dim is None:
        raise ValueError("GPT-J checkpoints without rotary_dim are not supported")
    return GPTXConfig(
        vocab_size=get("vocab_size"),
        hidden_size=n_embd,
        intermediate_size=get("n_inner") or 4 * n_embd,
        num_hidden_layers=get("n_layer") or get("num_hidden_layers"),
        num_attention_heads=get("n_head") or get("num_attention_heads"),
        max_position_embeddings=get("n_positions") or get("max_position_embeddings", 2048),
        layer_norm_eps=get("layer_norm_epsilon", 1e-5),
        position_style="rotary_gptj",
        rotary_dim=rotary_dim,
        parallel_residual=True,
        shared_layernorm=True,
        hidden_act=_map_act(get("activation_function", "gelu_new")),
        attention_bias=False,
        tie_word_embeddings=bool(get("tie_word_embeddings", False)),
        lm_head_bias=True,
    )


def gptj_params_from_hf(state_dict, config: GPTXConfig, dtype=jnp.float32) -> dict:
    sd = _normalize_keys(state_dict)
    L = config.num_hidden_layers

    def qkv(i):
        mats = [
            _to_numpy(sd[f"h.{i}.attn.{p}_proj.weight"], dtype).T for p in ("q", "k", "v")
        ]
        return np.concatenate(mats, axis=1)

    params = {
        "embed": {"wte": jnp.asarray(_to_numpy(sd["wte.weight"], dtype))},
        "layers": {
            "attn": {
                "w_qkv": jnp.asarray(np.stack([qkv(i) for i in range(L)])),
                "wo": _stack(sd, "h.{i}.attn.out_proj.weight", L, transpose=True, dtype=dtype),
            },
            "mlp": {
                "w_in": _stack(sd, "h.{i}.mlp.fc_in.weight", L, transpose=True, dtype=dtype),
                "b_in": _stack(sd, "h.{i}.mlp.fc_in.bias", L, dtype=dtype),
                "w_out": _stack(sd, "h.{i}.mlp.fc_out.weight", L, transpose=True, dtype=dtype),
                "b_out": _stack(sd, "h.{i}.mlp.fc_out.bias", L, dtype=dtype),
            },
            "ln_1": {
                "scale": _stack(sd, "h.{i}.ln_1.weight", L, dtype=dtype),
                "bias": _stack(sd, "h.{i}.ln_1.bias", L, dtype=dtype),
            },
        },
        "ln_f": {
            "scale": jnp.asarray(_to_numpy(sd["ln_f.weight"], dtype)),
            "bias": jnp.asarray(_to_numpy(sd["ln_f.bias"], dtype)),
        },
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = {
            "weight": jnp.asarray(_to_numpy(sd["lm_head.weight"], dtype).T),
            "bias": jnp.asarray(_to_numpy(sd["lm_head.bias"], dtype)),
        }
    return params


def opt_config_from_hf(hf_config) -> GPTXConfig:
    """OPT (reference baseline model family: OPT-30B offload regime,
    BASELINE.md). Learned positions at a +2 table offset, sequential pre-LN
    blocks, relu FFN, tied head."""
    get = _getter(hf_config)
    if not get("do_layer_norm_before", True):
        raise ValueError(
            "do_layer_norm_before=False (OPT-350M) is not supported: the zoo "
            "model is pre-LN; converting would silently misplace every norm"
        )
    if get("_remove_final_layer_norm"):
        raise ValueError("_remove_final_layer_norm checkpoints (early OPT snapshots) are not supported")
    h = get("hidden_size")
    proj = get("word_embed_proj_dim", h) or h
    if proj != h:
        raise ValueError(
            f"word_embed_proj_dim={proj} != hidden_size={h} (OPT-350M's factored "
            "embedding) is not supported"
        )
    if not get("enable_bias", True):
        raise ValueError("enable_bias=False OPT variants are not supported")
    if not get("layer_norm_elementwise_affine", True):
        raise ValueError("layer_norm_elementwise_affine=False OPT variants are not supported")
    return GPTXConfig(
        vocab_size=get("vocab_size"),
        hidden_size=h,
        intermediate_size=get("ffn_dim"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        max_position_embeddings=get("max_position_embeddings", 2048),
        layer_norm_eps=1e-5,
        position_style="learned",
        position_offset=2,
        parallel_residual=False,
        hidden_act=_map_act(get("activation_function", "relu")),
        attention_bias=True,
        tie_word_embeddings=bool(get("tie_word_embeddings", True)),
    )


def opt_params_from_hf(state_dict, config: GPTXConfig, dtype=jnp.float32) -> dict:
    sd = _normalize_keys(state_dict, prefixes=("model.decoder.", "decoder.", "model."))
    L = config.num_hidden_layers

    def qkv_w(i):
        return np.concatenate(
            [_to_numpy(sd[f"layers.{i}.self_attn.{p}_proj.weight"], dtype).T for p in ("q", "k", "v")],
            axis=1,
        )

    def qkv_b(i):
        return np.concatenate(
            [_to_numpy(sd[f"layers.{i}.self_attn.{p}_proj.bias"], dtype) for p in ("q", "k", "v")]
        )

    def ln(name):
        return {
            "scale": _stack(sd, f"layers.{{i}}.{name}.weight", L, dtype=dtype),
            "bias": _stack(sd, f"layers.{{i}}.{name}.bias", L, dtype=dtype),
        }

    params = {
        "embed": {
            "wte": jnp.asarray(_to_numpy(sd["embed_tokens.weight"], dtype)),
            "wpe": jnp.asarray(_to_numpy(sd["embed_positions.weight"], dtype)),
        },
        "layers": {
            "attn": {
                "w_qkv": jnp.asarray(np.stack([qkv_w(i) for i in range(L)])),
                "b_qkv": jnp.asarray(np.stack([qkv_b(i) for i in range(L)])),
                "wo": _stack(sd, "layers.{i}.self_attn.out_proj.weight", L, transpose=True, dtype=dtype),
                "bo": _stack(sd, "layers.{i}.self_attn.out_proj.bias", L, dtype=dtype),
            },
            "mlp": {
                "w_in": _stack(sd, "layers.{i}.fc1.weight", L, transpose=True, dtype=dtype),
                "b_in": _stack(sd, "layers.{i}.fc1.bias", L, dtype=dtype),
                "w_out": _stack(sd, "layers.{i}.fc2.weight", L, transpose=True, dtype=dtype),
                "b_out": _stack(sd, "layers.{i}.fc2.bias", L, dtype=dtype),
            },
            # OPT names its pre-MLP norm "final_layer_norm" per layer.
            "ln_1": ln("self_attn_layer_norm"),
            "ln_2": ln("final_layer_norm"),
        },
        "ln_f": {
            "scale": jnp.asarray(_to_numpy(sd["final_layer_norm.weight"], dtype)),
            "bias": jnp.asarray(_to_numpy(sd["final_layer_norm.bias"], dtype)),
        },
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = {"weight": jnp.asarray(_to_numpy(sd["lm_head.weight"], dtype).T)}
    return params


# -------------------------------------------------------------------- whisper
def whisper_config_from_hf(hf_config) -> WhisperConfig:
    """Whisper (audio seq2seq; HF ``WhisperForConditionalGeneration``)."""
    get = _getter(hf_config)
    act = get("activation_function", "gelu")
    if act != "gelu":
        raise ValueError(f"activation_function={act!r} is not supported (Whisper uses exact gelu)")
    if get("scale_embedding"):
        raise ValueError("scale_embedding=True Whisper variants are not supported")
    if get("tie_word_embeddings", True) is False:
        raise ValueError("untied-head Whisper variants are not supported (proj_out is tied)")
    return WhisperConfig(
        vocab_size=get("vocab_size"),
        num_mel_bins=get("num_mel_bins", 80),
        d_model=get("d_model"),
        encoder_layers=get("encoder_layers"),
        encoder_attention_heads=get("encoder_attention_heads"),
        decoder_layers=get("decoder_layers"),
        decoder_attention_heads=get("decoder_attention_heads"),
        encoder_ffn_dim=get("encoder_ffn_dim"),
        decoder_ffn_dim=get("decoder_ffn_dim"),
        max_source_positions=get("max_source_positions", 1500),
        max_target_positions=get("max_target_positions", 448),
        decoder_start_token_id=get("decoder_start_token_id", 50257),
        pad_token_id=get("pad_token_id", 50256),
        eos_token_id=get("eos_token_id", 50256),
    )


def whisper_params_from_hf(state_dict, config, dtype=jnp.float32) -> dict:
    sd = _normalize_keys(state_dict)  # strips the "model." wrapper

    def attn(side, L, name):
        p = {
            "wq": _stack(sd, f"{side}.layers.{{i}}.{name}.q_proj.weight", L, transpose=True, dtype=dtype),
            "bq": _stack(sd, f"{side}.layers.{{i}}.{name}.q_proj.bias", L, dtype=dtype),
            "wk": _stack(sd, f"{side}.layers.{{i}}.{name}.k_proj.weight", L, transpose=True, dtype=dtype),
            "wv": _stack(sd, f"{side}.layers.{{i}}.{name}.v_proj.weight", L, transpose=True, dtype=dtype),
            "bv": _stack(sd, f"{side}.layers.{{i}}.{name}.v_proj.bias", L, dtype=dtype),
            "wo": _stack(sd, f"{side}.layers.{{i}}.{name}.out_proj.weight", L, transpose=True, dtype=dtype),
            "bo": _stack(sd, f"{side}.layers.{{i}}.{name}.out_proj.bias", L, dtype=dtype),
        }
        return p

    def ln(side, L, name):
        return {
            "scale": _stack(sd, f"{side}.layers.{{i}}.{name}.weight", L, dtype=dtype),
            "bias": _stack(sd, f"{side}.layers.{{i}}.{name}.bias", L, dtype=dtype),
        }

    def mlp(side, L):
        return {
            "w_in": _stack(sd, f"{side}.layers.{{i}}.fc1.weight", L, transpose=True, dtype=dtype),
            "b_in": _stack(sd, f"{side}.layers.{{i}}.fc1.bias", L, dtype=dtype),
            "w_out": _stack(sd, f"{side}.layers.{{i}}.fc2.weight", L, transpose=True, dtype=dtype),
            "b_out": _stack(sd, f"{side}.layers.{{i}}.fc2.bias", L, dtype=dtype),
        }

    def top_ln(key):
        return {"scale": jnp.asarray(_to_numpy(sd[f"{key}.weight"], dtype)),
                "bias": jnp.asarray(_to_numpy(sd[f"{key}.bias"], dtype))}

    Le, Ld = config.encoder_layers, config.decoder_layers
    # torch Conv1d stores (out, in, K); ours is (K, in, out).
    conv = lambda k: {"w": jnp.asarray(_to_numpy(sd[f"{k}.weight"], dtype).transpose(2, 1, 0)),
                      "b": jnp.asarray(_to_numpy(sd[f"{k}.bias"], dtype))}
    return {
        "encoder": {
            "conv1": conv("encoder.conv1"),
            "conv2": conv("encoder.conv2"),
            "pos": jnp.asarray(_to_numpy(sd["encoder.embed_positions.weight"], dtype)),
            "layers": {
                "self_attn": attn("encoder", Le, "self_attn"),
                "self_norm": ln("encoder", Le, "self_attn_layer_norm"),
                "mlp": mlp("encoder", Le),
                "mlp_norm": ln("encoder", Le, "final_layer_norm"),
            },
            "final_norm": top_ln("encoder.layer_norm"),
        },
        "decoder": {
            "embed": jnp.asarray(_to_numpy(sd["decoder.embed_tokens.weight"], dtype)),
            "pos": jnp.asarray(_to_numpy(sd["decoder.embed_positions.weight"], dtype)),
            "layers": {
                "self_attn": attn("decoder", Ld, "self_attn"),
                "self_norm": ln("decoder", Ld, "self_attn_layer_norm"),
                "cross_attn": attn("decoder", Ld, "encoder_attn"),
                "cross_norm": ln("decoder", Ld, "encoder_attn_layer_norm"),
                "mlp": mlp("decoder", Ld),
                "mlp_norm": ln("decoder", Ld, "final_layer_norm"),
            },
            "final_norm": top_ln("decoder.layer_norm"),
        },
    }


# ------------------------------------------------------------------------ vit
def vit_config_from_hf(hf_config) -> "ViTConfig":
    from .vit import ViTConfig

    get = _getter(hf_config)
    act = get("hidden_act", "gelu")
    if act not in ("gelu", "gelu_python"):
        raise ValueError(f"hidden_act={act!r} is not supported (zoo ViT uses exact gelu)")
    if not get("qkv_bias", True):
        raise ValueError("qkv_bias=False ViT variants are not supported")
    n_labels = get("num_labels")
    if n_labels is None:
        n_labels = len(get("id2label") or {}) or 1000
    return ViTConfig(
        image_size=get("image_size", 224),
        patch_size=get("patch_size", 16),
        num_channels=get("num_channels", 3),
        hidden_size=get("hidden_size"),
        num_hidden_layers=get("num_hidden_layers"),
        num_attention_heads=get("num_attention_heads"),
        intermediate_size=get("intermediate_size"),
        num_labels=n_labels,
        layer_norm_eps=get("layer_norm_eps", 1e-12),
    )


def vit_params_from_hf(state_dict, config, dtype=jnp.float32) -> dict:
    sd = _normalize_keys(state_dict, prefixes=("vit.",))
    L = config.num_hidden_layers
    h = config.hidden_size

    def qkv(i, what):
        mats = [
            _to_numpy(sd[f"encoder.layer.{i}.attention.attention.{p}.{what}"], dtype)
            for p in ("query", "key", "value")
        ]
        if what == "weight":
            return np.concatenate([m.T for m in mats], axis=1)  # (h, 3h)
        return np.concatenate(mats)

    def ln(name):
        return {
            "scale": _stack(sd, f"encoder.layer.{{i}}.{name}.weight", L, dtype=dtype),
            "bias": _stack(sd, f"encoder.layer.{{i}}.{name}.bias", L, dtype=dtype),
        }

    # Conv kernel (h, C, P, P) → (C·P·P, h) in the (c, ph, pw) lane order the
    # model's reshape-patchify produces.
    proj = _to_numpy(sd["embeddings.patch_embeddings.projection.weight"], dtype)
    params = {
        "embed": {
            "patch": {"w": jnp.asarray(proj.reshape(h, -1).T),
                      "b": jnp.asarray(_to_numpy(sd["embeddings.patch_embeddings.projection.bias"], dtype))},
            "cls": jnp.asarray(_to_numpy(sd["embeddings.cls_token"], dtype)),
            "pos": jnp.asarray(_to_numpy(sd["embeddings.position_embeddings"], dtype)[0]),
        },
        "layers": {
            "attn": {
                "w_qkv": jnp.asarray(np.stack([qkv(i, "weight") for i in range(L)])),
                "b_qkv": jnp.asarray(np.stack([qkv(i, "bias") for i in range(L)])),
                "wo": _stack(sd, "encoder.layer.{i}.attention.output.dense.weight", L, transpose=True, dtype=dtype),
                "bo": _stack(sd, "encoder.layer.{i}.attention.output.dense.bias", L, dtype=dtype),
            },
            "mlp": {
                "w_in": _stack(sd, "encoder.layer.{i}.intermediate.dense.weight", L, transpose=True, dtype=dtype),
                "b_in": _stack(sd, "encoder.layer.{i}.intermediate.dense.bias", L, dtype=dtype),
                "w_out": _stack(sd, "encoder.layer.{i}.output.dense.weight", L, transpose=True, dtype=dtype),
                "b_out": _stack(sd, "encoder.layer.{i}.output.dense.bias", L, dtype=dtype),
            },
            "ln_1": ln("layernorm_before"),
            "ln_2": ln("layernorm_after"),
        },
        "ln_f": {
            "scale": jnp.asarray(_to_numpy(sd["layernorm.weight"], dtype)),
            "bias": jnp.asarray(_to_numpy(sd["layernorm.bias"], dtype)),
        },
    }
    head_w = sd.get("classifier.weight")
    if head_w is not None:
        params["classifier"] = {
            "w": jnp.asarray(_to_numpy(head_w, dtype).T),
            "b": jnp.asarray(_to_numpy(sd["classifier.bias"], dtype)),
        }
    else:  # backbone-only checkpoint: fresh head, in the requested dtype
        import jax as _jax

        head = np.asarray(
            _jax.random.normal(_jax.random.key(0), (h, config.num_labels)) / np.sqrt(h)
        )
        params["classifier"] = {
            "w": jnp.asarray(head.astype(np.dtype(dtype))),
            "b": jnp.zeros((config.num_labels,), dtype),
        }
    return params


# ----------------------------------------------------------------- dispatcher
_CONVERTERS = {
    "llama": (Llama, llama_config_from_hf, llama_params_from_hf),
    "gpt2": (GPT2, gpt2_config_from_hf, gpt2_params_from_hf),
    "bert": (BertForSequenceClassification, bert_config_from_hf, bert_params_from_hf),
    "t5": (T5ForConditionalGeneration, t5_config_from_hf, t5_params_from_hf),
    "mixtral": (MoELlama, mixtral_config_from_hf, mixtral_params_from_hf),
    "qwen2": (Llama, qwen2_config_from_hf, qwen2_params_from_hf),
    "qwen3": (Llama, qwen3_config_from_hf, qwen3_params_from_hf),
    "phi3": (Llama, phi3_config_from_hf, phi3_params_from_hf),
    # Mistral is the Llama recipe + sliding-window attention; the generalized
    # Llama converter handles both (sliding_window flows from the config).
    "mistral": (Llama, llama_config_from_hf, llama_params_from_hf),
    "gemma": (Llama, gemma_config_from_hf, gemma_params_from_hf),
    "gemma2": (Llama, gemma2_config_from_hf, gemma2_params_from_hf),
    # The classic-GPT trio behind the reference's BASELINE.md inference tables.
    "gpt_neox": (GPTX, gpt_neox_config_from_hf, gpt_neox_params_from_hf),
    "gptj": (GPTX, gptj_config_from_hf, gptj_params_from_hf),
    "opt": (GPTX, opt_config_from_hf, opt_params_from_hf),
    "whisper": (WhisperForConditionalGeneration, whisper_config_from_hf,
                whisper_params_from_hf),
    "vit": (ViTForImageClassification, vit_config_from_hf, vit_params_from_hf),
}





def from_hf(hf_model, dtype=jnp.float32):
    """Convert a live ``transformers`` model: returns ``(zoo_model, params)``
    with ``model.params`` already set, ready for ``Accelerator.prepare``."""
    cls, config_fn, params_fn = _get_converter(getattr(hf_model.config, "model_type", None))
    config = config_fn(hf_model.config)
    model = cls(config)
    model.params = params_fn(hf_model.state_dict(), config, dtype=dtype)
    return model, model.params


def from_hf_checkpoint(model_type: str, checkpoint: str, hf_config, dtype=jnp.float32):
    """Convert from safetensors file(s) on disk without instantiating torch
    (uses ``utils/modeling.load_state_dict``; ``checkpoint`` is a file or a
    directory with an index)."""
    from ..utils.modeling import _resolve_checkpoint_files, load_state_dict

    cls, config_fn, params_fn = _get_converter(model_type)
    sd = {}
    for f in _resolve_checkpoint_files(checkpoint):
        sd.update(load_state_dict(f))
    config = config_fn(hf_config)
    model = cls(config)
    model.params = params_fn(sd, config, dtype=dtype)
    return model, model.params
