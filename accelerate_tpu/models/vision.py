"""Small convolutional image classifier for the CV examples.

Workload parity with the reference's ``examples/cv_example.py`` /
``complete_cv_example.py`` (timm resnet50 fine-tuned on a pet-image folder,
BASELINE.json configs[1]). The reference leans on a torch CNN zoo; here the CV
example ships a compact TPU-first convnet instead: NHWC layout (XLA's native
conv layout on TPU), ``lax.conv_general_dilated`` so the convs tile onto the
MXU, fp32 GroupNorm (batch-size independent — works under any dp sharding),
bf16-friendly matmul head, global average pooling.

Returns ``loss`` when ``labels`` are present (HF convention the Accelerator
relies on — see ``modules.default_loss_extractor``).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

import jax
import jax.numpy as jnp

from ..modules import ModelOutput, Module
from ..ops.losses import cross_entropy_loss


@dataclass
class ConvNetConfig:
    num_classes: int = 10
    in_channels: int = 3
    widths: tuple = (32, 64, 128)
    norm_groups: int = 8
    compute_dtype: str = "float32"

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(widths=(16, 32), norm_groups=4)
        defaults.update(kw)
        return cls(**defaults)


def _group_norm(x, scale, bias, groups, eps=1e-5):
    # fp32 statistics regardless of compute dtype (norms stay fp32 on TPU).
    orig_dtype = x.dtype
    n, h, w, c = x.shape
    xg = x.astype(jnp.float32).reshape(n, h, w, groups, c // groups)
    mean = xg.mean(axis=(1, 2, 4), keepdims=True)
    var = xg.var(axis=(1, 2, 4), keepdims=True)
    xg = (xg - mean) * jax.lax.rsqrt(var + eps)
    x = xg.reshape(n, h, w, c) * scale + bias
    return x.astype(orig_dtype)


def _conv(x, kernel, stride):
    return jax.lax.conv_general_dilated(
        x,
        kernel.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


class ConvNetForImageClassification(Module):
    """Stacked conv → GroupNorm → relu stages (stride-2 downsample each), global
    average pool, linear head."""

    def __init__(self, config: ConvNetConfig):
        self.config = config
        self.params = None

    def init(self, rng, *example_inputs, **kwargs):
        cfg = self.config
        keys = jax.random.split(rng, len(cfg.widths) + 1)
        params = {"stages": [], "head": {}}
        c_in = cfg.in_channels
        for i, c_out in enumerate(cfg.widths):
            fan_in = 3 * 3 * c_in
            params["stages"].append(
                {
                    "kernel": jax.random.normal(keys[i], (3, 3, c_in, c_out), jnp.float32)
                    * np.sqrt(2.0 / fan_in),
                    "gn_scale": jnp.ones((c_out,), jnp.float32),
                    "gn_bias": jnp.zeros((c_out,), jnp.float32),
                }
            )
            c_in = c_out
        params["head"] = {
            "kernel": jax.random.normal(keys[-1], (c_in, cfg.num_classes), jnp.float32)
            * np.sqrt(1.0 / c_in),
            "bias": jnp.zeros((cfg.num_classes,), jnp.float32),
        }
        return params

    def apply(self, params, pixel_values=None, labels=None, train: bool = False, rngs=None, **kwargs):
        cfg = self.config
        x = pixel_values.astype(jnp.dtype(cfg.compute_dtype))
        for stage in params["stages"]:
            x = _conv(x, stage["kernel"], stride=2)
            x = _group_norm(x, stage["gn_scale"], stage["gn_bias"], cfg.norm_groups)
            x = jax.nn.relu(x)
        x = x.mean(axis=(1, 2))  # global average pool → (N, C)
        logits = (
            x.astype(jnp.float32) @ params["head"]["kernel"] + params["head"]["bias"]
        )
        out = ModelOutput(logits=logits)
        if labels is not None:
            out["loss"] = cross_entropy_loss(logits, labels)
        return out
