from .bert import BertConfig, BertForSequenceClassification
from .gpt2 import GPT2, GPT2Config
from .gptx import GPTX, GPTXConfig
from .llama import Llama, LlamaConfig
from .moe import MoELlama, MoELlamaConfig
from .t5 import T5Config, T5ForConditionalGeneration
from .vision import ConvNetConfig, ConvNetForImageClassification
from .vit import ViTConfig, ViTForImageClassification
from .whisper import WhisperConfig, WhisperForConditionalGeneration


def __getattr__(name):
    # Lazy: convert.py pulls in numpy/jax paths only needed for HF interop.
    if name in ("from_hf", "from_hf_checkpoint", "llama_config_from_hf",
                "llama_params_from_hf", "gpt2_config_from_hf", "gpt2_params_from_hf",
                "bert_config_from_hf", "bert_params_from_hf",
                "t5_config_from_hf", "t5_params_from_hf",
                "mixtral_config_from_hf", "mixtral_params_from_hf",
                "qwen2_config_from_hf", "qwen2_params_from_hf",
                "qwen3_config_from_hf", "qwen3_params_from_hf",
                "phi3_config_from_hf", "phi3_params_from_hf",
                "gemma_config_from_hf", "gemma_params_from_hf",
                "gpt_neox_config_from_hf", "gpt_neox_params_from_hf",
                "gptj_config_from_hf", "gptj_params_from_hf",
                "opt_config_from_hf", "opt_params_from_hf",
                "whisper_config_from_hf", "whisper_params_from_hf",
                "vit_config_from_hf", "vit_params_from_hf"):
        from . import convert

        return getattr(convert, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
