from .bert import BertConfig, BertForSequenceClassification
from .llama import Llama, LlamaConfig
