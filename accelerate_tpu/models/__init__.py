from .bert import BertConfig, BertForSequenceClassification
from .gpt2 import GPT2, GPT2Config
from .llama import Llama, LlamaConfig
from .moe import MoELlama, MoELlamaConfig
from .t5 import T5Config, T5ForConditionalGeneration
from .vision import ConvNetConfig, ConvNetForImageClassification
