"""Classic-GPT decoder family — GPT-NeoX, GPT-J, and OPT in one skeleton.

These are the three architectures the reference's headline big-model-inference
benchmark tables are built on (BASELINE.md: GPT-J-6B / GPT-NeoX-20B / OPT-30B
load-time and s/token; reference driver
``benchmarks/big_model_inference/big_model_inference.py``) — the reference
itself never defines them (they come from transformers). One configurable
skeleton covers all three because they differ only along documented axes:

- **positions**: rotary half-split (NeoX, partial ``rotary_pct``), rotary
  interleaved-pairs (GPT-J ``rotary_dim``), or a learned table with a lookup
  offset (OPT's +2 rows).
- **residual topology**: parallel attn+MLP off the same input (NeoX two norms,
  GPT-J one shared norm) vs sequential pre-LN blocks (OPT).
- **activation**: exact gelu (NeoX), tanh-gelu (GPT-J), relu (OPT).
- **head**: untied (NeoX), untied with bias (GPT-J), tied (OPT).

Same TPU-first shape as ``GPT2``/``Llama``: stacked-layer ``lax.scan``, the
embed/block/head stage protocol (pipeline- and layer-stream-capable), fused QKV
projection for one MXU matmul (converters de-interleave NeoX's per-head fused
layout), Megatron-style tp sharding rules, and the mask-derived ``positions``
channel that keeps ragged generation exact for both rotary and learned-table
variants.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..modules import ModelOutput, Module
from ..ops.attention import attention as _attention
from ..ops.losses import cross_entropy_loss
from .gpt2 import GPT2, _layer_norm
from .llama import rope_tables, apply_rope


def apply_rope_interleaved(x, cos, sin):
    """GPT-J rotary: pairs are adjacent lanes (0,1),(2,3),… — the
    ``rotate_every_two`` convention — vs the half-split Llama/NeoX layout.
    ``x``: (B, S, H, D_rot); ``cos``/``sin``: (B, S, D_rot/2)."""
    x1, x2 = x[..., ::2], x[..., 1::2]
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    o1 = x1 * cos - x2 * sin
    o2 = x2 * cos + x1 * sin
    return jnp.stack([o1, o2], axis=-1).reshape(x.shape).astype(x.dtype)


_POSITION_STYLES = ("rotary_neox", "rotary_gptj", "learned")
_ACTS = {
    "gelu": lambda x: jax.nn.gelu(x, approximate=False),
    "gelu_tanh": lambda x: jax.nn.gelu(x, approximate=True),
    "relu": jax.nn.relu,
}


@dataclass
class GPTXConfig:
    vocab_size: int = 50432
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    # 'rotary_neox' (half-split, partial width) | 'rotary_gptj' (interleaved
    # pairs) | 'learned' (OPT table with `position_offset` extra leading rows).
    position_style: str = "rotary_neox"
    rotary_dim: int | None = None  # None = full head_dim (rotary styles only)
    rope_theta: float = 10000.0
    # Length-independent rope scaling (linear/llama3/yarn dicts, the HF config
    # field) applied over the rotary lanes. 'dynamic' (NTK-by-length) is NOT
    # supported here — it would need the cache-capacity pinning Llama carries.
    rope_scaling: dict | None = None
    # True: x + attn(ln1(x)) + mlp(ln2(x)) — NeoX/GPT-J. False: sequential
    # pre-LN (OPT, and NeoX checkpoints with use_parallel_residual=False).
    parallel_residual: bool = True
    # GPT-J feeds attn and MLP the SAME ln_1 output (no ln_2 parameters).
    shared_layernorm: bool = False
    hidden_act: str = "gelu"
    attention_bias: bool = True  # NeoX/OPT yes; GPT-J projects bias-free
    position_offset: int = 0  # OPT's learned table starts at row 2
    tie_word_embeddings: bool = False  # OPT ties; NeoX/GPT-J don't
    lm_head_bias: bool = False  # GPT-J's untied head carries a bias
    remat: bool = False
    remat_policy: str = "nothing_saveable"
    attention_impl: str = "auto"
    matmul_precision: str = "default"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    def __post_init__(self):
        if self.position_style not in _POSITION_STYLES:
            raise ValueError(
                f"position_style must be one of {_POSITION_STYLES}, got {self.position_style!r}"
            )
        if self.hidden_act not in _ACTS:
            raise ValueError(f"hidden_act must be one of {sorted(_ACTS)}, got {self.hidden_act!r}")
        if self.position_style == "learned":
            if self.rotary_dim is not None:
                raise ValueError("rotary_dim is meaningless with learned positions")
        elif self.rotary_dim is None:
            self.rotary_dim = self.head_dim
        if self.rotary_dim is not None and self.rotary_dim % 2:
            raise ValueError(f"rotary_dim must be even, got {self.rotary_dim}")
        if self.rope_scaling:
            if self.position_style == "learned":
                raise ValueError("rope_scaling is meaningless with learned positions")
            rope_type = self.rope_scaling.get("rope_type", self.rope_scaling.get("type"))
            if rope_type == "dynamic":
                raise ValueError(
                    "dynamic (NTK-by-length) rope scaling is not supported by the "
                    "classic-GPT zoo model (its rope has no cache-capacity pinning); "
                    "linear/llama3/yarn are supported"
                )
        if self.shared_layernorm and not self.parallel_residual:
            raise ValueError("shared_layernorm requires parallel_residual (the GPT-J topology)")

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            max_position_embeddings=128,
        )
        defaults.update(kw)
        return cls(**defaults)


class GPTX(Module):
    # embed/block/head stage protocol — GPipe-eligible (parallel/pipeline.py).
    pipeline_capable = True
    scan_aux_keys: tuple = ()

    def __init__(self, config: GPTXConfig):
        self.config = config
        self.params = None

    # ------------------------------------------------------------------- init
    def init(self, rng, *example_inputs, **kwargs):
        cfg = self.config
        h, inter, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
        keys = jax.random.split(rng, 8)

        def dense(key, shape, scale_dim=None):
            fan_in = scale_dim if scale_dim is not None else (shape[-2] if len(shape) >= 3 else shape[0])
            return (jax.random.normal(key, shape, jnp.float32) / np.sqrt(fan_in)).astype(jnp.float32)

        embed = {"wte": dense(keys[0], (cfg.vocab_size, h), h)}
        if cfg.position_style == "learned":
            rows = cfg.max_position_embeddings + cfg.position_offset
            embed["wpe"] = dense(keys[1], (rows, h), h)
        attn = {"w_qkv": dense(keys[2], (L, h, 3 * h)), "wo": dense(keys[3], (L, h, h))}
        if cfg.attention_bias:
            attn["b_qkv"] = jnp.zeros((L, 3 * h), jnp.float32)
            attn["bo"] = jnp.zeros((L, h), jnp.float32)
        ln = lambda: {"scale": jnp.ones((L, h), jnp.float32), "bias": jnp.zeros((L, h), jnp.float32)}
        layers = {
            "attn": attn,
            "mlp": {
                "w_in": dense(keys[4], (L, h, inter)),
                "b_in": jnp.zeros((L, inter), jnp.float32),
                "w_out": dense(keys[5], (L, inter, h)),
                "b_out": jnp.zeros((L, h), jnp.float32),
            },
            "ln_1": ln(),
        }
        if not cfg.shared_layernorm:
            layers["ln_2"] = ln()
        params = {
            "embed": embed,
            "layers": layers,
            "ln_f": {"scale": jnp.ones((h,), jnp.float32), "bias": jnp.zeros((h,), jnp.float32)},
        }
        if not cfg.tie_word_embeddings:
            head = {"weight": dense(keys[6], (h, cfg.vocab_size))}
            if cfg.lm_head_bias:
                head["bias"] = jnp.zeros((cfg.vocab_size,), jnp.float32)
            params["lm_head"] = head
        return params

    # --------------------------------------------------------------- sharding
    def sharding_rules(self):
        """Fused QKV column-split on tp (GSPMD keeps the downstream split/head
        reshape correct for any layout); wo/w_out row-parallel; layer stack on
        pp — same scheme as ``GPT2.sharding_rules``."""
        return [
            (r"embed/wte", P("tp", "fsdp")),
            (r"embed/wpe", P(None, "fsdp")),
            (r"attn/w_qkv", P("pp", "fsdp", "tp")),
            (r"attn/b_qkv", P("pp", "tp")),
            (r"attn/wo", P("pp", "tp", "fsdp")),
            (r"attn/bo", P("pp")),
            (r"mlp/w_in", P("pp", "fsdp", "tp")),
            (r"mlp/b_in", P("pp", "tp")),
            (r"mlp/w_out", P("pp", "tp", "fsdp")),
            (r"mlp/b_out", P("pp")),
            (r"layers/ln_", P("pp")),
            (r"ln_f", P()),
            (r"lm_head/weight", P("fsdp", "tp")),
            (r"lm_head/bias", P("tp")),
        ]

    # ---------------------------------------------------------------- forward
    def embed(self, params, input_ids, positions=None, attention_mask=None):
        cfg = self.config
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
        from ..parallel.sharding import embedding_lookup

        x = embedding_lookup(params["embed"]["wte"], input_ids)
        ctx = {"attention_mask": attention_mask}
        if cfg.position_style == "learned":
            if S > cfg.max_position_embeddings:
                raise ValueError(
                    f"sequence length {S} exceeds max_position_embeddings "
                    f"{cfg.max_position_embeddings}"
                )
            x = x + embedding_lookup(params["embed"]["wpe"], positions + cfg.position_offset)
        else:
            cos, sin = rope_tables(
                positions, cfg.rotary_dim, cfg.rope_theta, cfg.rope_scaling,
                max_position_embeddings=cfg.max_position_embeddings,
            )
            ctx["cos"], ctx["sin"] = cos, sin
        return x.astype(params["embed"]["wte"].dtype), ctx

    def _mm(self, a, b):
        from ..ops.int8 import matmul

        return matmul(a, b, precision=self.config.matmul_precision)

    def _rope(self, x, ctx):
        cfg = self.config
        if cfg.position_style == "learned":
            return x
        rot = apply_rope if cfg.position_style == "rotary_neox" else apply_rope_interleaved
        d = cfg.rotary_dim
        if d == cfg.head_dim:
            return rot(x, ctx["cos"], ctx["sin"])
        return jnp.concatenate([rot(x[..., :d], ctx["cos"], ctx["sin"]), x[..., d:]], axis=-1)

    def block(self, layer, x, ctx, cache_layer=None):
        cfg = self.config
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        B, S, h = x.shape
        ln1 = _layer_norm(x, layer["ln_1"]["scale"], layer["ln_1"]["bias"], cfg.layer_norm_eps)
        a = layer["attn"]
        qkv = self._mm(ln1, a["w_qkv"])
        if "b_qkv" in a:
            qkv = qkv + a["b_qkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = self._rope(q.reshape(B, S, nh, hd), ctx)
        k = self._rope(k.reshape(B, S, nh, hd), ctx)
        v = v.reshape(B, S, nh, hd)
        new_cache = None
        if cache_layer is not None:
            from ..ops.attention import cached_attention

            pos = ctx["cache_pos"]
            k_cache = jax.lax.dynamic_update_slice(
                cache_layer["k"], k.astype(cache_layer["k"].dtype), (0, pos, 0, 0)
            )
            v_cache = jax.lax.dynamic_update_slice(
                cache_layer["v"], v.astype(cache_layer["v"].dtype), (0, pos, 0, 0)
            )
            attn = cached_attention(
                q, k_cache, v_cache,
                q_positions=ctx["positions"],
                kv_mask=ctx.get("kv_mask"),
            )
            new_cache = {"k": k_cache, "v": v_cache}
        else:
            attn = _attention(
                q, k, v, causal=True, mask=ctx["attention_mask"], impl=cfg.attention_impl
            )
        attn = self._mm(attn.reshape(B, S, h), layer["attn"]["wo"])
        if "bo" in layer["attn"]:
            attn = attn + layer["attn"]["bo"]
        act = _ACTS[cfg.hidden_act]
        if cfg.parallel_residual:
            # NeoX/GPT-J: both sub-blocks read the SAME input x, summed into one
            # residual add (GPT-J additionally shares ln_1's output).
            ln2 = ln1 if cfg.shared_layernorm else _layer_norm(
                x, layer["ln_2"]["scale"], layer["ln_2"]["bias"], cfg.layer_norm_eps
            )
            mid = act(self._mm(ln2, layer["mlp"]["w_in"]) + layer["mlp"]["b_in"])
            x = x + attn + self._mm(mid, layer["mlp"]["w_out"]) + layer["mlp"]["b_out"]
        else:
            x = x + attn
            ln2 = _layer_norm(x, layer["ln_2"]["scale"], layer["ln_2"]["bias"], cfg.layer_norm_eps)
            mid = act(self._mm(ln2, layer["mlp"]["w_in"]) + layer["mlp"]["b_in"])
            x = x + self._mm(mid, layer["mlp"]["w_out"]) + layer["mlp"]["b_out"]
        return x if new_cache is None else (x, new_cache)

    # Shared with GPT2/Llama: the head/loss contract the 1F1B schedule reads.
    _shift_labels = staticmethod(GPT2._shift_labels)

    def head(self, params, x, labels=None, attention_mask=None):
        cfg = self.config
        x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"], cfg.layer_norm_eps)
        if cfg.tie_word_embeddings:
            logits = x @ params["embed"]["wte"].T.astype(x.dtype)
        else:
            logits = x @ params["lm_head"]["weight"].astype(x.dtype)
            if "bias" in params["lm_head"]:
                logits = logits + params["lm_head"]["bias"].astype(logits.dtype)
        logits = logits.astype(jnp.float32)
        out = ModelOutput(logits=logits)
        if labels is not None:
            out["loss"] = cross_entropy_loss(logits, self._shift_labels(labels, attention_mask))
        return out

    # ------------------------------------------------------------------ cache
    def init_cache(self, batch_size: int, max_len: int, dtype=jnp.bfloat16):
        cfg = self.config
        if cfg.position_style == "learned" and max_len > cfg.max_position_embeddings:
            raise ValueError(
                f"cache length {max_len} exceeds max_position_embeddings "
                f"{cfg.max_position_embeddings}: the learned table cannot extend"
            )
        shape = (cfg.num_hidden_layers, batch_size, max_len, cfg.num_attention_heads, cfg.head_dim)
        return {
            "k": jnp.zeros(shape, dtype),
            "v": jnp.zeros(shape, dtype),
            "pos": jnp.zeros((), jnp.int32),
            "kv_mask": jnp.zeros((batch_size, max_len), jnp.int32),
        }

    def _apply_cached(self, params, input_ids, attention_mask, cache, labels=None,
                      positions=None):
        """``positions`` are *token* positions (rope angles / wpe rows); causal
        masking always uses cache slot indices — same split as Llama/GPT2."""
        B, S = input_ids.shape
        pos = cache["pos"]
        slot_positions = jnp.broadcast_to(
            pos + jnp.arange(S, dtype=jnp.int32)[None], (B, S)
        )
        token_positions = slot_positions if positions is None else positions
        chunk_mask = (
            attention_mask.astype(jnp.int32)
            if attention_mask is not None
            else jnp.ones((B, S), jnp.int32)
        )
        kv_mask = jax.lax.dynamic_update_slice(cache["kv_mask"], chunk_mask, (0, pos))
        x, ctx = self.embed(params, input_ids, token_positions, attention_mask)
        ctx["positions"] = slot_positions
        ctx["kv_mask"] = kv_mask
        ctx["cache_pos"] = pos

        def scan_step(x, inp):
            layer, ck, cv = inp
            x, new = self.block(layer, x, ctx, cache_layer={"k": ck, "v": cv})
            return x, (new["k"], new["v"])

        x, (nk, nv) = jax.lax.scan(scan_step, x, (params["layers"], cache["k"], cache["v"]))
        out = self.head(params, x, labels=labels, attention_mask=attention_mask)
        out["cache"] = {"k": nk, "v": nv, "pos": pos + S, "kv_mask": kv_mask}
        return out

    def apply(
        self,
        params,
        input_ids=None,
        labels=None,
        attention_mask=None,
        positions=None,
        cache=None,
        train: bool = False,
        rngs=None,
        pipeline=None,
        **kwargs,
    ):
        cfg = self.config
        if cache is not None:
            return self._apply_cached(
                params, input_ids, attention_mask, cache, labels=labels, positions=positions
            )
        x, ctx = self.embed(params, input_ids, positions, attention_mask)
        if pipeline is not None:
            x, _aux = pipeline.run(self, params["layers"], x, ctx)
        else:
            body = lambda x, layer: self.block(layer, x, ctx)
            if cfg.remat:
                from ..utils.dataclasses import resolve_remat_policy

                policy = resolve_remat_policy(cfg.remat_policy, getattr(cfg, "remat_save_names", ()))
                body = jax.checkpoint(body, policy=policy)

            def scan_step(x, layer):
                return body(x, layer), None

            x, _ = jax.lax.scan(scan_step, x, params["layers"])
        return self.head(params, x, labels=labels, attention_mask=attention_mask)

    # -------------------------------------------------------------- estimation
    def num_params(self) -> int:
        cfg = self.config
        h, inter, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
        layer = 4 * h * h + h * inter * 2 + inter + h
        if cfg.attention_bias:
            layer += 4 * h
        layer += (2 if cfg.shared_layernorm else 4) * h
        total = L * layer + cfg.vocab_size * h + 2 * h
        if cfg.position_style == "learned":
            total += (cfg.max_position_embeddings + cfg.position_offset) * h
        if not cfg.tie_word_embeddings:
            total += h * cfg.vocab_size + (cfg.vocab_size if cfg.lm_head_bias else 0)
        return total

    def flops_per_token(self) -> float:
        cfg = self.config
        attn_extra = 12 * cfg.num_hidden_layers * cfg.hidden_size * cfg.max_position_embeddings
        return 6 * self.num_params() + attn_extra
