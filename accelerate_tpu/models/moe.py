"""Mixture-of-experts Llama — the expert-parallel flagship.

Same decoder skeleton as ``Llama`` (scan over stacked layers, GQA attention,
RoPE) with the dense SwiGLU FFN replaced by a routed expert FFN
(``ops/moe.py``). Expert weights carry a leading ``E`` dim sharded on the mesh
``ep`` axis: expert compute stays on the owning shard and the combine einsum
becomes one all-reduce over ``ep`` per layer (row-parallel-style) — the
TPU-native analog of DeepSpeed-MoE's expert parallelism
(reference exposes only passthrough flags for that backend; SURVEY.md §2.4
lists EP as note-only).

The router's load-balancing auxiliary loss is accumulated across the layer scan
and added to the LM loss with ``router_aux_coef``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..ops.moe import moe_ffn
from .llama import Llama, LlamaConfig


@dataclass
class MoELlamaConfig(LlamaConfig):
    num_experts: int = 8
    moe_top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            max_position_embeddings=128,
            num_experts=4,
            moe_top_k=2,
        )
        defaults.update(kw)
        return cls(**defaults)


class MoELlama(Llama):
    def __init__(self, config: MoELlamaConfig):
        super().__init__(config)

    # ------------------------------------------------------------------- init
    def init(self, rng, *example_inputs, **kwargs):
        cfg = self.config
        params = super().init(rng, *example_inputs, **kwargs)
        h, inter, L, E = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers, cfg.num_experts
        keys = jax.random.split(jax.random.fold_in(rng, 17), 4)

        def dense(key, shape, scale_dim):
            return (jax.random.normal(key, shape, jnp.float32) / np.sqrt(scale_dim)).astype(jnp.float32)

        params["layers"]["mlp"] = {
            "router": dense(keys[0], (L, h, E), h),
            "w_gate": dense(keys[1], (L, E, h, inter), h),
            "w_up": dense(keys[2], (L, E, h, inter), h),
            "w_down": dense(keys[3], (L, E, inter, h), inter),
        }
        return params

    # --------------------------------------------------------------- sharding
    def sharding_rules(self):
        """Llama rules + expert weights: layer stack on ``pp``, experts on
        ``ep``, then the Megatron col/row split on fsdp/tp."""
        rules = [
            (r"mlp/router", P("pp", "fsdp", None)),
            (r"mlp/w_(gate|up)", P("pp", "ep", "fsdp", "tp")),
            (r"mlp/w_down", P("pp", "ep", "tp", "fsdp")),
        ]
        base = [r for r in super().sharding_rules() if "mlp" not in r[0]]
        return rules + base

    # ---------------------------------------------------------------- forward
    def mlp(self, layer, h2, ctx=None):
        cfg = self.config
        out, aux = moe_ffn(
            h2,
            layer["mlp"]["router"],
            layer["mlp"]["w_gate"],
            layer["mlp"]["w_up"],
            layer["mlp"]["w_down"],
            k=cfg.moe_top_k,
            capacity_factor=cfg.capacity_factor,
        )
        if ctx is not None:
            ctx["moe_aux"] = aux  # sown per call; read back by apply()'s scan body
        return out

    # The base ``Llama.apply`` drives the scan (and the pipelined schedule)
    # generically: declaring the sown key routes the router aux loss out of
    # every forward path — plain scan, remat, and GPipe pipeline alike.
    scan_aux_keys = ("moe_aux",)

    def aux_loss_coefs(self) -> dict:
        return {"moe_aux": self.config.router_aux_coef}

    def finalize_aux(self, out, aux: dict):
        a = aux.get("moe_aux")
        if a is not None:
            out["aux_loss"] = a
            if "loss" in out:
                out["loss"] = out["loss"] + self.config.router_aux_coef * a
        return out

    # -------------------------------------------------------------- estimation
    def num_params(self) -> int:
        cfg = self.config
        h, inter, L, E = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers, cfg.num_experts
        attn = (
            h * (cfg.num_attention_heads + 2 * cfg.num_key_value_heads) * cfg.head_dim
            + cfg.num_attention_heads * cfg.head_dim * h
        )
        moe = h * E + E * 3 * h * inter
        norms = 2 * h
        total = L * (attn + moe + norms) + cfg.vocab_size * h + h
        if not cfg.tie_word_embeddings:
            total += h * cfg.vocab_size
        return total

    def flops_per_token(self) -> float:
        """Per-token compute touches only the router + top-k active experts,
        not all E — 6·(active params) + attention."""
        cfg = self.config
        h, inter, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
        attn = (
            h * (cfg.num_attention_heads + 2 * cfg.num_key_value_heads) * cfg.head_dim
            + cfg.num_attention_heads * cfg.head_dim * h
        )
        active_moe = h * cfg.num_experts + cfg.moe_top_k * 3 * h * inter
        norms = 2 * h
        active = L * (attn + active_moe + norms) + cfg.vocab_size * h + h
        if not cfg.tie_word_embeddings:
            active += h * cfg.vocab_size
        attn_extra = 12 * L * h * cfg.max_position_embeddings
        return 6 * active + attn_extra
