"""BERT-style encoder + sequence-classification head.

Workload parity with the reference's flagship example (``examples/nlp_example.py``
— bert-base-cased on GLUE/MRPC, BASELINE.json configs[0]). Architecture follows
the standard transformer encoder recipe (post-LN, learned positions, GELU MLP,
pooler over [CLS]) implemented TPU-first: scan over stacked layers, bf16 matmuls
with fp32 norms/softmax, same sharding-rule scheme as the Llama model.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..modules import ModelOutput, Module
from ..ops.losses import cross_entropy_loss


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    num_labels: int = 2
    hidden_dropout_prob: float = 0.1
    remat: bool = False

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(
            vocab_size=512,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            max_position_embeddings=128,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def base(cls, **kw):
        return cls(**kw)


from ..ops.norms import layer_norm


class BertForSequenceClassification(Module):
    # Encoder pipeline training (Megatron's BertTrainStep parity, reference
    # utils/megatron_lm.py:445): the encoder stack splits across pp stages
    # through the same GPipe schedule as the decoder families — the stage
    # protocol below (embed/block/head) was already pipeline-shaped. Dropout
    # must be off under the pipeline (the stage body carries no rng channel);
    # apply() raises rather than silently changing the training recipe.
    pipeline_capable = True

    def __init__(self, config: BertConfig):
        self.config = config
        self.params = None

    def init(self, rng, *example_inputs, **kwargs):
        cfg = self.config
        h, inter, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
        keys = iter(jax.random.split(rng, 16))

        def dense(shape, scale_dim=None):
            scale = 0.02
            return jax.random.normal(next(keys), shape, jnp.float32) * scale

        def ln(shape_last):
            return {"scale": jnp.ones(shape_last, jnp.float32), "bias": jnp.zeros(shape_last, jnp.float32)}

        params = {
            "embeddings": {
                "word": dense((cfg.vocab_size, h)),
                "position": dense((cfg.max_position_embeddings, h)),
                "token_type": dense((cfg.type_vocab_size, h)),
                "norm": ln((h,)),
            },
            "layers": {
                "attn": {
                    "wq": dense((L, h, h)),
                    "bq": jnp.zeros((L, h), jnp.float32),
                    "wk": dense((L, h, h)),
                    "bk": jnp.zeros((L, h), jnp.float32),
                    "wv": dense((L, h, h)),
                    "bv": jnp.zeros((L, h), jnp.float32),
                    "wo": dense((L, h, h)),
                    "bo": jnp.zeros((L, h), jnp.float32),
                },
                "attn_norm": {"scale": jnp.ones((L, h)), "bias": jnp.zeros((L, h))},
                "mlp": {
                    "w_in": dense((L, h, inter)),
                    "b_in": jnp.zeros((L, inter), jnp.float32),
                    "w_out": dense((L, inter, h)),
                    "b_out": jnp.zeros((L, h), jnp.float32),
                },
                "mlp_norm": {"scale": jnp.ones((L, h)), "bias": jnp.zeros((L, h))},
            },
            "pooler": {"w": dense((h, h)), "b": jnp.zeros((h,), jnp.float32)},
            "classifier": {"w": dense((h, cfg.num_labels)), "b": jnp.zeros((cfg.num_labels,), jnp.float32)},
        }
        return params

    def sharding_rules(self):
        # Leading layer-stack dim sharded on pp (stage placement; trivial when
        # pp=1) — same scheme as the Llama rules.
        return [
            (r"embeddings/word", P("tp", "fsdp")),
            (r"attn/w[qkv]", P("pp", "fsdp", "tp")),
            (r"attn/b[qkv]", P("pp", "tp")),
            (r"attn/wo", P("pp", "tp", "fsdp")),
            (r"attn/bo", P("pp")),
            (r"mlp/w_in", P("pp", "fsdp", "tp")),
            (r"mlp/b_in", P("pp", "tp")),
            (r"mlp/w_out", P("pp", "tp", "fsdp")),
            (r"mlp/b_out", P("pp")),
            (r"layers/.*norm", P("pp")),
            (r"norm|pooler|classifier", P()),
        ]

    # ---------------------------------------------------------------- forward
    # Decomposed into embed/block/head (the stage protocol) so the same code
    # serves training (scan with dropout rng in the carry), pipelined inference
    # (``prepare_pippy``), and the layer-streamed offload runtime.
    def embed(self, params, input_ids, positions=None, attention_mask=None, token_type_ids=None):
        cfg = self.config
        B, S = input_ids.shape
        emb = params["embeddings"]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = (
            jnp.take(emb["word"], input_ids, axis=0)
            + emb["position"][None, :S]
            + jnp.take(emb["token_type"], token_type_ids, axis=0)
        ).astype(emb["word"].dtype)
        x = layer_norm(x, emb["norm"]["scale"], emb["norm"]["bias"], cfg.layer_norm_eps)
        if attention_mask is None:
            attention_mask = jnp.ones((B, S), jnp.int32)
        bias = jnp.where(attention_mask[:, None, None, :].astype(bool), 0.0, -1e30).astype(jnp.float32)
        return x, {"attention_mask": attention_mask, "bias": bias}

    def _dropout(self, x, rng, train_rate):
        if train_rate == 0.0 or rng is None:
            return x
        keep = jax.random.bernoulli(rng, 1.0 - train_rate, x.shape)
        return jnp.where(keep, x / (1.0 - train_rate), 0.0).astype(x.dtype)

    def block(self, layer, x, ctx, rng=None, drop_rate=0.0):
        """One encoder layer. Without ``rng`` (pipelined/streamed inference)
        dropout is off; the training scan passes a per-layer rng."""
        cfg = self.config
        nh, hd = cfg.num_attention_heads, cfg.head_dim
        B, S, _ = x.shape
        bias = ctx["bias"]
        if rng is not None:
            r1, r2 = jax.random.split(rng)
        else:
            r1 = r2 = None
        a = layer["attn"]
        q = (x @ a["wq"] + a["bq"]).reshape(B, S, nh, hd)
        k = (x @ a["wk"] + a["bk"]).reshape(B, S, nh, hd)
        v = (x @ a["wv"] + a["bv"]).reshape(B, S, nh, hd)
        scale = 1.0 / np.sqrt(hd)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale + bias
        probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, v).reshape(B, S, nh * hd)
        attn = self._dropout(attn @ a["wo"] + a["bo"], r1, drop_rate)
        x = layer_norm(x + attn, layer["attn_norm"]["scale"], layer["attn_norm"]["bias"], cfg.layer_norm_eps)
        m = layer["mlp"]
        hdn = jax.nn.gelu(x @ m["w_in"] + m["b_in"], approximate=False)
        hdn = self._dropout(hdn @ m["w_out"] + m["b_out"], r2, drop_rate)
        return layer_norm(x + hdn, layer["mlp_norm"]["scale"], layer["mlp_norm"]["bias"], cfg.layer_norm_eps)

    def head(self, params, x, labels=None, attention_mask=None):
        pooled = jnp.tanh(x[:, 0] @ params["pooler"]["w"] + params["pooler"]["b"])
        logits = (pooled @ params["classifier"]["w"] + params["classifier"]["b"]).astype(jnp.float32)
        out = ModelOutput(logits=logits)
        if labels is not None:
            out["loss"] = cross_entropy_loss(logits, labels)
        return out

    def apply(
        self,
        params,
        input_ids=None,
        attention_mask=None,
        token_type_ids=None,
        labels=None,
        train: bool = False,
        rngs=None,
        pipeline=None,
        **kwargs,
    ):
        cfg = self.config
        x, ctx = self.embed(params, input_ids, None, attention_mask, token_type_ids)
        dropout_rng = (rngs or {}).get("dropout") if train else None
        drop_rate = cfg.hidden_dropout_prob if train else 0.0

        if pipeline is not None:
            if drop_rate > 0.0 and dropout_rng is not None:
                raise ValueError(
                    "Pipelined BERT training has no per-stage dropout rng "
                    "channel; set hidden_dropout_prob=0.0 (or train without "
                    "the pipeline) rather than silently dropping dropout."
                )
            x, _ = pipeline.run(self, params["layers"], x, ctx)
            return self.head(params, x, labels=labels, attention_mask=attention_mask)

        def scan_body(carry, layer):
            x, rng = carry
            if rng is not None:
                rng, r = jax.random.split(rng)
            else:
                r = None
            x = self.block(layer, x, ctx, rng=r, drop_rate=drop_rate)
            return (x, rng), None

        body = scan_body
        if cfg.remat:
            body = jax.checkpoint(scan_body)
        (x, _), _ = jax.lax.scan(body, (x, dropout_rng), params["layers"])
        return self.head(params, x, labels=labels, attention_mask=attention_mask)
