"""ViT — vision transformer for image classification.

Fills the CV-transformer slot beside the ConvNet workload (the reference's
``cv_example`` is model-agnostic torch; here the model is part of the
framework). TPU-first patching: the stride-P conv IS a reshape + one matmul
(patches are non-overlapping), so the embedding rides the MXU with no conv op;
encoder layers run as one stacked-layer ``lax.scan`` with the shared fp32
LayerNorm and the ops attention kernel dispatch.

HF counterpart: ``ViTForImageClassification`` (parity in tests/test_vit.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..modules import ModelOutput, Module
from ..ops.attention import attention as _attention
from ..ops.losses import cross_entropy_loss
from ..ops.norms import layer_norm


@dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_channels: int = 3
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    num_labels: int = 1000
    layer_norm_eps: float = 1e-12
    qkv_bias: bool = True
    attention_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    def __post_init__(self):
        if self.image_size % self.patch_size:
            raise ValueError(
                f"image_size {self.image_size} not divisible by patch_size {self.patch_size}"
            )

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(image_size=32, patch_size=8, hidden_size=64,
                        num_hidden_layers=2, num_attention_heads=4,
                        intermediate_size=128, num_labels=10)
        defaults.update(kw)
        return cls(**defaults)


class ViTForImageClassification(Module):
    def __init__(self, config: ViTConfig):
        self.config = config
        self.params = None

    # ------------------------------------------------------------------- init
    def init(self, rng, *example_inputs, **kwargs):
        cfg = self.config
        h, L = cfg.hidden_size, cfg.num_hidden_layers
        keys = jax.random.split(rng, 8)
        d = lambda k, shape, fan: (jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan))
        patch_dim = cfg.num_channels * cfg.patch_size ** 2
        ln = lambda: {"scale": jnp.ones((L, h), jnp.float32), "bias": jnp.zeros((L, h), jnp.float32)}
        return {
            "embed": {
                "patch": {"w": d(keys[0], (patch_dim, h), patch_dim),
                          "b": jnp.zeros((h,), jnp.float32)},
                "cls": jnp.zeros((1, 1, h), jnp.float32),
                "pos": d(keys[1], (cfg.num_patches + 1, h), h),
            },
            "layers": {
                "attn": {
                    "w_qkv": d(keys[2], (L, h, 3 * h), h),
                    "b_qkv": jnp.zeros((L, 3 * h), jnp.float32),
                    "wo": d(keys[3], (L, h, h), h),
                    "bo": jnp.zeros((L, h), jnp.float32),
                },
                "mlp": {
                    "w_in": d(keys[4], (L, h, cfg.intermediate_size), h),
                    "b_in": jnp.zeros((L, cfg.intermediate_size), jnp.float32),
                    "w_out": d(keys[5], (L, cfg.intermediate_size, h), cfg.intermediate_size),
                    "b_out": jnp.zeros((L, h), jnp.float32),
                },
                "ln_1": ln(),
                "ln_2": ln(),
            },
            "ln_f": {"scale": jnp.ones((h,), jnp.float32), "bias": jnp.zeros((h,), jnp.float32)},
            "classifier": {"w": d(keys[6], (h, cfg.num_labels), h),
                           "b": jnp.zeros((cfg.num_labels,), jnp.float32)},
        }

    # --------------------------------------------------------------- sharding
    def sharding_rules(self):
        return [
            (r"embed/patch/w", P(None, "tp")),
            (r"embed/pos", P(None, "fsdp")),
            (r"attn/w_qkv", P(None, "fsdp", "tp")),
            (r"attn/b_qkv", P(None, "tp")),
            (r"attn/wo", P(None, "tp", "fsdp")),
            (r"mlp/w_in", P(None, "fsdp", "tp")),
            (r"mlp/b_in", P(None, "tp")),
            (r"mlp/w_out", P(None, "tp", "fsdp")),
            (r"ln_", P()),
            (r"classifier", P()),
        ]

    # ---------------------------------------------------------------- forward
    def _patchify(self, pixel_values):
        """(B, C, H, W) → (B, N, C·P·P) with the (c, ph, pw) lane order the
        converter's kernel flattening matches — the stride-P conv as one
        reshape + matmul."""
        cfg = self.config
        B, C, H, W = pixel_values.shape
        if (H, W) != (cfg.image_size, cfg.image_size) or C != cfg.num_channels:
            # The position table is a fixed (grid+1)-row grid; a different
            # size would silently apply a meaningless partial grid (HF ViT
            # raises on this mismatch too).
            raise ValueError(
                f"pixel_values {(C, H, W)} do not match the configured "
                f"({cfg.num_channels}, {cfg.image_size}, {cfg.image_size})"
            )
        Ph, Pw = H // cfg.patch_size, W // cfg.patch_size
        x = pixel_values.reshape(B, C, Ph, cfg.patch_size, Pw, cfg.patch_size)
        x = x.transpose(0, 2, 4, 1, 3, 5)  # (B, Ph, Pw, C, p, p)
        return x.reshape(B, Ph * Pw, C * cfg.patch_size ** 2)

    def apply(self, params, pixel_values=None, labels=None, train: bool = False,
              rngs=None, **kwargs):
        cfg = self.config
        eps = cfg.layer_norm_eps
        emb = params["embed"]
        x = self._patchify(jnp.asarray(pixel_values)) @ emb["patch"]["w"] + emb["patch"]["b"]
        B, N, h = x.shape
        cls = jnp.broadcast_to(emb["cls"].astype(x.dtype), (B, 1, h))
        x = jnp.concatenate([cls, x], axis=1) + emb["pos"][: N + 1].astype(x.dtype)
        nh, hd = cfg.num_attention_heads, cfg.head_dim

        def block(x, layer):
            z = layer_norm(x, layer["ln_1"]["scale"], layer["ln_1"]["bias"], eps)
            qkv = z @ layer["attn"]["w_qkv"] + layer["attn"]["b_qkv"]
            q, k, v = jnp.split(qkv, 3, axis=-1)
            T = z.shape[1]
            attn = _attention(
                q.reshape(B, T, nh, hd), k.reshape(B, T, nh, hd),
                v.reshape(B, T, nh, hd), causal=False, mask=None,
                impl=cfg.attention_impl,
            )
            x = x + (attn.reshape(B, T, h) @ layer["attn"]["wo"] + layer["attn"]["bo"])
            z = layer_norm(x, layer["ln_2"]["scale"], layer["ln_2"]["bias"], eps)
            mid = jax.nn.gelu(z @ layer["mlp"]["w_in"] + layer["mlp"]["b_in"], approximate=False)
            return x + (mid @ layer["mlp"]["w_out"] + layer["mlp"]["b_out"]), None

        x, _ = jax.lax.scan(block, x, params["layers"])
        x = layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"], eps)
        logits = (x[:, 0] @ params["classifier"]["w"] + params["classifier"]["b"]).astype(jnp.float32)
        out = ModelOutput(logits=logits)
        if labels is not None:
            out["loss"] = cross_entropy_loss(logits, jnp.asarray(labels))
        return out

    # -------------------------------------------------------------- estimation
    def num_params(self) -> int:
        cfg = self.config
        h, L, inter = cfg.hidden_size, cfg.num_hidden_layers, cfg.intermediate_size
        patch_dim = cfg.num_channels * cfg.patch_size ** 2
        layer = 3 * h * h + 3 * h + h * h + h + 2 * h * inter + inter + h + 4 * h
        return (L * layer + patch_dim * h + h + h + (cfg.num_patches + 1) * h
                + 2 * h + h * cfg.num_labels + cfg.num_labels)

    def flops_per_token(self) -> float:
        return 6 * self.num_params()
