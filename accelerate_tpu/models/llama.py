"""Llama-family decoder — the flagship model (BASELINE.json fsdp2 target).

Designed TPU-first rather than translated:

- **scan over stacked layers**: all per-layer weights carry a leading ``L`` dim and
  the block runs under ``jax.lax.scan`` — one compilation of one block instead of
  ``L`` inlined copies (fast compiles, and the natural substrate for pipeline
  parallelism later).
- **MXU-shaped matmuls**: weights stored (in_dim, out_dim) so every projection is
  a single ``x @ W``; attention uses one fused einsum per score/mix; all compute
  in bf16 under mixed precision with fp32 softmax/logits.
- **GQA**: ``n_kv_heads <= n_heads`` with repeated KV — matches Llama-2/3 shapes.
- **remat**: optional ``jax.checkpoint`` around each scanned block trades FLOPs
  for HBM (the reference delegates this to torch's activation checkpointing,
  ``accelerator.py:1698-1712``).
- **sharding rules**: Megatron-style tp (column-parallel QKV/up, row-parallel
  O/down), fsdp on the complementary dim, seq axis ``sp`` for long context.

Reference context: the reference trains Llama through FSDP2 wrappers
(``benchmarks/fsdp2/main.py``), never defining the model itself (it comes from
transformers). Here the model is part of the framework so the full stack —
kernels to collectives — is TPU-native.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from jax.ad_checkpoint import checkpoint_name

from ..modules import ModelOutput, Module
from ..ops.losses import cross_entropy_loss
from ..utils.dataclasses import resolve_remat_policy


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 2048
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    remat: bool = False
    remat_policy: str = "nothing_saveable"  # any jax.checkpoint_policies name
    attention_impl: str = "auto"  # 'auto' | 'dense' | 'flash' | 'ring' | 'ulysses'
    matmul_precision: str = "default"  # 'default' | 'int8' (QAT w/ STE bwd, ops/int8.py)
    # QKV projection biases (the Qwen2 recipe; Llama proper is bias-free).
    attention_bias: bool = False
    # Per-head RMSNorm on Q and K after the head reshape, before rope — the
    # Qwen3 recipe (weights are head_dim-wide, shared across heads).
    qk_norm: bool = False
    # Sliding-window attention (the Mistral recipe): each query attends only
    # the previous `sliding_window` positions. None = full causal.
    sliding_window: int | None = None
    # RoPE scaling for long-context checkpoints: None, or a dict with
    # rope_type 'linear' (positions/factor) or 'llama3' (frequency-banded
    # scaling, the Llama-3.1 recipe). Matches the HF config field.
    rope_scaling: dict | None = None
    # Per-head width; None = hidden/heads. Gemma decouples it (e.g. 2048/8
    # hidden/heads with 256-wide heads).
    head_dim: int | None = None
    # FFN activation: 'silu' (SwiGLU, the Llama recipe) or 'gelu_tanh'
    # (GeGLU, the Gemma recipe).
    hidden_act: str = "silu"
    # Embedding-lookup scale (Gemma multiplies by sqrt(hidden)); the tied LM
    # head is NOT scaled, so this cannot be baked into the table.
    embedding_multiplier: float = 1.0
    # Per-layer window sizes (None entry = full attention) for models mixing
    # attention regimes across depth: Gemma-2 alternates local/global, Qwen2
    # windows only layers >= max_window_layers. None = uniform sliding_window.
    # The layer scan splits into segments per regime (see _attention_segments).
    layer_windows: tuple | None = None
    # Gemma-2 score shaping: tanh softcap on attention scores / final logits,
    # and a query scaling override (query_pre_attn_scalar ** -0.5 instead of
    # head_dim ** -0.5).
    attn_logit_softcap: float | None = None
    final_logit_softcap: float | None = None
    query_pre_attn_scalar: float | None = None
    # Gemma-2 sandwich norms: post-attention and post-feedforward RMSNorms on
    # each sub-block's OUTPUT (before the residual add), with a separate
    # pre-feedforward norm — four norms per layer instead of two.
    sandwich_norms: bool = False
    # Compute the training loss by vocab-chunked streaming logsumexp straight
    # from hidden states (ops/losses.fused_cross_entropy_loss): the (B·S, V)
    # fp32 logit tensor never materializes. Training-memory lever for large
    # vocab x long context; outputs carry loss but NO logits when it engages.
    # The companion knobs are the vocab128k tuning surface (swept by
    # benchmarks/vocab128k_profile.py; ACCELERATE_FUSED_LOSS_* envs override
    # per-run without touching the config).
    fused_loss: bool = False
    fused_loss_chunk: int = 8192  # vocab tile per scan step
    fused_loss_dtype: str = "fp32"  # 'fp32' | 'bf16' (bf16 chunk exp, fp32 accum)
    fused_loss_unroll: int = 1  # chunk-scan unroll factor; 0 = fully unrolled
    fused_loss_backward: str = "custom"  # 'custom' (single-pass VJP) | 'ad'
    # Intermediates saved under remat_policy='names_saveable' — must be a
    # subset of the checkpoint_name tags the block plants ('attn_out',
    # 'mlp_out'). Saving only the residual-stream contributions costs 2·(B,S,h)
    # per layer where dots-saveable keeps every projection (q/k/v/gate/up ≈
    # (3h + 2·intermediate)·B·S) — the policy for shapes like h2048/i8192
    # where the MLP dots alone exceed the HBM the policy was meant to save.
    remat_save_names: tuple = ("attn_out", "mlp_out")

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_attention_heads
        if self.hidden_act not in ("silu", "gelu_tanh"):
            raise ValueError(f"hidden_act must be silu|gelu_tanh, got {self.hidden_act!r}")
        if self.fused_loss_chunk <= 0:
            raise ValueError(f"fused_loss_chunk must be > 0, got {self.fused_loss_chunk}")
        if self.fused_loss_dtype not in ("fp32", "bf16"):
            raise ValueError(
                f"fused_loss_dtype must be fp32|bf16, got {self.fused_loss_dtype!r}"
            )
        if self.fused_loss_unroll < 0:
            raise ValueError(
                f"fused_loss_unroll must be >= 0, got {self.fused_loss_unroll}"
            )
        if self.fused_loss_backward not in ("custom", "ad"):
            raise ValueError(
                f"fused_loss_backward must be custom|ad, got {self.fused_loss_backward!r}"
            )
        self.remat_save_names = tuple(self.remat_save_names)
        if self.layer_windows is not None:
            self.layer_windows = tuple(self.layer_windows)
            if len(self.layer_windows) != self.num_hidden_layers:
                raise ValueError(
                    f"layer_windows has {len(self.layer_windows)} entries for "
                    f"{self.num_hidden_layers} layers"
                )
            if len(set(self.layer_windows)) == 1:
                # Uniform per-layer windows ARE the plain sliding_window — fold
                # them so every consumer that reads only sliding_window (the
                # pipeline's stage scan, the sp guard) sees the truth.
                self.sliding_window = self.layer_windows[0]
                self.layer_windows = None

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            max_position_embeddings=128,
        )
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def llama2_7b(cls, **kw):
        return cls(**{**dict(), **kw})

    @classmethod
    def llama3_8b(cls, **kw):
        defaults = dict(
            vocab_size=128256,
            hidden_size=4096,
            intermediate_size=14336,
            num_hidden_layers=32,
            num_attention_heads=32,
            num_key_value_heads=8,
            rope_theta=500000.0,
            max_position_embeddings=8192,
        )
        defaults.update(kw)
        return cls(**defaults)


def _fused_loss_overrides(cfg) -> dict:
    """Fused-loss tuning knobs with per-run env overrides — the sweep surface
    (``ACCELERATE_FUSED_LOSS_{CHUNK,DTYPE,UNROLL,BACKWARD}``) used by bench.py
    and benchmarks/vocab128k_profile.py without touching the config object."""
    chunk = int(os.environ.get("ACCELERATE_FUSED_LOSS_CHUNK", "0") or 0)
    unroll = os.environ.get("ACCELERATE_FUSED_LOSS_UNROLL", "")
    return {
        "vocab_chunk": chunk if chunk > 0 else cfg.fused_loss_chunk,
        "chunk_dtype": os.environ.get("ACCELERATE_FUSED_LOSS_DTYPE", "") or cfg.fused_loss_dtype,
        "unroll": int(unroll) if unroll else cfg.fused_loss_unroll,
        "custom_backward": (
            os.environ.get("ACCELERATE_FUSED_LOSS_BACKWARD", "") or cfg.fused_loss_backward
        ) == "custom",
    }


def rms_norm(x, weight, eps):
    dtype = x.dtype
    x = x.astype(jnp.float32)
    x = x * jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)
    return (x * weight).astype(dtype)


SUPPORTED_ROPE_TYPES = ("default", "linear", "llama3", "yarn", "dynamic")


def _llama3_scale_inv_freq(inv_freq, scaling: dict):
    """Llama-3.1 frequency-banded RoPE scaling (the public llama3 recipe, as in
    transformers' Llama3RotaryEmbedding): low-frequency components are divided
    by ``factor``, high-frequency kept, the band between smoothly interpolated."""
    factor = scaling.get("factor", 8.0)
    low = scaling.get("low_freq_factor", 1.0)
    high = scaling.get("high_freq_factor", 4.0)
    original_max = scaling.get("original_max_position_embeddings", 8192)

    wavelen = 2.0 * np.pi / inv_freq
    low_freq_wavelen = original_max / low
    high_freq_wavelen = original_max / high
    smooth = (original_max / wavelen - low) / (high - low)
    smoothed = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
    scaled = np.where(wavelen > low_freq_wavelen, inv_freq / factor, inv_freq)
    is_medium = (wavelen >= high_freq_wavelen) & (wavelen <= low_freq_wavelen)
    return np.where(is_medium, smoothed, scaled).astype(np.float32)


def _yarn_inv_freq(head_dim, theta, scaling: dict):
    """YaRN frequency blending (the public recipe, as in transformers'
    ``_compute_yarn_parameters``): low-frequency components interpolate
    (divide by ``factor``), high-frequency extrapolate (unchanged), with a
    linear ramp between the correction dims derived from beta_fast/beta_slow.
    Returns ``(inv_freq, attention_factor)`` — the factor scales cos/sin."""
    import math

    dim = head_dim
    factor = float(scaling.get("factor", 1.0))
    original_max = scaling.get("original_max_position_embeddings") or scaling.get(
        "max_position_embeddings", 4096
    )
    beta_fast = scaling.get("beta_fast") or 32
    beta_slow = scaling.get("beta_slow") or 1

    attention_factor = scaling.get("attention_factor")
    mscale, mscale_all = scaling.get("mscale"), scaling.get("mscale_all_dim")

    def get_mscale(scale, m=1):
        return 1.0 if scale <= 1 else 0.1 * m * math.log(scale) + 1.0

    if attention_factor is None:
        if mscale and mscale_all:
            attention_factor = get_mscale(factor, mscale) / get_mscale(factor, mscale_all)
        else:
            attention_factor = get_mscale(factor)

    def correction_dim(num_rot):
        return (dim * math.log(original_max / (num_rot * 2 * math.pi))) / (2 * math.log(theta))

    low, high = correction_dim(beta_fast), correction_dim(beta_slow)
    if scaling.get("truncate", True):
        low, high = math.floor(low), math.ceil(high)
    low, high = max(low, 0), min(high, dim - 1)
    if low == high:
        high += 0.001

    pos_freqs = theta ** (np.arange(0, dim, 2, dtype=np.float32) / dim)
    extrapolation = 1.0 / pos_freqs
    interpolation = 1.0 / (factor * pos_freqs)
    ramp = np.clip((np.arange(dim // 2, dtype=np.float32) - low) / (high - low), 0, 1)
    extrapolation_factor = 1.0 - ramp
    inv_freq = interpolation * (1 - extrapolation_factor) + extrapolation * extrapolation_factor
    return inv_freq.astype(np.float32), float(attention_factor)


def rope_tables(positions, head_dim, theta, scaling: dict | None = None,
                seq_len: int | None = None, max_position_embeddings: int | None = None):
    """cos/sin tables for rotary embeddings, fp32. positions: (B, S) int.

    ``seq_len``/``max_position_embeddings`` feed the ``dynamic`` (NTK-aware)
    rope type, whose base stretches when the (static) forward length exceeds
    the pretraining window; shorter forwards use the unmodified base — the
    transformers semantic for a single forward pass. During cached decode the
    chunk length is 1, so frequencies stay fixed (consistent with the cache)."""
    attention_factor = 1.0
    if scaling:
        rope_type = scaling.get("rope_type", scaling.get("type", "default"))
    else:
        rope_type = "default"
    if rope_type == "dynamic" and scaling:
        max_pos = max_position_embeddings or scaling.get("max_position_embeddings", 2048)
        eff = max(seq_len or max_pos, max_pos)
        factor = float(scaling.get("factor", 1.0))
        dim = head_dim
        theta = theta * ((factor * eff / max_pos) - (factor - 1)) ** (dim / (dim - 2))
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float32) / head_dim))
    if scaling:
        if rope_type == "linear":
            inv_freq = inv_freq / float(scaling.get("factor", 1.0))
        elif rope_type == "llama3":
            inv_freq = _llama3_scale_inv_freq(inv_freq, scaling)
        elif rope_type == "yarn":
            if "original_max_position_embeddings" not in scaling and max_position_embeddings:
                scaling = {**scaling, "max_position_embeddings": max_position_embeddings}
            inv_freq, attention_factor = _yarn_inv_freq(head_dim, theta, scaling)
        elif rope_type not in (None, "default", "dynamic"):
            raise ValueError(
                f"Unsupported rope_type {rope_type!r} (supported: {SUPPORTED_ROPE_TYPES})"
            )
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # (B,S,D/2)
    return jnp.cos(angles) * attention_factor, jnp.sin(angles) * attention_factor


def apply_rope(x, cos, sin):
    """x: (B, S, H, D). Rotate pairs (even, odd) halves interleaved as
    [:D/2], [D/2:] (Llama convention)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


from ..ops.attention import attention as _attention


class Llama(Module):
    # Stage protocol (embed/block/head with a context-dict block) — eligible
    # for the GPipe training schedule (parallel/pipeline.py) when pp > 1.
    pipeline_capable = True
    # Context keys a block sows per layer that must surface as scan outputs
    # (MoE router aux loss); empty for the dense model.
    scan_aux_keys: tuple = ()

    def aux_loss_coefs(self) -> dict:
        """How each ``scan_aux_keys`` entry enters the total loss (coefficient
        per key). The 1F1B pipeline schedule reads this to seed the aux-loss
        gradients inside the schedule — it must agree with ``finalize_aux``."""
        return {}

    def __init__(self, config: LlamaConfig):
        self.config = config
        self.params = None

    # ------------------------------------------------------------------- init
    def init(self, rng, *example_inputs, **kwargs):
        cfg = self.config
        h, inter = cfg.hidden_size, cfg.intermediate_size
        hd = cfg.head_dim
        nh, nkv, L = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.num_hidden_layers
        keys = jax.random.split(rng, 10)

        def dense(key, shape, scale_dim=None):
            # Stacked-layer weights are (L, fan_in, fan_out): the fan-in is the
            # second-to-last dim, not the layer count.
            fan_in = scale_dim if scale_dim is not None else (shape[-2] if len(shape) >= 3 else shape[0])
            scale = 1.0 / np.sqrt(fan_in)
            return (jax.random.normal(key, shape, jnp.float32) * scale).astype(jnp.float32)

        params = {
            "embed": {"weight": dense(keys[0], (cfg.vocab_size, h), h)},
            "layers": {
                "attn": {
                    "wq": dense(keys[1], (L, h, nh * hd)),
                    "wk": dense(keys[2], (L, h, nkv * hd)),
                    "wv": dense(keys[3], (L, h, nkv * hd)),
                    "wo": dense(keys[4], (L, nh * hd, h)),
                    **(
                        {
                            "bq": jnp.zeros((L, nh * hd), jnp.float32),
                            "bk": jnp.zeros((L, nkv * hd), jnp.float32),
                            "bv": jnp.zeros((L, nkv * hd), jnp.float32),
                        }
                        if cfg.attention_bias
                        else {}
                    ),
                    **(
                        {
                            "q_norm": jnp.ones((L, hd), jnp.float32),
                            "k_norm": jnp.ones((L, hd), jnp.float32),
                        }
                        if cfg.qk_norm
                        else {}
                    ),
                },
                "mlp": {
                    "w_gate": dense(keys[5], (L, h, inter)),
                    "w_up": dense(keys[6], (L, h, inter)),
                    "w_down": dense(keys[7], (L, inter, h)),
                },
                "input_norm": {"weight": jnp.ones((L, h), jnp.float32)},
                "post_attn_norm": {"weight": jnp.ones((L, h), jnp.float32)},
                **(
                    {
                        "pre_ffw_norm": {"weight": jnp.ones((L, h), jnp.float32)},
                        "post_ffw_norm": {"weight": jnp.ones((L, h), jnp.float32)},
                    }
                    if cfg.sandwich_norms
                    else {}
                ),
            },
            "final_norm": {"weight": jnp.ones((h,), jnp.float32)},
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = {"weight": dense(keys[8], (h, cfg.vocab_size))}
        return params

    # --------------------------------------------------------------- sharding
    def sharding_rules(self):
        """Megatron-style tp + complementary fsdp + pipeline stages.

        The leading scan (layer-stack) dim is sharded on ``pp``: each pipeline
        stage owns a contiguous block of layers (GSPMD inserts the stage-to-stage
        transfers as the scan crosses shard boundaries). With ``pp=1`` the axis
        is trivial and the spec degenerates to unsharded — one rule set serves
        every mesh. Per-layer norm scales ride the same ``pp`` placement.
        """
        return [
            (r"embed/weight", P("tp", "fsdp")),
            (r"attn/w[qkv]", P("pp", "fsdp", "tp")),
            (r"attn/b[qkv]", P("pp", "tp")),
            (r"attn/wo", P("pp", "tp", "fsdp")),
            (r"mlp/w_(gate|up)", P("pp", "fsdp", "tp")),
            (r"mlp/w_down", P("pp", "tp", "fsdp")),
            (r"layers/.*norm", P("pp")),
            (r"norm", P()),
            (r"lm_head/weight", P("fsdp", "tp")),
        ]

    # ---------------------------------------------------------------- forward
    # The forward is decomposed into embed/block/head so the same code paths serve
    # the fused scan (training) and the layer-streamed offloaded-inference runtime
    # (``big_modeling.StreamedScanModel`` runs ``block`` once per layer with weights
    # DMA'd in just-in-time).
    def embed(self, params, input_ids, positions=None, attention_mask=None,
              rope_seq_len=None):
        """Token embedding + rotary tables. Returns (hidden, ctx).

        ``rope_seq_len`` overrides the effective length fed to length-dependent
        rope types (dynamic NTK): the cached decode path pins it to the cache
        capacity so every chunk — prefill and single-token steps alike — is
        rotated with ONE consistent set of frequencies."""
        cfg = self.config
        B, S = input_ids.shape
        from ..parallel.sharding import embedding_lookup

        with jax.named_scope("embed"):
            x = embedding_lookup(params["embed"]["weight"], input_ids)
            x = x.astype(params["embed"]["weight"].dtype)
            if cfg.embedding_multiplier != 1.0:
                # Gemma scales the lookup only — the tied head stays unscaled.
                x = x * jnp.asarray(cfg.embedding_multiplier, x.dtype)
            if positions is None:
                positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))
            cos, sin = rope_tables(
                positions, cfg.head_dim, cfg.rope_theta, cfg.rope_scaling,
                seq_len=rope_seq_len if rope_seq_len is not None else S,
                max_position_embeddings=cfg.max_position_embeddings,
            )
        return x, {"cos": cos, "sin": sin, "attention_mask": attention_mask}

    _WINDOW_FROM_CONFIG = object()  # sentinel: use cfg.sliding_window

    def block(self, layer, x, ctx, cache_layer=None, window=_WINDOW_FROM_CONFIG):
        """One decoder layer on the residual stream (runs under scan or streamed).

        With ``cache_layer`` (``{"k","v"}`` of shape (B, K, n_kv, D) plus
        ``ctx["cache_pos"]``) the layer writes this chunk's K/V into the cache at
        the write offset and attends against the whole cache — the incremental
        decoding path (reference counterpart: transformers' KV cache driven by
        the big_model_inference benchmark,
        ``benchmarks/big_model_inference/big_model_inference.py``). Returns
        ``(x, new_cache_layer)`` in that mode.

        ``window`` is the per-layer attention window (static); the default
        sentinel reads the uniform config value — the segmented layer driver
        (``_run_layers``) passes each segment's own window for mixed-regime
        models (Gemma-2, Qwen2 max_window_layers).
        """
        cfg = self.config
        if window is Llama._WINDOW_FROM_CONFIG:
            window = cfg.sliding_window
        nh, nkv, hd = cfg.num_attention_heads, cfg.num_key_value_heads, cfg.head_dim
        B, S, _ = x.shape
        cos, sin = ctx["cos"], ctx["sin"]
        scale = (
            cfg.query_pre_attn_scalar ** -0.5
            if cfg.query_pre_attn_scalar is not None
            else None
        )
        with jax.named_scope("attn"):
            h = rms_norm(x, layer["input_norm"]["weight"], cfg.rms_norm_eps)
            a = layer["attn"]
            q = self._mm(h, a["wq"])
            k = self._mm(h, a["wk"])
            v = self._mm(h, a["wv"])
            if "bq" in a:  # Qwen2-style QKV biases (static pytree structure)
                q, k, v = q + a["bq"], k + a["bk"], v + a["bv"]
            q = q.reshape(B, S, nh, hd)
            k = k.reshape(B, S, nkv, hd)
            v = v.reshape(B, S, nkv, hd)
            if "q_norm" in a:  # Qwen3 per-head QK norm (static pytree structure)
                q = rms_norm(q, a["q_norm"], cfg.rms_norm_eps)
                k = rms_norm(k, a["k_norm"], cfg.rms_norm_eps)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            new_cache = None
            if cache_layer is not None:
                from ..ops.attention import cached_attention

                pos = ctx["cache_pos"]
                k_cache = jax.lax.dynamic_update_slice(
                    cache_layer["k"], k.astype(cache_layer["k"].dtype), (0, pos, 0, 0)
                )
                v_cache = jax.lax.dynamic_update_slice(
                    cache_layer["v"], v.astype(cache_layer["v"].dtype), (0, pos, 0, 0)
                )
                attn_out = cached_attention(
                    q, k_cache, v_cache,
                    q_positions=ctx["positions"],
                    kv_mask=ctx.get("kv_mask"),
                    window=window,
                    softcap=cfg.attn_logit_softcap,
                    scale=scale,
                )
                new_cache = {"k": k_cache, "v": v_cache}
            else:
                if nkv != nh:
                    rep = nh // nkv
                    k = jnp.repeat(k, rep, axis=2)
                    v = jnp.repeat(v, rep, axis=2)
                attn_out = _attention(
                    q, k, v, causal=True, mask=ctx["attention_mask"],
                    impl=cfg.attention_impl, window=window,
                    softcap=cfg.attn_logit_softcap, scale=scale,
                )
            attn_out = self._mm(attn_out.reshape(B, S, nh * hd), layer["attn"]["wo"])
            attn_out = checkpoint_name(attn_out, "attn_out")
        if cfg.sandwich_norms:
            # Gemma-2: norm each sub-block's OUTPUT before the residual add.
            x = x + rms_norm(attn_out, layer["post_attn_norm"]["weight"], cfg.rms_norm_eps)
            h2 = rms_norm(x, layer["pre_ffw_norm"]["weight"], cfg.rms_norm_eps)
            m = self.mlp(layer, h2, ctx)
            x = x + rms_norm(m, layer["post_ffw_norm"]["weight"], cfg.rms_norm_eps)
        else:
            x = x + attn_out
            h2 = rms_norm(x, layer["post_attn_norm"]["weight"], cfg.rms_norm_eps)
            x = x + self.mlp(layer, h2, ctx)
        return x if new_cache is None else (x, new_cache)

    def mlp(self, layer, h2, ctx=None):
        """SwiGLU FFN on the normed residual. The MoE variant overrides this and
        sows its router aux loss into ``ctx`` (per-call dict, so no state leaks
        across traces)."""
        act = (
            jax.nn.silu
            if self.config.hidden_act == "silu"
            else lambda x: jax.nn.gelu(x, approximate=True)
        )
        with jax.named_scope("mlp"):
            gated = act(self._mm(h2, layer["mlp"]["w_gate"])) * self._mm(h2, layer["mlp"]["w_up"])
            return checkpoint_name(self._mm(gated, layer["mlp"]["w_down"]), "mlp_out")

    def _mm(self, a, b):
        """Block matmul through the precision dispatcher (ops/int8.py). The
        embedding and LM head stay exact — the usual QAT skip list."""
        from ..ops.int8 import matmul

        return matmul(a, b, precision=self.config.matmul_precision)

    @staticmethod
    def _shift_labels(labels, attention_mask):
        """Next-token targets: predict t+1 from t; final position untargeted.
        A position trains only if it is itself real (left-padding guard) AND
        its target token t+1 is real (right-padding guard)."""
        B = labels.shape[0]
        shifted = jnp.concatenate(
            [labels[:, 1:], jnp.full((B, 1), -100, labels.dtype)], axis=1
        )
        if attention_mask is not None:
            target_valid = jnp.concatenate(
                [attention_mask[:, 1:], jnp.zeros((B, 1), attention_mask.dtype)], axis=1
            )
            valid = target_valid.astype(bool) & attention_mask.astype(bool)
            shifted = jnp.where(valid, shifted, -100)
        return shifted

    def head(self, params, x, labels=None, attention_mask=None):
        """Final norm + LM head (+ shifted-label loss).

        The tied head keeps the embed table in its native (V, h) layout all
        the way into the matmul/fused loss: the old ``.T`` materialized a
        transposed copy of the table every step (~0.5 GB at V=128k bf16)
        whose cast/transpose gradient ops no dot-oriented remat policy could
        name."""
        cfg = self.config
        with jax.named_scope("lm_head"):
            x = rms_norm(x, params["final_norm"]["weight"], cfg.rms_norm_eps)
            if cfg.tie_word_embeddings:
                head_w = params["embed"]["weight"].astype(x.dtype)  # (V, h)
            else:
                head_w = params["lm_head"]["weight"]  # (h, V)
            if labels is not None and cfg.fused_loss:
                # Streaming-logsumexp loss from hidden states: the full logit
                # tensor never exists (see LlamaConfig.fused_loss).
                from ..ops.losses import fused_cross_entropy_loss

                knobs = _fused_loss_overrides(cfg)
                loss = fused_cross_entropy_loss(
                    x, head_w, self._shift_labels(labels, attention_mask),
                    logit_cap=cfg.final_logit_softcap,
                    head_transposed=cfg.tie_word_embeddings,
                    **knobs,
                )
                return ModelOutput(loss=loss)
            if cfg.tie_word_embeddings:
                logits = jax.lax.dot_general(x, head_w, (((2,), (1,)), ((), ())))
            else:
                logits = x @ head_w
            if cfg.final_logit_softcap is not None:
                from ..ops.attention import softcap_scores

                logits = softcap_scores(logits.astype(jnp.float32), cfg.final_logit_softcap)
            out = ModelOutput(logits=logits)
            if labels is not None:
                out["loss"] = cross_entropy_loss(
                    logits, self._shift_labels(labels, attention_mask)
                )
            return out

    # ------------------------------------------------------------------ cache
    def init_cache(self, batch_size: int, max_len: int, dtype=jnp.bfloat16):
        """Pre-allocated decode cache: static shapes so every decode step hits
        the same compiled program. K/V stacked over layers to ride the same
        ``lax.scan`` as training. ``kv_mask`` tracks which slots hold real
        tokens (padding-aware); ``pos`` is the write offset."""
        cfg = self.config
        shape = (cfg.num_hidden_layers, batch_size, max_len, cfg.num_key_value_heads, cfg.head_dim)
        return {
            "k": jnp.zeros(shape, dtype),
            "v": jnp.zeros(shape, dtype),
            "pos": jnp.zeros((), jnp.int32),
            "kv_mask": jnp.zeros((batch_size, max_len), jnp.int32),
        }

    def apply(
        self,
        params,
        input_ids=None,
        labels=None,
        attention_mask=None,
        positions=None,
        cache=None,
        train: bool = False,
        rngs=None,
        pipeline=None,
        **kwargs,
    ):
        cfg = self.config
        if cache is not None:
            return self._apply_cached(
                params, input_ids, attention_mask, cache, labels=labels, positions=positions
            )
        x, ctx = self.embed(params, input_ids, positions, attention_mask)
        aux_keys = tuple(self.scan_aux_keys)

        if pipeline is not None:
            # GPipe schedule over the pp mesh axis: stationary stage weights,
            # ppermuted activations (parallel/pipeline.py).
            x, aux = pipeline.run(self, params["layers"], x, ctx)
        else:
            x, aux = self._run_layers(params["layers"], x, ctx, aux_keys)
        out = self.head(params, x, labels=labels, attention_mask=attention_mask)
        return self.finalize_aux(out, aux)

    # --------------------------------------------------------- layer driver
    def _attention_segments(self):
        """Split the layer stack into scan segments by attention regime.

        Returns ``[(start, length, pattern)]`` where ``pattern`` is the tuple
        of per-layer windows the segment's scan body unrolls (length divisible
        by ``len(pattern)``). Uniform models are one segment with a period-1
        pattern — exactly the classic single scan. Gemma-2's alternating
        local/global folds into one scan over layer PAIRS (period 2), keeping
        compile time at one body; Qwen2's ``max_window_layers`` split yields
        two runs (VERDICT r2 #5).
        """
        cfg = self.config
        ws = cfg.layer_windows
        if ws is None:
            return [(0, cfg.num_hidden_layers, (cfg.sliding_window,))]
        from ..parallel.pipeline import _window_segments

        return _window_segments(ws)

    def _run_layers(self, stacked, x, ctx, aux_keys=()):
        """Run the stacked layers through per-regime scan segments; returns
        ``(x, aux_dict)`` with each aux key's mean over layers."""
        cfg = self.config
        L = cfg.num_hidden_layers
        aux_sums = {k: jnp.zeros((), jnp.float32) for k in aux_keys}

        for seg_start, seg_len, pattern in self._attention_segments():
            p = len(pattern)
            seg = stacked
            if not (seg_start == 0 and seg_len == L):
                seg = jax.tree_util.tree_map(
                    lambda t: jax.lax.slice_in_dim(t, seg_start, seg_start + seg_len), stacked
                )
            if p > 1:
                seg = jax.tree_util.tree_map(
                    lambda t: t.reshape(seg_len // p, p, *t.shape[1:]), seg
                )

            def scan_step(x, group, _pattern=pattern, _p=p):
                auxes = []
                for j in range(_p):
                    layer = (
                        jax.tree_util.tree_map(lambda t: t[j], group) if _p > 1 else group
                    )
                    ctx_call = dict(ctx) if aux_keys else ctx
                    x = self.block(layer, x, ctx_call, window=_pattern[j])
                    # Sown aux must become a real scan output *inside* any
                    # checkpoint boundary (no tracer leak across remat).
                    auxes.append(tuple(ctx_call.pop(k) for k in aux_keys))
                return x, auxes

            if cfg.remat:
                policy = resolve_remat_policy(cfg.remat_policy, cfg.remat_save_names)
                scan_step = jax.checkpoint(scan_step, policy=policy)

            x, aux_stack = jax.lax.scan(scan_step, x, seg)
            for j in range(p):
                for i, k in enumerate(aux_keys):
                    aux_sums[k] = aux_sums[k] + jnp.sum(aux_stack[j][i])
        return x, {k: v / L for k, v in aux_sums.items()}

    def finalize_aux(self, out, aux: dict):
        """Fold per-layer scan aux (``scan_aux_keys``) into the output; the
        dense model has none. MoE adds the router loss here so the dense and
        pipelined forwards share one seam."""
        return out

    def _apply_cached(self, params, input_ids, attention_mask, cache, labels=None,
                      positions=None):
        """Prefill/decode forward through the KV cache. The chunk is written at
        ``cache['pos']``; the output carries the advanced cache.

        ``positions`` (optional, (B,S)) are the *token* positions used for
        RoPE; causal masking always uses the cache *slot* indices. For RoPE a
        per-row constant offset between the two cancels, but ragged batches
        give absolute-position models (GPT-2 wpe) mask-derived token positions
        through this split (VERDICT r2 #6)."""
        B, S = input_ids.shape
        pos = cache["pos"]
        slot_positions = pos + jnp.arange(S, dtype=jnp.int32)[None]
        slot_positions = jnp.broadcast_to(slot_positions, (B, S))
        rope_positions = slot_positions if positions is None else positions
        chunk_mask = (
            attention_mask.astype(jnp.int32)
            if attention_mask is not None
            else jnp.ones((B, S), jnp.int32)
        )
        kv_mask = jax.lax.dynamic_update_slice(cache["kv_mask"], chunk_mask, (0, pos))

        # Length-dependent rope (dynamic NTK) must see ONE length for the whole
        # generation — the static cache capacity — or a decode chunk (S=1)
        # would be rotated with the unstretched base while the prefilled keys
        # used the stretched one (advisor r3 finding).
        x, ctx = self.embed(
            params, input_ids, rope_positions, attention_mask,
            rope_seq_len=cache["k"].shape[2],
        )
        ctx["positions"] = slot_positions
        ctx["kv_mask"] = kv_mask
        ctx["cache_pos"] = pos

        # Same per-regime segmentation as training (_run_layers): each
        # segment's scan applies its own static window to cached_attention.
        L = self.config.num_hidden_layers
        nk_parts, nv_parts = [], []
        for seg_start, seg_len, pattern in self._attention_segments():
            p = len(pattern)

            def sl(t):
                if seg_start == 0 and seg_len == L:
                    return t
                return jax.lax.slice_in_dim(t, seg_start, seg_start + seg_len)

            seg_layers = jax.tree_util.tree_map(sl, params["layers"])
            seg_k, seg_v = sl(cache["k"]), sl(cache["v"])
            if p > 1:
                fold = lambda t: t.reshape(seg_len // p, p, *t.shape[1:])
                seg_layers = jax.tree_util.tree_map(fold, seg_layers)
                seg_k, seg_v = fold(seg_k), fold(seg_v)

            def scan_step(x, inp, _pattern=pattern, _p=p):
                layer, ck, cv = inp
                if _p == 1:
                    x, new = self.block(
                        layer, x, ctx, cache_layer={"k": ck, "v": cv}, window=_pattern[0]
                    )
                    return x, (new["k"], new["v"])
                nks, nvs = [], []
                for j in range(_p):
                    lj = jax.tree_util.tree_map(lambda t: t[j], layer)
                    x, new = self.block(
                        lj, x, ctx, cache_layer={"k": ck[j], "v": cv[j]}, window=_pattern[j]
                    )
                    nks.append(new["k"])
                    nvs.append(new["v"])
                return x, (jnp.stack(nks), jnp.stack(nvs))

            x, (nk, nv) = jax.lax.scan(scan_step, x, (seg_layers, seg_k, seg_v))
            if p > 1:
                nk = nk.reshape(seg_len, *nk.shape[2:])
                nv = nv.reshape(seg_len, *nv.shape[2:])
            nk_parts.append(nk)
            nv_parts.append(nv)
        nk = nk_parts[0] if len(nk_parts) == 1 else jnp.concatenate(nk_parts)
        nv = nv_parts[0] if len(nv_parts) == 1 else jnp.concatenate(nv_parts)
        out = self.head(params, x, labels=labels, attention_mask=attention_mask)
        out["cache"] = {"k": nk, "v": nv, "pos": pos + S, "kv_mask": kv_mask}
        return out

    # -------------------------------------------------------------- estimation
    def num_params(self) -> int:
        cfg = self.config
        h, inter, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_hidden_layers
        attn = h * (cfg.num_attention_heads + 2 * cfg.num_key_value_heads) * cfg.head_dim + cfg.num_attention_heads * cfg.head_dim * h
        if cfg.attention_bias:
            attn += (cfg.num_attention_heads + 2 * cfg.num_key_value_heads) * cfg.head_dim
        if cfg.qk_norm:
            attn += 2 * cfg.head_dim
        mlp = 3 * h * inter
        norms = 2 * h
        total = L * (attn + mlp + norms) + cfg.vocab_size * h + h
        if not cfg.tie_word_embeddings:
            total += h * cfg.vocab_size
        return total

    def flops_per_token(self) -> float:
        """Approximate forward+backward FLOPs per token (6N + attention)."""
        cfg = self.config
        n = self.num_params()
        attn_extra = 12 * cfg.num_hidden_layers * cfg.hidden_size * cfg.max_position_embeddings
        return 6 * n + attn_extra
