"""Whisper — speech-to-text encoder-decoder (audio model family).

The reference's big-model machinery is modality-agnostic (device_map dispatch
and generate work for any transformers model); this gives the zoo an audio
family so that claim holds here too. Same seq2seq protocol as T5
(``encode``/``decode``/``init_cache``/``precompute_cross_kv``), so
``generate()`` drives it unchanged; same TPU-first skeleton as the decoders
(stacked-layer ``lax.scan``, Megatron tp rules, fp32 norms/logits).

Architecture (OpenAI Whisper, HF ``WhisperForConditionalGeneration``):

- **Encoder**: log-mel features (B, n_mels, T) → two gelu Conv1d's (the second
  stride-2) → add a FIXED sinusoidal position table (stored in the checkpoint,
  so it converts as a weight) → pre-LN self-attention layers → final norm.
- **Decoder**: token embedding + LEARNED positions (indexed by absolute
  position — the decode cache offsets them), pre-LN blocks of causal
  self-attention, cross-attention over the encoder output, gelu MLP.
- Quirk pinned by parity tests: ``k_proj`` carries NO bias while q/v/out do.
- Head: tied to the token embedding, no bias.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..modules import ModelOutput, Module
from ..ops.attention import attention as _attention, cached_attention
from ..ops.losses import cross_entropy_loss


from ..ops.norms import layer_norm as _layer_norm


@dataclass
class WhisperConfig:
    vocab_size: int = 51865
    num_mel_bins: int = 80
    d_model: int = 384
    encoder_layers: int = 4
    encoder_attention_heads: int = 6
    decoder_layers: int = 4
    decoder_attention_heads: int = 6
    encoder_ffn_dim: int = 1536
    decoder_ffn_dim: int = 1536
    max_source_positions: int = 1500  # AFTER the stride-2 conv
    max_target_positions: int = 448
    decoder_start_token_id: int = 50257
    pad_token_id: int = 50256
    eos_token_id: int = 50256
    layer_norm_eps: float = 1e-5
    attention_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.encoder_attention_heads

    def __post_init__(self):
        if self.encoder_attention_heads != self.decoder_attention_heads:
            raise ValueError("encoder/decoder head counts must match (Whisper ties them)")

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(
            vocab_size=256, num_mel_bins=8, d_model=64,
            encoder_layers=2, encoder_attention_heads=4,
            decoder_layers=2, decoder_attention_heads=4,
            encoder_ffn_dim=128, decoder_ffn_dim=128,
            max_source_positions=32, max_target_positions=32,
            decoder_start_token_id=1, pad_token_id=0, eos_token_id=2,
        )
        defaults.update(kw)
        return cls(**defaults)


class WhisperForConditionalGeneration(Module):
    # Encoder-decoder pipeline training: pp splits the DECODER stack, the
    # encoder (fixed 30s audio window, runs once) stays pp-replicated — the
    # same design as T5 (see T5ForConditionalGeneration's class docstring).
    pipeline_capable = True

    def __init__(self, config: WhisperConfig):
        self.config = config
        self.params = None

    # ------------------------------------------------------------------- init
    def _attn_params(self, key, L, h):
        ks = jax.random.split(key, 4)
        d = lambda k, shape, fan: (jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan))
        return {
            "wq": d(ks[0], (L, h, h), h), "bq": jnp.zeros((L, h), jnp.float32),
            "wk": d(ks[1], (L, h, h), h),  # no bias — the Whisper quirk
            "wv": d(ks[2], (L, h, h), h), "bv": jnp.zeros((L, h), jnp.float32),
            "wo": d(ks[3], (L, h, h), h), "bo": jnp.zeros((L, h), jnp.float32),
        }

    def _side(self, key, L, h, ffn, cross: bool):
        ks = jax.random.split(key, 4)
        d = lambda k, shape, fan: (jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan))
        ln = lambda: {"scale": jnp.ones((L, h), jnp.float32), "bias": jnp.zeros((L, h), jnp.float32)}
        layers = {
            "self_attn": self._attn_params(ks[0], L, h),
            "self_norm": ln(),
            "mlp": {
                "w_in": d(ks[1], (L, h, ffn), h), "b_in": jnp.zeros((L, ffn), jnp.float32),
                "w_out": d(ks[2], (L, ffn, h), ffn), "b_out": jnp.zeros((L, h), jnp.float32),
            },
            "mlp_norm": ln(),
        }
        if cross:
            layers["cross_attn"] = self._attn_params(ks[3], L, h)
            layers["cross_norm"] = ln()
        return layers

    @staticmethod
    def _sinusoids(length: int, channels: int) -> np.ndarray:
        """Whisper's fixed encoder position table (checkpoints store it, so a
        fresh init must match the same formula)."""
        log_timescale = np.log(10000.0) / (channels // 2 - 1)
        inv = np.exp(-log_timescale * np.arange(channels // 2))
        angles = np.arange(length)[:, None] * inv[None]
        return np.concatenate([np.sin(angles), np.cos(angles)], axis=1).astype(np.float32)

    def init(self, rng, *example_inputs, **kwargs):
        cfg = self.config
        h = cfg.d_model
        keys = jax.random.split(rng, 8)
        d = lambda k, shape, fan: (jax.random.normal(k, shape, jnp.float32) / np.sqrt(fan))
        ln = lambda: {"scale": jnp.ones((h,), jnp.float32), "bias": jnp.zeros((h,), jnp.float32)}
        return {
            "encoder": {
                "conv1": {"w": d(keys[0], (3, cfg.num_mel_bins, h), 3 * cfg.num_mel_bins),
                          "b": jnp.zeros((h,), jnp.float32)},
                "conv2": {"w": d(keys[1], (3, h, h), 3 * h),
                          "b": jnp.zeros((h,), jnp.float32)},
                "pos": jnp.asarray(self._sinusoids(cfg.max_source_positions, h)),
                "layers": self._side(keys[2], cfg.encoder_layers, h, cfg.encoder_ffn_dim, cross=False),
                "final_norm": ln(),
            },
            "decoder": {
                "embed": d(keys[3], (cfg.vocab_size, h), h),
                "pos": d(keys[4], (cfg.max_target_positions, h), h),
                "layers": self._side(keys[5], cfg.decoder_layers, h, cfg.decoder_ffn_dim, cross=True),
                "final_norm": ln(),
            },
        }

    # --------------------------------------------------------------- sharding
    def sharding_rules(self):
        """tp/fsdp rules on both stacks; the DECODER layer stack additionally
        shards its leading (layer) dim on ``pp`` — pipeline stages own
        contiguous decoder blocks, the encoder stays pp-replicated (same
        split as T5, see ``T5ForConditionalGeneration``'s class docstring)."""
        return [
            (r"decoder/embed", P("tp", "fsdp")),
            (r"decoder/pos", P(None, "fsdp")),
            (r"encoder/pos", P(None, "fsdp")),
            (r"decoder/layers/.*attn/w[qkv]", P("pp", "fsdp", "tp")),
            (r"decoder/layers/.*attn/b[qv]", P("pp", "tp")),
            (r"decoder/layers/.*attn/wo", P("pp", "tp", "fsdp")),
            (r"decoder/layers/mlp/w_in", P("pp", "fsdp", "tp")),
            (r"decoder/layers/mlp/b_in", P("pp", "tp")),
            (r"decoder/layers/mlp/w_out", P("pp", "tp", "fsdp")),
            (r"decoder/layers/", P("pp")),  # per-layer biases/norms ride pp
            (r"attn/w[qkv]", P(None, "fsdp", "tp")),
            (r"attn/b[qv]", P(None, "tp")),
            (r"attn/wo", P(None, "tp", "fsdp")),
            (r"mlp/w_in", P(None, "fsdp", "tp")),
            (r"mlp/b_in", P(None, "tp")),
            (r"mlp/w_out", P(None, "tp", "fsdp")),
            (r"conv", P()),
            (r"norm", P()),
        ]

    # --------------------------------------------------------------- building blocks
    def _attend(self, x, kv, attn, nh, mask_bias=None, causal=False):
        """Standard MHA; ``kv`` is ``x`` for self-attention or the encoder
        output for cross-attention. ``mask_bias`` is fp32 additive, broadcast
        against (B, nh, T, K) scores."""
        B, T, h = x.shape
        K = kv.shape[1]
        hd = h // nh
        q = (x @ attn["wq"] + attn["bq"]).reshape(B, T, nh, hd)
        k = (kv @ attn["wk"]).reshape(B, K, nh, hd)
        v = (kv @ attn["wv"] + attn["bv"]).reshape(B, K, nh, hd)
        if T == K and mask_bias is None:
            out = _attention(q, k, v, causal=causal, mask=None,
                             impl=self.config.attention_impl)
        else:
            scores = jnp.einsum("bthd,bkhd->bhtk", q, k).astype(jnp.float32)
            scores = scores * (hd ** -0.5)
            if causal and T == K:
                scores = jnp.where(
                    jnp.tril(jnp.ones((T, T), bool))[None, None], scores, -1e30
                )
            if mask_bias is not None:
                scores = scores + mask_bias
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            out = jnp.einsum("bhtk,bkhd->bthd", probs, v)
        return out.reshape(B, T, h) @ attn["wo"] + attn["bo"]

    def _block(self, layer, x, enc_out, nh, eps, cross: bool, causal: bool,
               enc_bias=None, self_bias=None):
        z = _layer_norm(x, layer["self_norm"]["scale"], layer["self_norm"]["bias"], eps)
        x = x + self._attend(z, z, layer["self_attn"], nh, mask_bias=self_bias,
                             causal=causal)
        if cross:
            z = _layer_norm(x, layer["cross_norm"]["scale"], layer["cross_norm"]["bias"], eps)
            x = x + self._attend(z, enc_out, layer["cross_attn"], nh, mask_bias=enc_bias)
        z = _layer_norm(x, layer["mlp_norm"]["scale"], layer["mlp_norm"]["bias"], eps)
        mid = jax.nn.gelu(z @ layer["mlp"]["w_in"] + layer["mlp"]["b_in"], approximate=False)
        x = x + mid @ layer["mlp"]["w_out"] + layer["mlp"]["b_out"]
        return x

    # ----------------------------------------------------------------- encoder
    def encode(self, params, input_features, attention_mask=None):
        """Log-mel features (B, n_mels, T) → encoder states (B, T//2, d).
        Whisper encoders attend the full (fixed-length) window — the returned
        mask is all-ones, present only to satisfy the seq2seq protocol."""
        cfg = self.config
        enc = params["encoder"]
        x = jnp.transpose(input_features, (0, 2, 1))  # (B, T, n_mels)
        dn = ("NHC", "HIO", "NHC")  # 1-D conv over the time axis
        x = jax.nn.gelu(jax.lax.conv_general_dilated(
            x, enc["conv1"]["w"].astype(x.dtype), (1,), ((1, 1),),
            dimension_numbers=dn) + enc["conv1"]["b"], approximate=False)
        x = jax.nn.gelu(jax.lax.conv_general_dilated(
            x, enc["conv2"]["w"].astype(x.dtype), (2,), ((1, 1),),
            dimension_numbers=dn) + enc["conv2"]["b"], approximate=False)
        S = x.shape[1]
        if S > cfg.max_source_positions:
            raise ValueError(
                f"encoder sequence {S} (after stride-2) exceeds "
                f"max_source_positions {cfg.max_source_positions}")
        x = x + enc["pos"][:S].astype(x.dtype)
        nh, eps = cfg.encoder_attention_heads, cfg.layer_norm_eps

        def step(x, layer):
            return self._block(layer, x, None, nh, eps, cross=False, causal=False), None

        x, _ = jax.lax.scan(step, x, enc["layers"])
        x = _layer_norm(x, enc["final_norm"]["scale"], enc["final_norm"]["bias"], eps)
        # HF Whisper ignores encoder attention masks (fixed 30s windows); a
        # user-supplied mask is at mel-frame length, NOT the stride-2 output
        # length, so passing it through would break cross-attention. Always
        # return the all-ones mask at the encoder's own length.
        return x, jnp.ones(x.shape[:2], jnp.int32)

    # ----------------------------------------------------------------- decoder
    def _decoder_stack(self, params, y, enc_out, enc_bias=None, self_bias=None):
        cfg = self.config
        nh, eps = cfg.decoder_attention_heads, cfg.layer_norm_eps
        dec = params["decoder"]

        def step(y, layer):
            return self._block(layer, y, enc_out, nh, eps, cross=True,
                               causal=True, enc_bias=enc_bias,
                               self_bias=self_bias), None

        y, _ = jax.lax.scan(step, y, dec["layers"])
        return _layer_norm(y, dec["final_norm"]["scale"], dec["final_norm"]["bias"], eps)

    def pipeline_layer_params(self, params):
        """The pipelined stack (decoder layers) for resolve_pipeline_spec."""
        return params["decoder"]["layers"]

    def block(self, layer, x, ctx):
        """One decoder block for the pipeline stage protocol — encoder output
        and the optional decoder pad bias arrive via the microbatched context."""
        cfg = self.config
        return self._block(
            layer, x, ctx["enc_out"], cfg.decoder_attention_heads,
            cfg.layer_norm_eps, cross=True, causal=True,
            enc_bias=ctx.get("enc_bias"), self_bias=ctx.get("self_bias"),
        )

    def _head(self, params, y):
        return (y @ params["decoder"]["embed"].T.astype(y.dtype)).astype(jnp.float32)

    def _shift_right(self, labels):
        cfg = self.config
        start = jnp.full((labels.shape[0], 1), cfg.decoder_start_token_id, labels.dtype)
        shifted = jnp.concatenate([start, labels[:, :-1]], axis=1)
        return jnp.where(shifted == -100, cfg.pad_token_id, shifted)

    def apply(
        self,
        params,
        input_features=None,
        attention_mask=None,
        decoder_input_ids=None,
        decoder_attention_mask=None,
        labels=None,
        train: bool = False,
        rngs=None,
        pipeline=None,
        **kwargs,
    ):
        if input_features is None:
            input_features = kwargs.get("input_ids")  # protocol alias
        if decoder_input_ids is None:
            if labels is None:
                raise ValueError("Need decoder_input_ids or labels")
            decoder_input_ids = self._shift_right(labels)
        enc_out, _mask = self.encode(params, input_features, attention_mask)
        y = jnp.take(params["decoder"]["embed"], decoder_input_ids, axis=0)
        T = decoder_input_ids.shape[1]
        y = (y + params["decoder"]["pos"][:T]).astype(enc_out.dtype)
        self_bias = None
        if decoder_attention_mask is not None:
            self_bias = jnp.where(
                decoder_attention_mask[:, None, None, :].astype(bool), 0.0, -1e30
            ).astype(jnp.float32)
        if pipeline is not None:
            # GPipe over the decoder stack; encoder replicated (class note).
            dec = params["decoder"]
            ctx = {"enc_out": enc_out, "self_bias": self_bias}
            y, _ = pipeline.run(self, dec["layers"], y, ctx)
            y = _layer_norm(y, dec["final_norm"]["scale"],
                            dec["final_norm"]["bias"], self.config.layer_norm_eps)
        else:
            y = self._decoder_stack(params, y, enc_out, self_bias=self_bias)
        logits = self._head(params, y)
        out = ModelOutput(logits=logits, encoder_last_hidden_state=enc_out)
        if labels is not None:
            # HF convention: labels arrive pre-masked with -100. Do NOT mask
            # pad_token_id here — real Whisper checkpoints have
            # pad_token_id == eos_token_id, so that would silently erase the
            # EOS supervision (T5's pad != eos makes the pattern safe there).
            out["loss"] = cross_entropy_loss(logits, labels)
        return out

    # ------------------------------------------------------------- generation
    def init_cache(self, batch_size: int, max_len: int, dtype=jnp.bfloat16):
        cfg = self.config
        if max_len > cfg.max_target_positions:
            raise ValueError(
                f"cache length {max_len} exceeds max_target_positions "
                f"{cfg.max_target_positions} (learned decoder positions)")
        shape = (cfg.decoder_layers, batch_size, max_len,
                 cfg.decoder_attention_heads, cfg.head_dim)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
                "pos": jnp.zeros((), jnp.int32)}

    def precompute_cross_kv(self, params, enc_out):
        """Cross-attention K/V per decoder layer, computed once per generation.
        Returns arrays (L, B, S, nh, hd)."""
        cfg = self.config
        nh, hd = cfg.decoder_attention_heads, cfg.head_dim
        B, S, _ = enc_out.shape
        ca = params["decoder"]["layers"]["cross_attn"]
        ck = jnp.einsum("bsh,lhi->lbsi", enc_out, ca["wk"]).reshape(-1, B, S, nh, hd)
        cv = (jnp.einsum("bsh,lhi->lbsi", enc_out, ca["wv"])
              + ca["bv"][:, None, None, :]).reshape(-1, B, S, nh, hd)
        return ck, cv

    def decode(self, params, decoder_input_ids, cache, enc_out, enc_attention_mask,
               cross_kv=None):
        """One cached decoder chunk (prefill or single step): self-attention
        through the cache, cross-attention against precomputed encoder K/V."""
        cfg = self.config
        B, Tc = decoder_input_ids.shape
        nh, hd, eps = cfg.decoder_attention_heads, cfg.head_dim, cfg.layer_norm_eps
        pos = cache["pos"]
        if cross_kv is None:
            cross_kv = self.precompute_cross_kv(params, enc_out)
        ck, cv = cross_kv
        enc_bias = None
        if enc_attention_mask is not None:
            enc_bias = jnp.where(
                enc_attention_mask[:, None, None, :].astype(bool), 0.0, -1e30
            ).astype(jnp.float32)

        positions = pos + jnp.arange(Tc, dtype=jnp.int32)
        y = jnp.take(params["decoder"]["embed"], decoder_input_ids, axis=0)
        y = y + jnp.take(params["decoder"]["pos"], positions, axis=0)
        y = y.astype(params["decoder"]["embed"].dtype)
        q_positions = jnp.broadcast_to(positions[None], (B, Tc))

        dec = params["decoder"]

        def step(y, inp):
            layer, k_cache, v_cache, lck, lcv = inp
            z = _layer_norm(y, layer["self_norm"]["scale"], layer["self_norm"]["bias"], eps)
            q = (z @ layer["self_attn"]["wq"] + layer["self_attn"]["bq"]).reshape(B, Tc, nh, hd)
            k = (z @ layer["self_attn"]["wk"]).reshape(B, Tc, nh, hd)
            v = (z @ layer["self_attn"]["wv"] + layer["self_attn"]["bv"]).reshape(B, Tc, nh, hd)
            k_cache = jax.lax.dynamic_update_slice(k_cache, k.astype(k_cache.dtype), (0, pos, 0, 0))
            v_cache = jax.lax.dynamic_update_slice(v_cache, v.astype(v_cache.dtype), (0, pos, 0, 0))
            attn = cached_attention(q, k_cache, v_cache, q_positions=q_positions)
            y = y + (attn.reshape(B, Tc, -1) @ layer["self_attn"]["wo"] + layer["self_attn"]["bo"])
            z = _layer_norm(y, layer["cross_norm"]["scale"], layer["cross_norm"]["bias"], eps)
            qc = (z @ layer["cross_attn"]["wq"] + layer["cross_attn"]["bq"]).reshape(B, Tc, nh, hd)
            scores = jnp.einsum("bthd,bkhd->bhtk", qc, lck.astype(qc.dtype)) * (hd ** -0.5)
            scores = scores.astype(jnp.float32)
            if enc_bias is not None:
                scores = scores + enc_bias
            probs = jax.nn.softmax(scores, axis=-1).astype(y.dtype)
            a = jnp.einsum("bhtk,bkhd->bthd", probs, lcv.astype(y.dtype))
            y = y + (a.reshape(B, Tc, -1) @ layer["cross_attn"]["wo"] + layer["cross_attn"]["bo"])
            z = _layer_norm(y, layer["mlp_norm"]["scale"], layer["mlp_norm"]["bias"], eps)
            mid = jax.nn.gelu(z @ layer["mlp"]["w_in"] + layer["mlp"]["b_in"], approximate=False)
            y = y + mid @ layer["mlp"]["w_out"] + layer["mlp"]["b_out"]
            return y, (k_cache, v_cache)

        y, (nk, nv) = jax.lax.scan(step, y, (dec["layers"], cache["k"], cache["v"], ck, cv))
        y = _layer_norm(y, dec["final_norm"]["scale"], dec["final_norm"]["bias"], eps)
        return ModelOutput(
            logits=self._head(params, y),
            cache={"k": nk, "v": nv, "pos": pos + Tc},
        )

    # -------------------------------------------------------------- estimation
    def num_params(self) -> int:
        cfg = self.config
        h = cfg.d_model
        attn = 4 * h * h + 3 * h  # wq/wk/wv/wo + q/v/o biases
        enc_layer = attn + 2 * h * cfg.encoder_ffn_dim + cfg.encoder_ffn_dim + h + 4 * h
        dec_layer = 2 * attn + 2 * h * cfg.decoder_ffn_dim + cfg.decoder_ffn_dim + h + 6 * h
        total = cfg.encoder_layers * enc_layer + cfg.decoder_layers * dec_layer
        total += 3 * cfg.num_mel_bins * h + h + 3 * h * h + h  # convs
        total += cfg.max_source_positions * h + cfg.max_target_positions * h
        total += cfg.vocab_size * h + 4 * h  # embed + two final norms
        return total

    def flops_per_token(self) -> float:
        return 6 * self.num_params()
