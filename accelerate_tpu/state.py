"""Process/device state singletons — the L2 layer.

Reference parity (``src/accelerate/state.py``):

- ``PartialState`` (:124) — joins the distributed job, discovers rank/world, selects
  the device, and offers process-control helpers (``wait_for_everyone`` :366,
  ``split_between_processes`` :414, ``main_process_first`` :505, on_*_process
  decorators). There the collective world is a torch.distributed process group
  chosen at :743-809 (nccl/gloo/xla/...); here it is the JAX distributed runtime
  (``jax.distributed.initialize``) plus a ``jax.sharding.Mesh`` whose named axes
  carry every parallelism strategy (see ``parallel/mesh.py``).
- ``AcceleratorState`` (:860) — layers mixed-precision and parallelism config on
  top, mutating ``distributed_type`` the way the reference does for
  DEEPSPEED/FSDP/MEGATRON/TP (:957-989).
- ``GradientState`` (:1204) — gradient-accumulation bookkeeping shared between
  ``Accelerator``, dataloaders, optimizer and scheduler wrappers. The reference's
  ``xm.mark_step`` XLA flush (:1297-1306) has no JAX analog: step boundaries are
  the jitted-function boundary.

All three use the borg pattern (``self.__dict__ = self._shared_state``, reference
:163,179) so every constructor call observes one process-wide state.

A note on "process": in the reference one rank == one GPU. In JAX one *process*
(host) owns many local devices, and arrays are global across all processes. Process
helpers here therefore operate at host granularity — the correct unit for host-side
work (data feeding, logging, checkpoint I/O) — while per-device work is expressed
through shardings on the mesh, not per-rank Python.
"""

from __future__ import annotations

import logging
import os
import weakref
from contextlib import contextmanager
from enum import Enum
from functools import wraps
from typing import Callable

import numpy as np

import jax

from .parallel.mesh import ParallelismConfig, batch_sharding_size
from .utils.constants import (
    ENV_COORDINATOR,
    ENV_CPU,
    ENV_DEBUG_MODE,
    ENV_FLEET_METRICS,
    ENV_HANDLE_PREEMPTION,
    ENV_HANG_TIMEOUT,
    ENV_METRICS_PORT,
    ENV_MIXED_PRECISION,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
    ENV_RESTART_ATTEMPT,
)
from .utils.environment import (
    maybe_enable_compilation_cache,
    parse_choice_from_env,
    parse_flag_from_env,
)

logger = logging.getLogger(__name__)


class DistributedType(str, Enum):
    """Topology/engine marker, mirroring the reference enum's role
    (``utils/dataclasses.py:554-589``) with TPU-native values.

    ``JAX_TPU``/``JAX_GPU``/``MULTI_CPU`` describe the launch topology; plugin
    configuration mutates ``AcceleratorState.distributed_type`` to the strategy
    values (``FSDP``/``TP``/``MEGATRON_STYLE``) exactly like the reference mutates
    to DEEPSPEED/FSDP/MEGATRON_LM/TP at ``state.py:957-989``.
    """

    NO = "NO"
    MULTI_CPU = "MULTI_CPU"
    JAX_TPU = "JAX_TPU"
    JAX_GPU = "JAX_GPU"
    FSDP = "FSDP"  # fsdp axis > 1 (≈ FSDP2 full-shard / ZeRO-3)
    TP = "TP"  # tp axis > 1
    MEGATRON_STYLE = "MEGATRON_STYLE"  # composed tp×pp×dp (3-D)


def is_initialized() -> bool:
    """Whether ``PartialState`` has been constructed (reference ``PartialState().initialized``)."""
    return PartialState._shared_state != {}


def _maybe_init_jax_distributed() -> None:
    """Join the multi-host job if the launcher set the env contract.

    The reference's analog is ``init_process_group`` at ``state.py:233,274`` (the
    NCCL/gloo rendezvous). Here the coordinator is the JAX distributed service;
    collectives themselves are compiled by XLA onto ICI/DCN, not brokered by this
    process group.
    """
    coordinator = os.environ.get(ENV_COORDINATOR)
    num_processes = int(os.environ.get(ENV_NUM_PROCESSES, "1"))
    if coordinator is None or num_processes <= 1:
        return
    if jax._src.distributed.global_state.client is not None:  # already initialized
        return
    process_id = int(os.environ.get(ENV_PROCESS_ID, "0"))
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


class PartialState:
    """Singleton owning process/device discovery and the default mesh.

    Reference: ``state.py:124`` (ctor :178-317).
    """

    _shared_state: dict = {}
    _known_attrs = [
        "_cpu",
        "backend",
        "device",
        "debug",
        "distributed_type",
        "fork_launched",
        "local_process_index",
        "num_processes",
        "process_index",
        "_mesh",
        "_parallelism_config",
        "_metrics_endpoint",
    ]

    def __init__(self, cpu: bool = False, **kwargs):
        self.__dict__ = self._shared_state
        if self.initialized:
            return
        self._cpu = cpu or parse_flag_from_env(ENV_CPU)
        self.debug = parse_flag_from_env(ENV_DEBUG_MODE)
        if self._cpu:
            # Force the host platform BEFORE any backend/distributed init so
            # multi-process rendezvous aggregates CPU devices, not accelerator
            # plugins (reference `cpu=True` semantics, state.py:295-307).
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                logger.warning("cpu=True requested but platform switch failed")
        # XLA latency-hiding preset (ACCELERATE_XLA_PRESET): merged into
        # LIBTPU_INIT_ARGS before ANY backend creation below — libtpu reads
        # the variable once at init, so this must precede the compilation
        # cache config, the distributed rendezvous, and default_backend().
        from .utils.xla_flags import install_preset_from_env

        install_preset_from_env()
        # Persistent XLA compilation cache (ACCELERATE_COMPILE_CACHE_DIR):
        # configured before the first compile so restarted jobs (and every
        # bench re-run) load their programs instead of re-building them.
        maybe_enable_compilation_cache()
        _maybe_init_jax_distributed()
        # Resilience wiring (resilience/): count this gang incarnation in the
        # goodput ledger (the launcher increments ACCELERATE_RESTART_ATTEMPT on
        # every relaunch), and install the preemption watcher EARLY when the
        # launch contract asks for it — a SIGTERM during the first compile or
        # data-loader warmup must set the sticky flag, not kill the process.
        from .resilience.goodput import get_ledger

        get_ledger().mark_process_start(
            attempt=int(os.environ.get(ENV_RESTART_ATTEMPT, "0") or 0)
        )
        if parse_flag_from_env(ENV_HANDLE_PREEMPTION):
            from .resilience.preemption import get_default_watcher

            get_default_watcher(install=True)
        # Hang watchdog (health/hang.py): started here so it guards the whole
        # process life; it only arms on the first step heartbeat, so a long
        # first compile cannot false-positive.
        hang_timeout = os.environ.get(ENV_HANG_TIMEOUT, "").strip()
        if hang_timeout:
            from .health.hang import install_default_watchdog

            try:
                install_default_watchdog(float(hang_timeout))
            except ValueError:
                raise ValueError(
                    f"{ENV_HANG_TIMEOUT}={hang_timeout!r} must be a positive "
                    "number of seconds"
                ) from None
        platform = jax.default_backend()
        if self._cpu and platform != "cpu":
            logger.warning(
                "cpu=True requested but backend resolved to %s; "
                "set jax.config jax_platforms='cpu' before any backend use.",
                platform,
            )
        self.num_processes = jax.process_count()
        self.process_index = jax.process_index()
        # Host-local index: with one process per host this equals process_index
        # modulo per-node layout; JAX does not expose a node rank, so launchers set
        # ACCELERATE_LOCAL_PROCESS_ID when it differs.
        self.local_process_index = int(
            os.environ.get("ACCELERATE_LOCAL_PROCESS_ID", self.process_index)
        )
        self.device = jax.local_devices()[0]
        self.backend = platform
        self.fork_launched = parse_flag_from_env("FORK_LAUNCHED", 0)
        if platform == "tpu":
            self.distributed_type = DistributedType.JAX_TPU
        elif platform == "gpu":
            self.distributed_type = DistributedType.JAX_GPU
        elif jax.device_count() > 1 or self.num_processes > 1:
            self.distributed_type = DistributedType.MULTI_CPU
        else:
            self.distributed_type = DistributedType.NO
        self._mesh = None
        self._parallelism_config = None
        # Telemetry wiring (telemetry/): the opt-in Prometheus endpoint starts
        # at init — like the watchdog, it must serve for the whole process
        # life, including a multi-minute first compile — while the timeline/
        # straggler pieces build lazily on first Accelerator.telemetry access.
        # After process discovery so co-located workers (the CPU-sim gang)
        # offset the port by their local rank instead of fighting for one
        # bind; the shared helper degrades a bind failure to a warning.
        self._metrics_endpoint = None
        # Disaggregated-serving tier membership (serving_net/roles.py): the
        # role is a launch-time property of the HOST — resolved here once so
        # commands, the serving frontend, and the fleet plane all agree —
        # and published as a labeled gauge so /fleet rows carry the tier
        # before any engine or frontend exists (warmup is visible per tier).
        from .serving_net.roles import resolve_serving_role

        self.serving_role = resolve_serving_role()
        if os.environ.get(ENV_METRICS_PORT, "").strip():
            from .telemetry import start_endpoint_from_env

            server = start_endpoint_from_env(self.local_process_index)
            if server is not None:
                # Publish the ACTUALLY bound host:port (the local-rank port
                # offset and ephemeral binds included) into the fleet KV
                # registry, so the aggregator, straggler warnings, and
                # operators read the real address instead of guessing it
                # from the env contract (telemetry/fleet.py).
                from .telemetry.fleet import publish_metrics_endpoint

                self._metrics_endpoint = publish_metrics_endpoint(
                    process_index=self.process_index, server=server
                )
                if self.serving_role.name != "unified":
                    from .telemetry.metrics import get_registry

                    get_registry().gauge(
                        "accelerate_serving_role",
                        "Serving tier this process runs (1 = the labeled role)",
                        labelnames=("role",),
                    ).set(1, role=self.serving_role.name)
                # Fleet aggregation plane (ACCELERATE_FLEET_METRICS): the
                # lead host scrapes every registered endpoint and serves the
                # joined series + rollups at /fleet on this same server.
                if parse_flag_from_env(ENV_FLEET_METRICS) and self.process_index == 0:
                    from .telemetry.fleet import (
                        FleetAggregator,
                        install_fleet_provider,
                    )

                    install_fleet_provider(FleetAggregator(state=self))

    def __repr__(self) -> str:
        return (
            f"Distributed environment: {self.distributed_type.value}  Backend: {self.backend}\n"
            f"Num processes: {self.num_processes}\n"
            f"Process index: {self.process_index}\n"
            f"Local process index: {self.local_process_index}\n"
            f"Device: {self.device}\n"
            f"Local devices: {jax.local_device_count()}  Global devices: {jax.device_count()}\n"
        )

    @classmethod
    def _reset_state(cls):
        """Reset singleton state — for testing (reference ``state.py:1188``)."""
        cls._shared_state.clear()

    @property
    def initialized(self) -> bool:
        return self._shared_state != {}

    # ---------------------------------------------------------------- topology
    @property
    def use_distributed(self) -> bool:
        """True when more than one device participates (reference :334-340 checks
        num_processes > 1; a single JAX process driving 8 chips is distributed in
        every sense that matters here)."""
        return self.num_devices > 1

    @property
    def num_devices(self) -> int:
        return jax.device_count()

    @property
    def local_device_count(self) -> int:
        return jax.local_device_count()

    @property
    def metrics_endpoint(self) -> str | None:
        """The metrics endpoint this worker ACTUALLY serves (``host:port``,
        bound port — ephemeral binds and the co-located-worker port offset
        included), published into the fleet KV registry at init; None when no
        endpoint is configured (telemetry/fleet.py)."""
        return self.__dict__.get("_metrics_endpoint")

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def is_local_main_process(self) -> bool:
        return self.local_process_index == 0

    @property
    def is_last_process(self) -> bool:
        return self.process_index == self.num_processes - 1

    # ------------------------------------------------------------------- mesh
    @property
    def mesh(self):
        """The default mesh: all devices on the ``dp`` axis. ``AcceleratorState``
        replaces this with the plugin-configured mesh."""
        if self._mesh is None:
            self._mesh = ParallelismConfig().build_mesh()
        return self._mesh

    def set_mesh(self, mesh, parallelism_config: ParallelismConfig | None = None):
        self._mesh = mesh
        self._parallelism_config = parallelism_config

    @property
    def parallelism_config(self) -> ParallelismConfig | None:
        return self._parallelism_config

    # -------------------------------------------------------- process control
    def wait_for_everyone(self):
        """Cross-host barrier (reference :366-402). No-op single-process; on a pod
        this synchronizes via a tiny global collective, the multihost_utils idiom."""
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("accelerate_tpu.wait_for_everyone")

    def _goes_first(self, is_main: bool):
        if not is_main:
            self.wait_for_everyone()
        yield
        if is_main:
            self.wait_for_everyone()

    @contextmanager
    def main_process_first(self):
        """Main process runs the block first, others wait (reference :505)."""
        yield from self._goes_first(self.is_main_process)

    @contextmanager
    def local_main_process_first(self):
        yield from self._goes_first(self.is_local_main_process)

    @contextmanager
    def split_between_processes(self, inputs, apply_padding: bool = False):
        """Split a list/tuple/dict/array evenly across processes (reference :414-504).

        When the length does not divide evenly, the first ``length % num_processes``
        processes receive one extra element. With ``apply_padding``, short shards
        are padded with the *global* final element so every process gets the same
        length (needed before global collectives with static shapes).
        """
        if self.num_processes == 1:
            yield inputs
            return
        if isinstance(inputs, dict):
            # Split each value's rows, not the dict's keys (reference :447-455).
            lengths = {k: len(v) for k, v in inputs.items()}
            if not lengths:
                yield inputs
                return
            if len(set(lengths.values())) > 1:
                raise ValueError(
                    f"All dict values must share a length to split; got {lengths}"
                )
            length = next(iter(lengths.values()))
        else:
            length = len(inputs)
        split_sizes = [length // self.num_processes] * self.num_processes
        for i in range(length % self.num_processes):
            split_sizes[i] += 1
        start = sum(split_sizes[: self.process_index])
        end = start + split_sizes[self.process_index]

        if isinstance(inputs, dict):
            shard = {k: v[start:end] for k, v in inputs.items()}
        else:
            shard = inputs[start:end]
        if apply_padding and split_sizes[self.process_index] < max(split_sizes):
            pad = max(split_sizes) - split_sizes[self.process_index]
            if isinstance(inputs, dict):
                # Pad with the global last row so even empty shards become rectangular.
                shard = {k: _pad_with_last(shard[k], pad, fallback=inputs[k]) for k in inputs}
            else:
                shard = _pad_with_last(shard, pad, fallback=inputs)
        yield shard

    def on_main_process(self, function: Callable = None):
        """Decorator: run only on the main process (reference :531)."""

        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_main_process:
                return function(*args, **kwargs)

        return wrapper

    def on_local_main_process(self, function: Callable = None):
        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_local_main_process:
                return function(*args, **kwargs)

        return wrapper

    def on_last_process(self, function: Callable):
        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_last_process:
                return function(*args, **kwargs)

        return wrapper

    def on_process(self, function: Callable = None, process_index: int = None):
        if function is None:
            return lambda f: self.on_process(f, process_index)

        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.process_index == process_index:
                return function(*args, **kwargs)

        return wrapper

    def on_local_process(self, function: Callable = None, local_process_index: int = None):
        if function is None:
            return lambda f: self.on_local_process(f, local_process_index)

        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.local_process_index == local_process_index:
                return function(*args, **kwargs)

        return wrapper

    def print(self, *args, **kwargs):
        if self.is_local_main_process:
            print(*args, **kwargs)

    def destroy_process_group(self):
        """Leave the distributed job (reference ``destroy_process_group`` :747)."""
        if jax._src.distributed.global_state.client is not None:
            jax.distributed.shutdown()

    def __getattr__(self, name: str):
        if name in self._known_attrs:
            raise AttributeError(
                f"`PartialState` object has no attribute `{name}`. "
                "This happens if `PartialState._reset_state()` was called and "
                "an `Accelerator` or `PartialState` was not reinitialized."
            )
        raise AttributeError(f"'PartialState' object has no attribute '{name}'")


def _pad_with_last(seq, pad: int, fallback=None):
    """Pad ``seq`` with ``pad`` copies of its last element; an empty shard borrows
    the last element of ``fallback`` (the full input) so it still pads."""
    source = seq if len(seq) else fallback
    if isinstance(seq, np.ndarray) or hasattr(seq, "shape"):
        reps = [np.asarray(source[-1:])] * pad
        return np.concatenate([np.asarray(seq), *reps], axis=0) if len(seq) else np.concatenate(reps, axis=0)
    return list(seq) + [source[-1]] * pad


class AcceleratorState:
    """Adds mixed precision + parallelism configuration on top of ``PartialState``.

    Reference: ``state.py:860`` (ctor :890-1008). The distributed_type mutation for
    FSDP/TP/Megatron (:957-989) is mirrored: a non-trivial ``ParallelismConfig``
    rewrites ``distributed_type`` so downstream code can branch the same way user
    code does in the reference ecosystem.
    """

    _shared_state: dict = {}
    _known_attrs = PartialState._known_attrs + [
        "mixed_precision",
        "dynamo_plugin",
        "use_ipex",
        "parallelism_config",
    ]

    def __init__(
        self,
        mixed_precision: str | None = None,
        cpu: bool = False,
        parallelism_config: ParallelismConfig | None = None,
        _from_accelerator: bool = False,
        **kwargs,
    ):
        self.__dict__ = self._shared_state
        if self.initialized:
            if parallelism_config is not None and self.parallelism_config != parallelism_config:
                raise ValueError(
                    "AcceleratorState already initialized with a different parallelism_config; "
                    "call AcceleratorState._reset_state() first."
                )
            if mixed_precision is not None and mixed_precision != self._mixed_precision:
                logger.warning(
                    "AcceleratorState already initialized; mixed_precision=%s ignored "
                    "(currently %s).",
                    mixed_precision,
                    self._mixed_precision,
                )
            return
        # Validate everything fallible BEFORE touching the borg shared dict, so a
        # failed construction doesn't leave a half-initialized singleton behind.
        mixed_precision = (
            parse_choice_from_env(ENV_MIXED_PRECISION, "no")
            if mixed_precision is None
            else str(mixed_precision)
        )
        if mixed_precision not in ("no", "bf16", "fp16", "fp8"):
            raise ValueError(
                f"Unknown mixed_precision mode: {mixed_precision!r}; choose from no/bf16/fp16/fp8"
            )
        if mixed_precision == "fp8":
            logger.warning(
                "fp8 requested: TPU generations through v5p have no fp8 ALUs; falling "
                "back to int8-quantized matmuls where configured, bf16 elsewhere."
            )
        if parallelism_config is None:
            parallelism_config = ParallelismConfig.from_env()
        # Build everything in locals first: mesh-shape validation errors must not
        # leave a half-initialized AcceleratorState singleton behind.
        partial = PartialState(cpu=cpu, **kwargs)
        mesh = parallelism_config.build_mesh()
        # Read sizes off the built mesh: it is the source of truth once slice
        # auto-detection (dcn) has resolved against the real device set.
        sizes = dict(mesh.shape)

        self._partial = partial
        # Share the dict contents: expose PartialState attrs through this object.
        for key, value in self._partial.__dict__.items():
            if key not in self.__dict__:
                self.__dict__[key] = value
        self._mixed_precision = mixed_precision
        self.parallelism_config = parallelism_config
        self._partial.set_mesh(mesh, parallelism_config)
        self.__dict__["_mesh"] = mesh

        # distributed_type mutation, mirroring reference state.py:957-989
        if sizes["tp"] > 1 and (sizes["pp"] > 1 or sizes["fsdp"] > 1):
            self.distributed_type = DistributedType.MEGATRON_STYLE
        elif sizes["fsdp"] > 1:
            self.distributed_type = DistributedType.FSDP
        elif sizes["tp"] > 1:
            self.distributed_type = DistributedType.TP
        else:
            self.distributed_type = self._partial.distributed_type

    def __repr__(self):
        return self._partial.__repr__() + f"Mixed precision type: {self.mixed_precision}\n"

    @classmethod
    def _reset_state(cls, reset_partial_state: bool = False):
        cls._shared_state.clear()
        if reset_partial_state:
            PartialState._reset_state()

    @property
    def initialized(self) -> bool:
        return self._shared_state != {}

    @property
    def mixed_precision(self) -> str:
        return self._mixed_precision

    @property
    def compute_dtype(self):
        import jax.numpy as jnp

        return jnp.bfloat16 if self._mixed_precision in ("bf16", "fp8") else (
            jnp.float16 if self._mixed_precision == "fp16" else jnp.float32
        )

    @property
    def mesh(self):
        return self._partial.mesh

    def replace_mesh(self, mesh, parallelism_config: ParallelismConfig | None = None):
        """Swap the process mesh after an elastic world-size change
        (``resilience/elastic.py``): every property reading the mesh live —
        batch placement, ``global_batch_divisor``, the sharding planner —
        sees the new world immediately. The caller owns moving live arrays
        onto it (``reshard_accelerator``)."""
        self._partial.set_mesh(mesh, parallelism_config)
        self.__dict__["_mesh"] = mesh
        if parallelism_config is not None:
            self.parallelism_config = parallelism_config

    @property
    def global_batch_divisor(self) -> int:
        """How many ways the global batch is sharded (dp*fsdp axes)."""
        return batch_sharding_size(self.mesh)

    # Delegate everything else to PartialState.
    def __getattr__(self, name: str):
        if name in ("_partial",) or name.startswith("__"):
            raise AttributeError(name)
        partial = self.__dict__.get("_partial")
        if partial is not None and hasattr(type(partial), name):
            return getattr(partial, name)
        if partial is not None and name in partial.__dict__:
            return partial.__dict__[name]
        if name in self._known_attrs:
            raise AttributeError(
                f"`AcceleratorState` object has no attribute `{name}`. "
                "This happens if `AcceleratorState._reset_state()` was called and "
                "an `Accelerator` or `AcceleratorState` was not reinitialized."
            )
        raise AttributeError(f"'AcceleratorState' object has no attribute '{name}'")


class GradientState:
    """Gradient-accumulation bookkeeping singleton (reference ``state.py:1204``).

    ``sync_gradients`` is True on accumulation boundaries — in the fused jitted
    train step this flag is carried as data (a traced boolean) rather than causing
    retraces; this mirror exists for the imperative facade and for the scheduler/
    optimizer wrappers. Registered dataloaders are tracked by weakref exactly like
    the reference (:1308-1339) so `end_of_dataloader`/`remainder` reflect the
    currently-iterating loader.
    """

    _shared_state: dict = {}

    def __init__(self, gradient_accumulation_plugin=None):
        self.__dict__ = self._shared_state
        if not self.initialized:
            self.sync_gradients = True
            self._dataloader_refs = []
            self.plugin_kwargs = {}
            self._is_xla_gradients_synced = False  # parity slot; always True in JAX
        if gradient_accumulation_plugin is not None:
            self.plugin_kwargs = gradient_accumulation_plugin.to_kwargs()

    @property
    def initialized(self) -> bool:
        return GradientState._shared_state != {}

    @property
    def num_steps(self) -> int:
        return self.plugin_kwargs.get("num_steps", 1)

    @property
    def adjust_scheduler(self) -> bool:
        return self.plugin_kwargs.get("adjust_scheduler", True)

    @property
    def sync_with_dataloader(self) -> bool:
        return self.plugin_kwargs.get("sync_with_dataloader", True)

    @property
    def active_dataloader(self):
        refs = [r() for r in self._dataloader_refs]
        refs = [r for r in refs if r is not None]
        return refs[-1] if refs else None

    @property
    def dataloader_references(self):
        return [r() for r in self._dataloader_refs]

    @property
    def end_of_dataloader(self) -> bool:
        dl = self.active_dataloader
        return getattr(dl, "end_of_dataloader", False) if dl is not None else False

    @property
    def remainder(self) -> int:
        dl = self.active_dataloader
        return getattr(dl, "remainder", -1) if dl is not None else -1

    def _set_sync_gradients(self, sync: bool):
        self.sync_gradients = sync

    def _add_dataloader(self, dataloader):
        self._dataloader_refs.append(weakref.ref(dataloader))

    def _remove_dataloader(self, dataloader):
        self._dataloader_refs = [
            r for r in self._dataloader_refs if r() is not None and r() is not dataloader
        ]

    @classmethod
    def _reset_state(cls):
        cls._shared_state.clear()

    def __repr__(self):
        return (
            f"Sync Gradients: {self.sync_gradients}\n"
            f"At end of current dataloader: {self.end_of_dataloader}\n"
            f"Extra samples added: {self.remainder}\n"
            f"Gradient accumulation plugin: {self.plugin_kwargs}\n"
        )
