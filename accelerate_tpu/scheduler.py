"""AcceleratedScheduler — LR schedule bookkeeping tied to real optimizer steps.

Reference parity: ``src/accelerate/scheduler.py:25`` steps the torch scheduler only
when every wrapped optimizer actually stepped (grad-accumulation skips, fp16
overflow skips), and advances by ``num_processes`` when batches aren't split so a
schedule authored for single-process step counts lands at the same lr-vs-samples
curve (:60-81).

Here the schedule is an optax-style ``Callable[[int], float]``. If the optimizer
was built with ``optax.inject_hyperparams`` the new lr is written through into the
optimizer's device state; otherwise the wrapper only tracks the count (useful when
the schedule is already baked into the transform via ``scale_by_schedule`` — then
``step()`` is pure bookkeeping and ``get_last_lr`` still reports the curve).
"""

from __future__ import annotations

import numpy as np

from .utils.transfer import host_fetch


class AcceleratedScheduler:
    def __init__(self, schedule, optimizers, step_with_optimizer: bool = True, split_batches: bool = False):
        if not callable(schedule):
            raise TypeError(f"expected a schedule callable (int -> float), got {type(schedule)}")
        self.schedule = schedule
        self.optimizers = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
        self.step_with_optimizer = step_with_optimizer
        # API-parity no-op: the reference uses split_batches to decide between
        # advancing 1 vs num_processes; here every step is a global step (see
        # step() below) so the flag has no effect.
        self.split_batches = split_batches
        self.step_count = 0
        self._last_lr = float(host_fetch(schedule(0)))
        from .state import GradientState

        self.gradient_state = GradientState()

    def step(self, *args, **kwargs):
        if not self.step_with_optimizer:
            self._advance(1)
            return
        # Accumulation: only count on sync boundaries (reference :63-69).
        if not self.gradient_state.sync_gradients:
            return
        # Skip if any optimizer skipped (fp16 overflow; reference :73-81).
        if any(opt.step_was_skipped for opt in self.optimizers):
            return
        # The reference advances by num_processes when batches aren't split
        # (scheduler.py:60-81) because each torch process's loader shard yields
        # num_processes× fewer batches than the single-process count schedules
        # are authored against. Here the prepared loader yields *global*
        # batches — every optimizer step is one global step on every process —
        # so one schedule tick per step is already the same lr-vs-samples curve.
        # (Scaling by the device-level dp×fsdp degree would exhaust the schedule
        # mesh-size× early: a 192-step schedule would hit its floor at step 24
        # on an 8-device mesh.)
        self._advance(1)

    def _advance(self, increment: int):
        self.step_count += increment
        self._last_lr = float(host_fetch(self.schedule(self.step_count)))
        for opt in self.optimizers:
            opt.set_learning_rate(self._last_lr)

    def get_last_lr(self):
        return [self._last_lr]

    def state_dict(self):
        return {"step_count": self.step_count, "last_lr": self._last_lr}

    def load_state_dict(self, state_dict):
        self.step_count = state_dict["step_count"]
        self._last_lr = state_dict["last_lr"]
        for opt in self.optimizers:
            opt.set_learning_rate(self._last_lr)
