"""AcceleratedScheduler — LR schedule bookkeeping tied to real optimizer steps.

Reference parity: ``src/accelerate/scheduler.py:25`` steps the torch scheduler only
when every wrapped optimizer actually stepped (grad-accumulation skips, fp16
overflow skips), and advances by ``num_processes`` when batches aren't split so a
schedule authored for single-process step counts lands at the same lr-vs-samples
curve (:60-81).

Here the schedule is an optax-style ``Callable[[int], float]``. If the optimizer
was built with ``optax.inject_hyperparams`` the new lr is written through into the
optimizer's device state; otherwise the wrapper only tracks the count (useful when
the schedule is already baked into the transform via ``scale_by_schedule`` — then
``step()`` is pure bookkeeping and ``get_last_lr`` still reports the curve).
"""

from __future__ import annotations

import numpy as np


class AcceleratedScheduler:
    def __init__(self, schedule, optimizers, step_with_optimizer: bool = True, split_batches: bool = False):
        if not callable(schedule):
            raise TypeError(f"expected a schedule callable (int -> float), got {type(schedule)}")
        self.schedule = schedule
        self.optimizers = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
        self.step_with_optimizer = step_with_optimizer
        self.split_batches = split_batches
        self.step_count = 0
        self._last_lr = float(np.asarray(schedule(0)))
        from .state import AcceleratorState, GradientState

        self.gradient_state = GradientState()
        self.accelerator_state = AcceleratorState() if AcceleratorState._shared_state else None

    def step(self, *args, **kwargs):
        if not self.step_with_optimizer:
            self._advance(1)
            return
        # Accumulation: only count on sync boundaries (reference :63-69).
        if not self.gradient_state.sync_gradients:
            return
        # Skip if any optimizer skipped (fp16 overflow; reference :73-81).
        if any(opt.step_was_skipped for opt in self.optimizers):
            return
        if self.split_batches:
            increment = 1
        else:
            # One global step consumes data-parallel-degree process-batches; a
            # schedule authored in per-process steps advances that much (reference
            # multiplies by num_processes for the same reason).
            increment = (
                self.accelerator_state.global_batch_divisor if self.accelerator_state is not None else 1
            )
        self._advance(increment)

    def _advance(self, increment: int):
        self.step_count += increment
        self._last_lr = float(np.asarray(self.schedule(self.step_count)))
        for opt in self.optimizers:
            opt.set_learning_rate(self._last_lr)

    def get_last_lr(self):
        return [self._last_lr]

    def state_dict(self):
        return {"step_count": self.step_count, "last_lr": self._last_lr}

    def load_state_dict(self, state_dict):
        self.step_count = state_dict["step_count"]
        self._last_lr = state_dict["last_lr"]
        for opt in self.optimizers:
            opt.set_learning_rate(self._last_lr)
