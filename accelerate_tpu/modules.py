"""Model protocol & adapters — how user models enter the compiled world.

The reference wraps live ``nn.Module`` objects (``accelerator.py:1515-1800``).
TPU-native, a model is a *pure function plus a parameter pytree*; this module
defines that protocol and adapters so users can bring:

- an ``accelerate_tpu.Module`` subclass (our model zoo in ``models/``),
- a ``flax.linen.Module``,
- a bare ``(init_fn, apply_fn)`` pair via ``FunctionalModel``.

The ``PreparedModel`` returned by ``Accelerator.prepare`` keeps the imperative feel
of the reference API — ``model(**batch)`` works, ``model.train()/.eval()`` work —
while everything under the call is a cached, jitted, sharded pure function.

HF-style convention: when the batch contains labels the forward returns an output
structure with a ``loss`` field; that is what powers the reference-shaped
``accelerator.backward(loss)`` flow (see ``accelerator.py`` here).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp


class ModelOutput(dict):
    """Dict with attribute access (``out.loss``, ``out.logits``) — pytree-friendly
    stand-in for transformers' ModelOutput."""

    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name)

    def __setattr__(self, name, value):
        self[name] = value


jax.tree_util.register_pytree_node(
    ModelOutput,
    lambda mo: (tuple(mo.values()), tuple(mo.keys())),
    lambda keys, vals: ModelOutput(zip(keys, vals)),
)


class Module:
    """Base for the model zoo: stateless config object + pure init/apply.

    Subclasses implement ``init(rng, *example_inputs) -> params`` and
    ``apply(params, *args, train=False, rngs=None, **kwargs)``.
    """

    def init(self, rng, *example_inputs, **kwargs):
        raise NotImplementedError

    def apply(self, params, *args, train: bool = False, rngs=None, **kwargs):
        raise NotImplementedError

    def init_params(self, rng=None, *example_inputs, **kwargs):
        """Materialize (or, under ``init_empty_weights``, abstractly shape) the
        parameter pytree and remember it on the model object.

        Under the ``big_modeling.init_empty_weights`` context the tree's leaves are
        ``jax.ShapeDtypeStruct`` — zero memory, the analog of the reference's
        meta-device allocation (``big_modeling.py:61-170`` there).
        """
        if rng is None:
            rng = jax.random.key(0)
        from .big_modeling import _empty_init_active

        if _empty_init_active():
            self.params = jax.eval_shape(self.init, rng, *example_inputs, **kwargs)
        else:
            self.params = self.init(rng, *example_inputs, **kwargs)
        return self.params

    # Optional: logical sharding rules {param-path-regex: PartitionSpec-template}
    # consumed by parallel/sharding.py. Default: automatic rules by shape.
    def sharding_rules(self):
        return None


@dataclasses.dataclass
class FunctionalModel(Module):
    """Adapter for a bare (init_fn, apply_fn) pair."""

    init_fn: Callable
    apply_fn: Callable

    def init(self, rng, *example_inputs, **kwargs):
        return self.init_fn(rng, *example_inputs, **kwargs)

    def apply(self, params, *args, train: bool = False, rngs=None, **kwargs):
        return self.apply_fn(params, *args, **kwargs)


class FlaxLinenAdapter(Module):
    """Adapter for ``flax.linen.Module`` instances.

    Forwards ``train`` as the conventional ``deterministic``/``train`` kwarg only
    when the module accepts it, and threads dropout rngs.
    """

    def __init__(self, linen_module):
        self.linen_module = linen_module

    def init(self, rng, *example_inputs, **kwargs):
        return self.linen_module.init(rng, *example_inputs, **kwargs)

    def apply(self, params, *args, train: bool = False, rngs=None, **kwargs):
        call_kwargs = dict(kwargs)
        if rngs is not None:
            call_kwargs["rngs"] = rngs
        try:
            return self.linen_module.apply(params, *args, **call_kwargs)
        except TypeError:
            call_kwargs.pop("rngs", None)
            return self.linen_module.apply(params, *args, **call_kwargs)


def is_torch_module(model) -> bool:
    """True for a live ``torch.nn.Module`` (without importing torch eagerly)."""
    if "torch" not in str(type(model).__mro__):
        return False
    try:
        import torch.nn as tnn

        return isinstance(model, tnn.Module)
    except ImportError:
        return False


def as_module(model) -> Module:
    """Coerce any supported model object to the Module protocol."""
    if isinstance(model, Module):
        return model
    if is_torch_module(model):
        # torch Modules happen to expose ``apply`` (their recursive-apply
        # helper), so without this check they would be mis-wrapped as
        # FunctionalModel and fail deep inside the first trace.
        raise TypeError(
            f"Cannot prepare a torch.nn.Module ({type(model).__name__}) directly: "
            "this framework runs pure JAX functions. Convert the checkpoint first — "
            "accelerate_tpu.from_hf(hf_model) maps supported transformers "
            "architectures to the model zoo (see models/convert.py)."
        )
    try:
        import flax.linen as nn

        if isinstance(model, nn.Module):
            return FlaxLinenAdapter(model)
    except ImportError:
        pass
    if callable(getattr(model, "init", None)) and callable(getattr(model, "apply", None)):
        return FunctionalModel(model.init, model.apply)
    raise TypeError(
        f"Cannot prepare model of type {type(model)}: expected an accelerate_tpu.Module, "
        "a flax.linen.Module, or an object with init/apply."
    )


def default_loss_extractor(outputs, batch):
    """Pull the scalar loss out of a forward result (HF convention)."""
    if isinstance(outputs, Mapping) and "loss" in outputs:
        return outputs["loss"]
    if hasattr(outputs, "loss"):
        return outputs.loss
    if isinstance(outputs, jax.Array) and outputs.ndim == 0:
        return outputs
    raise ValueError(
        "Could not extract a loss from the model outputs. Either return an output "
        "with a `loss` field (pass labels in the batch), or register a custom loss "
        "with `accelerator.set_loss_fn(lambda outputs, batch: ...)`."
    )
