"""Checkpoint save/load — sharded arrays, reference folder layout.

Reference parity: ``src/accelerate/checkpointing.py`` (:61-177 save, :179-311 load)
and the ``Accelerator.save_state/load_state`` drivers (``accelerator.py:3260/3426``)
with automatic ``checkpoints/checkpoint_<i>`` naming and ``total_limit`` rotation
(:3301-3323). Same folder layout and file names (``utils/constants.py:20-33``
there); array payloads differ by design:

- model/optimizer state → **orbax/tensorstore sharded checkpoints**: every process
  writes exactly its own shards, no host ever gathers the full model (the property
  FSDP's SHARDED_STATE_DICT buys in ``utils/fsdp_utils.py:101-325``, here for free
  because params are global sharded arrays);
- ``save_model`` → consolidated **safetensors** export with ``max_shard_size``
  file splitting + index json, byte-compatible with the HF ecosystem
  (reference ``accelerator.py:3117-3227``);
- RNG state → the JAX key + host numpy/python streams (reference saves
  torch/cuda/xla RNG, :174).
"""

from __future__ import annotations

import atexit
import json
import os
import pickle
import random
import shutil
import time
from pathlib import Path

import numpy as np

import jax
import jax.numpy as jnp

from .logging import get_logger
from .utils.constants import (
    CHECKPOINT_DIR_PREFIX,
    MODEL_NAME,
    OPTIMIZER_NAME,
    RNG_STATE_NAME,
    SAFE_WEIGHTS_INDEX_NAME,
    SAFE_WEIGHTS_NAME,
    SAMPLER_NAME,
    SCHEDULER_NAME,
)

logger = get_logger(__name__)


_PENDING_SAVES: list = []


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _queue_save(path, tree):
    """One checkpointer per item: orbax serializes saves on a single instance
    (each .save joins the previous), so overlapping the model AND optimizer
    writes with training requires separate instances, all joined by
    :func:`finish_pending_saves`."""
    ck = _checkpointer()
    ck.save(path, tree)
    _PENDING_SAVES.append(ck)


def finish_pending_saves():
    """Block until every queued (non-blocking) checkpoint write has committed.

    Called automatically by ``load_accelerator_state`` and by every
    automatic-naming save, so a resume can never read — nor rotation delete —
    a half-written folder from this process."""
    while _PENDING_SAVES:
        ck = _PENDING_SAVES.pop()
        ck.wait_until_finished()
        ck.close()  # release the background writer thread/resources


# A script that exits right after a non-blocking save_state must not drop the
# shard writes still draining on orbax's background thread. Accelerator.
# end_training() is the polite join; this is the backstop for scripts that
# never call it (trivially reentrant: the queue is empty on the second join).
atexit.register(finish_pending_saves)


def _reap_pending(max_pending: int = 4):
    """Bound the queue of unjoined background checkpointers: a long run calling
    ``save_state(blocking=False)`` to explicit output dirs (no rotation, no
    load) would otherwise accumulate writer threads indefinitely. Joining the
    oldest is cheap once its write has committed — and if it hasn't, blocking
    here is the backpressure we want."""
    while len(_PENDING_SAVES) > max_pending:
        ck = _PENDING_SAVES.pop(0)
        ck.wait_until_finished()
        ck.close()


def _flatten_params(params, prefix=""):
    """pytree → {'a.b.c': leaf} with dot-joined paths (HF-style keys)."""
    flat = {}
    items = jax.tree_util.tree_flatten_with_path(params)[0]
    from .parallel.sharding import path_str

    for path, leaf in items:
        flat[path_str(path).replace("/", ".")] = leaf
    return flat


def save_accelerator_state(accelerator, output_dir: str | None = None, safe_serialization: bool = True,
                           blocking: bool = True):
    """Save everything (reference ``save_accelerator_state`` :61 + driver :3260).

    ``blocking=False`` queues the sharded array writes on orbax's background
    thread and returns as soon as the host-side state is down — training
    continues while HBM drains to disk (orbax snapshots the arrays at call
    time, so subsequent optimizer steps don't corrupt the checkpoint). Join
    explicitly with :func:`finish_pending_saves`; ``load_accelerator_state``
    joins automatically."""
    from .resilience.goodput import get_ledger

    _t_save = time.perf_counter()
    project = accelerator.project_configuration
    if output_dir is None:
        if project.automatic_checkpoint_naming:
            output_dir = os.path.join(accelerator.project_dir, "checkpoints")
        else:
            raise ValueError("output_dir required unless automatic_checkpoint_naming is set")
    output_dir = os.path.abspath(output_dir)
    _reap_pending()  # bound the background-writer queue on every save path
    if project.automatic_checkpoint_naming:
        # EVERY process joins its own queued writers and all rendezvous
        # BEFORE the rotation decision: the decision reads each process's own
        # os.listdir, and a divergent listing (non-shared dir, racing rmtree)
        # must never strand a subset of ranks in a conditional barrier. Also,
        # rmtree under any host's in-flight write would destroy the checkpoint
        # and poison that writer with a deferred ENOENT (reference rotation
        # :3301-3323).
        finish_pending_saves()
        accelerator.wait_for_everyone()
        if project.total_limit is not None and accelerator.is_main_process:
            folders = [
                f for f in (os.listdir(output_dir) if os.path.isdir(output_dir) else [])
                if f.startswith(f"{CHECKPOINT_DIR_PREFIX}_")
            ]
            # Incomplete folders (crashed mid-save) are junk regardless of the
            # limit — drop them first so rotation never counts them against
            # (and deletes) the complete checkpoints the resume fallback needs.
            complete = []
            for f in folders:
                if _checkpoint_complete(os.path.join(output_dir, f), accelerator):
                    complete.append(f)
                else:
                    logger.warning(f"Rotating out incomplete checkpoint {f}")
                    shutil.rmtree(os.path.join(output_dir, f), ignore_errors=True)
            if len(complete) + 1 > project.total_limit:
                complete.sort(key=lambda f: int(f.rsplit("_", 1)[-1]))
                for stale in complete[: len(complete) + 1 - project.total_limit]:
                    shutil.rmtree(os.path.join(output_dir, stale), ignore_errors=True)
        output_dir = os.path.join(output_dir, f"{CHECKPOINT_DIR_PREFIX}_{project.iteration}")
        if os.path.isdir(output_dir):
            raise ValueError(f"Checkpoint directory {output_dir} already exists.")
    accelerator.wait_for_everyone()
    if accelerator.is_main_process:
        os.makedirs(output_dir, exist_ok=True)
    accelerator.wait_for_everyone()

    # Sharded model params, one dir per model.
    expected_items = []
    for i, model in enumerate(accelerator._models):
        suffix = "" if i == 0 else f"_{i}"
        _queue_save(os.path.join(output_dir, f"{MODEL_NAME}{suffix}"), model.handle.params)
        expected_items.append(f"{MODEL_NAME}{suffix}")
    # Sharded optimizer state.
    for i, opt in enumerate(accelerator._optimizers):
        suffix = "" if i == 0 else f"_{i}"
        if hasattr(opt, "_resolve_pending_finite"):
            opt._resolve_pending_finite()  # step_count/scale must be final on disk
        if opt.opt_state is not None:
            _queue_save(os.path.join(output_dir, f"{OPTIMIZER_NAME}{suffix}"), opt.opt_state)
            expected_items.append(f"{OPTIMIZER_NAME}{suffix}")
        _host_pickle(
            os.path.join(output_dir, f"{OPTIMIZER_NAME}{suffix}.meta.pkl"),
            {"step_count": opt._step_count, "scale": opt.scaler.scale if opt.scaler else None},
            accelerator,
        )
    # Manifest of queued orbax items: each commits atomically (tmp-dir rename),
    # so on load "every listed dir exists and no tmp litter" == "all array
    # writes from this save committed" — even for saves queued non-blocking.
    # The mesh record makes the checkpoint PORTABLE across world sizes:
    # load_accelerator_state compares it with the live mesh and demands an
    # explicit reshard=True (or elastic resume) on mismatch instead of
    # surfacing an opaque XLA sharding failure mid-restore.
    _host_pickle_json(
        os.path.join(output_dir, "manifest.json"),
        {"items": expected_items, "mesh": _mesh_record(accelerator)},
        accelerator,
    )
    if blocking:
        finish_pending_saves()
    # Schedulers / samplers / dataloaders / custom objects: host-side pickles.
    for i, sched in enumerate(accelerator._schedulers):
        suffix = "" if i == 0 else f"_{i}"
        _host_pickle(os.path.join(output_dir, f"{SCHEDULER_NAME}{suffix}.bin"), sched.state_dict(), accelerator)
    for i, dl in enumerate(accelerator._dataloaders):
        suffix = "" if i == 0 else f"_{i}"
        if hasattr(dl, "state_dict"):
            _host_pickle(os.path.join(output_dir, f"{SAMPLER_NAME}{suffix}.bin"), dl.state_dict(), accelerator)
    for i, obj in enumerate(accelerator._custom_objects):
        _host_pickle(os.path.join(output_dir, f"custom_checkpoint_{i}.pkl"), obj.state_dict(), accelerator)
    # RNG streams (reference :146-177).
    rng_state = {
        "python": random.getstate(),
        "numpy": np.random.get_state(),
        "step": accelerator.step,
    }
    for i, model in enumerate(accelerator._models):
        rng_state[f"model_{i}_key_counter"] = model.handle.step_counter
    _host_pickle(os.path.join(output_dir, f"{RNG_STATE_NAME}_{accelerator.process_index}.pkl"),
                 rng_state, accelerator, all_processes=True)
    if project.automatic_checkpoint_naming:
        project.iteration += 1
    # Fault injection (resilience/faults.py): a pending partial_ckpt fault
    # turns this save into the on-disk state of one interrupted mid-write —
    # committed writes are joined first so the corruption is deterministic.
    from .resilience.faults import active_plan

    plan = active_plan()
    if plan is not None and plan._pending_partial_ckpt:
        finish_pending_saves()
        plan.maybe_corrupt_checkpoint(output_dir)
    # Host-blocked save time is checkpoint badput (goodput ledger); a
    # non-blocking save's background drain intentionally isn't counted —
    # training overlaps it, which is the point.
    get_ledger().add("ckpt_save", time.perf_counter() - _t_save)
    logger.info(f"Saved accelerator state to {output_dir}")
    return output_dir


def _host_pickle(path, obj, accelerator, all_processes: bool = False):
    if accelerator.is_main_process or all_processes:
        with open(path, "wb") as f:
            pickle.dump(obj, f)


def _host_pickle_json(path, obj, accelerator):
    if accelerator.is_main_process:
        with open(path, "w") as f:
            json.dump(obj, f)


def _mesh_record(accelerator) -> dict:
    """Mesh axis sizes, process count, and dp degree — the metadata that
    decides whether a checkpoint restores in place or needs resharding."""
    from .parallel.sharding import data_parallel_degree

    mesh = accelerator.mesh
    return {
        "axes": {name: int(size) for name, size in mesh.shape.items()},
        "process_count": int(max(jax.process_count(), 1)),
        "data_parallel": int(data_parallel_degree(mesh)),
        # Needed to restore the GLOBAL batch, not just the arrays: a fresh
        # process relaunched at a different size rescales accumulation from
        # this absolute record (save-time accum x save-time dp is the
        # samples-per-update invariant).
        "gradient_accumulation_steps": int(accelerator.gradient_accumulation_steps),
        # Informational (not part of the compatibility comparison): restore
        # is layout-agnostic either way — each array lands host-sharded on
        # the LIVE optimizer plan, so a ZeRO-on checkpoint restores into a
        # ZeRO-off process and vice versa without resharding ceremony.
        "zero_sharding": bool(
            any(getattr(o, "zero_active", False) for o in accelerator._optimizers)
        ),
    }


def _check_mesh_compatible(input_dir: str, accelerator, reshard: bool):
    """Compare the checkpoint's mesh record (manifest.json) with the live
    mesh. Silent when they match or the checkpoint predates the record;
    pointed error on mismatch unless ``reshard=True`` opted in."""
    manifest_path = os.path.join(input_dir, "manifest.json")
    if not os.path.isfile(manifest_path):
        return
    try:
        with open(manifest_path) as f:
            saved = json.load(f).get("mesh")
    except (OSError, ValueError):
        return
    if not saved:
        return  # pre-metadata checkpoint: nothing to compare
    current = _mesh_record(accelerator)
    if saved["axes"] == current["axes"] and saved.get("process_count") == current["process_count"]:
        return
    if reshard:
        _rescale_accumulation(accelerator, saved, current)
        logger.warning(
            f"Resharding checkpoint {os.path.basename(input_dir)}: written under "
            f"mesh {saved['axes']}, restoring onto {current['axes']} (host-sharded "
            "read + device_put onto the target shardings; no full-replication "
            "spike)."
        )
        return
    raise RuntimeError(
        f"Checkpoint {input_dir} was written under mesh {saved['axes']} "
        f"({saved.get('process_count', '?')} process(es), "
        f"dp={saved.get('data_parallel', '?')}) but the current mesh is "
        f"{current['axes']} ({current['process_count']} process(es), "
        f"dp={current['data_parallel']}): resharding is required. Pass "
        "load_state(..., reshard=True) to redistribute the arrays onto "
        "the current layout, or resume through "
        "run_resilient(elastic=True) which does so automatically."
    )


def _rescale_accumulation(accelerator, saved: dict, current: dict):
    """Hold samples_per_update = per_device_batch x dp x accum invariant
    across a cross-mesh restore. The record is ABSOLUTE (save-time accum and
    dp), so the rescale is idempotent: the in-process elastic path — where
    ``reshard_accelerator`` already rescaled the live value — lands on the
    same number, and a FRESH process relaunched at a different size (which
    never saw a ``WorldSizeChange``) gets the contract applied here."""
    from .resilience.elastic import rescaled_accumulation

    saved_dp = saved.get("data_parallel")
    saved_accum = saved.get("gradient_accumulation_steps")
    if not saved_dp or not saved_accum:
        return  # pre-record checkpoint: nothing to hold invariant against
    new_accum = rescaled_accumulation(
        saved_accum, saved_dp, current["data_parallel"], context="Cross-mesh restore"
    )
    if new_accum != accelerator.gradient_accumulation_steps:
        logger.warning(
            f"Cross-mesh restore: gradient accumulation "
            f"{accelerator.gradient_accumulation_steps} -> {new_accum} "
            f"(save-time {saved_accum} x dp {saved_dp} / dp "
            f"{current['data_parallel']}; global batch preserved)."
        )
        accelerator.gradient_accumulation_steps = new_accum


def _checkpoint_complete(path: str, accelerator) -> bool:
    """Did this checkpoint folder's array writes commit?

    Orbax commits each item atomically (tmp-suffixed dir renamed on commit), so
    an interrupted non-blocking save leaves ``*.orbax-checkpoint-tmp*`` litter
    and/or missing item dirs while the host-side pickles already exist. The
    save-time ``manifest.json`` lists every queued item (model AND optimizer
    state — a missing optimizer item would otherwise resume with silently
    reinitialized moments); pre-manifest checkpoints fall back to checking the
    model item dirs."""
    try:
        entries = os.listdir(path)
    except OSError:
        return False
    if any(".orbax-checkpoint-tmp" in e for e in entries):
        return False
    manifest_path = os.path.join(path, "manifest.json")
    if os.path.isfile(manifest_path):
        try:
            with open(manifest_path) as f:
                items = json.load(f)["items"]
        except (OSError, ValueError, KeyError):
            return False
        return all(os.path.isdir(os.path.join(path, item)) for item in items)
    for i, _ in enumerate(accelerator._models):
        suffix = "" if i == 0 else f"_{i}"
        if not os.path.isdir(os.path.join(path, f"{MODEL_NAME}{suffix}")):
            return False
    return True


def load_accelerator_state(accelerator, input_dir: str | None = None,
                           reshard: bool = False, **kwargs):
    """Reference ``load_accelerator_state`` :179 + driver :3426.

    ``reshard=True`` accepts a checkpoint written under a DIFFERENT mesh
    (axis sizes / process count) and restores it onto the live layout: every
    array is read host-sharded by orbax/tensorstore against the abstract
    target (each process fetches only the index ranges its new shards need)
    and lands directly on the current ``NamedSharding`` — no host ever
    materializes the full array and there is no replication spike. Without
    it, a mesh mismatch raises a pointed error up front instead of an opaque
    XLA sharding failure mid-restore."""
    from .resilience.goodput import get_ledger

    _t_load = time.perf_counter()
    finish_pending_saves()  # never resume from a checkpoint still being written
    project = accelerator.project_configuration
    if input_dir is None:
        if not project.automatic_checkpoint_naming:
            raise ValueError("input_dir required unless automatic_checkpoint_naming is set")
        base = os.path.join(accelerator.project_dir, "checkpoints")
        folders = sorted(
            (f for f in os.listdir(base) if f.startswith(f"{CHECKPOINT_DIR_PREFIX}_")),
            key=lambda f: int(f.rsplit("_", 1)[-1]),
        )
        # Newest complete folder wins: a crash mid non-blocking save leaves the
        # newest checkpoint_N partially written — fall back rather than fail.
        incomplete = []
        for f in reversed(folders):
            candidate = os.path.join(base, f)
            if _checkpoint_complete(candidate, accelerator):
                input_dir = candidate
                break
            logger.warning(f"Skipping incomplete checkpoint {candidate}")
            incomplete.append(candidate)
        # Align the auto-naming state with what's actually on disk: incomplete
        # folders can never be resumed — delete the litter — and the next save
        # must target the index after the resumed folder (or 0 when nothing
        # survived), or a restarted process (iteration reset to 0) collides
        # with leftover folders on its first save and crash-loops.
        if accelerator.is_main_process:
            for junk in incomplete:
                shutil.rmtree(junk, ignore_errors=True)
        accelerator.wait_for_everyone()
        if input_dir is None:
            # Nothing resumable, but the litter is gone and the naming state
            # aligned: the caller can start fresh and save safely.
            project.iteration = 0
            raise FileNotFoundError(f"No complete checkpoint found under {base}")
        project.iteration = int(os.path.basename(input_dir).rsplit("_", 1)[-1]) + 1
    input_dir = os.path.abspath(input_dir)
    _check_mesh_compatible(input_dir, accelerator, reshard)

    ckptr = _checkpointer()
    for i, model in enumerate(accelerator._models):
        suffix = "" if i == 0 else f"_{i}"
        abstract = jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype, sharding=p.sharding),
            model.handle.params,
        )
        model.handle.params = ckptr.restore(os.path.join(input_dir, f"{MODEL_NAME}{suffix}"), abstract)
    for i, opt in enumerate(accelerator._optimizers):
        suffix = "" if i == 0 else f"_{i}"
        opt_dir = os.path.join(input_dir, f"{OPTIMIZER_NAME}{suffix}")
        if os.path.isdir(opt_dir):
            opt._ensure_initialized()
            abstract = jax.tree_util.tree_map(
                lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype, sharding=p.sharding),
                opt.opt_state,
            )
            opt.opt_state = ckptr.restore(opt_dir, abstract)
        meta_path = os.path.join(input_dir, f"{OPTIMIZER_NAME}{suffix}.meta.pkl")
        if os.path.isfile(meta_path):
            with open(meta_path, "rb") as f:
                meta = pickle.load(f)
            opt._step_count = meta.get("step_count", 0)
            if opt.scaler is not None and meta.get("scale"):
                opt.scaler.scale = meta["scale"]
    for i, sched in enumerate(accelerator._schedulers):
        suffix = "" if i == 0 else f"_{i}"
        path = os.path.join(input_dir, f"{SCHEDULER_NAME}{suffix}.bin")
        if os.path.isfile(path):
            with open(path, "rb") as f:
                sched.load_state_dict(pickle.load(f))
    for i, dl in enumerate(accelerator._dataloaders):
        suffix = "" if i == 0 else f"_{i}"
        path = os.path.join(input_dir, f"{SAMPLER_NAME}{suffix}.bin")
        if os.path.isfile(path) and hasattr(dl, "load_state_dict"):
            with open(path, "rb") as f:
                dl.load_state_dict(pickle.load(f))
    for i, obj in enumerate(accelerator._custom_objects):
        path = os.path.join(input_dir, f"custom_checkpoint_{i}.pkl")
        if os.path.isfile(path):
            with open(path, "rb") as f:
                obj.load_state_dict(pickle.load(f))
    rng_path = os.path.join(input_dir, f"{RNG_STATE_NAME}_{accelerator.process_index}.pkl")
    if not os.path.isfile(rng_path) and reshard:
        # A grown gang has ranks the old world never had: fall back to rank
        # 0's streams (identical across ranks at save time for the JAX key
        # counters; host RNG divergence only affects host-side draws).
        fallback = os.path.join(input_dir, f"{RNG_STATE_NAME}_0.pkl")
        if os.path.isfile(fallback):
            logger.warning(
                f"No RNG state for process {accelerator.process_index} in "
                f"{input_dir} (written by a smaller world); restoring rank 0's."
            )
            rng_path = fallback
    if os.path.isfile(rng_path):
        with open(rng_path, "rb") as f:
            rng_state = pickle.load(f)
        random.setstate(rng_state["python"])
        np.random.set_state(rng_state["numpy"])
        accelerator.step = rng_state.get("step", 0)
        for i, model in enumerate(accelerator._models):
            if f"model_{i}_key_counter" in rng_state:
                model.handle.step_counter = rng_state[f"model_{i}_key_counter"]
    get_ledger().add("ckpt_restore", time.perf_counter() - _t_load)
    logger.info(f"Loaded accelerator state from {input_dir}")
    return input_dir


# ------------------------------------------------------------- model exports
def parse_shard_size(max_shard_size) -> int:
    if isinstance(max_shard_size, int):
        return max_shard_size
    units = {"KB": 10**3, "MB": 10**6, "GB": 10**9, "KIB": 2**10, "MIB": 2**20, "GIB": 2**30}
    s = str(max_shard_size).upper().replace(" ", "")
    for unit, mult in units.items():
        if s.endswith(unit):
            return int(float(s[: -len(unit)]) * mult)
    return int(s)


def save_model(accelerator, model, save_directory, max_shard_size="10GB", safe_serialization=True):
    """Consolidated safetensors export with HF-compatible sharding/index
    (reference ``save_model`` :3117-3227)."""
    params = accelerator.get_state_dict(model)  # host numpy tree
    if not accelerator.is_main_process:
        # Symmetric with the main-rank barrier below: every rank reaches
        # wait_for_everyone exactly once, on complementary arms.
        accelerator.wait_for_everyone()  # accelerate-lint: disable=rank-divergent-collective
        return
    export_full_weights(params, save_directory, max_shard_size=max_shard_size,
                        safe_serialization=safe_serialization)
    # The main-rank half of the same symmetric fence (see the guard above).
    accelerator.wait_for_everyone()  # accelerate-lint: disable=rank-divergent-collective


def export_full_weights(params, save_directory, max_shard_size="10GB", safe_serialization=True):
    """Write a consolidated weight export from a (host) param tree — the shared
    engine behind ``save_model`` and `accelerate-tpu merge-weights` (reference
    ``merge_fsdp_weights`` fsdp_utils.py:354-407)."""
    os.makedirs(save_directory, exist_ok=True)
    flat = _flatten_params(params)
    if not safe_serialization:
        from flax import serialization

        from .utils.constants import WEIGHTS_NAME

        with open(os.path.join(save_directory, WEIGHTS_NAME), "wb") as f:
            f.write(serialization.msgpack_serialize({k: np.asarray(v) for k, v in flat.items()}))
        return
    limit = parse_shard_size(max_shard_size)
    shards, current, size = [], {}, 0
    for key, val in flat.items():
        nbytes = np.asarray(val).nbytes
        if current and size + nbytes > limit:
            shards.append(current)
            current, size = {}, 0
        current[key] = np.ascontiguousarray(val)
        size += nbytes
    if current:
        shards.append(current)

    from safetensors.numpy import save_file

    if len(shards) == 1:
        save_file(shards[0], os.path.join(save_directory, SAFE_WEIGHTS_NAME))
    else:
        index = {"metadata": {"total_size": sum(np.asarray(v).nbytes for v in flat.values())}, "weight_map": {}}
        for i, shard in enumerate(shards):
            name = SAFE_WEIGHTS_NAME.replace(".safetensors", f"-{i + 1:05d}-of-{len(shards):05d}.safetensors")
            save_file(shard, os.path.join(save_directory, name))
            for key in shard:
                index["weight_map"][key] = name
        with open(os.path.join(save_directory, SAFE_WEIGHTS_INDEX_NAME), "w") as f:
            json.dump(index, f, indent=2)


def load_model_weights(save_directory, template_params):
    """Inverse of ``save_model``: read (possibly sharded) safetensors back into the
    structure of ``template_params``."""
    from safetensors.numpy import load_file

    save_directory = Path(save_directory)
    flat = {}
    index_file = save_directory / SAFE_WEIGHTS_INDEX_NAME
    if index_file.is_file():
        index = json.loads(index_file.read_text())
        for name in sorted(set(index["weight_map"].values())):
            flat.update(load_file(save_directory / name))
    elif (save_directory / SAFE_WEIGHTS_NAME).is_file():
        flat.update(load_file(save_directory / SAFE_WEIGHTS_NAME))
    else:
        from flax import serialization

        from .utils.constants import WEIGHTS_NAME

        flat.update(serialization.msgpack_restore((save_directory / WEIGHTS_NAME).read_bytes()))

    from .parallel.sharding import path_str

    items = jax.tree_util.tree_flatten_with_path(template_params)
    leaves = []
    for path, leaf in items[0]:
        key = path_str(path).replace("/", ".")
        if key not in flat:
            raise KeyError(f"weight {key} missing from checkpoint {save_directory}")
        leaves.append(flat[key])
    return jax.tree_util.tree_unflatten(items[1], leaves)


def save_custom_state(obj, path, index: int = 0):
    """Reference ``save_custom_state`` :313."""
    with open(os.path.join(path, f"custom_checkpoint_{index}.pkl"), "wb") as f:
        pickle.dump(obj.state_dict(), f)


def load_custom_state(obj, path, index: int = 0):
    """Reference ``load_custom_state`` :323."""
    with open(os.path.join(path, f"custom_checkpoint_{index}.pkl"), "rb") as f:
        obj.load_state_dict(pickle.load(f))
