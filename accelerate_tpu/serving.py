"""Continuous batching — a slot-based serving engine over the KV-cache path.

The reference serves through transformers' ``generate`` one batch at a time:
a batch runs until its LAST row finishes, so short requests pay for long ones
(head-of-line blocking). ``ContinuousBatcher`` keeps a fixed number of slots
decoding together and refills a slot the moment its sequence finishes — the
scheduling idea of vLLM/Orca, shaped for XLA's static-compilation model:

- **One decode program plus one admit program per prompt-length bucket**:
  the decode step covers all B slots at once, and an admit prefills one
  slot's prompt while the others' state rides along untouched. No shape ever
  depends on which requests are in flight, so nothing recompiles as traffic
  changes.
- **One global write offset, per-slot validity** — the same trick as batched
  speculative decoding (``generation._assisted_generate_batched``): every
  cache write lands at the global offset for ALL slots and rows that didn't
  really produce a token simply mask the slot out of their ``kv_mask``.
  Attention needs only slot-causality + validity, both hole-tolerant; rope
  positions ride the separate per-row ``positions`` channel, so absolute- and
  rotary-position models are exact.
- The cost of that simplicity is cache capacity: slots consume global cache
  columns even while other rows hole them out. ``compact()`` reclaims the
  holes — a stable full-cache gather pulls each row's valid columns to the
  front, drops retired requests' columns, and rewinds the write offset —
  and runs automatically at the backpressure point, so ``max_cache_len``
  sizes to the working set of concurrently LIVE tokens, not the whole
  queue. A genuinely-too-small cache still raises an actionable error
  instead of corrupting state.

**Prefix caching** (``set_prefix``): a prompt prefix shared by every request
(system prompt, few-shot block, a long document) is prefilled ONCE into the
head of the cache and stays valid for all slots across evictions — requests
then submit only their suffixes. Prefill compute and cache columns for the
prefix are paid once per wave instead of once per request.

**Per-request generation controls** (``submit`` kwargs): each request may
carry its own ``max_new_tokens``, ``temperature``, ``eos_token_id``, and
``stop_sequences``, heterogeneously within one wave. Per-slot scalars ride the
engine state through the same compiled programs — nothing recompiles as the
mix changes. Length/temperature/eos act on-device per slot; multi-token stop
sequences are detected host-side at the sync cadence (the slot frees at most
``sync_every - 1`` steps late) and the OUTPUT is truncated exactly at the
first stop occurrence, so results never depend on cadence.

Correctness contract (pinned by tests/test_serving.py): in greedy mode each
request's output is EXACTLY ``generate(model, prompt, temperature=0)`` for
that prompt alone (with a prefix set: for ``prefix + suffix``), regardless of
how requests interleave. In sampling mode
each request draws from its own stream — ``fold_in(engine_rng, request_id)``
folded again by step index — so a request's sampled tokens depend only on
(engine rng, request id), not on traffic or slot assignment; they are
reproducible but not bit-equal to a solo ``generate()`` (whose split chain
differs).

Sliding-window models serve exactly: ``cached_attention`` measures windows in
VALID-slot distance, so the slot scheme's masked holes don't stretch the
window (ops/attention.py — on the contiguous solo cache the two distances
coincide, which is what makes engine output == solo output).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from .generation import _unwrap, left_align, mask_positions
from .utils.environment import safe_donate_argnums


def _first_stop_end(row: np.ndarray, stops: tuple) -> int | None:
    """End index (exclusive) of the earliest-ending completed stop-sequence
    occurrence in ``row``, or None. Earliest END, so a later-starting shorter
    stop that completes first wins — the order generation actually stops in."""
    best = None
    for s in stops:
        L = int(s.size)
        if L > row.size:
            continue
        win = np.lib.stride_tricks.sliding_window_view(row, L)
        hits = np.nonzero((win == s).all(axis=1))[0]
        if hits.size:
            end = int(hits[0]) + L
            if best is None or end < best:
                best = end
    return best


@dataclass
class _Request:
    rid: int
    prompt: np.ndarray  # (P,) real tokens, no padding
    max_new: int
    temperature: float
    eos: int  # -1 = none
    stop: tuple  # tuple of np.int32 arrays; () = none


class ContinuousBatcher:
    """Slot-based continuous batching over a decoder-only cached model.

    Usage::

        engine = ContinuousBatcher(model, batch_slots=4, max_new_tokens=64,
                                   max_cache_len=4096, eos_token_id=eos)
        ids = [engine.submit(p) for p in prompts]       # any ragged lengths
        outputs = engine.run()                           # {rid: np.ndarray}

    ``run()`` drives admits + decode steps until every submitted request has
    finished; ``submit`` may be called again afterwards (slots and the cache
    are re-usable until ``max_cache_len`` is exhausted; ``reset()`` reclaims
    everything).
    """

    def __init__(
        self,
        model,
        *,
        batch_slots: int,
        max_new_tokens: int,
        max_cache_len: int,
        params=None,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        rng=None,
        eos_token_id: int | None = None,
        pad_token_id: int = 0,
        cache_dtype=jnp.bfloat16,
        bucket_sizes: tuple = (16, 32, 64, 128, 256, 512, 1024),
        sync_every: int = 8,
    ):
        module, mparams = _unwrap(model)
        self.module = module
        self.params = params if params is not None else mparams
        if self.params is None:
            raise ValueError("Model has no params; pass params= or init the model first.")
        if hasattr(module, "encode"):
            raise ValueError("ContinuousBatcher supports decoder-only cached models.")
        self.B = batch_slots
        self.max_new = max_new_tokens
        self.C = max_cache_len
        self.temperature = temperature
        self.top_k, self.top_p = top_k, top_p
        self.eos = -1 if eos_token_id is None else eos_token_id
        self.pad = pad_token_id
        self.cache_dtype = cache_dtype
        self.buckets = tuple(sorted(bucket_sizes))
        if sync_every < 1:
            raise ValueError(f"sync_every must be >= 1, got {sync_every}")
        # How many decode steps to enqueue between host checks. The host
        # round-trip (detecting finished slots) is the serving loop's only
        # sync; batching K steps per check amortizes it — finished slots idle
        # at most K-1 extra steps and the cache consumes at most K-1 extra
        # columns per wave, both accounted for in the capacity reservation.
        self.sync_every = sync_every
        self._rng = rng if rng is not None else jax.random.key(0)
        self._queue: deque[_Request] = deque()
        self._next_rid = 0
        self._results: dict[int, np.ndarray] = {}
        self._admit_fns: dict[tuple, object] = {}
        self._prefix_fns: dict[int, object] = {}
        self._decode_fn = None
        self._compact_fn = None
        # Compaction reclaims columns only when something RETIRED since the
        # last compact (retirement is what creates dead columns); keying the
        # auto-trigger on this flag — not on position movement — keeps
        # sustained backpressure from re-gathering the cache every window.
        self._retired_since_compact = False
        self._prefix_tokens: np.ndarray | None = None
        self.reset()

    # ------------------------------------------------------------- lifecycle
    def reset(self, keep_prefix: bool = True):
        """Fresh cache and slot state. Queued (not-yet-admitted) requests and
        already-finished results survive; in-flight slots are wiped — the
        capacity-error path re-queues them first, so catch + ``reset()`` +
        ``run()`` retries everything. A shared prefix (``set_prefix``) is
        re-prefilled automatically so the retry flow stays exact; pass
        ``keep_prefix=False`` to drop it."""
        B = self.B
        self._cache = self.module.init_cache(B, self.C, dtype=self.cache_dtype)
        self._tok = jnp.full((B,), self.pad, jnp.int32)
        self._pos = jnp.zeros((B,), jnp.int32)  # next rope position per slot
        self._n_out = jnp.zeros((B,), jnp.int32)
        self._active = jnp.zeros((B,), bool)
        self._out_buf = jnp.full((B, self.max_new), self.pad, jnp.int32)
        self._keys = jnp.broadcast_to(self._rng, (B,))
        # Per-slot generation controls (heterogeneous per request; traced
        # values, so the compiled programs are shared across any mix).
        self._slot_max = jnp.full((B,), self.max_new, jnp.int32)
        self._slot_temp = jnp.full((B,), float(self.temperature or 0.0), jnp.float32)
        self._slot_eos = jnp.full((B,), self.eos, jnp.int32)
        self._slot_req: list[_Request | None] = [None] * B
        # Host-side mirror of cache["pos"]: it advances deterministically
        # (+bucket per admit, +sync_every per decode window; compact() rewinds
        # it from the one readback it already pays), so capacity checks never
        # need a device readback.
        self._host_pos = 0
        self._retired_since_compact = False
        # Shared-prefix state: number of leading cache columns holding the
        # common prefix (valid for every slot, never evicted).
        self._pfx = 0
        if keep_prefix and self._prefix_tokens is not None:
            tokens, self._prefix_tokens = self._prefix_tokens, None
            self.set_prefix(tokens)
        elif not keep_prefix:
            self._prefix_tokens = None

    def set_prefix(self, prefix_ids) -> int:
        """Shared-prefix caching: prefill ONE copy of a prompt prefix common to
        every request (a system prompt, few-shot examples, a long document)
        into the head of the cache, valid for all slots. Subsequent
        ``submit()`` calls pass only each request's *suffix*; outputs are
        exactly ``generate(model, prefix + suffix)`` per request (pinned by
        tests/test_serving.py). The prefix occupies its length ONCE instead of
        once per admitted request — the capacity (and prefill-compute) win of
        vLLM-style prompt caching, shaped for the static slot scheme: prefix
        columns sit below every admit's write offset, so slot-causal attention
        sees them and eviction never touches them.

        Must be called on a fresh cache (right after construction or
        ``reset()``); ``reset()`` re-prefills it automatically so the
        capacity-retry flow stays exact. Returns the prefix length."""
        prefix = np.asarray(prefix_ids, np.int32).reshape(-1)
        if prefix.size == 0:
            raise ValueError("empty prefix")
        if self._host_pos != 0 or any(r is not None for r in self._slot_req):
            raise RuntimeError(
                "set_prefix needs a fresh cache (no admitted requests, no "
                "prior prefix): call reset(keep_prefix=False) first."
            )
        P = int(prefix.size)
        if P + self.buckets[0] + self.max_new + self.sync_every - 1 > self.C:
            raise ValueError(
                f"prefix length {P} leaves no room for even one "
                f"smallest-bucket request within max_cache_len={self.C}"
            )
        if P not in self._prefix_fns:
            module = self.module
            cache_dtype = self.cache_dtype

            def fill(params, cache, ids):
                # Prefill ONE row against a throwaway batch-1 cache of exactly
                # the prefix length, then broadcast the resulting KV columns
                # into every slot's row — identical state to a B-row prefill
                # at 1/B the FLOPs (the rows would be bitwise copies).
                mask = jnp.ones(ids.shape, jnp.int32)
                small = module.init_cache(1, P, dtype=cache_dtype)
                out = module.apply(params, input_ids=ids, attention_mask=mask,
                                   cache=small, positions=mask_positions(mask))
                sk, sv = out["cache"]["k"], out["cache"]["v"]
                B = cache["kv_mask"].shape[0]
                wide = lambda t: jnp.broadcast_to(t, (t.shape[0], B) + t.shape[2:])
                return {
                    **cache,
                    "k": cache["k"].at[:, :, :P].set(wide(sk)),
                    "v": cache["v"].at[:, :, :P].set(wide(sv)),
                    "pos": cache["pos"] + P,
                    "kv_mask": cache["kv_mask"].at[:, :P].set(1),
                }

            self._prefix_fns[P] = jax.jit(fill, donate_argnums=safe_donate_argnums((1,)))
        self._cache = self._prefix_fns[P](self.params, self._cache,
                                          jnp.asarray(prefix)[None])
        self._host_pos = P
        self._pfx = P
        self._prefix_tokens = prefix
        return P

    @property
    def cache_columns_used(self) -> int:
        """Global cache columns consumed so far this wave (prefix + admits +
        decode windows, out of ``max_cache_len``) — the capacity a ``reset()``
        reclaims. Public mirror of the engine's host-side position counter."""
        return self._host_pos

    def compact(self) -> int:
        """Reclaim holed cache columns: gather each row's VALID slots to the
        front (stable, so relative order is preserved) and rewind the global
        write offset to the longest row's valid count. Returns the number of
        columns freed.

        Why this is exact (pinned by tests): rope/wpe rotations are baked
        into K at write time and ride the gather unchanged; causal masking
        needs only slot ORDER (every valid key lands below the new write
        offset); sliding windows measure valid-slot distance, which a
        permutation of holes cannot change; and the shared prefix — valid in
        every row, first in every row's order — keeps columns [0, pfx).
        In-flight slot state (rope positions, output buffers) is untouched.

        Cost: one full-cache gather (O(L·B·C·H·D) bytes), so it runs when
        capacity pressure makes the alternative a dead-end — ``run()``
        triggers it automatically on backpressure — or explicitly between
        waves. This is the compaction step the r5 utilization measurement
        motivated (PERF.md): a wave of heterogeneous lengths reclaims the
        ~90% of consumed area that holes occupy instead of requiring
        ``reset()``."""
        if self._host_pos == 0:
            return 0
        if self._compact_fn is None:
            def run(cache, dead, pfx):
                km = cache["kv_mask"]
                # A retired request's columns stay valid until its slot is
                # re-admitted (eviction is lazy); compaction is exactly when
                # they die — their output is already collected. Prefix
                # columns survive (valid for every future occupant).
                col = jnp.arange(km.shape[1])[None]
                km = jnp.where(dead[:, None] & (col >= pfx), 0, km)
                # Stable argsort of (1 - valid): valid slots first, in order.
                perm = jnp.argsort(1 - km, axis=1, stable=True)  # (B, C)
                pk = perm[None, :, :, None, None]
                return {
                    "k": jnp.take_along_axis(cache["k"], pk, axis=2),
                    "v": jnp.take_along_axis(cache["v"], pk, axis=2),
                    "kv_mask": jnp.take_along_axis(km, perm, axis=1),
                    "pos": jnp.max(jnp.sum(km, axis=1)).astype(cache["pos"].dtype),
                }

            self._compact_fn = jax.jit(run, donate_argnums=safe_donate_argnums((0,)))
        dead = jnp.asarray([r is None for r in self._slot_req])
        self._cache = self._compact_fn(self._cache, dead, jnp.int32(self._pfx))
        new_pos = int(self._cache["pos"])
        freed = self._host_pos - new_pos
        self._host_pos = new_pos
        self._retired_since_compact = False
        return freed

    @property
    def cache_utilization(self) -> float:
        """Fraction of the consumed cache area (B rows × ``cache_columns_used``
        columns) whose slots are valid for their row — the engine's capacity
        honesty metric. Holes from eviction, retired requests, and
        inactive-row decode writes all count against it, so under
        heterogeneous lengths this decays across a wave until ``compact()``
        (auto-triggered at backpressure, or explicit) reclaims the holes;
        the r5 measured decay that motivated compaction is recorded in
        PERF.md."""
        if self._host_pos == 0:
            return 1.0
        km = np.asarray(jax.device_get(self._cache["kv_mask"]))[:, : self._host_pos]
        return float(km.mean())

    def submit(
        self,
        prompt_ids,
        *,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        eos_token_id: int | None = None,
        stop_sequences=None,
    ) -> int:
        """Queue one prompt (1-D array of token ids). Returns a request id.

        Per-request overrides (engine defaults when omitted):
        ``max_new_tokens`` (must be <= the engine's, which sizes the output
        buffer), ``temperature`` (0 = greedy; rows mix freely within one
        wave), ``eos_token_id``, and ``stop_sequences`` — an iterable of
        token-id sequences; generation stops at the first completed
        occurrence, which is INCLUDED in the returned ids (like eos). Stop
        detection runs host-side at the sync cadence, but the returned output
        is truncated at the exact first occurrence, so results are
        cadence-independent."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if prompt.size > self.buckets[-1]:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the largest bucket "
                f"{self.buckets[-1]}; raise bucket_sizes."
            )
        max_new = self.max_new if max_new_tokens is None else int(max_new_tokens)
        if not (1 <= max_new <= self.max_new):
            raise ValueError(
                f"per-request max_new_tokens must be in [1, {self.max_new}] "
                f"(the engine's max_new_tokens sizes the output buffer), got {max_new}"
            )
        temp = float(self.temperature or 0.0) if temperature is None else float(temperature)
        eos = self.eos if eos_token_id is None else int(eos_token_id)
        stop = ()
        if stop_sequences:
            stop = tuple(np.asarray(s, np.int32).reshape(-1) for s in stop_sequences)
            if any(s.size == 0 for s in stop):
                raise ValueError("empty stop sequence")
        rid = self._next_rid
        self._next_rid += 1
        self._queue.append(_Request(rid, prompt, max_new, temp, eos, stop))
        return rid

    # ------------------------------------------------------------- sampling
    def _sample_rows(self, logits, keys, step_idx, temps):
        """Per-row draw from per-request streams: row r's key folded by its
        own step index — sampled tokens depend only on (engine rng, request
        id, step), never on traffic or slot assignment. ``temps`` (B,) is the
        per-request temperature; 0 rows take the raw argmax (exact greedy),
        so greedy and sampled requests mix inside one compiled program."""
        from .generation import _warp_scores

        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # Per-row temperature is a traced value, so _warp_scores' scalar
        # temperature short-circuit can't apply — divide by a safe temp here,
        # then reuse _warp_scores (at T=1) for the top-k/top-p chain so the
        # masking semantics can never diverge from generate()'s. top_k/top_p
        # stay engine-global (static).
        safe_t = jnp.where(temps > 0.0, temps, 1.0)
        scores = _warp_scores(logits.astype(jnp.float32) / safe_t[:, None],
                              1.0, self.top_k, self.top_p)

        def one(lg, k, n):
            return jax.random.categorical(jax.random.fold_in(k, n), lg).astype(jnp.int32)

        sampled = jax.vmap(one)(scores, keys, step_idx)
        return jnp.where(temps > 0.0, sampled, greedy)

    # ------------------------------------------------------------- compiled
    def _admit_fn(self, P: int):
        """Compiled prefill of ONE slot's prompt (bucket length P): the whole
        (B, P) chunk runs so shapes stay request-independent; rows other than
        the target slot carry a zero attention mask, so their kv_mask stays
        invalid for the written block automatically. Keyed on (P, prefix
        columns): with a shared prefix, eviction spares the prefix region and
        token positions start past the prefix."""
        pfx = self._pfx
        if (P, pfx) in self._admit_fns:
            return self._admit_fns[(P, pfx)]
        module = self.module
        pad = self.pad

        def run(params, cache, state, slot, prompt_row, mask_row, rid, base_rng,
                req_max, req_temp, req_eos):
            (tok, pos, n_out, active, out_buf, keys,
             slot_max, slot_temp, slot_eos) = state
            B = tok.shape[0]
            # evict the slot's previous occupant: its KV must stop being
            # attendable before the new prompt writes into the same row —
            # but the shared-prefix columns stay valid for every occupant
            cache = {**cache, "kv_mask": cache["kv_mask"].at[slot, pfx:].set(0)}
            ids = jnp.zeros((B, P), jnp.int32).at[slot].set(prompt_row)
            mask = jnp.zeros((B, P), jnp.int32).at[slot].set(mask_row)
            out = module.apply(params, input_ids=ids, attention_mask=mask,
                               cache=cache, positions=mask_positions(mask) + pfx)
            real_len = jnp.sum(mask_row).astype(jnp.int32) + pfx
            key = jax.random.fold_in(base_rng, rid)  # the request's own stream
            keys = keys.at[slot].set(key)
            slot_max = slot_max.at[slot].set(req_max)
            slot_temp = slot_temp.at[slot].set(req_temp)
            slot_eos = slot_eos.at[slot].set(req_eos)
            first = self._sample_rows(
                out["logits"][slot, -1][None], key[None],
                jnp.zeros((1,), jnp.int32), req_temp[None],
            )[0]
            tok = tok.at[slot].set(first)
            pos = pos.at[slot].set(real_len)
            n_out = n_out.at[slot].set(1)
            # even an immediate eos is emitted (HF convention); the slot stays
            # active only if there is room and the first token wasn't eos
            out_buf = out_buf.at[slot].set(jnp.full((self.max_new,), pad, jnp.int32))
            out_buf = out_buf.at[slot, 0].set(first)
            done0 = (first == req_eos) | (req_max <= 1)
            active = active.at[slot].set(~done0)
            state = (tok, pos, n_out, active, out_buf, keys,
                     slot_max, slot_temp, slot_eos)
            return out["cache"], state, done0

        fn = jax.jit(run, donate_argnums=(1, 2))
        self._admit_fns[(P, pfx)] = fn
        return fn

    def _decode(self):
        """Compiled ``sync_every``-token window for all B slots — ONE program
        dispatch per host check (a ``lax.scan`` over steps), so neither local
        dispatch overhead nor a remote tunnel's per-call RTT is paid per
        token. Inactive rows feed pads and their freshly written cache
        columns are invalidated."""
        if self._decode_fn is not None:
            return self._decode_fn
        module = self.module
        pad = self.pad

        def run(params, cache, state):
            def one_step(carry, _):
                cache, state = carry
                (tok, pos, n_out, active, out_buf, keys,
                 slot_max, slot_temp, slot_eos) = state
                B = tok.shape[0]
                col = cache["pos"]  # global slot this step writes
                feed = jnp.where(active, tok, pad)
                out = module.apply(params, input_ids=feed[:, None], cache=cache,
                                   positions=pos[:, None])
                nxt = self._sample_rows(out["logits"][:, -1], keys, n_out, slot_temp)
                nxt = jnp.where(active, nxt, pad)
                cache2 = out["cache"]
                # hole out the column for rows that didn't produce a token
                cache2 = {
                    **cache2,
                    "kv_mask": cache2["kv_mask"].at[:, col].set(
                        jnp.where(active, cache2["kv_mask"][:, col], 0)
                    ),
                }
                emit_idx = jnp.clip(n_out, 0, self.max_new - 1)
                cur = out_buf[jnp.arange(B), emit_idx]
                out_buf = out_buf.at[jnp.arange(B), emit_idx].set(
                    jnp.where(active, nxt, cur)
                )
                n_out = n_out + active.astype(jnp.int32)
                still = active & (nxt != slot_eos) & (n_out < slot_max)
                state = (nxt, pos + 1, n_out, still, out_buf, keys,
                         slot_max, slot_temp, slot_eos)
                return (cache2, state), None

            (cache, state), _ = jax.lax.scan(
                one_step, (cache, state), None, length=self.sync_every
            )
            return cache, state

        # Donating cache+state halves the live KV footprint (the cache is the
        # engine's dominant allocation and is dead after each window).
        self._decode_fn = jax.jit(run, donate_argnums=(1, 2))
        return self._decode_fn

    # ----------------------------------------------------------------- loop
    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise AssertionError  # guarded in submit()

    def _collect(self, s: int, active_np):
        req = self._slot_req[s]
        if req is None or active_np[s]:
            return
        row = np.asarray(self._out_buf[s])
        n = int(self._n_out[s])
        row = row[:n].copy()
        if req.eos >= 0 and (row == req.eos).any():
            row = row[: int(np.argmax(row == req.eos)) + 1]
        end = _first_stop_end(row, req.stop)
        if end is not None:
            # Exact truncation at the first completed stop occurrence —
            # tokens decoded past it (host scan lags by <= sync_every - 1
            # steps) are discarded, so output is cadence-independent.
            row = row[:end]
        self._results[req.rid] = row
        self._slot_req[s] = None
        self._retired_since_compact = True  # its columns are now reclaimable

    def _sync(self, state):
        (self._tok, self._pos, self._n_out, self._active, self._out_buf,
         self._keys, self._slot_max, self._slot_temp, self._slot_eos) = state

    def run(self) -> dict[int, np.ndarray]:
        """Drive admits + decode until the queue drains and all slots finish.
        Returns THIS wave's results only: {request_id: generated token ids
        (eos included, no pads)} for every request finished during the call."""
        state = (self._tok, self._pos, self._n_out, self._active, self._out_buf,
                 self._keys, self._slot_max, self._slot_temp, self._slot_eos)
        while True:
            self._sync(state)  # _collect reads the instance fields
            active_np = np.array(state[3])  # writable copy: the stop scan flips entries
            # Host-side stop-sequence scan: frees a matched slot at the sync
            # cadence (<= sync_every - 1 steps late; the OUTPUT is truncated
            # exactly in _collect, so only slot-turnaround timing varies).
            stop_slots = [
                s for s in range(self.B)
                if active_np[s] and self._slot_req[s] is not None and self._slot_req[s].stop
            ]
            if stop_slots:
                out_np = np.asarray(state[4])
                n_np = np.asarray(state[2])
                new_active = state[3]
                for s in stop_slots:
                    row = out_np[s][: int(n_np[s])]
                    if _first_stop_end(row, self._slot_req[s].stop) is not None:
                        new_active = new_active.at[s].set(False)
                        active_np[s] = False
                state = state[:3] + (new_active,) + state[4:]
                self._sync(state)
            for s in range(self.B):
                self._collect(s, active_np)
            # Capacity reservation must cover the LONGEST remaining run among
            # active slots, not just the incoming request's own max_new:
            # decode windows consume global columns until the longest-running
            # request finishes, so a short admit reserving only its own
            # length would let a long-running neighbor push cache['pos'] past
            # max_cache_len with no runtime guard (the clamped writes would
            # silently corrupt the last column). r5 review finding.
            n_np = np.asarray(state[2])
            max_remaining = max(
                (self._slot_req[s].max_new - int(n_np[s])
                 for s in range(self.B)
                 if self._slot_req[s] is not None and active_np[s]),
                default=0,
            )
            free = [s for s in range(self.B) if self._slot_req[s] is None]
            while free and self._queue:
                req = self._queue.popleft()
                s = free.pop(0)
                P = self._bucket(req.prompt.size)
                reserve = max(req.max_new, max_remaining)
                need = P + reserve + self.sync_every - 1
                if self._host_pos + need > self.C and self._retired_since_compact:
                    # Capacity pressure + something retired since the last
                    # compact: reclaim its columns before deferring or
                    # dead-ending. The retirement flag (not position
                    # movement) gates this, so sustained backpressure while
                    # one long request runs never re-gathers the cache.
                    self.compact()
                if self._host_pos + need > self.C:
                    self._queue.appendleft(req)
                    if any(r is not None for r in self._slot_req):
                        # Backpressure, not failure: let the in-flight slots
                        # finish (each decode window frees capacity pressure
                        # by retiring requests) and retry the admit later.
                        break
                    # Nothing in flight and still no room: a true dead end.
                    # Re-queue is already done, so catch + reset() + run()
                    # retries everything (finished results stay banked).
                    raise RuntimeError(
                        f"cache capacity exhausted (pos={self._host_pos}, "
                        f"need {P + reserve} more of {self.C}); raise "
                        "max_cache_len, or catch this, reset(), and run() again."
                    )
                row = np.full((P,), self.pad, np.int32)
                mrow = np.zeros((P,), np.int32)
                row[: req.prompt.size] = req.prompt
                mrow[: req.prompt.size] = 1
                # left-align inside the bucket so the last real token sits at P-1
                row_j, mrow_j = left_align(row[None], mrow[None])
                self._cache, state, _fin0 = self._admit_fn(P)(
                    self.params, self._cache, state, s, row_j[0], mrow_j[0],
                    jnp.int32(req.rid), self._rng,
                    jnp.int32(req.max_new), jnp.float32(req.temperature),
                    jnp.int32(req.eos),
                )
                self._host_pos += P
                # Keep the instance fields pointing at LIVE buffers: the admit
                # donated the previous ones, and a capacity raise later in
                # this pass must leave the engine in a clean recoverable state.
                self._sync(state)
                self._slot_req[s] = req
                max_remaining = max(max_remaining, req.max_new)
                # (an immediate-eos slot is collected at the next loop-top
                # check — no blocking readback of the admit result here)
            if not self._queue and not any(r is not None for r in self._slot_req):
                break
            # ONE dispatch advances all slots by sync_every tokens; the
            # np.asarray at the loop top is the only blocking host round-trip.
            self._cache, state = self._decode()(self.params, self._cache, state)
            self._host_pos += self.sync_every
        self._sync(state)
        wave, self._results = self._results, {}
        return {rid: wave[rid] for rid in sorted(wave)}
