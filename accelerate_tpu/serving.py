"""Continuous batching — a slot-based serving engine over the KV-cache path.

The reference serves through transformers' ``generate`` one batch at a time:
a batch runs until its LAST row finishes, so short requests pay for long ones
(head-of-line blocking). ``ContinuousBatcher`` keeps a fixed number of slots
decoding together and refills a slot the moment its sequence finishes — the
scheduling idea of vLLM/Orca, shaped for XLA's static-compilation model:

- **One decode program plus one admit program per prompt-length bucket**:
  the decode step covers all B slots at once, and an admit prefills one
  slot's prompt while the others' state rides along untouched. No shape ever
  depends on which requests are in flight, so nothing recompiles as traffic
  changes.
- **One global write offset, per-slot validity** — the same trick as batched
  speculative decoding (``generation._assisted_generate_batched``): every
  cache write lands at the global offset for ALL slots and rows that didn't
  really produce a token simply mask the slot out of their ``kv_mask``.
  Attention needs only slot-causality + validity, both hole-tolerant; rope
  positions ride the separate per-row ``positions`` channel, so absolute- and
  rotary-position models are exact.
- The cost of that simplicity is cache capacity: slots consume global cache
  columns even while other rows hole them out. ``compact()`` reclaims the
  holes — a stable full-cache gather pulls each row's valid columns to the
  front, drops retired requests' columns, and rewinds the write offset —
  and runs automatically at the backpressure point, so ``max_cache_len``
  sizes to the working set of concurrently LIVE tokens, not the whole
  queue. A genuinely-too-small cache still raises an actionable error
  instead of corrupting state.

**Prefix caching** (``set_prefix``): a prompt prefix shared by every request
(system prompt, few-shot block, a long document) is prefilled ONCE into the
head of the cache and stays valid for all slots across evictions — requests
then submit only their suffixes. Prefill compute and cache columns for the
prefix are paid once per wave instead of once per request.

**Per-request generation controls** (``submit`` kwargs): each request may
carry its own ``max_new_tokens``, ``temperature``, ``eos_token_id``, and
``stop_sequences``, heterogeneously within one wave. Per-slot scalars ride the
engine state through the same compiled programs — nothing recompiles as the
mix changes. Length/temperature/eos act on-device per slot; multi-token stop
sequences are detected host-side at the sync cadence (the slot frees at most
``sync_every - 1`` steps late) and the OUTPUT is truncated exactly at the
first stop occurrence, so results never depend on cadence.

Correctness contract (pinned by tests/test_serving.py): in greedy mode each
request's output is EXACTLY ``generate(model, prompt, temperature=0)`` for
that prompt alone (with a prefix set: for ``prefix + suffix``), regardless of
how requests interleave. In sampling mode
each request draws from its own stream — ``fold_in(engine_rng, request_id)``
folded again by step index — so a request's sampled tokens depend only on
(engine rng, request id), not on traffic or slot assignment; they are
reproducible but not bit-equal to a solo ``generate()`` (whose split chain
differs).

Sliding-window models serve exactly: ``cached_attention`` measures windows in
VALID-slot distance, so the slot scheme's masked holes don't stretch the
window (ops/attention.py — on the contiguous solo cache the two distances
coincide, which is what makes engine output == solo output).

**Paged KV mode** (``paged=True`` — the production deployment shape,
docs/serving.md): the contiguous per-slot cache is replaced by a block pool
(ops/paged_attention.py) — ``num_blocks`` blocks of ``block_size`` token
slots shared by every slot through per-slot block tables of static
``max_blocks_per_slot`` width, so every program stays compiled-once while
HBM is consumed per *chain*, not per ``B x max_cache_len`` rectangle:

- **Allocation is host free-list surgery**: a request reserves its whole
  worst-case chain at admission (the only capacity decision point), and a
  retired request's chain frees at collect — compaction without a device
  permutation. Stale bits of reused blocks are masked by a chain-frontier
  comparison, so the free list never needs device-side scrubbing.
- **Cross-request prefix sharing** generalizes ``set_prefix``: hole-free
  full blocks are indexed by their chain-prefix tokens and any request whose
  prompt starts with an indexed chain ALIASES those blocks (refcounted) —
  K/V are pure functions of (params, token prefix) because rope/wpe ride the
  position channel, which is exactly what makes the bits shareable.
- **Chunked prefill** interleaves with decode: ``submit()`` splits prompts
  into ``prefill_chunk``-token chunks and each engine iteration dispatches at
  most ONE chunk between decode windows, bounding per-step decode stall by a
  chunk's compute instead of a prompt's. Prompts may exceed the largest
  bucket (up to ``max_tokens_per_request``).
- **SLO-aware admission** (``slo=SLOTargets(...)``): per-request TTFT/TPOT
  accounting in the goodput-ledger idiom decides whether to admit, chunk,
  defer, or escalate a prefill (``slo_report()``); TTFT/TPOT histograms and
  pool gauges publish to the MetricsRegistry (docs/observability.md).
- **The decode/chunk programs** gather each slot's chain into a contiguous
  view with one uniform write window and run the UNMODIFIED model forward
  over it (the reference block-table lowering), then scatter written columns
  back onto chain tails. The engine loop runs one window AHEAD of its sync:
  each window's (active, n_out, out_buf) report is read only after the next
  window is dispatched, so the steady-state loop performs zero blocking
  transfers (pinned by tests).

The greedy correctness contract is unchanged and mode-independent: paged
outputs are bit-identical to the contiguous engine and to per-request
``generate()``.
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from .generation import _unwrap, left_align, mask_positions
from .ops.int8 import quantize_kv
from .ops.paged_attention import gather_block_mask, gather_view, init_kv_pool
from .utils.environment import safe_donate_argnums
from .utils.transfer import host_fetch


_SERVING_COUNTERS = None  # telemetry.metrics.cached_handles accessor
_SERVING_SLO_METRICS = None
_SERVING_SPEC_METRICS = None


def _serving_counters():
    """(submitted, completed, tokens) telemetry counters — the per-request
    paths pay only the .inc() (cached_handles hoists the registry lookup)."""
    global _SERVING_COUNTERS
    if _SERVING_COUNTERS is None:
        from .telemetry.metrics import cached_handles

        _SERVING_COUNTERS = cached_handles(lambda registry: (
            registry.counter(
                "accelerate_serving_requests_total",
                "Requests submitted to the engine",
            ),
            registry.counter(
                "accelerate_serving_requests_completed_total", "Requests finished"
            ),
            registry.counter(
                "accelerate_serving_tokens_total", "Tokens generated by the engine"
            ),
        ))
    return _SERVING_COUNTERS()


def _slo_metrics():
    """(ttft_hist, tpot_hist, blocks_free_gauge, pool_util_gauge) — the
    serving SLO/telemetry handles (docs/observability.md), hoisted like the
    request counters so the per-request paths pay only the observe/set."""
    global _SERVING_SLO_METRICS
    if _SERVING_SLO_METRICS is None:
        from .telemetry.metrics import cached_handles

        _SERVING_SLO_METRICS = cached_handles(lambda registry: (
            registry.histogram(
                "accelerate_serving_ttft_seconds",
                "Observed time-to-first-token per request (sync-cadence granularity)",
            ),
            registry.histogram(
                "accelerate_serving_tpot_seconds",
                "Observed time-per-output-token per request (finish-ttft over tokens)",
            ),
            registry.gauge(
                "accelerate_serving_kv_pool_blocks_free",
                "Free blocks in the paged KV pool",
            ),
            registry.gauge(
                "accelerate_serving_kv_pool_utilization",
                "Allocated fraction of the paged KV pool's blocks",
            ),
        ))
    return _SERVING_SLO_METRICS()


def _spec_metrics():
    """(proposed_total, accepted_total, acceptance_gauge) — the speculative-
    decoding telemetry handles (docs/observability.md): cumulative draft
    tokens proposed/accepted plus the running acceptance-rate gauge, hoisted
    like the request counters so each verify round pays only the inc/set."""
    global _SERVING_SPEC_METRICS
    if _SERVING_SPEC_METRICS is None:
        from .telemetry.metrics import cached_handles

        _SERVING_SPEC_METRICS = cached_handles(lambda registry: (
            registry.counter(
                "accelerate_spec_proposed_tokens_total",
                "Draft tokens proposed by the speculative decoder",
            ),
            registry.counter(
                "accelerate_spec_accepted_tokens_total",
                "Draft tokens accepted by the target verifier",
            ),
            registry.gauge(
                "accelerate_spec_acceptance_rate",
                "Cumulative accepted/proposed draft-token ratio",
            ),
        ))
    return _SERVING_SPEC_METRICS()


@dataclass
class SLOTargets:
    """Per-request latency targets the paged engine's admission loop steers
    by (the goodput-ledger idiom applied to serving: classify every scheduling
    decision, account per-request TTFT/TPOT against explicit targets).

    ``ttft_s``: target time-to-first-token. A queued request whose projected
    TTFT is at risk gets its remaining prefill escalated to bigger chunks
    (fewer interleave gaps — prefill completes sooner at the cost of larger
    per-step decode stalls). ``tpot_s``: target time-per-output-token for
    in-flight decoders. While the recent decode-window pace is over budget,
    prefill chunks are deferred (decode keeps priority) unless that would put
    a waiting request's TTFT at risk — TTFT outranks TPOT on conflict, the
    standard serving trade. ``None`` disables a dimension."""

    ttft_s: float | None = None
    tpot_s: float | None = None


def _first_stop_end(row: np.ndarray, stops: tuple) -> int | None:
    """End index (exclusive) of the earliest-ending completed stop-sequence
    occurrence in ``row``, or None. Earliest END, so a later-starting shorter
    stop that completes first wins — the order generation actually stops in."""
    best = None
    for s in stops:
        L = int(s.size)
        if L > row.size:
            continue
        win = np.lib.stride_tricks.sliding_window_view(row, L)
        hits = np.nonzero((win == s).all(axis=1))[0]
        if hits.size:
            end = int(hits[0]) + L
            if best is None or end < best:
                best = end
    return best


# Ring bound on per-request latency samples and the dispatch trace a
# long-lived engine retains (the Prometheus histograms keep the full
# distributions; these only back slo_report()'s recent view and the tests'
# structural pins).
_SLO_HISTORY = 4096


@dataclass
class _Request:
    rid: int
    prompt: np.ndarray  # (P,) real tokens, no padding
    max_new: int
    temperature: float
    eos: int  # -1 = none
    stop: tuple  # tuple of np.int32 arrays; () = none
    submit_t: float = 0.0  # monotonic submit time (TTFT/TPOT accounting)


class ContinuousBatcher:
    """Slot-based continuous batching over a decoder-only cached model.

    Usage::

        engine = ContinuousBatcher(model, batch_slots=4, max_new_tokens=64,
                                   max_cache_len=4096, eos_token_id=eos)
        ids = [engine.submit(p) for p in prompts]       # any ragged lengths
        outputs = engine.run()                           # {rid: np.ndarray}

    ``run()`` drives admits + decode steps until every submitted request has
    finished; ``submit`` may be called again afterwards (slots and the cache
    are re-usable until ``max_cache_len`` is exhausted; ``reset()`` reclaims
    everything).
    """

    def __init__(
        self,
        model,
        *,
        batch_slots: int,
        max_new_tokens: int,
        max_cache_len: int,
        params=None,
        temperature: float = 0.0,
        top_k: int | None = None,
        top_p: float | None = None,
        rng=None,
        eos_token_id: int | None = None,
        pad_token_id: int = 0,
        cache_dtype=jnp.bfloat16,
        bucket_sizes: tuple = (16, 32, 64, 128, 256, 512, 1024),
        sync_every: int = 8,
        paged: bool = False,
        block_size: int = 16,
        num_blocks: int | None = None,
        prefill_chunk: int | None = None,
        max_tokens_per_request: int | None = None,
        slo: SLOTargets | None = None,
        kernels: str | None = None,
        speculative_k: int = 0,
        draft_model=None,
        kv_quant: str | None = None,
        matmul_precision: str | None = None,
        trace_requests: bool = True,
    ):
        module, mparams = _unwrap(model)
        # Weight-quantized serving (opt-in dtype policy): swap the model's
        # matmul primitive for the kernel-backed int8 path (ops/int8.py) via a
        # memoized config variant — the params are untouched (dynamic
        # quantization happens inside the matmul), so the SAME checkpoint
        # serves both precisions.
        if matmul_precision in ("", "default"):
            matmul_precision = None
        if matmul_precision is not None:
            from .generation import _precision_variant

            module = _precision_variant(module, matmul_precision)
        self.matmul_precision = matmul_precision
        self.module = module
        self.params = params if params is not None else mparams
        if self.params is None:
            raise ValueError("Model has no params; pass params= or init the model first.")
        if hasattr(module, "encode"):
            raise ValueError("ContinuousBatcher supports decoder-only cached models.")
        self.B = batch_slots
        self.max_new = max_new_tokens
        self.C = max_cache_len
        self.temperature = temperature
        self.top_k, self.top_p = top_k, top_p
        self.eos = -1 if eos_token_id is None else eos_token_id
        self.pad = pad_token_id
        self.cache_dtype = cache_dtype
        self.buckets = tuple(sorted(bucket_sizes))
        if sync_every < 1:
            raise ValueError(f"sync_every must be >= 1, got {sync_every}")
        # How many decode steps to enqueue between host checks. The host
        # round-trip (detecting finished slots) is the serving loop's only
        # sync; batching K steps per check amortizes it — finished slots idle
        # at most K-1 extra steps and the cache consumes at most K-1 extra
        # columns per wave, both accounted for in the capacity reservation.
        self.sync_every = sync_every
        # ----------------------------------------------- decode-speed levers
        # Speculative decoding + int8 KV blocks (ISSUE 20): constructor args
        # win; unset values resolve from the launcher env contract
        # (ACCELERATE_SPECULATIVE_K / _DRAFT_MODEL / _KV_QUANT) so a serving
        # tier picks them up with zero code, like kernels/SLO targets.
        from .utils.constants import ENV_KV_QUANT, ENV_SPECULATIVE_K

        if not speculative_k:
            speculative_k = int(os.environ.get(ENV_SPECULATIVE_K, "0") or 0)
        self.speculative_k = int(speculative_k)
        if self.speculative_k < 0:
            raise ValueError(f"speculative_k must be >= 0, got {speculative_k}")
        if kv_quant is None:
            kv_quant = os.environ.get(ENV_KV_QUANT) or None
        if kv_quant in ("", "none", "off"):
            kv_quant = None
        if kv_quant not in (None, "int8"):
            raise ValueError(f"kv_quant must be None or 'int8', got {kv_quant!r}")
        self.kv_quant = kv_quant
        # ---------------------------------------------------- paged KV mode
        # paged=True swaps the contiguous (B, max_cache_len) cache for a
        # block pool (ops/paged_attention.py): `num_blocks` blocks of
        # `block_size` token slots shared by all slots via per-slot block
        # tables (static max_blocks_per_slot, so every program stays
        # compiled-once). `max_cache_len` is reinterpreted as the POOL's
        # total token capacity (num_blocks defaults to max_cache_len //
        # block_size); `prefill_chunk` bounds each prefill dispatch so long
        # prompts interleave with decode instead of stalling it.
        self.paged = bool(paged)
        self.block_size = int(block_size)
        if slo is None:
            # The launcher's SLO env contract reaches a serving tier with
            # zero code: ACCELERATE_SLO_TTFT/TPOT resolve here unless the
            # caller pinned targets (or their absence) explicitly.
            from .telemetry.slo import serving_slo_from_env

            slo = serving_slo_from_env()
        self.slo = slo
        if self.paged:
            if self.block_size < 1:
                raise ValueError(f"block_size must be >= 1, got {block_size}")
            if num_blocks is None:
                num_blocks = max(1, self.C // self.block_size)
            self.num_blocks = int(num_blocks)
            if prefill_chunk is None:
                # Largest block-aligned chunk within the biggest bucket: full
                # (non-final) chunks stay hole-free and block-aligned, which
                # is what makes their blocks registrable for cross-request
                # sharing. Clamped to the largest bucket for the degenerate
                # block_size > buckets[-1] case (chunks then just aren't
                # block-aligned, so they skip share registration).
                prefill_chunk = min(self.buckets[-1], max(
                    self.block_size,
                    (self.buckets[-1] // self.block_size) * self.block_size,
                ))
            self.prefill_chunk = int(prefill_chunk)
            if self.prefill_chunk < 1 or self.prefill_chunk > self.buckets[-1]:
                raise ValueError(
                    f"prefill_chunk must be in [1, largest bucket "
                    f"{self.buckets[-1]}], got {prefill_chunk}"
                )
            # Per-request token ceiling (prompt incl. any shared prefix +
            # output). Sizes the static per-slot block table: the chain may
            # additionally hold the final chunk's bucket padding and up to
            # ~3 windows of post-finish slack (finish detection + the
            # one-window sync lookahead), all block-rounded.
            if max_tokens_per_request is None:
                max_tokens_per_request = self.buckets[-1] + self.max_new
            self.max_tokens_per_request = int(max_tokens_per_request)
            # The final chunk is BUCKET-padded, and _bucket rounds a
            # <=prefill_chunk remainder up to at most _bucket(prefill_chunk)
            # (coarse bucket lists round far past prefill_chunk itself), so
            # that is the padding the static table must budget for.
            # Spec-decode verify rounds write (k+1)-token windows instead of
            # sync_every-token ones, so the post-finish slack is measured in
            # the LARGER of the two window widths.
            self._decode_slack = 3 * max(self.sync_every, self.speculative_k + 1)
            worst_chain = (
                self.max_tokens_per_request + self._bucket(self.prefill_chunk)
                + self._decode_slack
            )
            self.max_blocks_per_slot = -(-worst_chain // self.block_size)
        else:
            for name, value in (("num_blocks", num_blocks),
                                ("prefill_chunk", prefill_chunk),
                                ("max_tokens_per_request", max_tokens_per_request)):
                if value is not None:
                    raise ValueError(f"{name} requires paged=True")
            if self.speculative_k:
                raise ValueError("speculative_k requires paged=True")
            if self.kv_quant:
                raise ValueError("kv_quant requires paged=True")
        # Pallas kernel-layer spec for the engine's compiled programs
        # (ops/registry.py; docs/kernels.md): None = the launcher contract
        # (ACCELERATE_KERNELS) resolved at trace time; an explicit string
        # (e.g. "pallas" / "paged_gather=off") pins the engine regardless of
        # env. The paged mode's chain-view assembly dispatches through op
        # ``paged_gather`` — the Pallas chain-walk skips bucket-padded slots
        # and never materializes the intermediate (B, M, bs, ...) gather;
        # token output is bit-identical either way (tests/test_kernels.py).
        if kernels is not None:
            from .ops.registry import parse_kernel_spec

            parse_kernel_spec(kernels)  # validate eagerly
        self.kernels = kernels
        # Speculative decoding: resolve the draft model. Its paged pool
        # mirrors the target pool's block geometry exactly, so ONE set of
        # host block tables / free-list bookkeeping indexes both.
        self._draft_module = None
        self._draft_params = None
        if self.speculative_k:
            if draft_model is None:
                from .utils.constants import ENV_DRAFT_MODEL

                draft_model = self._build_draft_from_preset(
                    os.environ.get(ENV_DRAFT_MODEL) or "tiny"
                )
            d_module, d_params = _unwrap(draft_model)
            if d_params is None:
                raise ValueError(
                    "draft model has no params; init it first or pass a "
                    "prepared/initialized model as draft_model="
                )
            self._draft_module = d_module
            self._draft_params = d_params
        elif draft_model is not None:
            raise ValueError("draft_model requires speculative_k > 0")
        self._rng = rng if rng is not None else jax.random.key(0)
        self._queue: deque[_Request] = deque()
        self._next_rid = 0
        self._results: dict[int, np.ndarray] = {}
        self._admit_fns: dict[tuple, object] = {}
        self._prefix_fns: dict[int, object] = {}
        self._chunk_fns: dict[int, object] = {}
        self._decode_fn = None
        self._verify_fn = None
        self._compact_fn = None
        # Cumulative speculative-decoding ledger (host side, both exposed via
        # spec_report() and the accelerate_spec_* metrics handles).
        self._spec_proposed = 0
        self._spec_accepted = 0
        # SLO/throughput accounting (both modes): per-request wall-clock
        # marks and the admission loop's decision tallies. Both ring-bounded
        # (_SLO_HISTORY): a long-lived engine serves unbounded requests, and
        # the histograms already hold the full distribution — the dicts only
        # back slo_report()'s recent-sample view.
        self._req_times: dict[int, dict] = {}
        self._slo_decisions = {
            "admitted": 0, "chunked_prefills": 0, "deferred_prefills": 0,
            "escalated_monolithic": 0, "aliased_blocks": 0,
        }
        self._peak_consumed_slots = 0
        # Host-side trace of paged dispatches ("chunk:<P>" / "decode"):
        # the structural evidence behind the bounded-stall contract (tests
        # pin that no two prefill chunks ever run back-to-back while a
        # decoder is active, and that every chunk is <= prefill_chunk's
        # bucket — so a decode step waits on at most one chunk's compute).
        self._dispatch_log: list[str] = []
        # Compaction reclaims columns only when something RETIRED since the
        # last compact (retirement is what creates dead columns); keying the
        # auto-trigger on this flag — not on position movement — keeps
        # sustained backpressure from re-gathering the cache every window.
        self._retired_since_compact = False
        self._prefix_tokens: np.ndarray | None = None
        # Per-request lifecycle tracing (telemetry/requests.py): every hook
        # fires from host bookkeeping the loop performs anyway, so tracing
        # adds zero device transfers (pinned by tests/test_fleet.py). A TTFT
        # breach books accelerate_slo_breaches_total + a flight event and can
        # arm a trace capture via the installed profile trigger.
        if trace_requests:
            from .telemetry.requests import RequestTracer

            self.tracer: RequestTracer | None = RequestTracer(slo=self.slo)
        else:
            self.tracer = None
        # Token-streaming sink (serving_net/frontend.py installs one):
        # ``stream(rid, tokens, final)`` — per-window deltas from the report
        # the loop already reads, then ONE final call carrying the
        # authoritative (eos/stop-truncated) output. None = no streaming and
        # no extra report fetches.
        self.stream = None
        self._streamed: dict[int, int] = {}
        self.reset()

    def _build_draft_from_preset(self, preset: str):
        """Materialize the env-named draft model (``ACCELERATE_DRAFT_MODEL``,
        default ``tiny``): a zoo config preset re-shaped to the target's
        vocabulary and position budget, deterministically initialized (fixed
        seed) so every host of a serving fleet builds the SAME draft weights.
        Checkpointed drafts pass ``draft_model=`` directly instead."""
        from .models.llama import Llama, LlamaConfig

        factory = getattr(LlamaConfig, preset, None)
        if factory is None or not callable(factory):
            raise ValueError(
                f"unknown draft-model preset {preset!r} (a LlamaConfig "
                "classmethod name like 'tiny')"
            )
        overrides = {}
        tcfg = getattr(self.module, "config", None)
        if tcfg is not None and hasattr(tcfg, "vocab_size"):
            overrides["vocab_size"] = tcfg.vocab_size
        if tcfg is not None and hasattr(tcfg, "max_position_embeddings"):
            overrides["max_position_embeddings"] = tcfg.max_position_embeddings
        d_module = Llama(factory(**overrides))
        d_module.init_params(jax.random.key(0))
        return d_module

    # ------------------------------------------------------------- lifecycle
    def reset(self, keep_prefix: bool = True):
        """Fresh cache and slot state. Queued (not-yet-admitted) requests and
        already-finished results survive; in-flight slots are wiped — the
        capacity-error path re-queues them first, so catch + ``reset()`` +
        ``run()`` retries everything. A shared prefix (``set_prefix``) is
        re-prefilled automatically so the retry flow stays exact; pass
        ``keep_prefix=False`` to drop it."""
        B = self.B
        self._streamed.clear()
        if self.tracer is not None:
            # In-flight slots are about to be wiped: their lifecycle records
            # close as cancelled (queued requests survive and stay queued).
            for req in getattr(self, "_slot_req", []):
                if req is not None:
                    self.tracer.cancel(req.rid)
        if self.paged:
            self._reset_paged(keep_prefix)
            return
        self._cache = self.module.init_cache(B, self.C, dtype=self.cache_dtype)
        self._tok = jnp.full((B,), self.pad, jnp.int32)
        self._pos = jnp.zeros((B,), jnp.int32)  # next rope position per slot
        self._n_out = jnp.zeros((B,), jnp.int32)
        self._active = jnp.zeros((B,), bool)
        self._out_buf = jnp.full((B, self.max_new), self.pad, jnp.int32)
        self._keys = jnp.broadcast_to(self._rng, (B,))
        # Per-slot generation controls (heterogeneous per request; traced
        # values, so the compiled programs are shared across any mix).
        self._slot_max = jnp.full((B,), self.max_new, jnp.int32)
        self._slot_temp = jnp.full((B,), float(self.temperature or 0.0), jnp.float32)
        self._slot_eos = jnp.full((B,), self.eos, jnp.int32)
        self._slot_req: list[_Request | None] = [None] * B
        # Host-side mirror of cache["pos"]: it advances deterministically
        # (+bucket per admit, +sync_every per decode window; compact() rewinds
        # it from the one readback it already pays), so capacity checks never
        # need a device readback.
        self._host_pos = 0
        self._retired_since_compact = False
        # Shared-prefix state: number of leading cache columns holding the
        # common prefix (valid for every slot, never evicted).
        self._pfx = 0
        if keep_prefix and self._prefix_tokens is not None:
            tokens, self._prefix_tokens = self._prefix_tokens, None
            self.set_prefix(tokens)
        elif not keep_prefix:
            self._prefix_tokens = None

    def _reset_paged(self, keep_prefix: bool = True):
        """Paged-mode ``reset()``: fresh pool, tables, free-list, and slot
        state. The shared-prefix TOKENS survive ``keep_prefix=True`` (paged
        prefix caching is lazy: the first request of the next wave re-prefills
        the prefix blocks and later requests alias them — see
        ``set_prefix``), but all resident blocks are dropped."""
        B = self.B
        self._pool = init_kv_pool(
            self.module, self.num_blocks, self.block_size,
            dtype=self.cache_dtype, quant=self.kv_quant,
        )
        # The draft pool mirrors the target pool's block geometry (same
        # num_blocks/block_size/max_blocks_per_slot), so a chain's block i
        # holds target KV in self._pool AND draft KV in self._draft_pool
        # under the SAME host table entry. It stays unquantized: the draft is
        # tiny, its pool a rounding error next to the target's.
        self._draft_pool = (
            init_kv_pool(self._draft_module, self.num_blocks, self.block_size,
                         dtype=self.cache_dtype)
            if self.speculative_k else None
        )
        self._tok = jnp.full((B,), self.pad, jnp.int32)
        self._pos = jnp.zeros((B,), jnp.int32)
        self._n_out = jnp.zeros((B,), jnp.int32)
        self._active = jnp.zeros((B,), bool)
        self._out_buf = jnp.full((B, self.max_new), self.pad, jnp.int32)
        self._keys = jnp.broadcast_to(self._rng, (B,))
        self._slot_max = jnp.full((B,), self.max_new, jnp.int32)
        self._slot_temp = jnp.full((B,), float(self.temperature or 0.0), jnp.float32)
        self._slot_eos = jnp.full((B,), self.eos, jnp.int32)
        self._slot_req: list[_Request | None] = [None] * B
        # Host-side paged bookkeeping. Block 0 is the reserved trash block
        # (ops/paged_attention.py): never allocated, never mask-valid.
        self._tables_np = np.zeros((B, self.max_blocks_per_slot), np.int32)
        self._slot_len = np.zeros((B,), np.int64)      # chain slots (incl holes)
        self._slot_base = np.zeros((B,), np.int64)     # real tokens in chain
        self._slot_mode = ["free"] * B                  # free | prefill | decode
        self._slot_chunks: list[list] = [[] for _ in range(B)]
        self._slot_blocks: list[list[int]] = [[] for _ in range(B)]
        self._slot_tokens: list[np.ndarray | None] = [None] * B
        self._free_blocks = list(range(1, self.num_blocks + 1))
        self._block_ref = np.zeros((self.num_blocks + 1,), np.int64)
        self._share_index: dict[bytes, int] = {}
        self._block_key: dict[int, bytes] = {}
        self._host_pos = 0
        self._pfx = 0
        self._retired_since_compact = False
        if not keep_prefix:
            self._prefix_tokens = None

    def set_prefix(self, prefix_ids) -> int:
        """Shared-prefix caching: prefill ONE copy of a prompt prefix common to
        every request (a system prompt, few-shot examples, a long document)
        into the head of the cache, valid for all slots. Subsequent
        ``submit()`` calls pass only each request's *suffix*; outputs are
        exactly ``generate(model, prefix + suffix)`` per request (pinned by
        tests/test_serving.py). The prefix occupies its length ONCE instead of
        once per admitted request — the capacity (and prefill-compute) win of
        vLLM-style prompt caching, shaped for the static slot scheme: prefix
        columns sit below every admit's write offset, so slot-causal attention
        sees them and eviction never touches them.

        Must be called on a fresh cache (right after construction or
        ``reset()``); ``reset()`` re-prefills it automatically so the
        capacity-retry flow stays exact. Returns the prefix length."""
        prefix = np.asarray(prefix_ids, np.int32).reshape(-1)
        if prefix.size == 0:
            raise ValueError("empty prefix")
        if self.paged:
            # Paged prefix caching is a special case of cross-request block
            # aliasing: the stored prefix is prepended to every submit()'s
            # prompt, the FIRST request prefills it into blocks, and every
            # later request whose chain starts with those full blocks aliases
            # them (refcounted — they stay resident while any chain uses
            # them). No eager broadcast prefill, no reserved cache head.
            if any(m != "free" for m in self._slot_mode) or self._prefix_tokens is not None:
                raise RuntimeError(
                    "set_prefix needs a fresh cache (no admitted requests, no "
                    "prior prefix): call reset(keep_prefix=False) first."
                )
            P = int(prefix.size)
            if P + self.buckets[0] + self.max_new > self.max_tokens_per_request:
                raise ValueError(
                    f"prefix length {P} leaves no room for even one "
                    f"smallest-bucket request within max_tokens_per_request="
                    f"{self.max_tokens_per_request}"
                )
            self._prefix_tokens = prefix
            self._pfx = P
            return P
        if self._host_pos != 0 or any(r is not None for r in self._slot_req):
            raise RuntimeError(
                "set_prefix needs a fresh cache (no admitted requests, no "
                "prior prefix): call reset(keep_prefix=False) first."
            )
        P = int(prefix.size)
        if P + self.buckets[0] + self.max_new + self.sync_every - 1 > self.C:
            raise ValueError(
                f"prefix length {P} leaves no room for even one "
                f"smallest-bucket request within max_cache_len={self.C}"
            )
        if P not in self._prefix_fns:
            module = self.module
            cache_dtype = self.cache_dtype

            def fill(params, cache, ids):
                # Prefill ONE row against a throwaway batch-1 cache of exactly
                # the prefix length, then broadcast the resulting KV columns
                # into every slot's row — identical state to a B-row prefill
                # at 1/B the FLOPs (the rows would be bitwise copies).
                mask = jnp.ones(ids.shape, jnp.int32)
                small = module.init_cache(1, P, dtype=cache_dtype)
                out = module.apply(params, input_ids=ids, attention_mask=mask,
                                   cache=small, positions=mask_positions(mask))
                sk, sv = out["cache"]["k"], out["cache"]["v"]
                B = cache["kv_mask"].shape[0]
                wide = lambda t: jnp.broadcast_to(t, (t.shape[0], B) + t.shape[2:])
                return {
                    **cache,
                    "k": cache["k"].at[:, :, :P].set(wide(sk)),
                    "v": cache["v"].at[:, :, :P].set(wide(sv)),
                    "pos": cache["pos"] + P,
                    "kv_mask": cache["kv_mask"].at[:, :P].set(1),
                }

            self._prefix_fns[P] = jax.jit(fill, donate_argnums=safe_donate_argnums((1,)))
        self._cache = self._prefix_fns[P](self.params, self._cache,
                                          jnp.asarray(prefix)[None])
        self._host_pos = P
        self._pfx = P
        self._prefix_tokens = prefix
        return P

    @property
    def cache_columns_used(self) -> int:
        """Global cache columns consumed so far this wave (prefix + admits +
        decode windows, out of ``max_cache_len``) — the capacity a ``reset()``
        reclaims. Public mirror of the engine's host-side position counter.
        In paged mode: pool token-slots currently allocated to chains."""
        if self.paged:
            return self.blocks_in_use * self.block_size
        return self._host_pos

    @property
    def blocks_in_use(self) -> int:
        """Paged mode: pool blocks currently owned by at least one chain."""
        if not self.paged:
            return 0
        return self.num_blocks - len(self._free_blocks)

    @property
    def kv_cache_bytes(self) -> int:
        """Persistent device bytes of the KV store — the contiguous cache's
        k/v arrays, or the paged pool (trash block included). The denominator
        of the serving bench's admitted-tokens-per-cache-byte capacity
        metric, and the quantity ``accelerate-tpu memcheck --serving`` gates
        against the HBM budget. A quantized pool (``kv_quant="int8"``) prices
        its per-token scale planes too; speculative decoding adds the draft
        pool's blocks — both layouts the memcheck gate must cover."""
        store = self._pool if self.paged else self._cache
        total = int(store["k"].nbytes + store["v"].nbytes)
        if "k_scale" in store:
            total += int(store["k_scale"].nbytes + store["v_scale"].nbytes)
        draft = getattr(self, "_draft_pool", None)
        if draft is not None:
            total += int(draft["k"].nbytes + draft["v"].nbytes)
        return total

    @property
    def kv_consumed_slots_peak(self) -> int:
        """Peak token-slots of KV storage the wave actually consumed:
        ``B x max(cache_columns_used)`` for the contiguous scheme (every slot
        holds every global column) vs peak allocated pool slots for the paged
        scheme (chains only) — the apples-to-apples capacity comparison
        (bytes per slot are identical across modes)."""
        return self._peak_consumed_slots

    def pool_stats(self) -> dict:
        """Host-side paged-pool snapshot (no device readback)."""
        if not self.paged:
            return {"paged": False}
        return {
            "paged": True,
            "block_size": self.block_size,
            "num_blocks": self.num_blocks,
            "blocks_free": len(self._free_blocks),
            "blocks_in_use": self.blocks_in_use,
            "shared_blocks": len(self._block_key),
            "max_blocks_per_slot": self.max_blocks_per_slot,
            "pool_bytes": self.kv_cache_bytes,
            "kv_quant": self.kv_quant,
            "speculative_k": self.speculative_k,
            "draft_pool_bytes": (
                int(self._draft_pool["k"].nbytes + self._draft_pool["v"].nbytes)
                if self._draft_pool is not None else 0
            ),
        }

    def spec_report(self) -> dict:
        """Cumulative speculative-decoding acceptance ledger (host-side, no
        device readback beyond what verify rounds already paid): draft tokens
        proposed/accepted and the acceptance rate — the serving analog of
        slo_report(), consumed by bench.py's BENCH_SPEC cell and the journal
        run_summary's accepted-tokens/s fields."""
        proposed, accepted = self._spec_proposed, self._spec_accepted
        return {
            "speculative_k": self.speculative_k,
            "proposed_tokens": proposed,
            "accepted_tokens": accepted,
            "acceptance_rate": accepted / proposed if proposed else None,
        }

    def slo_report(self) -> dict:
        """Per-request TTFT/TPOT accounting + the admission loop's decision
        tallies (the goodput-ledger idiom for serving): what was admitted,
        chunked, deferred, or escalated, and the observed latency samples
        behind the ``accelerate_serving_ttft/tpot_seconds`` histograms."""
        ttft = [
            t["first_token"] - t["submit"]
            for t in self._req_times.values() if "first_token" in t
        ]
        tpot = [t["tpot"] for t in self._req_times.values() if "tpot" in t]
        return {
            "targets": {
                "ttft_s": self.slo.ttft_s if self.slo else None,
                "tpot_s": self.slo.tpot_s if self.slo else None,
            },
            "decisions": dict(self._slo_decisions),
            "ttft_s": ttft,
            "tpot_s": tpot,
            "requests": len(self._req_times),
        }

    def compact(self) -> int:
        """Reclaim holed cache columns: gather each row's VALID slots to the
        front (stable, so relative order is preserved) and rewind the global
        write offset to the longest row's valid count. Returns the number of
        columns freed.

        Why this is exact (pinned by tests): rope/wpe rotations are baked
        into K at write time and ride the gather unchanged; causal masking
        needs only slot ORDER (every valid key lands below the new write
        offset); sliding windows measure valid-slot distance, which a
        permutation of holes cannot change; and the shared prefix — valid in
        every row, first in every row's order — keeps columns [0, pfx).
        In-flight slot state (rope positions, output buffers) is untouched.

        Cost: one full-cache gather (O(L·B·C·H·D) bytes), so it runs when
        capacity pressure makes the alternative a dead-end — ``run()``
        triggers it automatically on backpressure — or explicitly between
        waves. This is the compaction step the r5 utilization measurement
        motivated (PERF.md): a wave of heterogeneous lengths reclaims the
        ~90% of consumed area that holes occupy instead of requiring
        ``reset()``."""
        if self.paged:
            # Paged compaction is block-table surgery and happens eagerly:
            # a retired request's chain is refcount-freed at collect time, so
            # there is never a device permutation to run and nothing left to
            # reclaim here. Kept callable so wave-boundary compact() calls
            # are mode-agnostic.
            return 0
        if self._host_pos == 0:
            return 0
        if self._compact_fn is None:
            def run(cache, dead, pfx):
                km = cache["kv_mask"]
                # A retired request's columns stay valid until its slot is
                # re-admitted (eviction is lazy); compaction is exactly when
                # they die — their output is already collected. Prefix
                # columns survive (valid for every future occupant).
                col = jnp.arange(km.shape[1])[None]
                km = jnp.where(dead[:, None] & (col >= pfx), 0, km)
                # Stable argsort of (1 - valid): valid slots first, in order.
                perm = jnp.argsort(1 - km, axis=1, stable=True)  # (B, C)
                pk = perm[None, :, :, None, None]
                return {
                    "k": jnp.take_along_axis(cache["k"], pk, axis=2),
                    "v": jnp.take_along_axis(cache["v"], pk, axis=2),
                    "kv_mask": jnp.take_along_axis(km, perm, axis=1),
                    "pos": jnp.max(jnp.sum(km, axis=1)).astype(cache["pos"].dtype),
                }

            self._compact_fn = jax.jit(run, donate_argnums=safe_donate_argnums((0,)))
        dead = jnp.asarray([r is None for r in self._slot_req])
        self._cache = self._compact_fn(self._cache, dead, jnp.int32(self._pfx))
        new_pos = int(host_fetch(self._cache["pos"]))  # the one readback compact pays
        freed = self._host_pos - new_pos
        self._host_pos = new_pos
        self._retired_since_compact = False
        return freed

    @property
    def cache_utilization(self) -> float:
        """Fraction of the consumed cache area (B rows × ``cache_columns_used``
        columns) whose slots are valid for their row — the engine's capacity
        honesty metric. Holes from eviction, retired requests, and
        inactive-row decode writes all count against it, so under
        heterogeneous lengths this decays across a wave until ``compact()``
        (auto-triggered at backpressure, or explicit) reclaims the holes;
        the r5 measured decay that motivated compaction is recorded in
        PERF.md."""
        if self.paged:
            # Valid tokens over allocated pool slots: holes are only bucket
            # padding in final prefill chunks + masked inactive-step decode
            # writes, and whole chains free at retirement — which is why the
            # paged scheme wins on exactly this metric.
            used = sorted(set(range(1, self.num_blocks + 1)) - set(self._free_blocks))
            if not used:
                return 1.0
            mask = host_fetch(self._pool["mask"])
            return float(mask[np.asarray(used, np.int64)].mean())
        if self._host_pos == 0:
            return 1.0
        km = host_fetch(self._cache["kv_mask"])[:, : self._host_pos]
        return float(km.mean())

    def submit(
        self,
        prompt_ids,
        *,
        max_new_tokens: int | None = None,
        temperature: float | None = None,
        eos_token_id: int | None = None,
        stop_sequences=None,
        request_id: int | None = None,
        tier: str = "unified",
    ) -> int:
        """Queue one prompt (1-D array of token ids). Returns a request id.

        Per-request overrides (engine defaults when omitted):
        ``max_new_tokens`` (must be <= the engine's, which sizes the output
        buffer), ``temperature`` (0 = greedy; rows mix freely within one
        wave), ``eos_token_id``, and ``stop_sequences`` — an iterable of
        token-id sequences; generation stops at the first completed
        occurrence, which is INCLUDED in the returned ids (like eos). Stop
        detection runs host-side at the sync cadence, but the returned output
        is truncated at the exact first occurrence, so results are
        cadence-independent.

        ``request_id`` threads an EXTERNAL id (the serving_net router assigns
        one per fleet request) through this engine instead of the local
        counter, so the request's lifecycle records carry the SAME rid on
        every tier it crosses (router admission → prefill chunks → chain
        handoff → decode) and /fleet rollups join them into one trace;
        ``tier`` labels this engine's tracer record with the serving role
        that made it. The local counter jumps past any external id, so
        auto-assigned and router-assigned ids never collide."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("empty prompt")
        if self.paged:
            # Chunked prefill lifts the one-bucket prompt bound: the chain
            # just has to fit the per-request token ceiling (prompt incl.
            # prefix + output buffer). The prefix is prepended HERE so the
            # whole downstream path sees one logical token stream — block
            # aliasing then recovers the shared-prefix capacity win.
            if self._prefix_tokens is not None:
                prompt = np.concatenate([self._prefix_tokens, prompt])
            limit = self.max_tokens_per_request - (
                self.max_new if max_new_tokens is None else int(max_new_tokens)
            )
            if prompt.size > limit:
                raise ValueError(
                    f"prompt length {prompt.size} (incl. prefix) exceeds "
                    f"max_tokens_per_request={self.max_tokens_per_request} "
                    f"minus the output reservation; raise max_tokens_per_request."
                )
        elif prompt.size > self.buckets[-1]:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the largest bucket "
                f"{self.buckets[-1]}; raise bucket_sizes."
            )
        max_new = self.max_new if max_new_tokens is None else int(max_new_tokens)
        if not (1 <= max_new <= self.max_new):
            raise ValueError(
                f"per-request max_new_tokens must be in [1, {self.max_new}] "
                f"(the engine's max_new_tokens sizes the output buffer), got {max_new}"
            )
        temp = float(self.temperature or 0.0) if temperature is None else float(temperature)
        eos = self.eos if eos_token_id is None else int(eos_token_id)
        stop = ()
        if stop_sequences:
            stop = tuple(np.asarray(s, np.int32).reshape(-1) for s in stop_sequences)
            if any(s.size == 0 for s in stop):
                raise ValueError("empty stop sequence")
        if request_id is None:
            rid = self._next_rid
            self._next_rid += 1
        else:
            rid = int(request_id)
            if rid < 0:
                raise ValueError(f"request_id must be >= 0, got {request_id}")
            if (
                rid in self._results
                or any(q.rid == rid for q in self._queue)
                or any(r is not None and r.rid == rid for r in self._slot_req)
            ):
                raise ValueError(f"request_id {rid} is already in use")
            self._next_rid = max(self._next_rid, rid + 1)
        now = time.monotonic()
        self._queue.append(_Request(rid, prompt, max_new, temp, eos, stop, now))
        self._req_times[rid] = {"submit": now}
        if self.tracer is not None:
            self.tracer.submit(rid, int(prompt.size), submit_t=now, tier=tier)
        while len(self._req_times) > _SLO_HISTORY:
            # Insertion-ordered: evict the oldest sample (a still-in-flight
            # old rid just loses its latency SAMPLE, never its result).
            self._req_times.pop(next(iter(self._req_times)))
        _serving_counters()[0].inc()
        return rid

    # ------------------------------------------------------------- sampling
    def _sample_rows(self, logits, keys, step_idx, temps):
        """Per-row draw from per-request streams: row r's key folded by its
        own step index — sampled tokens depend only on (engine rng, request
        id, step), never on traffic or slot assignment. ``temps`` (B,) is the
        per-request temperature; 0 rows take the raw argmax (exact greedy),
        so greedy and sampled requests mix inside one compiled program."""
        from .generation import _warp_scores

        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # Per-row temperature is a traced value, so _warp_scores' scalar
        # temperature short-circuit can't apply — divide by a safe temp here,
        # then reuse _warp_scores (at T=1) for the top-k/top-p chain so the
        # masking semantics can never diverge from generate()'s. top_k/top_p
        # stay engine-global (static).
        safe_t = jnp.where(temps > 0.0, temps, 1.0)
        scores = _warp_scores(logits.astype(jnp.float32) / safe_t[:, None],
                              1.0, self.top_k, self.top_p)

        def one(lg, k, n):
            return jax.random.categorical(jax.random.fold_in(k, n), lg).astype(jnp.int32)

        sampled = jax.vmap(one)(scores, keys, step_idx)
        return jnp.where(temps > 0.0, sampled, greedy)

    # ------------------------------------------------------------- compiled
    def _admit_fn(self, P: int):
        """Compiled prefill of ONE slot's prompt (bucket length P): the whole
        (B, P) chunk runs so shapes stay request-independent; rows other than
        the target slot carry a zero attention mask, so their kv_mask stays
        invalid for the written block automatically. Keyed on (P, prefix
        columns): with a shared prefix, eviction spares the prefix region and
        token positions start past the prefix."""
        pfx = self._pfx
        if (P, pfx) in self._admit_fns:
            return self._admit_fns[(P, pfx)]
        module = self.module
        pad = self.pad

        def run(params, cache, state, slot, prompt_row, mask_row, rid, base_rng,
                req_max, req_temp, req_eos):
            (tok, pos, n_out, active, out_buf, keys,
             slot_max, slot_temp, slot_eos) = state
            B = tok.shape[0]
            # evict the slot's previous occupant: its KV must stop being
            # attendable before the new prompt writes into the same row —
            # but the shared-prefix columns stay valid for every occupant
            cache = {**cache, "kv_mask": cache["kv_mask"].at[slot, pfx:].set(0)}
            ids = jnp.zeros((B, P), jnp.int32).at[slot].set(prompt_row)
            mask = jnp.zeros((B, P), jnp.int32).at[slot].set(mask_row)
            out = module.apply(params, input_ids=ids, attention_mask=mask,
                               cache=cache, positions=mask_positions(mask) + pfx)
            real_len = jnp.sum(mask_row).astype(jnp.int32) + pfx
            key = jax.random.fold_in(base_rng, rid)  # the request's own stream
            keys = keys.at[slot].set(key)
            slot_max = slot_max.at[slot].set(req_max)
            slot_temp = slot_temp.at[slot].set(req_temp)
            slot_eos = slot_eos.at[slot].set(req_eos)
            first = self._sample_rows(
                out["logits"][slot, -1][None], key[None],
                jnp.zeros((1,), jnp.int32), req_temp[None],
            )[0]
            tok = tok.at[slot].set(first)
            pos = pos.at[slot].set(real_len)
            n_out = n_out.at[slot].set(1)
            # even an immediate eos is emitted (HF convention); the slot stays
            # active only if there is room and the first token wasn't eos
            out_buf = out_buf.at[slot].set(jnp.full((self.max_new,), pad, jnp.int32))
            out_buf = out_buf.at[slot, 0].set(first)
            done0 = (first == req_eos) | (req_max <= 1)
            active = active.at[slot].set(~done0)
            state = (tok, pos, n_out, active, out_buf, keys,
                     slot_max, slot_temp, slot_eos)
            return out["cache"], state, done0

        fn = jax.jit(run, donate_argnums=safe_donate_argnums((1, 2)))
        self._admit_fns[(P, pfx)] = fn
        return fn

    # ------------------------------------------------------- compiled (paged)
    def _paged_view_cache(self, pool, tables, lens, write_cols: int):
        """Gather every slot's block chain into a contiguous view cache plus a
        fresh ``write_cols``-wide write window at one uniform offset — the
        shape that lets the unmodified model forward (one global write
        offset, hole-tolerant kv_mask, positions channel) run over paged
        storage. The frontier comparison masks stale bits of reused
        (freed→reallocated) blocks, so the free-list never needs device-side
        scrubbing."""
        bs = self.block_size
        t = self.max_blocks_per_slot * bs
        # Registry-dispatched assembly (op `paged_gather`): the Pallas
        # chain-walk kernel skips slots with an empty chain (bucket padding /
        # drained slots — their view rows are masked garbage on the reference
        # path and zeros on the kernel path; attention provably ignores both).
        active = lens > 0
        # int8 pools (kv_quant) dequantize HERE, at view assembly: the Pallas
        # gather kernel folds the per-token rescale into its DMA-to-VMEM step
        # (ops/pallas/paged_decode.py), the reference path multiplies after
        # the gather — bit-identical either way (the registry parity seam).
        scales_k = pool.get("k_scale")
        scales_v = pool.get("v_scale")
        out_dt = self.cache_dtype if scales_k is not None else None
        view_k = gather_view(pool["k"], tables, active=active, scales=scales_k,
                             out_dtype=out_dt,
                             backend=self.kernels)      # (L, B, T, Hkv, D)
        view_v = gather_view(pool["v"], tables, active=active, scales=scales_v,
                             out_dtype=out_dt,
                             backend=self.kernels)
        vmask = gather_block_mask(pool["mask"], tables)  # (B, T)
        b = vmask.shape[0]
        vmask = jnp.where(jnp.arange(t)[None] < lens[:, None], vmask, 0)
        zeros = jnp.zeros(view_k.shape[:2] + (write_cols,) + view_k.shape[3:],
                          view_k.dtype)
        return {
            "k": jnp.concatenate([view_k, zeros], axis=2),
            "v": jnp.concatenate([view_v, zeros], axis=2),
            "pos": jnp.int32(t),
            "kv_mask": jnp.concatenate(
                [vmask, jnp.zeros((b, write_cols), jnp.int32)], axis=1
            ),
        }

    def _scatter_pool(self, pool, blk, off, k_new, v_new, mask_new):
        """Append freshly written view columns onto chain tails — the single
        pool write point shared by the chunk / decode-window / spec-verify
        programs. An int8 pool (``kv_quant``) quantizes the written rows here,
        one (int8 payload, f32 scale) pair per token row (ops/int8.quantize_kv
        — a committed row is never rescaled, which is what lets blocks fill
        incrementally), and dequantizes at view assembly, so the quantization
        seam is invisible to the model forward."""
        if "k_scale" in pool:
            qk, sk = quantize_kv(k_new)
            qv, sv = quantize_kv(v_new)
            return {
                "k": pool["k"].at[:, blk, off].set(qk),
                "v": pool["v"].at[:, blk, off].set(qv),
                "k_scale": pool["k_scale"].at[:, blk, off].set(sk),
                "v_scale": pool["v_scale"].at[:, blk, off].set(sv),
                "mask": pool["mask"].at[blk, off].set(mask_new),
            }
        return {
            "k": pool["k"].at[:, blk, off].set(k_new),
            "v": pool["v"].at[:, blk, off].set(v_new),
            "mask": pool["mask"].at[blk, off].set(mask_new),
        }

    def _chunk_fn(self, P: int):
        """Compiled prefill of ONE ``P``-token chunk of one slot's prompt
        against the paged pool: gather the slot chains, run the whole (B, P)
        chunk (shapes stay request-independent — rows other than the target
        slot ride along masked), scatter the target slot's written columns
        onto its chain tail, and on the FINAL chunk sample the request's
        first token and arm the slot for decode. One program per chunk
        bucket, shared by mid-prompt and final chunks (``is_final`` is a
        traced scalar; the state writes are harmless for mid chunks — the
        slot stays inactive and the final chunk rewrites them)."""
        if P in self._chunk_fns:
            return self._chunk_fns[P]
        module = self.module
        d_module = self._draft_module
        pad = self.pad
        bs = self.block_size
        t = self.max_blocks_per_slot * bs
        spec = bool(self.speculative_k)

        def body(params, pool, state, tables, lens, slot, chunk_row, mask_row,
                 base_pos, is_final, rid, base_rng, req_max, req_temp, req_eos,
                 d_params=None, d_pool=None):
            (tok, pos, n_out, active, out_buf, keys,
             slot_max, slot_temp, slot_eos) = state
            B = tok.shape[0]
            cache = self._paged_view_cache(pool, tables, lens, P)
            ids = jnp.zeros((B, P), jnp.int32).at[slot].set(chunk_row)
            mask = jnp.zeros((B, P), jnp.int32).at[slot].set(mask_row)
            # Token positions continue the slot's REAL-token count (holes
            # from bucket padding never shift positions), so rope/wpe are
            # exact across chunk boundaries and identical to a monolithic
            # prefill of the same prompt.
            out = module.apply(params, input_ids=ids, attention_mask=mask,
                               cache=cache, positions=mask_positions(mask) + base_pos)
            idx = lens[slot] + jnp.arange(P)
            blk = tables[slot][idx // bs]
            off = idx % bs
            pool = self._scatter_pool(
                pool, blk, off,
                out["cache"]["k"][:, slot, t:t + P],
                out["cache"]["v"][:, slot, t:t + P],
                jnp.where(blk != 0, mask_row, 0),
            )
            if spec:
                # Speculative mode: the draft model prefills the SAME chunk
                # into its mirrored pool inside this program, so every
                # resident chain (including aliased shared-prefix blocks,
                # which are written exactly once, here) carries draft KV by
                # the time the first verify round needs it.
                d_cache = self._paged_view_cache(d_pool, tables, lens, P)
                d_out = d_module.apply(
                    d_params, input_ids=ids, attention_mask=mask, cache=d_cache,
                    positions=mask_positions(mask) + base_pos)
                d_pool = self._scatter_pool(
                    d_pool, blk, off,
                    d_out["cache"]["k"][:, slot, t:t + P],
                    d_out["cache"]["v"][:, slot, t:t + P],
                    jnp.where(blk != 0, mask_row, 0),
                )
            real = jnp.sum(mask_row).astype(jnp.int32)
            key = jax.random.fold_in(base_rng, rid)  # the request's own stream
            keys = keys.at[slot].set(key)
            slot_max = slot_max.at[slot].set(req_max)
            slot_temp = slot_temp.at[slot].set(req_temp)
            slot_eos = slot_eos.at[slot].set(req_eos)
            first = self._sample_rows(
                out["logits"][slot, -1][None], key[None],
                jnp.zeros((1,), jnp.int32), req_temp[None],
            )[0]
            tok = tok.at[slot].set(first)
            pos = pos.at[slot].set(base_pos + real)
            n_out = n_out.at[slot].set(1)
            out_buf = out_buf.at[slot].set(jnp.full((self.max_new,), pad, jnp.int32))
            out_buf = out_buf.at[slot, 0].set(first)
            done0 = (first == req_eos) | (req_max <= 1)
            active = active.at[slot].set(is_final & ~done0)
            state = (tok, pos, n_out, active, out_buf, keys,
                     slot_max, slot_temp, slot_eos)
            if spec:
                return pool, d_pool, state
            return pool, state

        if spec:
            def run(params, d_params, pool, d_pool, state, tables, lens, slot,
                    chunk_row, mask_row, base_pos, is_final, rid, base_rng,
                    req_max, req_temp, req_eos):
                return body(params, pool, state, tables, lens, slot, chunk_row,
                            mask_row, base_pos, is_final, rid, base_rng,
                            req_max, req_temp, req_eos, d_params, d_pool)

            donations = (2, 3, 4)
            donated_leaves = (
                len(jax.tree_util.tree_leaves(self._pool))
                + len(jax.tree_util.tree_leaves(self._draft_pool))
                + len(jax.tree_util.tree_leaves(self._state_tuple()))
            )
        else:
            def run(params, pool, state, tables, lens, slot, chunk_row,
                    mask_row, base_pos, is_final, rid, base_rng, req_max,
                    req_temp, req_eos):
                return body(params, pool, state, tables, lens, slot, chunk_row,
                            mask_row, base_pos, is_final, rid, base_rng,
                            req_max, req_temp, req_eos)

            donations = (1, 2)
            donated_leaves = len(jax.tree_util.tree_leaves(self._pool)) + len(
                jax.tree_util.tree_leaves(self._state_tuple())
            )
        effective_donate = safe_donate_argnums(donations)
        fn = jax.jit(run, donate_argnums=effective_donate)
        param_leaves = jax.tree_util.tree_leaves(self.params)
        from .ops.registry import resolved_backends

        # The prefill-tier analog of the decode window's audit metadata: a
        # prefill-ONLY host (serving_net roles) never builds the decode
        # program, so memcheck --serving --serving-role prefill and the
        # `prefill_paged` fingerprint golden price/pin THIS program instead.
        memory_classes = {
            "kv_pool": (lambda: self._pool, lambda: None),
            "params": (lambda: self.params, lambda: None),
        }
        if spec:
            memory_classes["draft_pool"] = (lambda: self._draft_pool, lambda: None)
            memory_classes["draft_params"] = (lambda: self._draft_params, lambda: None)
        fn._audit_meta = {
            "builder": "serving_prefill_chunk",
            "compute_dtype": (
                str(np.dtype(param_leaves[0].dtype).name) if param_leaves else None
            ),
            "expected_donations": donations,
            "expected_donated_leaves": donated_leaves,
            "donation_dropped_by_policy": not effective_donate,
            "kernels": {"spec": self.kernels,
                        "backends": resolved_backends(self.kernels)},
            "jaxpr_thunk": lambda *a, **k: jax.make_jaxpr(run)(*a, **k),
            "memory_classes": memory_classes,
        }
        self._chunk_fns[P] = fn
        return fn

    def _decode_paged(self):
        """Compiled ``sync_every``-token window over block tables: ONE gather
        of every slot's chain (the reference block-table lowering —
        ops/paged_attention.py), a ``lax.scan`` of decode steps writing into
        a uniform view window, then one scatter of the written columns onto
        each committed slot's chain tail. Returns ``(pool, state, report)``
        where ``report`` is an optimization-barrier'd (active, n_out,
        out_buf) copy the host can read AFTER donating ``state`` to the next
        window — the one-window-lookahead handle that makes the steady-state
        engine loop's sync non-blocking."""
        if self._decode_fn is not None:
            return self._decode_fn
        module = self.module
        pad = self.pad
        bs = self.block_size
        t = self.max_blocks_per_slot * bs
        w = self.sync_every

        def run(params, pool, tables, lens, commit, force_stop, state):
            (tok, pos, n_out, active, out_buf, keys,
             slot_max, slot_temp, slot_eos) = state
            B = tok.shape[0]
            # Host-side stop-sequence verdicts from the previous window's
            # report land here (the paged analog of the contiguous loop's
            # in-place active flip).
            active = active & ~force_stop
            state = (tok, pos, n_out, active, out_buf, keys,
                     slot_max, slot_temp, slot_eos)
            cache = self._paged_view_cache(pool, tables, lens, w)

            def one_step(carry, _):
                cache, state = carry
                (tok, pos, n_out, active, out_buf, keys,
                 slot_max, slot_temp, slot_eos) = state
                col = cache["pos"]  # view column this step writes
                feed = jnp.where(active, tok, pad)
                out = module.apply(params, input_ids=feed[:, None], cache=cache,
                                   positions=pos[:, None])
                nxt = self._sample_rows(out["logits"][:, -1], keys, n_out, slot_temp)
                nxt = jnp.where(active, nxt, pad)
                cache2 = out["cache"]
                cache2 = {
                    **cache2,
                    "kv_mask": cache2["kv_mask"].at[:, col].set(
                        jnp.where(active, cache2["kv_mask"][:, col], 0)
                    ),
                }
                emit_idx = jnp.clip(n_out, 0, self.max_new - 1)
                cur = out_buf[jnp.arange(B), emit_idx]
                out_buf = out_buf.at[jnp.arange(B), emit_idx].set(
                    jnp.where(active, nxt, cur)
                )
                n_out = n_out + active.astype(jnp.int32)
                still = active & (nxt != slot_eos) & (n_out < slot_max)
                state = (nxt, pos + 1, n_out, still, out_buf, keys,
                         slot_max, slot_temp, slot_eos)
                return (cache2, state), None

            (cache, state), _ = jax.lax.scan(one_step, (cache, state), None, length=w)
            # Persist the window: committed slots append their written view
            # columns (valid or holed — the per-slot chain mirrors the
            # contiguous scheme's unconditional global advance); everything
            # else lands in the trash block with a forced-zero mask, so
            # block 0 is provably never attendable.
            idx = lens[:, None] + jnp.arange(w)[None]
            blk = jnp.where(
                commit[:, None],
                jnp.take_along_axis(tables, (idx // bs).astype(jnp.int32), axis=1),
                0,
            )
            off = (idx % bs).astype(jnp.int32)
            wm = cache["kv_mask"][:, t:t + w]
            pool = self._scatter_pool(
                pool, blk, off, cache["k"][:, :, t:t + w],
                cache["v"][:, :, t:t + w], jnp.where(blk != 0, wm, 0),
            )
            report = jax.lax.optimization_barrier((state[3], state[2], state[4]))
            return pool, state, report

        effective_donate = safe_donate_argnums((1, 6))
        self._decode_fn = jax.jit(run, donate_argnums=effective_donate)
        donated_leaves = len(jax.tree_util.tree_leaves(self._pool)) + len(
            jax.tree_util.tree_leaves(self._state_tuple())
        )
        param_leaves = jax.tree_util.tree_leaves(self.params)
        compute_dtype = (
            str(np.dtype(param_leaves[0].dtype).name) if param_leaves else None
        )
        from .ops.registry import resolved_backends

        self._decode_fn._audit_meta = {
            "builder": "serving_decode_paged",
            "compute_dtype": compute_dtype,
            "expected_donations": (1, 6),
            "expected_donated_leaves": donated_leaves,
            "donation_dropped_by_policy": not effective_donate,
            # Which kernel backend each registered op resolved to at build
            # time, so audits/fingerprints record the engine's kernel config
            # (the paged path dispatches `paged_gather`), plus a jaxpr thunk
            # so the auditor's pallas_call inventory sees the kernel eqns
            # pre-partitioning.
            "kernels": {"spec": self.kernels,
                        "backends": resolved_backends(self.kernels)},
            "jaxpr_thunk": lambda *a, **k: jax.make_jaxpr(run)(*a, **k),
            # The static-memory join for `accelerate-tpu memcheck --serving`:
            # the persistent pool is the class the per-device KV budget gate
            # prices (the gathered view + write window land in XLA's temp
            # workspace via memory_analysis, not here).
            "memory_classes": {
                "kv_pool": (lambda: self._pool, lambda: None),
                "params": (lambda: self.params, lambda: None),
            },
        }
        return self._decode_fn

    def _spec_verify(self):
        """Compiled speculative verify round (``speculative_k`` = k > 0): ONE
        program that (1) runs k+1 greedy single-token draft steps over the
        draft pool's chain view — the tokens it FEEDS are exactly
        ``[current_token, d_0 .. d_{k-1}]``, so after the scan the draft
        cache holds KV for every window column — then (2) verifies all k
        proposals in ONE target forward over a (k+1)-token window (the
        chunked-prefill multi-token machinery), sampling the target's choice
        at every position with the SAME per-request stream indices
        (``fold_in(key, n_out + j)``) the plain decode window would use.

        Acceptance is the longest matched prefix of (choices, drafts); the
        fix-up token at the first mismatch is the target's own choice, so for
        every EMITTED position the logits are conditioned on exactly the
        tokens the non-speculative engine would have fed — greedy output is
        bit-identical to non-speculative BY CONSTRUCTION, and sampled output
        stays traffic-independent (tests/test_speculative.py pins both).

        Rejection is block-table truncation, the same surgery compaction
        uses: rejected window columns' writes land in the trash block with a
        zero mask and the host simply does not advance the chain frontier
        past them — no device scrub. Returns ``(pool, d_pool, state,
        produced, report)``: ``produced`` (tokens committed per slot, current
        + accepted drafts) is fetched eagerly — the one blocking (B,)
        readback a verify round pays for k-fold fewer target passes —
        while ``report`` is the usual barrier'd (active, n_out, out_buf)
        handle processed one round late."""
        if self._verify_fn is not None:
            return self._verify_fn
        module = self.module
        d_module = self._draft_module
        pad = self.pad
        bs = self.block_size
        t = self.max_blocks_per_slot * bs
        k = self.speculative_k
        S = k + 1

        def run(params, d_params, pool, d_pool, tables, lens, commit,
                force_stop, state):
            (tok, pos, n_out, active, out_buf, keys,
             slot_max, slot_temp, slot_eos) = state
            B = tok.shape[0]
            active = active & ~force_stop & commit
            # --- draft leg: k+1 greedy steps. The last proposal (fed
            # nothing) is discarded, but feeding k+1 steps means the last
            # ACCEPTED draft token's draft-KV is written too — without it a
            # fully-accepted round would leave the draft chain one column
            # short of the target chain.
            d_cache = self._paged_view_cache(d_pool, tables, lens, S)

            def d_step(carry, _):
                d_cache, d_tok, d_pos = carry
                feed = jnp.where(active, d_tok, pad)
                d_out = d_module.apply(d_params, input_ids=feed[:, None],
                                       cache=d_cache, positions=d_pos[:, None])
                nxt = jnp.argmax(d_out["logits"][:, -1], axis=-1).astype(jnp.int32)
                return (d_out["cache"], nxt, d_pos + 1), feed

            (d_cache, _, _), fed = jax.lax.scan(
                d_step, (d_cache, tok, pos), None, length=S
            )
            ids = fed.T  # (B, S): [cur, d_0 .. d_{k-1}] per row
            # --- target leg: ONE forward over the whole window.
            cache = self._paged_view_cache(pool, tables, lens, S)
            mask = jnp.broadcast_to(active[:, None], (B, S)).astype(jnp.int32)
            out = module.apply(
                params, input_ids=jnp.where(active[:, None], ids, pad),
                attention_mask=mask, cache=cache,
                positions=pos[:, None] + jnp.arange(S)[None],
            )
            choices = jnp.stack(
                [self._sample_rows(out["logits"][:, j], keys, n_out + j, slot_temp)
                 for j in range(S)], axis=1)            # (B, S)
            # --- acceptance: longest matched prefix; position j (if emitted)
            # emits choices[:, j]. n_acc = index of first mismatch (k when
            # every draft matched), so positions 0..n_acc are emittable.
            match = choices[:, :k] == ids[:, 1:]        # (B, k)
            n_acc = jnp.argmin(
                jnp.concatenate([match, jnp.zeros((B, 1), bool)], axis=1)
                .astype(jnp.int32), axis=1)
            j_idx = jnp.arange(S)[None]
            noteos = choices != slot_eos[:, None]
            # Every emission cutoff (mismatch, per-request length, prior eos)
            # is monotone in j, so the emit mask is a per-row prefix and
            # `produced` is its length (>= 1 for active rows: position 0 is
            # the non-spec step the window subsumes).
            prior_ok = jnp.concatenate(
                [jnp.ones((B, 1), bool),
                 jnp.cumprod(noteos[:, :-1].astype(jnp.int32), axis=1).astype(bool)],
                axis=1)
            em = (active[:, None] & (j_idx <= n_acc[:, None])
                  & (n_out[:, None] + j_idx < slot_max[:, None]) & prior_ok)
            produced = jnp.sum(em.astype(jnp.int32), axis=1)  # (B,)
            rows = jnp.arange(B)
            for j in range(S):
                emit_idx = jnp.clip(n_out + j, 0, self.max_new - 1)
                cur_v = out_buf[rows, emit_idx]
                out_buf = out_buf.at[rows, emit_idx].set(
                    jnp.where(em[:, j], choices[:, j], cur_v))
            n_out2 = n_out + produced
            last = choices[rows, jnp.clip(produced - 1, 0, S - 1)]
            tok2 = jnp.where(produced > 0, last, tok)
            eos_hit = jnp.any(em & ~noteos, axis=1)
            still = active & ~eos_hit & (n_out2 < slot_max)
            state = (tok2, pos + produced, n_out2, still, out_buf, keys,
                     slot_max, slot_temp, slot_eos)
            # --- commit: window column j holds the KV of INPUT token j (cur
            # at j=0, accepted draft = emitted choice after). Exactly the
            # first `produced` columns belong to the final sequence — the
            # round's last emitted choice becomes the next current token,
            # whose KV is written next round — so everything past them never
            # commits (trash block, zero mask): rejection without a scrub.
            idx = lens[:, None] + jnp.arange(S)[None]
            wvalid = active[:, None] & (jnp.arange(S)[None] < produced[:, None])
            blk = jnp.where(
                wvalid,
                jnp.take_along_axis(
                    tables,
                    jnp.clip(idx // bs, 0, tables.shape[1] - 1).astype(jnp.int32),
                    axis=1),
                0)
            off = (idx % bs).astype(jnp.int32)
            vcache = out["cache"]
            pool = self._scatter_pool(
                pool, blk, off, vcache["k"][:, :, t:t + S],
                vcache["v"][:, :, t:t + S],
                jnp.where(blk != 0, vcache["kv_mask"][:, t:t + S], 0),
            )
            d_pool = self._scatter_pool(
                d_pool, blk, off, d_cache["k"][:, :, t:t + S],
                d_cache["v"][:, :, t:t + S],
                jnp.where(blk != 0, d_cache["kv_mask"][:, t:t + S], 0),
            )
            report = jax.lax.optimization_barrier((state[3], state[2], state[4]))
            return pool, d_pool, state, produced, report

        effective_donate = safe_donate_argnums((2, 3, 8))
        self._verify_fn = jax.jit(run, donate_argnums=effective_donate)
        donated_leaves = (
            len(jax.tree_util.tree_leaves(self._pool))
            + len(jax.tree_util.tree_leaves(self._draft_pool))
            + len(jax.tree_util.tree_leaves(self._state_tuple()))
        )
        param_leaves = jax.tree_util.tree_leaves(self.params)
        from .ops.registry import resolved_backends

        self._verify_fn._audit_meta = {
            "builder": "serving_spec_verify",
            "compute_dtype": (
                str(np.dtype(param_leaves[0].dtype).name) if param_leaves else None
            ),
            "expected_donations": (2, 3, 8),
            "expected_donated_leaves": donated_leaves,
            "donation_dropped_by_policy": not effective_donate,
            "kernels": {"spec": self.kernels,
                        "backends": resolved_backends(self.kernels)},
            "jaxpr_thunk": lambda *a, **kw: jax.make_jaxpr(run)(*a, **kw),
            "memory_classes": {
                "kv_pool": (lambda: self._pool, lambda: None),
                "draft_pool": (lambda: self._draft_pool, lambda: None),
                "params": (lambda: self.params, lambda: None),
                "draft_params": (lambda: self._draft_params, lambda: None),
            },
        }
        return self._verify_fn

    def _decode(self):
        """Compiled ``sync_every``-token window for all B slots — ONE program
        dispatch per host check (a ``lax.scan`` over steps), so neither local
        dispatch overhead nor a remote tunnel's per-call RTT is paid per
        token. Inactive rows feed pads and their freshly written cache
        columns are invalidated."""
        if self.paged:
            return self._decode_paged()
        if self._decode_fn is not None:
            return self._decode_fn
        module = self.module
        pad = self.pad

        def run(params, cache, state):
            def one_step(carry, _):
                cache, state = carry
                (tok, pos, n_out, active, out_buf, keys,
                 slot_max, slot_temp, slot_eos) = state
                B = tok.shape[0]
                col = cache["pos"]  # global slot this step writes
                feed = jnp.where(active, tok, pad)
                out = module.apply(params, input_ids=feed[:, None], cache=cache,
                                   positions=pos[:, None])
                nxt = self._sample_rows(out["logits"][:, -1], keys, n_out, slot_temp)
                nxt = jnp.where(active, nxt, pad)
                cache2 = out["cache"]
                # hole out the column for rows that didn't produce a token
                cache2 = {
                    **cache2,
                    "kv_mask": cache2["kv_mask"].at[:, col].set(
                        jnp.where(active, cache2["kv_mask"][:, col], 0)
                    ),
                }
                emit_idx = jnp.clip(n_out, 0, self.max_new - 1)
                cur = out_buf[jnp.arange(B), emit_idx]
                out_buf = out_buf.at[jnp.arange(B), emit_idx].set(
                    jnp.where(active, nxt, cur)
                )
                n_out = n_out + active.astype(jnp.int32)
                still = active & (nxt != slot_eos) & (n_out < slot_max)
                state = (nxt, pos + 1, n_out, still, out_buf, keys,
                         slot_max, slot_temp, slot_eos)
                return (cache2, state), None

            (cache, state), _ = jax.lax.scan(
                one_step, (cache, state), None, length=self.sync_every
            )
            return cache, state

        # Donating cache+state halves the live KV footprint (the cache is the
        # engine's dominant allocation and is dead after each window).
        effective_donate = safe_donate_argnums((1, 2))
        self._decode_fn = jax.jit(run, donate_argnums=effective_donate)
        # Builder metadata for the auditor/fingerprint (the serving analog of
        # Accelerator._builder_audit_meta): the donation contract over
        # cache+state and the params' compute dtype. Leaf counts read the
        # LIVE cache/state fields, whose structure is fixed at __init__.
        donated_leaves = len(jax.tree_util.tree_leaves(self._cache)) + len(
            jax.tree_util.tree_leaves(self._state_tuple())
        )
        param_leaves = jax.tree_util.tree_leaves(self.params)
        compute_dtype = (
            str(np.dtype(param_leaves[0].dtype).name) if param_leaves else None
        )
        self._decode_fn._audit_meta = {
            "builder": "serving_decode",
            "compute_dtype": compute_dtype,
            "expected_donations": (1, 2),
            "expected_donated_leaves": donated_leaves,
            "donation_dropped_by_policy": not effective_donate,
        }
        return self._decode_fn

    # ---------------------------------------------------------------- audit
    def _state_tuple(self):
        return (self._tok, self._pos, self._n_out, self._active, self._out_buf,
                self._keys, self._slot_max, self._slot_temp, self._slot_eos)

    def _decode_args(self):
        """The decode program's full argument tuple against the engine's
        CURRENT cache/state — what audit_decode/fingerprint_decode lower
        with. Program contracts are value-independent, so live host
        bookkeeping values are fine."""
        if self.paged:
            return (
                self.params, self._pool, jnp.asarray(self._tables_np),
                jnp.asarray(self._slot_len, dtype=jnp.int32),
                jnp.asarray([m == "decode" for m in self._slot_mode]),
                jnp.zeros((self.B,), bool), self._state_tuple(),
            )
        return (self.params, self._cache, self._state_tuple())

    def audit_decode(self, **kwargs):
        """Statically audit the compiled ``sync_every``-token decode window
        (analysis/audit.py) against the engine's current cache/state:
        collective inventory, donation aliasing (cache+state are donated —
        the KV-footprint halving must actually alias), host callbacks.
        Lowers and compiles but never decodes a token."""
        from .analysis import audit_built

        return audit_built(self._decode(), *self._decode_args(), **kwargs)

    def fingerprint_decode(self, config: str = "decode", **kwargs):
        """Canonical :class:`~.analysis.fingerprint.ProgramFingerprint` of
        the compiled decode window — the serving entry in the drift-gate
        matrix (``accelerate-tpu fingerprint``). Lowers and compiles but
        never decodes a token."""
        from .analysis.fingerprint import fingerprint_built

        return fingerprint_built(
            self._decode(), *self._decode_args(), config=config, **kwargs
        )

    def _verify_args(self):
        """The spec-verify program's full argument tuple against the engine's
        current pools/state (value-independent, like ``_decode_args``)."""
        if not self.speculative_k:
            raise ValueError(
                "the spec-verify program exists only with speculative_k > 0"
            )
        return (
            self.params, self._draft_params, self._pool, self._draft_pool,
            jnp.asarray(self._tables_np),
            jnp.asarray(self._slot_len, dtype=jnp.int32),
            jnp.asarray([m == "decode" for m in self._slot_mode]),
            jnp.zeros((self.B,), bool), self._state_tuple(),
        )

    def audit_verify(self, **kwargs):
        """Statically audit the compiled speculative verify round (donation
        aliasing over both pools + state, kernel inventory, memory classes).
        Lowers and compiles but never decodes a token."""
        from .analysis import audit_built

        return audit_built(self._spec_verify(), *self._verify_args(), **kwargs)

    def fingerprint_verify(self, config: str = "spec_verify", **kwargs):
        """Canonical fingerprint of the compiled speculative verify round —
        the spec-decoding entry in the drift-gate matrix (a silently vanished
        draft leg or dequant seam classifies as violation). Lowers and
        compiles but never decodes a token."""
        from .analysis.fingerprint import fingerprint_built

        return fingerprint_built(
            self._spec_verify(), *self._verify_args(), config=config, **kwargs
        )

    def _chunk_args(self, P: int):
        """The ``P``-token chunk program's full argument tuple against the
        engine's current pool/state — what the prefill-tier audit/fingerprint
        lower with (value-independent, like ``_decode_args``)."""
        if not self.paged:
            raise ValueError("the chunk program exists only in paged mode")
        tail = (
            jnp.asarray(self._tables_np),
            jnp.asarray(self._slot_len, dtype=jnp.int32), jnp.int32(0),
            jnp.zeros((P,), jnp.int32), jnp.ones((P,), jnp.int32),
            jnp.int32(0), jnp.asarray(True), jnp.int32(0), self._rng,
            jnp.int32(self.max_new), jnp.float32(0.0), jnp.int32(self.eos),
        )
        if self.speculative_k:
            return (self.params, self._draft_params, self._pool,
                    self._draft_pool, self._state_tuple()) + tail
        return (self.params, self._pool, self._state_tuple()) + tail

    def fingerprint_prefill(self, config: str = "prefill_paged", **kwargs):
        """Canonical fingerprint of the compiled ``prefill_chunk``-token
        prefill program — the prefill-ONLY tier's entry in the drift-gate
        matrix (a disaggregated prefill host never runs the decode window,
        so the decode golden cannot cover its program contract). Lowers and
        compiles but never prefills a token."""
        from .analysis.fingerprint import fingerprint_built

        P = self.prefill_chunk
        return fingerprint_built(
            self._chunk_fn(P), *self._chunk_args(P), config=config, **kwargs
        )

    # ----------------------------------------------------------------- loop
    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise AssertionError  # guarded in submit()

    def _finish(self, req: _Request, row: np.ndarray):
        """Bank one finished request's output (shared by both cache modes):
        exact eos/stop truncation — tokens decoded past the stop (host scan
        lags by the sync cadence) are discarded, so output is
        cadence-independent — plus completion counters and the TTFT/TPOT
        histogram observations."""
        row = row.copy()
        if req.eos >= 0 and (row == req.eos).any():
            row = row[: int(np.argmax(row == req.eos)) + 1]
        end = _first_stop_end(row, req.stop)
        if end is not None:
            row = row[:end]
        self._results[req.rid] = row
        times = self._req_times.get(req.rid)
        if times is not None:
            times["finish"] = time.monotonic()
            ft = times.get("first_token")
            if ft is not None:
                ttft_hist, tpot_hist = _slo_metrics()[:2]
                ttft_hist.observe(max(0.0, ft - times["submit"]))
                if row.size > 1:
                    times["tpot"] = (times["finish"] - ft) / (row.size - 1)
                    tpot_hist.observe(max(0.0, times["tpot"]))
        _, completed, tokens = _serving_counters()
        completed.inc()
        tokens.inc(int(row.size))
        if self.tracer is not None:
            self.tracer.finish(
                req.rid, int(row.size),
                tpot_s=(times or {}).get("tpot"),
                at=(times or {}).get("finish"),
            )
        if self.stream is not None:
            self._streamed.pop(req.rid, None)
            self._emit_stream(req.rid, row, True)

    def _emit_stream(self, rid: int, tokens: np.ndarray, final: bool):
        """Deliver one streaming event best-effort: a broken sink (a client
        that hung up mid-stream) must never take the engine loop down."""
        try:
            self.stream(rid, tokens, final)
        except Exception:
            pass

    def _collect(self, s: int, active_np):
        req = self._slot_req[s]
        if req is None or active_np[s]:
            return
        row = host_fetch(self._out_buf[s])
        n = int(host_fetch(self._n_out[s]))
        self._finish(req, row[:n])
        self._slot_req[s] = None
        self._retired_since_compact = True  # its columns are now reclaimable

    def _sync(self, state):
        (self._tok, self._pos, self._n_out, self._active, self._out_buf,
         self._keys, self._slot_max, self._slot_temp, self._slot_eos) = state

    # ------------------------------------------------------------ paged loop
    def _alias_lookup(self, prompt: np.ndarray):
        """Longest resident block chain whose tokens prefix ``prompt``:
        cross-request prefix sharing as refcounted aliasing. Capped one token
        short of the whole prompt so the final token always runs through a
        prefill chunk (its logits seed the first sampled token)."""
        bs = self.block_size
        blocks = []
        for k in range(1, (prompt.size - 1) // bs + 1):
            blk = self._share_index.get(prompt[: k * bs].tobytes())
            if blk is None:
                break
            blocks.append(blk)
        return blocks

    def prefix_match_tokens(self, prompt_ids) -> int:
        """How many leading tokens of ``prompt_ids`` are already resident in
        this engine's shared-block index — the prefix-cache affinity answer
        behind GET /v1/prefixes (serving_net: the router sends each worker a
        prompt's chain prefix and routes to the longest match, so cache-hit
        routing is a host-side lookup, never a device touch). A configured
        shared prefix counts exactly as submit() would prepend it."""
        if not self.paged:
            return 0
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if self._prefix_tokens is not None:
            prompt = np.concatenate([self._prefix_tokens, prompt])
        return len(self._alias_lookup(prompt)) * self.block_size

    def in_flight(self) -> int:
        """Requests queued or occupying a slot — the least-loaded routing
        signal GET /v1/stats publishes (host bookkeeping only)."""
        return len(self._queue) + sum(r is not None for r in self._slot_req)

    def release_request(self, rid: int) -> bool:
        """Retire request ``rid``'s slot and refcount-free its chain —
        host-side bookkeeping only. This is the free half of the handoff
        tiers' free-on-ack discipline (serving_net/handoff.py): an exporter
        keeps the chain resident until the importer acks, then releases it
        here; a failed handoff releases it too, so pool blocks never leak.
        Idempotent — returns False when ``rid`` no longer holds a slot."""
        if not self.paged:
            return False
        s = next(
            (s for s in range(self.B)
             if self._slot_req[s] is not None and self._slot_req[s].rid == rid),
            None,
        )
        if s is None:
            return False
        self._req_times.pop(rid, None)
        self._free_chain(s)
        self._publish_pool_gauges()
        return True

    def _plan_chunks(self, remainder: np.ndarray, chunk_size: int) -> list:
        """Split the un-aliased prompt tail into prefill chunks: exact
        ``chunk_size`` pieces (hole-free, block-aligned — registrable for
        sharing) plus one final ragged piece in (0, chunk_size]."""
        final = (remainder.size - 1) % chunk_size + 1
        n_full = (remainder.size - final) // chunk_size
        return [
            remainder[i * chunk_size:(i + 1) * chunk_size] for i in range(n_full)
        ] + [remainder[n_full * chunk_size:]]

    def _register_shared(self, s: int, c0: int, p: int):
        """After a hole-free block-aligned chunk lands, index its full blocks
        by their chain-prefix tokens so later requests alias them. First
        writer wins: a key already mapping to another chain's block leaves
        this chain's copy private."""
        bs = self.block_size
        if c0 % bs or p % bs:
            return
        toks = self._slot_tokens[s]
        for j in range(p // bs):
            end = c0 + (j + 1) * bs
            blk = self._slot_blocks[s][end // bs - 1]
            key = toks[:end].tobytes()
            if key not in self._share_index:
                self._share_index[key] = blk
                self._block_key[blk] = key

    def _free_chain(self, s: int):
        """Retire slot ``s``'s chain: refcount-decrement every block, return
        rc-0 blocks to the free list (unregistering their share keys). This
        IS paged compaction — block-table surgery instead of the contiguous
        scheme's device-wide gather."""
        for blk in self._slot_blocks[s]:
            self._block_ref[blk] -= 1
            if self._block_ref[blk] == 0:
                self._free_blocks.append(blk)
                key = self._block_key.pop(blk, None)
                if key is not None:
                    self._share_index.pop(key, None)
        self._slot_blocks[s] = []
        self._tables_np[s, :] = 0
        self._slot_len[s] = 0
        self._slot_base[s] = 0
        self._slot_tokens[s] = None
        self._slot_req[s] = None
        self._slot_chunks[s] = []
        self._slot_mode[s] = "free"

    def _log_dispatch(self, event: str):
        self._dispatch_log.append(event)
        if len(self._dispatch_log) > 2 * _SLO_HISTORY:
            del self._dispatch_log[:_SLO_HISTORY]

    def _publish_pool_gauges(self):
        if not self.paged:
            return
        _, _, free_gauge, util_gauge = _slo_metrics()
        free_gauge.set(float(len(self._free_blocks)))
        util_gauge.set(1.0 - len(self._free_blocks) / max(1, self.num_blocks))

    def _admit_paged(self, now: float):
        """Fill free slots from the queue: alias resident prefix blocks,
        reserve the WHOLE request's worst-case chain up front (prompt chunks
        with bucket padding + max_new - 1 decode slots + 3 windows of
        finish-detection slack), and stage the chunk plan. Up-front
        reservation makes admission the only capacity decision point — decode
        windows can never strand mid-request."""
        free_slots = [s for s in range(self.B) if self._slot_mode[s] == "free"]
        bs = self.block_size
        while free_slots and self._queue:
            req = self._queue[0]
            blocks = self._alias_lookup(req.prompt)
            k = len(blocks)
            remainder = req.prompt[k * bs:]
            chunk_size, escalated = self.prefill_chunk, False
            if (
                self.slo is not None and self.slo.ttft_s is not None
                and now - req.submit_t > 0.5 * self.slo.ttft_s
                and self.buckets[-1] > self.prefill_chunk
            ):
                # TTFT at risk: escalate to the biggest chunk the buckets
                # allow — prefill completes in fewer interleave gaps at the
                # cost of larger per-step decode stalls.
                chunk_size, escalated = self.buckets[-1], True
            chunks = self._plan_chunks(remainder, chunk_size)
            aligned = k * bs + sum(
                c.size if i + 1 < len(chunks) else self._bucket(c.size)
                for i, c in enumerate(chunks)
            )
            need = aligned + (req.max_new - 1) + self._decode_slack
            if escalated and need > self.max_blocks_per_slot * bs:
                # Escalation's extra bucket padding would overflow the static
                # table; fall back to the standard chunk plan.
                chunks = self._plan_chunks(remainder, self.prefill_chunk)
                escalated = False
                aligned = k * bs + sum(
                    c.size if i + 1 < len(chunks) else self._bucket(c.size)
                    for i, c in enumerate(chunks)
                )
                need = aligned + (req.max_new - 1) + self._decode_slack
            if need > self.max_blocks_per_slot * bs:
                raise AssertionError(
                    f"internal: chain need {need} exceeds the static table "
                    f"({self.max_blocks_per_slot} x {bs}) — submit() validation out of sync"
                )
            need_blocks = -(-need // bs) - k
            if need_blocks > len(self._free_blocks):
                break  # backpressure; the loop dead-ends loudly if nothing can free
            self._queue.popleft()
            s = free_slots.pop(0)
            fresh = [self._free_blocks.pop(0) for _ in range(need_blocks)]
            chain = blocks + fresh
            for blk in chain:
                self._block_ref[blk] += 1
            self._tables_np[s, :] = 0
            self._tables_np[s, : len(chain)] = chain
            self._slot_blocks[s] = chain
            self._slot_len[s] = k * bs
            self._slot_base[s] = k * bs  # aliased region is all real tokens
            self._slot_chunks[s] = chunks
            self._slot_tokens[s] = req.prompt
            self._slot_req[s] = req
            self._slot_mode[s] = "prefill"
            self._slo_decisions["admitted"] += 1
            self._slo_decisions["aliased_blocks"] += k
            if len(chunks) > 1:
                self._slo_decisions["chunked_prefills"] += 1
            if escalated:
                self._slo_decisions["escalated_monolithic"] += 1
            if self.tracer is not None:
                self.tracer.admit(
                    req.rid, "escalate" if escalated else "admit",
                    aliased_blocks=k, chunks=len(chunks),
                )
            self._peak_consumed_slots = max(
                self._peak_consumed_slots, self.blocks_in_use * bs
            )

    def _pick_chunk_slot(self, now: float, window_pace: float | None):
        """At most ONE prefill chunk interleaves per engine iteration — the
        bounded-decode-stall contract. SLO pacing: while the observed decode
        window pace is over the TPOT budget, prefill defers (decode keeps
        priority) unless the oldest waiting request's TTFT is itself at
        risk — TTFT outranks TPOT on conflict."""
        slots = [
            s for s in range(self.B)
            if self._slot_mode[s] == "prefill" and self._slot_chunks[s]
        ]
        if not slots:
            return None
        slots.sort(key=lambda s: self._slot_req[s].submit_t)
        s = slots[0]
        if (
            self.slo is not None and self.slo.tpot_s is not None
            and window_pace is not None
            and window_pace > self.slo.tpot_s * self.sync_every
            and any(m == "decode" for m in self._slot_mode)
        ):
            ttft_risk = (
                self.slo.ttft_s is not None
                and now - self._slot_req[s].submit_t > 0.5 * self.slo.ttft_s
            )
            if not ttft_risk:
                self._slo_decisions["deferred_prefills"] += 1
                if self.tracer is not None:
                    self.tracer.defer(self._slot_req[s].rid)
                return None
        return s

    def _dispatch_chunk(self, s: int, state):
        chunk = self._slot_chunks[s].pop(0)
        final = not self._slot_chunks[s]
        if final:
            p = self._bucket(int(chunk.size))
            row = np.full((p,), self.pad, np.int32)
            mrow = np.zeros((p,), np.int32)
            row[: chunk.size] = chunk
            mrow[: chunk.size] = 1
            # left-align inside the bucket so the last real token sits at
            # p-1 (its logits row seeds the first sampled token)
            row_j, mrow_j = left_align(row[None], mrow[None])
            row_j, mrow_j = row_j[0], mrow_j[0]
        else:
            p = int(chunk.size)  # exact: hole-free, registrable
            row_j = jnp.asarray(chunk)
            mrow_j = jnp.ones((p,), jnp.int32)
        req = self._slot_req[s]
        c0 = int(self._slot_len[s])
        tail = (
            jnp.asarray(self._tables_np),
            jnp.asarray(self._slot_len, dtype=jnp.int32), jnp.int32(s),
            row_j, mrow_j, jnp.int32(self._slot_base[s]), jnp.asarray(final),
            jnp.int32(req.rid), self._rng, jnp.int32(req.max_new),
            jnp.float32(req.temperature), jnp.int32(req.eos),
        )
        if self.speculative_k:
            self._pool, self._draft_pool, state = self._chunk_fn(p)(
                self.params, self._draft_params, self._pool, self._draft_pool,
                state, *tail,
            )
        else:
            self._pool, state = self._chunk_fn(p)(
                self.params, self._pool, state, *tail,
            )
        self._sync(state)  # instance fields track the LIVE (post-donation) buffers
        self._log_dispatch(f"chunk:{p}")
        if self.tracer is not None:
            self.tracer.prefill_chunk(req.rid, p, final)
        if not final:
            self._register_shared(s, c0, p)
        self._slot_len[s] += p
        self._slot_base[s] += int(chunk.size)
        if final:
            self._slot_mode[s] = "decode"
        return state

    def _dispatch_decode(self, state, force_stop: np.ndarray):
        commit = np.asarray([m == "decode" for m in self._slot_mode], bool)
        window = (self.speculative_k + 1) if self.speculative_k else self.sync_every
        for s in np.nonzero(commit)[0]:
            if self._slot_len[s] + window > len(self._slot_blocks[s]) * self.block_size:
                raise AssertionError(
                    "internal: slot chain reservation exhausted mid-request"
                )
        produced_np = None
        if self.speculative_k:
            (self._pool, self._draft_pool, state, produced,
             report) = self._spec_verify()(
                self.params, self._draft_params, self._pool, self._draft_pool,
                jnp.asarray(self._tables_np),
                jnp.asarray(self._slot_len, dtype=jnp.int32),
                jnp.asarray(commit), jnp.asarray(force_stop), state,
            )
            self._sync(state)
            # The one blocking readback a verify round pays (traded for
            # k-fold fewer target passes): each chain's frontier advances by
            # the slot's COMMITTED count — not advancing past rejected
            # columns IS the block-table truncation.
            produced_np = np.asarray(host_fetch(produced), np.int64)
            self._slot_len += produced_np
            live = produced_np > 0
            proposed = int(live.sum()) * self.speculative_k
            if proposed:
                accepted = int((produced_np[live] - 1).sum())
                self._spec_proposed += proposed
                self._spec_accepted += accepted
                prop_c, acc_c, rate_g = _spec_metrics()
                prop_c.inc(proposed)
                acc_c.inc(accepted)
                rate_g.set(self._spec_accepted / max(1, self._spec_proposed))
            self._log_dispatch(f"verify:{self.speculative_k}")
        else:
            self._pool, state, report = self._decode()(
                self.params, self._pool, jnp.asarray(self._tables_np),
                jnp.asarray(self._slot_len, dtype=jnp.int32), jnp.asarray(commit),
                jnp.asarray(force_stop), state,
            )
            self._sync(state)
            self._slot_len[commit] += self.sync_every
            self._log_dispatch("decode")
        # Tag the report with the occupants it describes: by the time it is
        # processed (one window later), a collected slot may already host a
        # NEW request — its rows in this report belong to the old one.
        req_map = [
            self._slot_req[s].rid if commit[s] and self._slot_req[s] is not None
            else None
            for s in range(self.B)
        ]
        if self.tracer is not None:
            for s, rid in enumerate(req_map):
                if rid is None:
                    continue
                self.tracer.decode_window(rid)
                if produced_np is not None and produced_np[s] > 0:
                    self.tracer.spec_round(
                        rid, proposed=self.speculative_k,
                        accepted=int(produced_np[s] - 1),
                    )
        return state, (report, req_map)

    def _process_report(self, report, force_stop: np.ndarray):
        """Consume one decode window's report (active, n_out, out_buf):
        record first-token times, run the host-side stop-sequence scan
        (verdicts ride ``force_stop`` into the NEXT window), collect finished
        requests, and free their chains. The report was optimization-
        barrier'd out of the donated state, so reading it here — after the
        next window was already dispatched — is the non-blocking sync."""
        report, req_map = report
        active_np = host_fetch(report[0]).copy()
        n_np = host_fetch(report[1])
        out_np = None
        now = time.monotonic()
        for s in range(self.B):
            req = self._slot_req[s]
            if (
                req is None or self._slot_mode[s] != "decode"
                or req_map[s] != req.rid
            ):
                # Slot was empty at dispatch, or has been refilled since —
                # this report's row describes the previous occupant.
                continue
            times = self._req_times.get(req.rid)
            if times is not None and "first_token" not in times and n_np[s] >= 1:
                times["first_token"] = now
                if self.tracer is not None:
                    self.tracer.first_token(req.rid, at=now)
            if self.stream is not None and active_np[s]:
                # Per-window token deltas for the SSE front end, read off the
                # SAME one-window-late report the stop scan and collection
                # already fetch — streaming adds no sync point. Deltas are
                # pre-truncation (a multi-token stop lands one window late,
                # the cadence caveat submit() documents); the FINAL event
                # from _finish carries the authoritative output.
                if out_np is None:
                    out_np = host_fetch(report[2])
                done = self._streamed.get(req.rid, 0)
                n = int(n_np[s])
                if n > done:
                    self._emit_stream(req.rid, out_np[s][done:n].copy(), False)
                    self._streamed[req.rid] = n
            if active_np[s] and req.stop:
                if out_np is None:
                    out_np = host_fetch(report[2])
                if _first_stop_end(out_np[s][: int(n_np[s])], req.stop) is not None:
                    force_stop[s] = True
            if not active_np[s]:
                if out_np is None:
                    out_np = host_fetch(report[2])
                self._finish(req, out_np[s][: int(n_np[s])])
                self._free_chain(s)
        self._publish_pool_gauges()

    def _run_paged(self) -> dict[int, np.ndarray]:
        """The paged engine loop: per iteration, admit; dispatch at most ONE
        prefill chunk; dispatch one decode window; then process the
        PREVIOUS window's report — a one-window lookahead, so the window
        just dispatched overlaps all host work including the report fetch
        (zero blocking transfers in steady state, pinned by tests). Decode
        stall per iteration is bounded by one chunk's compute instead of one
        prompt's — the chunked-prefill contract."""
        state = self._state_tuple()
        pending = None
        force_stop = np.zeros((self.B,), bool)
        last_dispatch_t = None
        window_pace = None
        while True:
            now = time.monotonic()
            self._admit_paged(now)
            chunk_slot = self._pick_chunk_slot(now, window_pace)
            if chunk_slot is not None:
                state = self._dispatch_chunk(chunk_slot, state)
            decoding = any(m == "decode" for m in self._slot_mode)
            new_pending = None
            if decoding:
                state, new_pending = self._dispatch_decode(state, force_stop)
                force_stop[:] = False
                t = time.monotonic()
                if last_dispatch_t is not None:
                    dt = t - last_dispatch_t
                    window_pace = dt if window_pace is None else 0.5 * window_pace + 0.5 * dt
                last_dispatch_t = t
            if pending is not None:
                self._process_report(pending, force_stop)
            pending = new_pending
            if pending is None and chunk_slot is None and not decoding:
                if self._queue:
                    if any(m != "free" for m in self._slot_mode):
                        continue
                    raise RuntimeError(
                        f"KV pool capacity exhausted ({len(self._free_blocks)} of "
                        f"{self.num_blocks} blocks free; the next request needs "
                        "more); raise max_cache_len/num_blocks, or catch this, "
                        "reset(), and run() again."
                    )
                if all(m == "free" for m in self._slot_mode):
                    break
        self._sync(state)
        self._publish_pool_gauges()
        wave, self._results = self._results, {}
        return {rid: wave[rid] for rid in sorted(wave)}

    def run(self) -> dict[int, np.ndarray]:
        """Drive admits + decode until the queue drains and all slots finish.
        Returns THIS wave's results only: {request_id: generated token ids
        (eos included, no pads)} for every request finished during the call."""
        if self.paged:
            return self._run_paged()
        state = (self._tok, self._pos, self._n_out, self._active, self._out_buf,
                 self._keys, self._slot_max, self._slot_temp, self._slot_eos)
        while True:
            self._sync(state)  # _collect reads the instance fields
            # Counted fetch + writable copy: the stop scan flips entries.
            active_np = host_fetch(state[3]).copy()
            # Host-side stop-sequence scan: frees a matched slot at the sync
            # cadence (<= sync_every - 1 steps late; the OUTPUT is truncated
            # exactly in _collect, so only slot-turnaround timing varies).
            stop_slots = [
                s for s in range(self.B)
                if active_np[s] and self._slot_req[s] is not None and self._slot_req[s].stop
            ]
            if stop_slots:
                out_np = host_fetch(state[4])
                n_np = host_fetch(state[2])
                new_active = state[3]
                for s in stop_slots:
                    row = out_np[s][: int(n_np[s])]
                    if _first_stop_end(row, self._slot_req[s].stop) is not None:
                        new_active = new_active.at[s].set(False)
                        active_np[s] = False
                state = state[:3] + (new_active,) + state[4:]
                self._sync(state)
            for s in range(self.B):
                self._collect(s, active_np)
            # Capacity reservation must cover the LONGEST remaining run among
            # active slots, not just the incoming request's own max_new:
            # decode windows consume global columns until the longest-running
            # request finishes, so a short admit reserving only its own
            # length would let a long-running neighbor push cache['pos'] past
            # max_cache_len with no runtime guard (the clamped writes would
            # silently corrupt the last column). r5 review finding.
            n_np = host_fetch(state[2])
            max_remaining = max(
                (self._slot_req[s].max_new - int(n_np[s])
                 for s in range(self.B)
                 if self._slot_req[s] is not None and active_np[s]),
                default=0,
            )
            free = [s for s in range(self.B) if self._slot_req[s] is None]
            while free and self._queue:
                req = self._queue.popleft()
                s = free.pop(0)
                P = self._bucket(req.prompt.size)
                reserve = max(req.max_new, max_remaining)
                need = P + reserve + self.sync_every - 1
                if self._host_pos + need > self.C and self._retired_since_compact:
                    # Capacity pressure + something retired since the last
                    # compact: reclaim its columns before deferring or
                    # dead-ending. The retirement flag (not position
                    # movement) gates this, so sustained backpressure while
                    # one long request runs never re-gathers the cache.
                    self.compact()
                if self._host_pos + need > self.C:
                    self._queue.appendleft(req)
                    if any(r is not None for r in self._slot_req):
                        # Backpressure, not failure: let the in-flight slots
                        # finish (each decode window frees capacity pressure
                        # by retiring requests) and retry the admit later.
                        break
                    # Nothing in flight and still no room: a true dead end.
                    # Re-queue is already done, so catch + reset() + run()
                    # retries everything (finished results stay banked).
                    raise RuntimeError(
                        f"cache capacity exhausted (pos={self._host_pos}, "
                        f"need {P + reserve} more of {self.C}); raise "
                        "max_cache_len, or catch this, reset(), and run() again."
                    )
                row = np.full((P,), self.pad, np.int32)
                mrow = np.zeros((P,), np.int32)
                row[: req.prompt.size] = req.prompt
                mrow[: req.prompt.size] = 1
                # left-align inside the bucket so the last real token sits at P-1
                row_j, mrow_j = left_align(row[None], mrow[None])
                self._cache, state, _fin0 = self._admit_fn(P)(
                    self.params, self._cache, state, s, row_j[0], mrow_j[0],
                    jnp.int32(req.rid), self._rng,
                    jnp.int32(req.max_new), jnp.float32(req.temperature),
                    jnp.int32(req.eos),
                )
                self._host_pos += P
                # Host-side wall clock in the HOST engine loop (the linter's
                # traced_names heuristic collides on the jitted bodies all
                # being named `run` too).
                admit_t = time.monotonic()  # accelerate-lint: disable=traced-host-impurity
                self._req_times.setdefault(req.rid, {"submit": req.submit_t})[
                    "first_token"
                ] = admit_t
                if self.tracer is not None:
                    # Contiguous admits prefill AND sample the first token in
                    # one dispatch: admission and first-token coincide.
                    self.tracer.admit(req.rid)
                    self.tracer.first_token(req.rid, at=admit_t)
                self._peak_consumed_slots = max(
                    self._peak_consumed_slots, self.B * self._host_pos
                )
                # Keep the instance fields pointing at LIVE buffers: the admit
                # donated the previous ones, and a capacity raise later in
                # this pass must leave the engine in a clean recoverable state.
                self._sync(state)
                self._slot_req[s] = req
                max_remaining = max(max_remaining, req.max_new)
                # (an immediate-eos slot is collected at the next loop-top
                # check — no blocking readback of the admit result here)
            if not self._queue and not any(r is not None for r in self._slot_req):
                break
            # ONE dispatch advances all slots by sync_every tokens; the
            # np.asarray at the loop top is the only blocking host round-trip.
            self._cache, state = self._decode()(self.params, self._cache, state)
            self._host_pos += self.sync_every
            self._peak_consumed_slots = max(
                self._peak_consumed_slots, self.B * self._host_pos
            )
        self._sync(state)
        wave, self._results = self._results, {}
        return {rid: wave[rid] for rid in sorted(wave)}
