"""Big-model loading & offloaded inference.

Reference parity: ``src/accelerate/big_modeling.py`` — ``init_empty_weights``/
``init_on_device`` (:61-170), ``cpu_offload``/``disk_offload``/
``cpu_offload_with_hook`` (:173-307), ``dispatch_model`` (:309-526),
``load_checkpoint_and_dispatch`` (:529-668), ``attach_layerwise_casting_hooks``
(:670-766).

TPU re-design:

- **empty init** — the reference monkeypatches ``nn.Module.register_parameter`` to
  allocate on the meta device. Functionally pure models make this trivial:
  ``jax.eval_shape`` traces ``init`` without running it, yielding a pytree of
  ``ShapeDtypeStruct`` (zero bytes). The context manager here just flips the flag
  ``Module.init_params`` consults.
- **dispatch** — a device_map's chip entries become ``jax.device_put`` placements
  (or a NamedSharding over the whole mesh — on TPU, *sharding* across chips via
  GSPMD replaces the reference's per-GPU block placement as the preferred layout);
  ``"cpu"``/``"disk"`` entries stay host-side and are streamed per layer by
  ``StreamedScanModel`` — the hook hot loop of the reference (hooks.py:328-402
  there), reshaped into one compiled block program + just-in-time DMA.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Mapping

import numpy as np

import jax
import jax.numpy as jnp

from .hooks import AlignDevicesHook, CpuOffload, UserCpuOffloadHook, add_hook_to_module
from .modules import ModelOutput, Module
from .utils.modeling import (
    check_device_map,
    device_for_target,
    get_balanced_memory,
    get_max_memory,
    infer_auto_device_map,
    load_checkpoint_in_model,
    named_parameters,
    param_target,
    unflatten_names,
)
from .utils.offload import OffloadedWeightsLoader, offload_state_dict

logger = logging.getLogger(__name__)

_EMPTY_INIT_DEPTH = 0


def _empty_init_active() -> bool:
    return _EMPTY_INIT_DEPTH > 0


@contextlib.contextmanager
def init_empty_weights(include_buffers: bool = False):
    """Under this context ``model.init_params(...)`` produces abstract
    ``ShapeDtypeStruct`` leaves instead of real arrays (reference
    ``init_empty_weights`` :61-110 allocates on the meta device).

    70B-parameter models can be planned (``infer_auto_device_map``,
    ``estimate-memory``) without a byte of array storage.
    """
    global _EMPTY_INIT_DEPTH
    _EMPTY_INIT_DEPTH += 1
    try:
        yield
    finally:
        _EMPTY_INIT_DEPTH -= 1


@contextlib.contextmanager
def init_on_device(device):
    """Initialize params directly onto ``device`` (reference ``init_on_device``
    :113-170). ``device`` must be a ``jax.Device``; for sharded initialization
    use ``Accelerator.prepare`` (the sharding planner), not this context."""
    if not hasattr(device, "platform"):
        raise TypeError(
            f"init_on_device expects a jax.Device, got {type(device).__name__}; "
            "for sharded placement pass the model through Accelerator.prepare()."
        )
    default = jax.config.jax_default_device
    try:
        jax.config.update("jax_default_device", device)
        yield
    finally:
        jax.config.update("jax_default_device", default)


# ------------------------------------------------------------------ offload APIs
def cpu_offload(model, execution_device=None, offload_buffers: bool = False, state_dict=None):
    """Whole-model host offload: params live on host RAM, move to HBM per forward
    (reference ``cpu_offload`` :173-212)."""
    if execution_device is None:
        execution_device = jax.local_devices()[0]
    params = getattr(model, "params", None)
    if params is not None:
        model.params = jax.tree_util.tree_map(
            lambda p: np.asarray(jax.device_get(p)) if isinstance(p, jax.Array) else p, params
        )
    add_hook_to_module(model, AlignDevicesHook(execution_device=execution_device, io_same_device=True))
    return model


def cpu_offload_with_hook(model, execution_device=None, prev_module_hook=None):
    """Host offload with a user-controlled eviction handle, for model chains
    (reference ``cpu_offload_with_hook`` :215-254)."""
    hook = CpuOffload(execution_device=execution_device, prev_module_hook=prev_module_hook)
    add_hook_to_module(model, hook)
    user_hook = UserCpuOffloadHook(model, hook)
    return model, user_hook


def disk_offload(model, offload_dir: str, execution_device=None, offload_buffers: bool = False):
    """Whole-model disk offload via memmap folder (reference ``disk_offload``
    :257-307)."""
    params = getattr(model, "params", None)
    if params is None:
        raise ValueError("Model has no params to offload; call model.init_params() first.")
    flat = {
        k: np.asarray(jax.device_get(v)) for k, v in named_parameters(params).items()
        if isinstance(v, (jax.Array, np.ndarray))
    }
    offload_state_dict(offload_dir, flat)
    weights_map = OffloadedWeightsLoader(save_folder=offload_dir)
    # Keep only abstract leaves in memory.
    model.params = jax.tree_util.tree_map(
        lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype) if hasattr(p, "shape") else p, params
    )
    if execution_device is None:
        execution_device = jax.local_devices()[0]
    add_hook_to_module(
        model,
        AlignDevicesHook(
            execution_device=execution_device, weights_map=weights_map, io_same_device=True
        ),
    )
    return model


def attach_layerwise_casting_hooks(
    module,
    storage_dtype=None,
    compute_dtype=jnp.bfloat16,
    skip_modules_pattern=None,
    skip_modules_classes=None,
    non_blocking: bool = False,
):
    """Store params in a narrow dtype, upcast inside the forward (reference
    ``attach_layerwise_casting_hooks`` :670-766)."""
    from .hooks import LayerwiseCastingHook

    if storage_dtype is None:
        storage_dtype = jnp.bfloat16
    add_hook_to_module(module, LayerwiseCastingHook(storage_dtype, compute_dtype))
    return module


# ---------------------------------------------------------------------- dispatch
def dispatch_model(
    model,
    device_map: Mapping[str, str],
    main_device=None,
    state_dict=None,
    offload_dir: str | None = None,
    offload_index: Mapping | None = None,
    offload_buffers: bool = False,
    skip_keys=None,
    preload_module_classes=None,
    force_hooks: bool = False,
):
    """Execute a placement plan (reference ``dispatch_model`` :309-526).

    Chip-resident blocks are ``device_put`` where the plan says; ``cpu``/``disk``
    blocks stay host/memmap-resident. When any block is offloaded the returned
    model runs via ``StreamedScanModel`` (layer streaming) if the model exposes the
    embed/block/head protocol, else a whole-tree ``AlignDevicesHook``.
    """
    params = getattr(model, "params", None)
    if params is None:
        raise ValueError("Model has no params; call model.init_params() (possibly under init_empty_weights).")
    check_device_map(params, dict(device_map))

    flat = named_parameters(params)
    targets = {name: param_target(name, dict(device_map)) for name in flat}
    has_offload = any(t in ("cpu", "disk") for t in targets.values())
    has_disk = any(t == "disk" for t in targets.values())

    if has_disk and offload_dir is None and offload_index is None:
        raise ValueError(
            "Disk offload requested in device_map but no offload_dir was given "
            "(reference raises the same, big_modeling.py:377-381)."
        )

    # Chip placement policy (the TPU-first divergence from the reference): a plan
    # spanning MULTIPLE chips is executed as GSPMD *sharding* over a mesh of those
    # chips — XLA inserts the inter-chip transfers/collectives — rather than the
    # reference's block-per-device placement with hook-driven activation moves
    # (hooks.py:373-402 there), which has no compiled-graph analog.
    chip_targets = sorted({t for t in targets.values() if t not in ("cpu", "disk")})
    chip_sharding = None
    if len(chip_targets) > 1:
        from jax.sharding import Mesh

        from .parallel.sharding import plan_param_shardings

        plan_devices = [device_for_target(t) for t in chip_targets]
        chip_mesh = Mesh(np.array(plan_devices), ("fsdp",))
        sharding_tree = plan_param_shardings(params, chip_mesh)
        chip_sharding = dict(
            zip(
                named_parameters(params).keys(),
                jax.tree_util.tree_leaves(
                    sharding_tree, is_leaf=lambda x: hasattr(x, "spec")
                ),
            )
        )

    new_flat = {}
    disk_spill = {}
    for name, leaf in flat.items():
        t = targets[name]
        if t == "disk":
            if isinstance(leaf, (jax.Array, np.ndarray)):
                disk_spill[name] = np.asarray(jax.device_get(leaf))
            new_flat[name] = jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
        elif t == "cpu":
            new_flat[name] = (
                np.asarray(jax.device_get(leaf)) if isinstance(leaf, jax.Array) else leaf
            )
        elif not isinstance(leaf, (jax.Array, np.ndarray)):
            new_flat[name] = leaf  # abstract leaf without weights: left for load_checkpoint
        elif chip_sharding is not None:
            new_flat[name] = jax.device_put(leaf, chip_sharding[name])
        else:
            new_flat[name] = jax.device_put(leaf, device_for_target(t))
    if disk_spill:
        if offload_dir is None:
            raise ValueError(
                "device_map sends live weights to 'disk' but no offload_dir was given; "
                "pass offload_dir= (offload_index alone only covers weights already on disk)."
            )
        offload_state_dict(offload_dir, disk_spill)
    model.params = unflatten_names(new_flat, params)
    model._at_device_map = dict(device_map)

    if not has_offload and not force_hooks:
        return model

    weights_map = OffloadedWeightsLoader(
        state_dict={k: v for k, v in new_flat.items() if isinstance(v, np.ndarray)},
        save_folder=offload_dir if has_disk else None,
        index=offload_index,
    )
    execution_device = main_device or jax.local_devices()[0]

    if _supports_streaming(model, targets):
        return StreamedScanModel(model, weights_map, execution_device)
    add_hook_to_module(
        model,
        AlignDevicesHook(
            execution_device=execution_device, weights_map=weights_map, io_same_device=True
        ),
    )
    return model


def _supports_streaming(model, targets) -> bool:
    """Layer streaming needs the embed/block/head protocol + stacked layers, and
    only the 'layers' subtree offloaded (embed/head resident)."""
    if not all(hasattr(model, m) for m in ("embed", "block", "head")):
        return False
    params = getattr(model, "params", None)
    if not isinstance(params, dict) or "layers" not in params:
        return False
    offloaded_nonlayers = [
        n for n, t in targets.items()
        if t in ("cpu", "disk") and not n.startswith("layers.")
    ]
    return not offloaded_nonlayers


class StreamedScanModel:
    """Layer-streamed execution for stacked-scan decoder models.

    The TPU-shaped replacement for per-module AlignDevicesHooks (reference
    hooks.py:328-402): ONE compiled block program, and per layer a just-in-time
    ``jax.device_put`` of that layer's weight slice. ``device_put`` is async, so
    layer ``i+1``'s host→HBM DMA overlaps layer ``i``'s compute (double
    buffering) — the same overlap the reference approximates with
    ``non_blocking=True`` copies.
    """

    def __init__(self, model, weights_map, execution_device):
        self.model = model
        self.weights_map = weights_map
        self.execution_device = execution_device
        # jit caches are keyed on the function object — build each wrapper ONCE so
        # repeated inference calls reuse the compiled programs.
        self._block_fn = jax.jit(lambda layer, x, ctx: model.block(layer, x, ctx))
        self._block_cache_fn = jax.jit(
            lambda layer, ck, cv, x, ctx: model.block(
                layer, x, ctx, cache_layer={"k": ck, "v": cv}
            )
        )
        self._embed_fn = jax.jit(lambda p, ids, pos, am: model.embed(p, ids, pos, am))
        # Cached decode must pin length-dependent rope (dynamic NTK) to the
        # cache capacity — same consistency rule as Llama._apply_cached; only
        # rope models expose the kwarg (GPT-2's learned positions don't).
        import inspect as _inspect

        if "rope_seq_len" in _inspect.signature(model.embed).parameters:
            self._embed_cached_fn = jax.jit(
                lambda p, ids, pos, am, rl: model.embed(p, ids, pos, am, rope_seq_len=rl),
                static_argnums=4,
            )
        else:
            self._embed_cached_fn = None
        self._head_fn = jax.jit(
            lambda p, x, lab, am: model.head(p, x, labels=lab, attention_mask=am)
        )
        cfg = getattr(model, "config", None)
        self.num_layers = getattr(cfg, "num_hidden_layers", None) or getattr(
            cfg, "num_layers", None
        )
        if self.num_layers is None:
            # Infer from any stacked leaf's leading dim.
            leaf = jax.tree_util.tree_leaves(model.params["layers"])[0]
            self.num_layers = leaf.shape[0]

    @property
    def config(self):
        return self.model.config

    @property
    def params(self):
        return self.model.params

    def _layer_host_slice(self, i: int):
        """Layer i's weights as host arrays, read lazily (memmap slice reads only
        that layer's bytes from disk)."""
        template = self.model.params["layers"]
        flat = {}
        for name, leaf in named_parameters(template).items():
            full_name = f"layers.{name}"
            if full_name in self.weights_map:
                stacked = self.weights_map[full_name]
                flat[name] = np.asarray(stacked[i])
            elif isinstance(leaf, jax.Array):
                flat[name] = leaf[i]
            else:
                raise KeyError(f"No weights available for {full_name}")
        return unflatten_names(flat, template)

    def _resident_nonlayer_params(self):
        out = dict(self.model.params)
        out.pop("layers", None)
        return jax.device_put(out, self.execution_device)

    def init_cache(self, batch_size: int, max_len: int, dtype=jnp.bfloat16):
        """Decode cache for streamed generation. Per-layer K/V kept as a LIST of
        (B, K, n_kv, D) arrays (not stacked over L): each token's forward
        updates one layer's slice at a time while that layer's weights stream
        in, so a stacked array would force a full-cache copy per layer."""
        if not hasattr(self.model, "init_cache"):
            raise TypeError(f"{type(self.model).__name__} does not support KV caching")
        # eval_shape: get the stacked layout WITHOUT materializing it — the
        # stacked cache can be tens of GB for the offloaded models this class
        # exists for, so allocate per-layer buffers directly on the chip.
        spec = jax.eval_shape(
            lambda: self.model.init_cache(batch_size, max_len, dtype=dtype)
        )
        k_shape, v_shape = spec["k"].shape[1:], spec["v"].shape[1:]
        with jax.default_device(self.execution_device):
            return {
                "k": [jnp.zeros(k_shape, dtype) for _ in range(self.num_layers)],
                "v": [jnp.zeros(v_shape, dtype) for _ in range(self.num_layers)],
                "pos": jnp.zeros((), jnp.int32),
                "kv_mask": jnp.zeros((batch_size, max_len), jnp.int32),
            }

    def __call__(self, input_ids=None, labels=None, attention_mask=None, positions=None,
                 cache=None, **kw):
        nonlayer = self._resident_nonlayer_params()
        if cache is not None:
            return self._call_cached(
                nonlayer, input_ids, labels, attention_mask, cache, positions=positions
            )
        x, ctx = self._embed_fn(nonlayer, input_ids, positions, attention_mask)
        # Double-buffered streaming: prefetch layer i+1 while layer i computes.
        next_layer = jax.device_put(self._layer_host_slice(0), self.execution_device)
        for i in range(self.num_layers):
            layer = next_layer
            if i + 1 < self.num_layers:
                next_layer = jax.device_put(
                    self._layer_host_slice(i + 1), self.execution_device
                )
            x = self._block_fn(layer, x, ctx)
        return self._head_fn(nonlayer, x, labels, attention_mask)

    def _call_cached(self, nonlayer, input_ids, labels, attention_mask, cache,
                     positions=None):
        """Incremental forward through the per-layer KV cache, weights streamed.
        ``positions`` = token positions for the embedding (mask-derived for
        ragged batches); slot indices always drive the causal mask."""
        B, S = input_ids.shape
        pos = cache["pos"]
        q_positions = jnp.broadcast_to(
            pos + jnp.arange(S, dtype=jnp.int32)[None], (B, S)
        )
        embed_positions = q_positions if positions is None else positions
        chunk_mask = (
            jnp.asarray(attention_mask, jnp.int32)
            if attention_mask is not None
            else jnp.ones((B, S), jnp.int32)
        )
        kv_mask = jax.lax.dynamic_update_slice(cache["kv_mask"], chunk_mask, (0, pos))
        if self._embed_cached_fn is not None:
            cache_capacity = cache["k"][0].shape[1]
            x, ctx = self._embed_cached_fn(
                nonlayer, input_ids, embed_positions, attention_mask, cache_capacity
            )
        else:
            x, ctx = self._embed_fn(nonlayer, input_ids, embed_positions, attention_mask)
        ctx = dict(ctx)
        ctx["positions"] = q_positions
        ctx["kv_mask"] = kv_mask
        ctx["cache_pos"] = pos

        new_k, new_v = [], []
        next_layer = jax.device_put(self._layer_host_slice(0), self.execution_device)
        for i in range(self.num_layers):
            layer = next_layer
            if i + 1 < self.num_layers:
                next_layer = jax.device_put(
                    self._layer_host_slice(i + 1), self.execution_device
                )
            x, updated = self._block_cache_fn(layer, cache["k"][i], cache["v"][i], x, ctx)
            new_k.append(updated["k"])
            new_v.append(updated["v"])
        out = self._head_fn(nonlayer, x, labels, attention_mask)
        out["cache"] = {"k": new_k, "v": new_v, "pos": pos + S, "kv_mask": kv_mask}
        return out

    def apply(self, params, *args, **kwargs):
        if params is not None and params is not self.model.params:
            # Honor the Module.apply(params, ...) contract: run with the caller's
            # tree (layers still stream from it / the weights_map per slice).
            saved = self.model.params
            self.model.params = params
            try:
                return self(*args, **kwargs)
            finally:
                self.model.params = saved
        return self(*args, **kwargs)

    def eval(self):
        return self

    def train(self, mode: bool = True):
        if mode:
            raise RuntimeError("StreamedScanModel is inference-only (offloaded dispatch).")
        return self


# --------------------------------------------------------- load-and-dispatch
def load_checkpoint_and_dispatch(
    model,
    checkpoint: str,
    device_map: Mapping[str, str] | str | None = None,
    max_memory: Mapping | None = None,
    no_split_module_classes=None,
    offload_folder: str | None = None,
    offload_buffers: bool = False,
    dtype=None,
    offload_state_dict: bool | None = None,
    skip_keys=None,
    preload_module_classes=None,
    force_hooks: bool = False,
    strict: bool = False,
):
    """infer plan → load shards → dispatch (reference ``load_checkpoint_and_dispatch``
    :529-668). ``device_map='auto'|'balanced'|'balanced_low_0'|'sequential'``
    mirrors the reference's accepted strings (:600-610)."""
    params = getattr(model, "params", None)
    if params is None:
        raise ValueError("Call model.init_params() (ideally under init_empty_weights()) first.")
    if isinstance(device_map, str):
        if device_map not in ("auto", "balanced", "balanced_low_0", "sequential"):
            raise ValueError(
                "If passing a string for `device_map`, please choose 'auto', 'balanced', "
                "'balanced_low_0' or 'sequential'."
            )
        if device_map != "sequential":
            max_memory = get_balanced_memory(
                params, max_memory=max_memory, dtype=dtype,
                low_zero=(device_map == "balanced_low_0"),
            )
        device_map = infer_auto_device_map(params, max_memory=max_memory, dtype=dtype)
    loaded = load_checkpoint_in_model(
        params,
        checkpoint,
        device_map=device_map,
        offload_folder=offload_folder,
        dtype=dtype,
        strict=strict,
    )
    model.params = loaded
    if device_map is None:
        model.params = jax.device_put(loaded, jax.local_devices()[0])
        return model
    offload_index = None
    import os

    if offload_folder is not None and os.path.isfile(os.path.join(offload_folder, "index.json")):
        import json

        with open(os.path.join(offload_folder, "index.json")) as fh:
            offload_index = json.load(fh)
    return dispatch_model(
        model,
        device_map=device_map,
        offload_dir=offload_folder,
        offload_index=offload_index,
        offload_buffers=offload_buffers,
        skip_keys=skip_keys,
        force_hooks=force_hooks,
    )
