"""Miscellaneous utilities.

Reference parity: ``src/accelerate/utils/other.py`` — ``save``/``load`` (:330-411),
``extract_model_from_parallel`` (:197-280), ``convert_bytes`` (:467),
``check_os_kernel`` (:477), ``merge_dicts``, ``is_port_in_use``. Torch-specific
pieces (``wait_for_everyone`` re-export, TE recipe handling) live elsewhere here.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import platform
import re
import socket
from pathlib import Path

import numpy as np

import jax

logger = logging.getLogger(__name__)


def extract_model_from_parallel(model, keep_fp32_wrapper: bool = True, recursive: bool = False):
    """Peel framework wrappers off a model (reference :197-280 unwraps DDP/FSDP/
    compiled modules). Here the only wrapper is ``PreparedModel``."""
    from ..accelerator import PreparedModel

    while isinstance(model, PreparedModel):
        model = model.module
    return model


def save(obj, f, save_on_each_node: bool = False, safe_serialization: bool = False):
    """Save ``obj`` only on the main process (per node if ``save_on_each_node``),
    mirroring reference ``save`` :330-364. Arrays are materialized to host first.

    With ``safe_serialization`` a flat dict of arrays is written as safetensors;
    otherwise pickle (covering arbitrary Python state, like the reference's
    ``torch.save`` default path).
    """
    from ..state import PartialState

    state = PartialState()
    obj = jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)) if isinstance(x, jax.Array) else x, obj
    )
    should = state.is_local_main_process if save_on_each_node else state.is_main_process
    if not should:
        return
    if safe_serialization:
        from safetensors.numpy import save_file

        from ..checkpointing import _flatten_params

        save_file(_flatten_params(obj), f, metadata={"format": "np"})
    else:
        with open(f, "wb") as fh:
            pickle.dump(obj, fh)


def load(f, map_location=None, **kwargs):
    """Load a file written by :func:`save` (reference ``load`` :367-411)."""
    f = str(f)
    if f.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return load_file(f)
    with open(f, "rb") as fh:
        return pickle.load(fh)


def merge_dicts(source: dict, destination: dict) -> dict:
    """Recursively merge ``source`` into ``destination`` (reference :446-464)."""
    for key, value in source.items():
        if isinstance(value, dict):
            node = destination.setdefault(key, {})
            merge_dicts(value, node)
        else:
            destination[key] = value
    return destination


def is_port_in_use(port: int | str | None = None) -> bool:
    """Reference :451-458 — used by the launcher to pick a free coordinator port."""
    if port is None:
        port = 29500
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        return s.connect_ex(("localhost", int(port))) == 0


def convert_bytes(size: float) -> str:
    """Human-readable bytes (reference :467-474): 1024 -> '1.0 KB'."""
    for unit in ["bytes", "KB", "MB", "GB", "TB"]:
        if size < 1024.0:
            return f"{round(size, 2)} {unit}"
        size /= 1024.0
    return f"{round(size, 2)} PB"


def check_os_kernel():
    """Warn on Linux kernels < 5.5 (reference :477-494: pre-5.5 kernels hang
    multi-host rendezvous)."""
    info = platform.uname()
    if info.system != "Linux":
        return
    _, version, *_ = re.split(r"(\d+\.\d+\.\d+)", info.release)
    major, minor, _ = (int(x) for x in version.split("."))
    if (major, minor) < (5, 5):
        logger.warning(
            "Detected kernel version %s, which is below the recommended minimum of 5.5.0; "
            "this can cause the process to hang.",
            version,
        )


def write_basic_config(mixed_precision: str = "no", save_location: str | None = None):
    """Create a minimal default config yaml non-interactively (reference
    ``utils/other.py:414-443``) — used by notebook/CI setups."""
    from ..commands.config_args import ClusterConfig, default_config_file

    path = Path(save_location) if save_location is not None else Path(default_config_file)
    if path.exists():
        logger.warning("Config file already exists at %s; skipping.", path)
        return False
    path.parent.mkdir(parents=True, exist_ok=True)
    config = ClusterConfig(
        compute_environment="LOCAL_MACHINE",
        distributed_type="JAX_TPU",
        mixed_precision=mixed_precision,
        num_processes=1,
    )
    config.to_yaml_file(path)
    return path


def get_pretty_name(obj) -> str:
    """Best-effort display name for checkpoint registration (reference :497-508)."""
    if not hasattr(obj, "__qualname__") and not hasattr(obj, "__name__"):
        obj = getattr(obj, "__class__", obj)
    if hasattr(obj, "__qualname__"):
        return obj.__qualname__
    if hasattr(obj, "__name__"):
        return obj.__name__
    return str(obj)


def save_json(obj, path: str | os.PathLike, indent: int = 2) -> None:
    with open(path, "w") as fh:
        json.dump(obj, fh, indent=indent, sort_keys=True)


def load_json(path: str | os.PathLike):
    with open(path) as fh:
        return json.load(fh)
