"""Disk-backed weight storage for bigger-than-HBM models.

Reference parity: ``src/accelerate/utils/offload.py`` — ``offload_state_dict`` (:85),
``OffloadedWeightsLoader`` (:127-191), ``PrefixedDataset`` (:104), ``offload_weight``/
``load_offload_weight`` — numpy memmap files plus an ``index.json`` of
shape/dtype metadata. The format here is identical (one ``<name>.dat`` memmap per
tensor), so offload folders are interoperable in shape with the reference's.

TPU angle: the consumer is ``hooks.StreamedBlockRunner`` which reads a block's
memmaps and ``jax.device_put``s them into donated buffers just-in-time — host→HBM
DMA overlapped with the previous block's compute where possible.
"""

from __future__ import annotations

import json
import os
from collections.abc import Mapping

import numpy as np


def offload_weight(weight, weight_name: str, offload_folder: str, index: dict | None = None):
    """Write one array as a memmap file (reference ``offload_weight`` :30-52)."""
    dtype = None
    weight = np.asarray(weight)
    if str(weight.dtype) == "bfloat16":
        # numpy memmap has no bf16: store as int16 raw bits, record logical dtype
        # (same trick the reference uses :36-40).
        weight = weight.view(np.int16)
        dtype = "bfloat16"
    array = weight
    tensor_file = os.path.join(offload_folder, f"{weight_name}.dat")
    if index is not None:
        if dtype is None:
            dtype = str(array.dtype)
        index[weight_name] = {"dtype": dtype, "shape": list(array.shape)}
    if array.ndim == 0:
        array = array[None]
    os.makedirs(offload_folder, exist_ok=True)
    file_array = np.memmap(tensor_file, dtype=array.dtype, mode="w+", shape=array.shape)
    file_array[:] = array[:]
    file_array.flush()
    return index


def load_offloaded_weight(weight_file: str, weight_info: dict) -> np.ndarray:
    """Read one memmapped array back (reference ``load_offloaded_weight`` :55-82)."""
    shape = tuple(weight_info["shape"])
    if shape == ():
        shape = (1,)
    dtype = weight_info["dtype"]
    if dtype == "bfloat16":
        import jax.numpy as jnp

        # View (not copy) keeps the memmap lazy: callers slicing one layer read
        # only that layer's bytes from disk.
        arr = np.memmap(weight_file, dtype=np.int16, shape=shape, mode="r").view(
            jnp.bfloat16.dtype
        )
    else:
        arr = np.memmap(weight_file, dtype=dtype, shape=shape, mode="r")
    if tuple(weight_info["shape"]) == ():
        arr = arr[0]
    return arr


def save_offload_index(index: dict, offload_folder: str):
    if index is None or len(index) == 0:
        return
    os.makedirs(offload_folder, exist_ok=True)
    offload_index_file = os.path.join(offload_folder, "index.json")
    current_index = {}
    if os.path.isfile(offload_index_file):
        with open(offload_index_file) as f:
            current_index = json.load(f)
    current_index.update(index)
    with open(offload_index_file, "w") as f:
        json.dump(current_index, f, indent=2)


def offload_state_dict(save_dir: str, state_dict: Mapping) -> None:
    """Offload a whole flat state dict (reference ``offload_state_dict`` :85-101)."""
    os.makedirs(save_dir, exist_ok=True)
    index = {}
    for name, parameter in state_dict.items():
        index = offload_weight(parameter, name, save_dir, index=index)
    save_offload_index(index, save_dir)


class PrefixedDataset(Mapping):
    """View of a mapping with a key prefix applied (reference ``PrefixedDataset``
    :104-124)."""

    def __init__(self, dataset: Mapping, prefix: str):
        self.dataset = dataset
        self.prefix = prefix

    def __getitem__(self, key):
        return self.dataset[f"{self.prefix}{key}"]

    def __iter__(self):
        return iter([key for key in self.dataset if key.startswith(self.prefix)])

    def __len__(self):
        return len([key for key in self.dataset if key.startswith(self.prefix)])


class OffloadedWeightsLoader(Mapping):
    """Unified lazy view over in-memory weights + a disk offload folder (reference
    ``OffloadedWeightsLoader`` :127-191). ``__getitem__`` returns host numpy arrays;
    device placement is the caller's concern (hooks stream them in)."""

    def __init__(
        self,
        state_dict: Mapping | None = None,
        save_folder: str | os.PathLike | None = None,
        index: Mapping | None = None,
        device=None,
    ):
        if state_dict is None and save_folder is None and index is None:
            raise ValueError("Need either a `state_dict`, a `save_folder` or an `index`.")
        self.state_dict = dict(state_dict or {})
        if index is None and save_folder is not None:
            with open(os.path.join(save_folder, "index.json")) as f:
                index = json.load(f)
        self.index = dict(index or {})
        self.save_folder = save_folder
        self.all_keys = list(self.state_dict.keys())
        self.all_keys.extend([key for key in self.index if key not in self.all_keys])
        self.device = device

    def __getitem__(self, key: str):
        if key in self.state_dict:
            return np.asarray(self.state_dict[key])
        weight_info = self.index[key]
        if weight_info.get("safetensors_file") is not None:
            from safetensors import safe_open

            with safe_open(weight_info["safetensors_file"], framework="np") as f:
                return f.get_tensor(weight_info.get("weight_name", key))
        weight_file = os.path.join(self.save_folder, f"{key}.dat")
        return load_offloaded_weight(weight_file, weight_info)

    def __iter__(self):
        return iter(self.all_keys)

    def __len__(self):
        return len(self.all_keys)


def extract_submodules_state_dict(state_dict: Mapping, submodule_names: list[str]) -> dict:
    """Subset a flat dict to the given block prefixes (reference
    ``extract_submodules_state_dict`` :194-213)."""
    result = {}
    for name in submodule_names:
        result.update(
            {
                key: param
                for key, param in state_dict.items()
                if key == name or key.startswith(name + ".")
            }
        )
    return result
