"""Weight-only quantization — the bitsandbytes-parity layer.

Reference parity: ``src/accelerate/utils/bnb.py:44-194`` (``load_and_quantize_model``
driving 8/4-bit bitsandbytes conversion + offload integration) and
``BnbQuantizationConfig`` (``utils/dataclasses.py:2653-2807``). The parity target
is the API; the implementation is TPU-native by necessity — there are no CUDA
bnb kernels here:

- **int8**: symmetric per-channel absmax quantization of 2-D+ weights. Storage is
  ``int8`` + a bf16 scale per output channel (channel = last axis).
- **int4**: same scheme packed two nibbles per byte (``int4 ∈ [-8, 7]``).
- **compute**: weights are dequantized at forward entry by a hook
  (``DequantizeHook``) and the scale-multiply fuses into the consuming matmul
  under jit — XLA's analog of bnb's fused dequant epilogue. Memory savings hold
  at rest (params pytree stays quantized); transient bf16 copies exist only
  inside a forward, mirroring bnb's activation-time dequant.

Skip rules mirror bnb defaults: 1-D leaves (norms, biases) and configured
``skip_modules`` (e.g. the lm head, reference ``bnb.py:124-136``) stay in full
precision.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

import jax
import jax.numpy as jnp

QUANT_KEY = "_quantized"  # marker key inside a legacy quantized-leaf dict


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """A quantized weight leaf: int8 (or nibble-packed int4) data + per-channel
    scales. Registered as a pytree node with ``bits``/logical ``shape`` as static
    aux data, so quantized param trees flow through jit tracing, ``device_put``
    tree_maps, and checkpoint flattening without scalar Python leaves polluting
    the tree."""

    def __init__(self, data, scale, bits: int, shape: tuple):
        self.data = data
        self.scale = scale
        self.bits = int(bits)
        self.shape = tuple(shape)

    def tree_flatten(self):
        return (self.data, self.scale), (self.bits, self.shape)

    @classmethod
    def tree_unflatten(cls, aux, children):
        data, scale = children
        bits, shape = aux
        return cls(data, scale, bits, shape)

    @property
    def nbytes(self) -> int:
        return int(self.data.nbytes + self.scale.nbytes)

    def __repr__(self):
        return f"QuantizedTensor(bits={self.bits}, shape={self.shape})"


@dataclass
class QuantizationConfig:
    """Mirrors ``BnbQuantizationConfig`` fields that make sense on TPU
    (reference ``utils/dataclasses.py:2653-2807``)."""

    load_in_8bit: bool = False
    load_in_4bit: bool = False
    compute_dtype: str = "bfloat16"  # bnb_4bit_compute_dtype analog
    skip_modules: list = field(default_factory=list)  # llm_int8_skip_modules analog
    keep_in_fp32_modules: list = field(default_factory=list)

    def __post_init__(self):
        if self.load_in_8bit and self.load_in_4bit:
            raise ValueError("load_in_8bit and load_in_4bit can't both be set")
        if not (self.load_in_8bit or self.load_in_4bit):
            raise ValueError("Set load_in_8bit or load_in_4bit")

    @property
    def bits(self) -> int:
        return 8 if self.load_in_8bit else 4

    @property
    def target_dtype(self):
        return jnp.dtype(self.compute_dtype)


def quantize_leaf(w, bits: int) -> QuantizedTensor:
    """Symmetric absmax per-channel quantization; channel = last axis. Stacked
    layers (ndim >= 3, leading axis = layer) keep per-layer scales — bnb
    quantizes per matrix, so one outlier layer must not degrade the stack."""
    w = jnp.asarray(w)
    qmax = 127.0 if bits == 8 else 7.0
    reduce_axes = tuple(range(1, w.ndim - 1)) if w.ndim >= 3 else tuple(range(w.ndim - 1))
    absmax = jnp.max(jnp.abs(w), axis=reduce_axes, keepdims=True)
    scale = (absmax / qmax).astype(jnp.float32)
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(w / scale), -qmax - 1, qmax).astype(jnp.int8)
    if bits == 4:
        # Pack nibble pairs over the flattened array (shape-agnostic; odd sizes
        # get one pad nibble).
        flat = q.reshape(-1)
        if flat.size % 2:
            flat = jnp.concatenate([flat, jnp.zeros((1,), jnp.int8)])
        lo = flat[0::2] & 0x0F
        hi = (flat[1::2] & 0x0F) << 4
        q = (lo | hi).astype(jnp.int8)
    return QuantizedTensor(q, scale, bits, tuple(w.shape))


def dequantize_leaf(leaf, dtype=jnp.bfloat16):
    if isinstance(leaf, QuantizedTensor):
        q, scale, bits, shape = leaf.data, leaf.scale, leaf.bits, leaf.shape
    else:  # legacy marker-dict form
        q, scale, bits, shape = leaf["data"], leaf["scale"], leaf["bits"], tuple(leaf["shape"])
    if bits == 4:
        lo = (q & 0x0F).astype(jnp.int8)
        lo = jnp.where(lo > 7, lo - 16, lo)  # sign-extend nibble
        hi = (q >> 4) & 0x0F
        hi = jnp.where(hi > 7, hi - 16, hi).astype(jnp.int8)
        size = int(np.prod(shape))
        full = jnp.stack([lo, hi], axis=1).reshape(-1)[:size].reshape(shape)
        return (full * scale).astype(dtype)
    return (q.astype(jnp.float32) * scale).astype(dtype)


def is_quantized_leaf(x) -> bool:
    if isinstance(x, QuantizedTensor):
        return True
    return isinstance(x, dict) and x.get(QUANT_KEY) is True


def _should_quantize(name: str, leaf, config: QuantizationConfig) -> bool:
    arr = jnp.asarray(leaf) if not hasattr(leaf, "ndim") else leaf
    if arr.ndim < 2:
        return False  # norms/biases stay full precision (bnb skips nn.LayerNorm etc.)
    skip = list(config.skip_modules) + list(config.keep_in_fp32_modules)
    return not any(s and s in name for s in skip)


def quantize_tree(params, config: QuantizationConfig):
    """Quantize eligible leaves of a param pytree (quantized leaves become
    :class:`QuantizedTensor` pytree nodes)."""
    from .modeling import named_parameters

    flat = {}
    for name, leaf in named_parameters(params).items():
        if _should_quantize(name, leaf, config):
            flat[name] = quantize_leaf(leaf, config.bits)
        else:
            flat[name] = leaf
    return _unflatten_with_quant(flat, params)


def _unflatten_with_quant(flat: dict, template):
    """Like ``unflatten_names`` but rebuilds nested dicts directly; quantized
    leaves are :class:`QuantizedTensor` values placed as-is."""
    out = {}
    for name, value in flat.items():
        parts = name.split(".")
        node = out
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return out


def dequantize_tree(params, dtype=jnp.bfloat16):
    """Rebuild a full-precision tree (inverse of ``quantize_tree``)."""
    if is_quantized_leaf(params):
        return dequantize_leaf(params, dtype)
    if isinstance(params, dict):
        return {k: dequantize_tree(v, dtype) for k, v in params.items()}
    return params


def quantized_nbytes(params) -> int:
    return sum(np.asarray(leaf).nbytes for leaf in jax.tree_util.tree_leaves(params))


def load_and_quantize_model(
    model,
    weights_location: str | None = None,
    quantization_config: QuantizationConfig | None = None,
    device_map=None,
    no_split_module_classes=None,
    offload_folder: str | None = None,
):
    """bnb-parity entry point (reference ``load_and_quantize_model`` bnb.py:44-194):
    optionally load checkpoint weights, quantize the param tree in place, and hook
    ``model.apply`` so forwards see dequantized weights in ``compute_dtype``."""
    if quantization_config is None:
        raise ValueError("quantization_config is required")
    if weights_location is not None:
        from .modeling import load_checkpoint_in_model

        model.params = load_checkpoint_in_model(
            model.params, weights_location, device_map=device_map,
            offload_folder=offload_folder,
        )
    if model.params is None:
        raise ValueError("Model has no params; init or load weights first")
    model.params = quantize_tree(model.params, quantization_config)

    from ..hooks import DequantizeHook, add_hook_to_module

    add_hook_to_module(model, DequantizeHook(quantization_config.target_dtype))
    model.is_quantized = True
    return model
