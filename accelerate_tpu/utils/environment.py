"""Environment parsing/patching helpers.

Reference parity: ``src/accelerate/utils/environment.py`` — ``parse_flag_from_env``,
``parse_choice_from_env``, ``patch_environment`` (:326), ``clear_environment`` (:291),
``purge_accelerate_environment`` (:362-420). NUMA-affinity and CUDA-P2P checks are
GPU-specific and intentionally absent; the TPU analog (megacore/ICI layout) is owned
by the XLA runtime.
"""

from __future__ import annotations

import functools
import os
from contextlib import contextmanager

from .constants import ENV_COMPILE_CACHE_DIR, ENV_COMPILE_CACHE_MIN_SECS, ENV_PREFIX


def str_to_bool(value: str) -> int:
    """Convert a string (env var) to 1/0. Accepts y/yes/t/true/on/1 and n/no/f/false/off/0."""
    value = value.lower()
    if value in ("y", "yes", "t", "true", "on", "1"):
        return 1
    if value in ("n", "no", "f", "false", "off", "0"):
        return 0
    raise ValueError(f"invalid truth value {value!r}")


def parse_flag_from_env(key: str, default: bool = False) -> bool:
    value = os.environ.get(key, str(default))
    try:
        return bool(str_to_bool(value))
    except ValueError:
        raise ValueError(f"If set, {key} must be yes/no/1/0/true/false, got {value!r}.")


def parse_choice_from_env(key: str, default: str = "no") -> str:
    return os.environ.get(key, str(default))


def pin_cpu_platform(n_devices: int = 8) -> None:
    """Force the CPU backend with ``n_devices`` virtual devices.

    Single audited home for the axon workaround (the TPU plugin overrides
    JAX_PLATFORMS at import time and can hang backend init when the tunnel is
    absent, so we pin via jax.config — which wins — in addition to the env
    contract). Must run before the first jax backend touch in the process;
    callers that may run after backend init should verify
    ``len(jax.devices()) == n_devices`` afterward and fall back to a clean
    subprocess. Used by tests/conftest.py, __graft_entry__.py, and bench.py.
    """
    import re

    opt = f"--xla_force_host_platform_device_count={n_devices}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", opt, flags)
    else:
        flags = (flags + " " + opt).strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")


def maybe_enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``cache_dir`` (or the
    ``ACCELERATE_COMPILE_CACHE_DIR`` env contract). Returns the resolved
    directory, or None when the feature is not configured.

    Idempotent and safe to call at any point before the first compile of the
    programs that should hit the cache; ``PartialState`` calls it on
    construction so every entrypoint that builds an ``Accelerator`` (bench.py,
    launched scripts, notebook_launcher workers) gets it for free. XLA's
    default gates skip sub-second compiles, which on a tunneled or CPU test
    rig covers exactly nothing — ``ACCELERATE_COMPILE_CACHE_MIN_COMPILE_SECS``
    (default 0: persist everything) tunes that.
    """
    cache_dir = cache_dir or os.environ.get(ENV_COMPILE_CACHE_DIR) or None
    if not cache_dir:
        return None
    cache_dir = os.path.abspath(os.path.expanduser(cache_dir))
    os.makedirs(cache_dir, exist_ok=True)

    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    min_secs = float(os.environ.get(ENV_COMPILE_CACHE_MIN_SECS, "0") or 0.0)
    for opt, val in (
        ("jax_persistent_cache_min_compile_time_secs", min_secs),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(opt, val)
        except Exception:  # older jax without the knob — the dir alone works
            pass
    return cache_dir


def safe_donate_argnums(argnums: tuple) -> tuple:
    """Gate buffer donation on backends where it is actually safe.

    jaxlib 0.4.3x CPU executables **deserialized from the persistent
    compilation cache** mis-handle input-output aliasing: running them with
    donated inputs corrupts the allocator heap (reproducible segfault /
    ``malloc(): memory corruption`` once an orbax *restore* churns the heap —
    exactly the resume-after-restart path the compilation cache exists to
    accelerate). Donation on CPU buys nothing (host RAM, no HBM pressure), so
    when both features would combine — CPU backend AND an active persistent
    cache — donation is dropped; TPU/GPU always keep it, where it is the
    HBM-pressure win the fused train step is built around.
    """
    import jax

    if jax.default_backend() == "cpu" and jax.config.jax_compilation_cache_dir:
        return ()
    return tuple(argnums)


def get_int_from_env(env_keys, default: int) -> int:
    """Return the first positive int found among env_keys."""
    for key in env_keys:
        val = int(os.environ.get(key, -1))
        if val >= 0:
            return val
    return default


@contextmanager
def patch_environment(**kwargs):
    """Temporarily set environment variables; restores previous values on exit.

    Mirrors ``src/accelerate/utils/environment.py:326``.
    """
    existing = {}
    for key, value in kwargs.items():
        key = key.upper()
        if key in os.environ:
            existing[key] = os.environ[key]
        os.environ[key] = str(value)
    try:
        yield
    finally:
        for key in kwargs:
            key = key.upper()
            if key in existing:
                os.environ[key] = existing[key]
            else:
                os.environ.pop(key, None)


@contextmanager
def clear_environment():
    """Temporarily empty ``os.environ``; restores on exit (reference :291)."""
    saved = dict(os.environ)
    os.environ.clear()
    try:
        yield
    finally:
        os.environ.clear()
        os.environ.update(saved)


def purge_accelerate_environment(fn):
    """Decorator that runs ``fn`` with all ``ACCELERATE_*`` vars removed and restores
    them afterwards (reference :362-420). Used by the test harness for state hygiene.
    """

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        saved = {k: v for k, v in os.environ.items() if k.startswith(ENV_PREFIX)}
        for k in saved:
            del os.environ[k]
        try:
            return fn(*args, **kwargs)
        finally:
            for k in list(os.environ):
                if k.startswith(ENV_PREFIX):
                    del os.environ[k]
            os.environ.update(saved)

    return wrapper
