"""Model-placement algorithms: size accounting, memory balancing, placement planning,
checkpoint loading.

Reference parity: ``src/accelerate/utils/modeling.py`` (2,204 LoC) — the largest
algorithmic file there. The TPU re-design keeps the *planning algorithms* (greedy
layer placement with tied-weight and no-split handling: ``infer_auto_device_map``
:1307-1614, ``get_balanced_memory`` :948-1080, ``compute_module_sizes`` :681-722,
``find_tied_parameters`` :584-637, ``load_checkpoint_in_model`` :1809-2069,
``load_state_dict`` :1641-1735) but changes the object of planning:

- a "module" is a **prefix of the parameter pytree** (params are the model; there
  are no stateful submodules to move),
- a "device" is an entry of ``{"tpu:0": hbm_bytes, ..., "cpu": host_bytes,
  "disk": inf}`` — chips first, then host RAM, then disk, exactly the reference's
  ``max_memory`` contract,
- the plan's *execution* (``dispatch_model``) places each prefix's arrays on its
  assigned chip — or registers it for streaming from host/disk (``hooks.py``).

Parameters are described abstractly (``jax.ShapeDtypeStruct``) so planning a 70B
model costs no memory — the analog of the reference's meta-device trick.
"""

from __future__ import annotations

import json
import logging
import os
import re
from collections import defaultdict
from typing import Mapping

import numpy as np

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

from .constants import SAFE_WEIGHTS_INDEX_NAME, WEIGHTS_INDEX_NAME


# --------------------------------------------------------------------------- sizes
def dtype_byte_size(dtype) -> float:
    """Bytes per element (reference ``dtype_byte_size`` :658-678; handles sub-byte
    int4/fp4 the same way)."""
    dtype_str = str(jnp.dtype(dtype)) if not isinstance(dtype, str) else dtype
    if dtype_str == "bool":
        return 1 / 8
    m = re.search(r"[^\d](\d+)(_\w+)?$", dtype_str)
    if m is None:
        raise ValueError(f"`dtype` is not a valid dtype: {dtype}.")
    return int(m.group(1)) / 8


def named_parameters(params, prefix: str = "") -> dict:
    """Flatten a param pytree to ``{'a.b.c': leaf}`` (dot-joined, HF key style)."""
    from ..parallel.sharding import path_str

    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        key = path_str(path).replace("/", ".")
        flat[prefix + key] = leaf
    return flat


def _leaf_nbytes(leaf) -> int:
    shape = getattr(leaf, "shape", ())
    dtype = getattr(leaf, "dtype", jnp.float32)
    return int(np.prod(shape, dtype=np.int64) * dtype_byte_size(dtype)) if shape else int(
        dtype_byte_size(dtype)
    )


def compute_module_sizes(
    params, dtype=None, special_dtypes: Mapping[str, object] | None = None
) -> dict:
    """Size in bytes of every pytree prefix (reference ``compute_module_sizes``
    :681-722: each named parameter's size is charged to all its ancestors)."""
    sizes: dict[str, int] = defaultdict(int)
    for name, leaf in named_parameters(params).items():
        if special_dtypes is not None and name in special_dtypes:
            size = int(np.prod(leaf.shape, dtype=np.int64) * dtype_byte_size(special_dtypes[name]))
        elif dtype is not None:
            size = int(np.prod(leaf.shape, dtype=np.int64) * dtype_byte_size(dtype))
        else:
            size = _leaf_nbytes(leaf)
        sizes[""] += size
        parts = name.split(".")
        for i in range(1, len(parts) + 1):
            sizes[".".join(parts[:i])] += size
    return dict(sizes)


def compute_module_total_buffer_size(params, dtype=None) -> int:
    """Parity slot (reference :725-741): our models keep no non-param buffers; any
    pytree leaf is a parameter, so this is 0."""
    return 0


def calculate_maximum_sizes(params):
    """(total_size, largest_layer) — drives ``estimate-memory`` (reference
    ``calculate_maximum_sizes`` utils/modeling.py:1081-1098)."""
    sizes = compute_module_sizes(params)
    total = sizes.get("", 0)
    no_split = get_top_level_blocks(params)
    largest = max(((sizes[b], b) for b in no_split), default=(total, ""))
    return total, largest


# ----------------------------------------------------------------------- structure
def get_top_level_blocks(params) -> list[str]:
    """The placement granularity: repeated blocks (e.g. ``layers.0``..``layers.N``)
    plus top-level leaves — the analog of the reference's ``no_split_module_classes``
    boundary, derived structurally instead of by class name."""
    names = list(named_parameters(params))
    blocks: list[str] = []
    seen = set()
    for name in names:
        parts = name.split(".")
        # group 'layers.<i>.*' under 'layers.<i>'; everything else under its
        # first path component.
        if len(parts) >= 2 and parts[1].isdigit():
            block = ".".join(parts[:2])
        else:
            block = parts[0]
        if block not in seen:
            seen.add(block)
            blocks.append(block)
    return blocks


def find_tied_parameters(params) -> list[list[str]]:
    """Groups of names sharing one underlying array (reference
    ``find_tied_parameters`` :584-637 compares object identity; embedding/LM-head
    tying is the canonical case)."""
    by_id: dict[int, list[str]] = defaultdict(list)
    for name, leaf in named_parameters(params).items():
        by_id[id(leaf)].append(name)
    return [sorted(group) for group in by_id.values() if len(group) > 1]


# ------------------------------------------------------------------ device memory
# Shared headroom contract: planners budget 90% of capacity (the reference's
# ``get_max_memory`` scaling) — the same fraction the static memory auditor
# (analysis/memory.py) and ``accelerate-tpu memcheck`` gate their OOM verdict
# on, so "fits" means the same thing at plan time and at audit time.
HBM_HEADROOM = 0.9


def _device_hbm_bytes(device) -> int:
    stats_fn = getattr(device, "memory_stats", None)
    if stats_fn is not None:
        try:
            stats = stats_fn()
            if stats and "bytes_limit" in stats:
                return int(stats["bytes_limit"])
        except Exception:
            pass
    table = {  # per-chip HBM by generation
        "v4": 32 << 30,
        "v5 lite": 16 << 30,
        "v5litepod": 16 << 30,
        "v5p": 95 << 30,
        "v6 lite": 32 << 30,
        "v6e": 32 << 30,
    }
    kind = getattr(device, "device_kind", "").lower()
    for key, val in table.items():
        if key in kind:
            return val
    return 16 << 30  # conservative default; CPU "devices" in tests hit this too


def device_hbm_bytes(device=None) -> int:
    """Per-chip memory capacity in bytes: live ``memory_stats()['bytes_limit']``
    when the backend reports one, else the per-generation HBM table (v4 32G /
    v5e 16G / v5p 95G / v6e 32G; conservative 16G default). The denominator of
    both the placement planner's budgets and the static memory auditor's
    OOM verdict (analysis/memory.py)."""
    if device is None:
        device = jax.local_devices()[0]  # accelerate-lint: disable=raw-device-baseline
    return _device_hbm_bytes(device)


def get_max_memory(max_memory: Mapping | None = None) -> dict:
    """Available memory per placement target (reference ``get_max_memory``
    :774-857): all addressable chips (90% of HBM, like the reference's headroom
    scaling), then host RAM, then unbounded disk."""
    if max_memory is not None:
        out = {}
        for key, val in max_memory.items():
            out[key] = convert_file_size_to_int(val) if isinstance(val, str) else int(val)
        return out
    out = {}
    for i, dev in enumerate(jax.local_devices()):
        out[f"{dev.platform}:{i}"] = int(device_hbm_bytes(dev) * HBM_HEADROOM)
    try:
        host_bytes = os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError):  # pragma: no cover
        host_bytes = 64 << 30
    out["cpu"] = int(host_bytes * HBM_HEADROOM)
    return out


def convert_file_size_to_int(size: int | str) -> int:
    """'10GB'/'1GiB' → bytes (reference utils/modeling.py:100-134)."""
    if isinstance(size, int):
        return size
    mem_size = size.upper().strip()
    units = [
        ("GIB", 1 << 30), ("MIB", 1 << 20), ("KIB", 1 << 10),
        ("GB", 10**9), ("MB", 10**6), ("KB", 10**3), ("B", 1),
    ]
    for suffix, mult in units:
        if mem_size.endswith(suffix):
            return int(float(mem_size[: -len(suffix)]) * mult)
    if mem_size.isdigit():
        return int(mem_size)
    raise ValueError("`size` is not in a valid format. Use an integer or '10GB'/'1GiB'.")


def get_balanced_memory(
    params,
    max_memory: Mapping | None = None,
    no_split_module_classes=None,
    dtype=None,
    special_dtypes=None,
    low_zero: bool = False,
) -> dict:
    """Cap per-chip budgets so layers spread evenly instead of greedily filling
    chip 0 (reference ``get_balanced_memory`` :948-1080; ``low_zero`` keeps the
    first chip light for generate()-style peak activations)."""
    max_memory = get_max_memory(max_memory)
    accel_keys = [k for k in max_memory if k != "cpu" and k != "disk"]
    num_devices = len([k for k in accel_keys if max_memory[k] > 0])
    if num_devices == 0:
        return max_memory
    if num_devices == 1:
        low_zero = False

    sizes = compute_module_sizes(params, dtype=dtype, special_dtypes=special_dtypes)
    total = sizes.get("", 0)
    per_device = total // (num_devices - 1 if low_zero else num_devices)

    # Reference adds the mean block size as headroom so the greedy fit has slack.
    blocks = get_top_level_blocks(params)
    block_sizes = [sizes[b] for b in blocks if b in sizes]
    if block_sizes:
        mean_block = int(sum(block_sizes) / len(block_sizes))
        buffer = int(1.25 * max(block_sizes)) if len(block_sizes) > 1 else mean_block
        per_device += buffer

    out = dict(max_memory)
    for i, key in enumerate(accel_keys):
        budget = 0 if (low_zero and i == 0) else per_device
        out[key] = min(budget, max_memory[key])
    return out


# ------------------------------------------------------------- placement planning
def infer_auto_device_map(
    params,
    max_memory: Mapping | None = None,
    no_split_module_classes=None,
    dtype=None,
    special_dtypes=None,
    verbose: bool = False,
    clean_result: bool = True,
    offload_buffers: bool = False,
) -> dict:
    """Greedy block placement over chips → host → disk (reference
    ``infer_auto_device_map`` :1307-1614).

    Returns ``{block_prefix: target}`` with targets ``"tpu:i"``/``"cpu"``/``"disk"``.
    Tied-weight groups are co-located (the reference's hardest case, :1418-1519):
    when a block contains a parameter tied into an already-placed group, it is
    assigned to that group's target regardless of budget order.
    """
    max_memory = get_max_memory(max_memory)
    sizes = compute_module_sizes(params, dtype=dtype, special_dtypes=special_dtypes)
    blocks = get_top_level_blocks(params)
    tied_groups = find_tied_parameters(params)

    targets = [k for k in max_memory if k not in ("cpu", "disk")] + ["cpu", "disk"]
    budgets = {k: max_memory.get(k, 0) for k in targets}
    budgets["disk"] = float("inf")

    device_map: dict[str, str] = {}
    tied_target: dict[str, str] = {}  # param name -> placed target
    all_names = list(named_parameters(params))

    ti = 0
    for block in blocks:
        size = sizes.get(block, 0)
        block_params = [n for n in all_names if n == block or n.startswith(block + ".")]

        # Tied co-location first.
        forced = None
        for group in tied_groups:
            group_set = set(group)
            if any(p in group_set for p in block_params):
                placed = [tied_target[p] for p in group if p in tied_target]
                if placed:
                    forced = placed[0]
                    break
        if forced is not None:
            device_map[block] = forced
            if verbose:
                logger.info("block %s → %s (tied)", block, forced)
        else:
            while ti < len(targets) - 1 and budgets[targets[ti]] < size:
                if verbose:
                    logger.info(
                        "target %s full (%d left < %d needed)", targets[ti], budgets[targets[ti]], size
                    )
                ti += 1
            device_map[block] = targets[ti]
            budgets[targets[ti]] -= size
        for p in block_params:
            tied_target[p] = device_map[block]

    if clean_result:
        # Merge blocks that all landed on the same target under their parent
        # (reference clean_device_map :1287-1306).
        device_map = _clean_device_map(device_map)
    return device_map


def _clean_device_map(device_map: dict, module_name: str = "") -> dict:
    prefix = module_name + "." if module_name else ""
    values = [v for k, v in device_map.items() if k.startswith(prefix)]
    if len(set(values)) == 1 and len(values) > 1 and module_name:
        for k in [k for k in device_map if k.startswith(prefix)]:
            del device_map[k]
        device_map[module_name] = values[0]
    children = {k.split(".")[len(module_name.split(".")) if module_name else 0] for k in device_map
                if k != module_name and k.startswith(prefix)}
    for child in children:
        child_name = f"{module_name}.{child}" if module_name else child
        if child_name in device_map:
            continue
        _clean_device_map(device_map, child_name)
    return device_map


def check_device_map(params, device_map: dict) -> None:
    """Every parameter must be covered by some prefix (reference ``check_device_map``
    :1617-1638)."""
    names = list(named_parameters(params))
    uncovered = [
        n for n in names
        if not any(n == k or n.startswith(k + ".") or k == "" for k in device_map)
    ]
    if uncovered:
        raise ValueError(
            f"The device_map provided does not cover all parameters: {uncovered[:5]}"
            + ("..." if len(uncovered) > 5 else "")
        )


def check_tied_parameters_in_config(params, device_map: dict) -> list:
    """Tied groups split across targets (reference warns at :1418ff)."""
    bad = []
    for group in find_tied_parameters(params):
        placements = {param_target(n, device_map) for n in group}
        if len(placements) > 1:
            bad.append(group)
    return bad


def param_target(name: str, device_map: dict) -> str:
    """Resolve a parameter name through a prefix device_map."""
    best = None
    for key in device_map:
        if key == "" or name == key or name.startswith(key + "."):
            if best is None or len(key) > len(best):
                best = key
    if best is None:
        raise KeyError(f"{name} not covered by device_map")
    return device_map[best]


def device_for_target(target: str):
    """Map a plan target string to a jax.Device (or None for cpu/disk)."""
    if target in ("cpu", "disk"):
        return None
    plat, _, idx = target.partition(":")
    devices = [d for d in jax.local_devices() if d.platform == plat]
    if not devices:
        devices = jax.local_devices()
    return devices[int(idx) % len(devices)] if idx else devices[0]


# ------------------------------------------------------------ checkpoint loading
def load_state_dict(checkpoint_file: str, device_map: dict | None = None) -> dict:
    """Load a (safetensors|msgpack|pickle) shard lazily to host (reference
    ``load_state_dict`` :1641-1735 — safetensors framework='np' keeps it zero-copy
    mmap until arrays are consumed)."""
    if checkpoint_file.endswith(".safetensors"):
        from safetensors import safe_open

        out = {}
        with safe_open(checkpoint_file, framework="np") as f:
            for key in f.keys():
                out[key] = f.get_tensor(key)
        return out
    if checkpoint_file.endswith(".msgpack"):
        from flax import serialization

        with open(checkpoint_file, "rb") as fh:
            return serialization.msgpack_restore(fh.read())
    import pickle

    with open(checkpoint_file, "rb") as fh:
        return pickle.load(fh)


def load_checkpoint_in_model(
    params,
    checkpoint: str,
    device_map: dict | None = None,
    offload_folder: str | None = None,
    dtype=None,
    offload_state_dict: bool = False,
    strict: bool = False,
):
    """Fill an (abstract or concrete) param pytree from checkpoint file(s)
    (reference ``load_checkpoint_in_model`` :1809-2069).

    ``checkpoint`` may be a single ``.safetensors``/pickle file, a sharded-index
    json, or a directory containing either. Returns a new pytree whose leaves are
    host numpy arrays — or, for prefixes mapped to ``"disk"``, entries registered
    in ``offload_folder`` (see ``utils/offload.py``) with abstract leaves kept.
    """
    from .offload import offload_weight, save_offload_index

    files = _resolve_checkpoint_files(checkpoint)
    loaded: dict[str, np.ndarray] = {}
    for f in files:
        loaded.update(load_state_dict(f))

    names = named_parameters(params)
    missing = [n for n in names if n not in loaded]
    unexpected = [k for k in loaded if k not in names]
    if strict and (missing or unexpected):
        raise RuntimeError(
            f"Error loading state_dict: missing keys {missing[:5]}, unexpected {unexpected[:5]}"
        )

    offload_index: dict = {}
    out_flat = {}
    for name, leaf in names.items():
        if name not in loaded:
            out_flat[name] = leaf  # keep initialization (or abstract struct)
            continue
        value = loaded[name]
        if dtype is not None and np.issubdtype(value.dtype, np.floating):
            value = value.astype(jnp.dtype(dtype))
        target = param_target(name, device_map) if device_map else "cpu"
        if target == "disk":
            if offload_folder is None:
                raise ValueError("offload_folder required when device_map contains 'disk' entries")
            offload_weight(value, name, offload_folder, index=offload_index)
            out_flat[name] = jax.ShapeDtypeStruct(value.shape, value.dtype)
        else:
            out_flat[name] = value
    if offload_index:
        save_offload_index(offload_index, offload_folder)
    return unflatten_names(out_flat, params)


def _resolve_checkpoint_files(checkpoint: str) -> list[str]:
    if os.path.isdir(checkpoint):
        for index_name in (SAFE_WEIGHTS_INDEX_NAME, WEIGHTS_INDEX_NAME):
            index = os.path.join(checkpoint, index_name)
            if os.path.isfile(index):
                return _resolve_checkpoint_files(index)
        cand = sorted(
            os.path.join(checkpoint, f)
            for f in os.listdir(checkpoint)
            if f.endswith((".safetensors", ".msgpack"))
        )
        if cand:
            return cand
        raise ValueError(f"No checkpoint files found in directory {checkpoint}")
    if checkpoint.endswith(".index.json"):
        with open(checkpoint) as fh:
            index = json.load(fh)
        folder = os.path.dirname(checkpoint)
        return sorted({os.path.join(folder, f) for f in index["weight_map"].values()})
    if os.path.isfile(checkpoint):
        return [checkpoint]
    raise ValueError(f"Checkpoint {checkpoint} not found")


def unflatten_names(flat: dict, template) -> dict:
    """Rebuild a pytree with the template's structure from {'a.b.c': leaf}."""
    paths_and_leaves = jax.tree_util.tree_flatten_with_path(template)
    from ..parallel.sharding import path_str

    leaves = []
    for path, leaf in paths_and_leaves[0]:
        key = path_str(path).replace("/", ".")
        leaves.append(flat.get(key, leaf))
    return jax.tree_util.tree_unflatten(paths_and_leaves[1], leaves)


def get_mixed_precision_context_manager(*args, **kwargs):  # pragma: no cover
    """Parity slot (reference :2070-2113): dtype policy is applied inside compiled
    steps; there is no dynamic autocast context to build."""
    import contextlib

    return contextlib.nullcontext()


def align_module_device(*args, **kwargs):  # pragma: no cover - parity stub
    import contextlib

    return contextlib.nullcontext()
