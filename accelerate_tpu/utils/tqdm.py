"""Main-process-only progress bars (reference ``utils/tqdm.py``).

``tqdm(iterable, main_process_only=True)`` renders the bar only on the main
process so an N-process launch doesn't print N interleaved bars. Pass
``main_process_only=False`` to get a bar on every process.
"""

from __future__ import annotations


def tqdm(*args, main_process_only: bool = True, **kwargs):
    """Drop-in ``tqdm.auto.tqdm`` that is a no-op bar on non-main processes."""
    try:
        from tqdm.auto import tqdm as _tqdm
    except ImportError as e:  # pragma: no cover - tqdm ships with the image
        raise ImportError("tqdm is required for accelerate_tpu.utils.tqdm") from e

    if main_process_only:
        from ..state import PartialState

        if not PartialState().is_main_process:
            kwargs["disable"] = True
    return _tqdm(*args, **kwargs)
