"""OOM-resilient execution helpers.

Reference parity: ``src/accelerate/utils/memory.py`` — ``find_executable_batch_size``
(:119-183), ``release_memory`` (:70), ``clear_device_cache`` (:43). The reference
retries on CUDA OOM; on TPU the equivalent failure is an ``XlaRuntimeError`` whose
message carries ``RESOURCE_EXHAUSTED`` (HBM oversubscription detected at compile or
run time). The retry loop halves the batch size exactly like the reference.
"""

from __future__ import annotations

import functools
import gc
import inspect

import jax


def clear_device_cache(garbage_collection: bool = False) -> None:
    """Drop cached compiled programs and (optionally) force a GC pass.

    Reference ``clear_device_cache`` :43-67 calls per-backend ``empty_cache``; XLA has
    no user-managed allocator cache, but dropping dead compilation-cache entries and
    deleted-array references frees HBM held by live executables' donated aliases.
    """
    if garbage_collection:
        gc.collect()
    try:
        jax.clear_caches()
    except Exception:  # pragma: no cover - defensive; clear_caches is best-effort
        pass


def release_memory(*objects):
    """Drop references and clear caches; returns Nones in place of the inputs
    (reference ``release_memory`` :70-101 usage: ``a, b = release_memory(a, b)``)."""
    if not isinstance(objects, list):
        objects = list(objects)
    for i in range(len(objects)):
        objects[i] = None
    clear_device_cache(garbage_collection=True)
    return objects


def is_oom_exception(exception: BaseException) -> bool:
    """Whether an exception is an HBM/RAM exhaustion we can retry past.

    Reference ``should_reduce_batch_size`` :104-116 string-matches CUDA/CPU OOM; the
    XLA analogs are RESOURCE_EXHAUSTED statuses and allocation-failure messages.
    """
    statuses = (
        "RESOURCE_EXHAUSTED",
        "Out of memory",
        "out of memory",
        "Attempting to allocate",
        "Failed to allocate",
    )
    if isinstance(exception, MemoryError):
        return True
    msg = str(exception)
    return isinstance(exception, Exception) and any(s in msg for s in statuses)


def find_executable_batch_size(function=None, starting_batch_size: int = 128):
    """Decorator retrying ``function(batch_size, ...)`` with halved batch sizes on OOM.

    Mirrors reference :119-183 including the introspective error when the wrapped
    function doesn't take ``batch_size`` first. Each retry clears device caches so a
    previous attempt's compiled executable doesn't hold the HBM that made it fail.
    """
    if function is None:
        return functools.partial(find_executable_batch_size, starting_batch_size=starting_batch_size)

    batch_size_box = [starting_batch_size]

    @functools.wraps(function)
    def wrapper(*args, **kwargs):
        nonlocal batch_size_box
        batch_size_box[0] = starting_batch_size
        clear_device_cache(garbage_collection=True)
        params = list(inspect.signature(function).parameters.keys())
        if len(params) < (1 + len(args)) or params[0] != "batch_size":
            arg_str = ", ".join([f"{arg}={value}" for arg, value in zip(params[1:], args[1:])])
            raise TypeError(
                f"Batch size was passed into `{function.__name__}` as the first argument "
                f"when called.\nRemove this as the decorator already does so: "
                f"`{function.__name__}({arg_str})`"
            )
        while True:
            if batch_size_box[0] == 0:
                raise RuntimeError("No executable batch size found, reached zero.")
            try:
                return function(batch_size_box[0], *args, **kwargs)
            except Exception as e:
                if is_oom_exception(e):
                    clear_device_cache(garbage_collection=True)
                    batch_size_box[0] //= 2
                else:
                    raise

    return wrapper


def get_xpu_available_memory():  # pragma: no cover - parity stub
    raise NotImplementedError("XPU is not a TPU-framework target")
