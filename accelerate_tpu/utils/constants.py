"""Constants shared across the framework.

Reference parity: ``src/accelerate/utils/constants.py:20-33`` defines the checkpoint
file-name contract (model/optimizer/scheduler/sampler/scaler/rng file names). We keep
the same folder layout and naming so checkpoints are navigable by users coming from
the reference, while the array payloads are sharding-aware (orbax/tensorstore) rather
than pickled torch tensors.
"""

MODEL_NAME = "model"
OPTIMIZER_NAME = "optimizer"
SCHEDULER_NAME = "scheduler"
SAMPLER_NAME = "sampler"
DATALOADER_NAME = "dataloader"
RNG_STATE_NAME = "random_states"
PARAMS_NAME = "params"

SAFE_WEIGHTS_NAME = "model.safetensors"
SAFE_WEIGHTS_INDEX_NAME = "model.safetensors.index.json"
WEIGHTS_NAME = "model.msgpack"
WEIGHTS_INDEX_NAME = "model.msgpack.index.json"
SAFE_WEIGHTS_PATTERN_NAME = "model{suffix}.safetensors"
WEIGHTS_PATTERN_NAME = "model{suffix}.msgpack"

# Sharded (orbax-style) checkpoint directory names inside a checkpoint folder.
SHARDED_MODEL_DIR = "model_sharded"
SHARDED_OPTIMIZER_DIR = "optimizer_sharded"

# Environment-variable contract (consumed by PartialState / AcceleratorState and set
# by the launcher, mirroring the reference's ACCELERATE_* contract set in
# src/accelerate/utils/launch.py:100-352).
ENV_PREFIX = "ACCELERATE_"
ENV_COORDINATOR = "ACCELERATE_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "ACCELERATE_NUM_PROCESSES"
ENV_PROCESS_ID = "ACCELERATE_PROCESS_ID"
ENV_MIXED_PRECISION = "ACCELERATE_MIXED_PRECISION"
ENV_CPU = "ACCELERATE_USE_CPU"
ENV_DEBUG_MODE = "ACCELERATE_DEBUG_MODE"
ENV_MESH_SHAPE = "ACCELERATE_MESH_SHAPE"
# Persistent XLA compilation cache (jax_compilation_cache_dir): set to a
# directory to stop every process start from re-paying minutes of compiles.
ENV_COMPILE_CACHE_DIR = "ACCELERATE_COMPILE_CACHE_DIR"
ENV_COMPILE_CACHE_MIN_SECS = "ACCELERATE_COMPILE_CACHE_MIN_COMPILE_SECS"
# Resilience contract (resilience/): install the SIGTERM/SIGINT preemption
# watcher at PartialState init, the deterministic fault-injection plan
# ("step:<N>=<action>[:<arg>];..."), and the gang incarnation counter the
# launcher increments on every relaunch (TORCHELASTIC_RESTART_COUNT analog).
ENV_HANDLE_PREEMPTION = "ACCELERATE_HANDLE_PREEMPTION"
ENV_FAULT_PLAN = "ACCELERATE_FAULT_PLAN"
ENV_RESTART_ATTEMPT = "ACCELERATE_RESTART_ATTEMPT"
# Elastic world-size training (resilience/elastic.py): opt run_resilient into
# re-forming the mesh at whatever dp degree the surviving devices support
# after a shrink/grow, and the floor below which a shrink refuses to re-form
# (the job would rather queue for capacity than limp on too few replicas).
ENV_ELASTIC = "ACCELERATE_ELASTIC"
ENV_MIN_DATA_PARALLEL = "ACCELERATE_MIN_DATA_PARALLEL"
# Training-health contract (health/): the always-on numerics sentinel ("0"
# disables it), the loss-spike robust z-score threshold, and the hang
# watchdog's heartbeat deadline in seconds (installed at PartialState init so
# a hang during the first compile is still caught once stepping begins).
ENV_GUARD_NUMERICS = "ACCELERATE_GUARD_NUMERICS"
ENV_SPIKE_ZSCORE = "ACCELERATE_SPIKE_ZSCORE"
ENV_HANG_TIMEOUT = "ACCELERATE_HANG_TIMEOUT"
# Telemetry contract (telemetry/): the always-on step timeline + span ring
# ("0" disables the per-step hooks), the opt-in Prometheus endpoint's port
# (empty or 0 = no HTTP server; the registry still feeds the tracker stack),
# and the straggler monitor's slowness ratio (a host slower than threshold ×
# the cross-host median step time raises a rate-limited warning).
ENV_TELEMETRY = "ACCELERATE_TELEMETRY"
ENV_METRICS_PORT = "ACCELERATE_METRICS_PORT"
ENV_STRAGGLER_THRESHOLD = "ACCELERATE_STRAGGLER_THRESHOLD"
# Profiling & flight recorder (telemetry/profiler.py / flight.py;
# docs/observability.md "Profiling"): explicit capture step ranges
# ("10-12,50" — 1-based, inclusive), the slow-step robust z-score trigger
# (tri-state like telemetry: unset = library default off, an explicit 0
# disables), the capture output root, the max-captures-per-run budget, and
# where flight-recorder black-box dumps land.
ENV_PROFILE_STEPS = "ACCELERATE_PROFILE_STEPS"
ENV_PROFILE_SLOW_ZSCORE = "ACCELERATE_PROFILE_SLOW_ZSCORE"
ENV_PROFILE_DIR = "ACCELERATE_PROFILE_DIR"
ENV_PROFILE_MAX_CAPTURES = "ACCELERATE_PROFILE_MAX_CAPTURES"
ENV_FLIGHT_DIR = "ACCELERATE_FLIGHT_DIR"
# Fleet observability plane (telemetry/fleet.py / slo.py;
# docs/observability.md "Fleet aggregation" / "SLO sentinel"): opt the lead
# host into aggregating every worker's registered metrics endpoint at /fleet
# (tri-state like telemetry — an explicit 0 reaches workers as a disable),
# and the continuous SLO targets the sentinel evaluates (seconds; tri-state
# per the profile_slow_zscore precedent — an explicit 0 scrubs an inherited
# value and disables that dimension).
ENV_FLEET_METRICS = "ACCELERATE_FLEET_METRICS"
ENV_SLO_STEP_TIME = "ACCELERATE_SLO_STEP_TIME"
ENV_SLO_TTFT = "ACCELERATE_SLO_TTFT"
ENV_SLO_TPOT = "ACCELERATE_SLO_TPOT"
# Disaggregated serving tier (serving_net/; docs/serving.md "Disaggregated
# serving"): which role this process plays in a multi-host serving fleet —
# ``unified`` (the single-host default: prefill + decode in one engine),
# ``prefill`` (chunked prefill only; finished KV chains ship to a decode
# host), ``decode`` (imports chains and decodes), or ``router`` (the
# prefix-affinity front door). Tri-state per the kernels precedent: unset =
# unified, an explicit ``unified`` scrubs an inherited value. The router
# endpoint is where non-router workers report for rollup joins (and where
# clients point at the fleet); tri-state like profile_steps ('' scrubs).
ENV_SERVING_ROLE = "ACCELERATE_SERVING_ROLE"
ENV_ROUTER_ENDPOINT = "ACCELERATE_ROUTER_ENDPOINT"
# Serving-tier fault tolerance (serving_net/lease.py; docs/serving.md
# "Failure semantics"): how many times the router re-dispatches a failed
# request on a surviving worker under the same rid, how long a worker's
# heartbeat-refreshed discovery lease stays valid without a refresh, and how
# long a SIGTERM'd serving worker waits for in-flight requests before it
# exits. All three are tri-state per the SLO precedent — unset = library
# default (2 retries / 15 s TTL / 30 s grace), an explicit 0 scrubs an
# inherited value back to the default.
ENV_SERVING_RETRY_BUDGET = "ACCELERATE_SERVING_RETRY_BUDGET"
ENV_SERVING_LEASE_TTL = "ACCELERATE_SERVING_LEASE_TTL"
ENV_DRAIN_GRACE_S = "ACCELERATE_DRAIN_GRACE_S"
# Durable telemetry journal (telemetry/journal.py; docs/observability.md
# "Telemetry journal & fleet timeline"): a directory arms the append-only
# per-host JSONL journal that sinks every stream the process already pays for
# (step boundaries, spans, flight events, request legs, SLO breaches, goodput
# deltas) — SIGKILL-durable per the JSONTracker flush-per-record precedent,
# bounded by size rotation. Tri-state like profile_steps: unset = journaling
# off (zero cost), a path = on, an explicit '' scrubs an inherited value.
# The ring-size knobs tune the in-memory RequestTracer / FlightRecorder
# retention (tri-state per the SLO precedent — an explicit 0 scrubs an
# inherited value back to the library defaults of 1024 / 2048 events).
ENV_JOURNAL_DIR = "ACCELERATE_JOURNAL_DIR"
ENV_TRACE_RING = "ACCELERATE_TRACE_RING"
ENV_FLIGHT_RING = "ACCELERATE_FLIGHT_RING"
# Dispatch amortization (docs/performance.md "Dispatch amortization"): the
# default K for Accelerator.build_train_window (1 = one dispatch per step),
# and the curated XLA latency-hiding flag preset installed into
# LIBTPU_INIT_ARGS at PartialState init, before backend creation
# (utils/xla_flags.py: off | latency | collective_matmul).
ENV_TRAIN_WINDOW = "ACCELERATE_TRAIN_WINDOW"
ENV_XLA_PRESET = "ACCELERATE_XLA_PRESET"

# Profile-guided autotuner (tune/; docs/tuning.md): the max short-bench trials
# one `accelerate-tpu tune` run may spend. Tri-state per the train-window
# precedent — unset = library default (tune/space.DEFAULT_TUNE_BUDGET), a
# positive value caps the trials, and the launcher scrubs an explicit 0 so a
# stale inherited value never leaks into a child run.
ENV_TUNE_BUDGET = "ACCELERATE_TUNE_BUDGET"

# Cross-replica (ZeRO-style) sharding of optimizer state + the weight update
# along the dp axis (arxiv 2004.13336): opt-state HBM drops to ~1/dp and the
# fused update lowers as reduce-scatter(grads) → sharded clip+update →
# all-gather(new params). Launcher contract: ``--zero_sharding`` /
# ``--no-zero_sharding`` (tri-state; an explicit off scrubs an inherited env).
ENV_ZERO_SHARDING = "ACCELERATE_ZERO_SHARDING"

# Pallas kernel layer (ops/pallas/, ops/registry.py; docs/kernels.md): the
# per-op backend spec. A bare token applies to every registered op
# (``pallas`` — compiled Mosaic on TPU, interpret-mode elsewhere;
# ``interpret`` — force the interpreter (CPU parity testing); ``reference`` /
# ``off`` — the always-available reference lowerings), or a comma-separated
# per-op map like ``paged_decode=pallas,int8_matmul=off``. Launcher contract:
# ``--kernels`` (tri-state; an explicit off scrubs an inherited env).
ENV_KERNELS = "ACCELERATE_KERNELS"

# Serving decode-speed levers (serving.py; docs/serving.md "Speculative
# decoding" / "Quantized KV blocks"): how many draft tokens each verify round
# proposes per slot (0 = speculation off), which zoo config preset builds the
# deterministically-initialized draft model when the engine isn't handed one
# (``tiny`` default; checkpointed drafts pass draft_model= in code), and the
# paged pool's block storage dtype (``int8`` = quantized blocks with
# per-token scales; unset/empty = the cache dtype). All tri-state per the
# kernels precedent — the launcher scrubs an explicit 0/empty so a stale
# inherited value never leaks into a child run.
ENV_SPECULATIVE_K = "ACCELERATE_SPECULATIVE_K"
ENV_DRAFT_MODEL = "ACCELERATE_DRAFT_MODEL"
ENV_KV_QUANT = "ACCELERATE_KV_QUANT"

# ``dcn`` is the slice axis of a multi-slice pod: replicas connected by
# data-center network rather than ICI. It is outermost so only the axes meant
# to cross slices (data parallelism / LocalSGD replicas) ever ride DCN; all
# model axes (pp/tp/sp/ep, and fsdp by default) stay inside a slice's ICI.
MESH_AXIS_ORDER = ("dcn", "pp", "dp", "fsdp", "ep", "sp", "tp")
BATCH_SHARDING_AXES = ("dcn", "dp", "fsdp")

# Default config location, mirroring the reference's
# ~/.cache/huggingface/accelerate/default_config.yaml
# (src/accelerate/commands/config/config_args.py:30-41).
DEFAULT_CONFIG_FOLDER = "accelerate_tpu"
DEFAULT_CONFIG_FILE = "default_config.yaml"

CHECKPOINT_DIR_PREFIX = "checkpoint"

MITA_PROFILE_DIR = "profile_trace"
