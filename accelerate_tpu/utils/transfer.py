"""Counted device→host fetches — the instrument behind the async-hot-loop tests.

The hot training loop must never stall the dispatching thread on a device→host
round-trip: a blocking fetch serializes dispatch behind the device, turning an
async pipeline into lock-step. Every place the framework *deliberately* pulls a
scalar to the host (the optimizer's deferred ``found_inf`` resolution, the
health guard's verdict drain) routes through :func:`host_fetch`, so tests can
assert the hot path's transfer budget instead of hoping.

A fetch of an array whose result is already materialized (``Array.is_ready()``)
costs a copy but no stall; a fetch of an in-flight array additionally counts as
*blocking* — the thing the deferred-resolution machinery exists to avoid.
"""

from __future__ import annotations

import numpy as np

_stats = {"fetches": 0, "blocking": 0}


def array_is_ready(x) -> bool:
    """Whether ``x``'s result is materialized (True for non-jax values)."""
    is_ready = getattr(x, "is_ready", None)
    if callable(is_ready):
        try:
            return bool(is_ready())
        except Exception:
            return True
    return True


def host_fetch(x):
    """Pull ``x`` to the host as numpy, counting the transfer (and whether it
    had to block on an unmaterialized result)."""
    _stats["fetches"] += 1
    if not array_is_ready(x):
        _stats["blocking"] += 1
    return np.asarray(x)


def transfer_stats() -> dict:
    """Snapshot of the counters: ``{"fetches": total, "blocking": stalls}``."""
    return dict(_stats)


def reset_transfer_stats():
    _stats["fetches"] = 0
    _stats["blocking"] = 0
