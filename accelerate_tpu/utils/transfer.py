"""Counted host↔device transfers — the instrument behind the async-hot-loop tests.

The hot training loop must never stall the dispatching thread on a transfer in
either direction:

- **device→host**: a blocking fetch serializes dispatch behind the device,
  turning an async pipeline into lock-step. Every place the framework
  *deliberately* pulls a scalar to the host (the optimizer's deferred
  ``found_inf`` resolution, the health guard's verdict drain) routes through
  :func:`host_fetch`, so tests can assert the hot path's transfer budget
  instead of hoping. A fetch of an array whose result is already materialized
  (``Array.is_ready()``) costs a copy but no stall; a fetch of an in-flight
  array additionally counts as *blocking*.
- **host→device**: a synchronous batch upload idles the accelerator between
  steps. The :class:`~..data_loader.DeviceBatchPrefetcher` moves every input
  ``device_put`` onto a background thread and routes it through
  :func:`host_put`; when the *training* thread has to wait for a batch that
  is not staged yet, the wait is recorded via :func:`record_input_wait` as a
  blocking input transfer plus its wall-clock cost — which is what lets the
  prefetcher's zero-blocking claim be measured, not asserted.

``StepTimeline.summary()`` and the Prometheus registry expose both directions.
"""

from __future__ import annotations

import numpy as np

_stats = {
    "fetches": 0,       # deliberate device→host fetches
    "blocking": 0,      # ...that stalled on an unmaterialized result
    "h2d_puts": 0,      # deliberate host→device batch uploads
    "h2d_blocking": 0,  # input waits: the train loop stalled on an upload
    "input_wait_s": 0.0,  # wall-clock the train loop spent in those stalls
    # Bumped by reset_transfer_stats: consumers holding a delta baseline
    # (StepTimeline._transfer0) compare generations and re-anchor at zero
    # instead of producing negative deltas when someone resets the globals
    # underneath them.
    "resets": 0,
}


def array_is_ready(x) -> bool:
    """Whether ``x``'s result is materialized (True for non-jax values)."""
    is_ready = getattr(x, "is_ready", None)
    if callable(is_ready):
        try:
            return bool(is_ready())
        except Exception:
            return True
    return True


def host_fetch(x):
    """Pull ``x`` to the host as numpy, counting the transfer (and whether it
    had to block on an unmaterialized result)."""
    _stats["fetches"] += 1
    if not array_is_ready(x):
        _stats["blocking"] += 1
    return np.asarray(x)


def host_put(x, placer):
    """Dispatch a deliberate host→device upload: ``placer(x)`` (a
    ``device_put``/``make_global_batch`` closure), counted. The put itself is
    async — dispatching it never blocks — so blocking is accounted on the
    *consumer* side via :func:`record_input_wait`, not here."""
    _stats["h2d_puts"] += 1
    return placer(x)


def host_view(x):
    """``np.asarray`` with the counting discipline: a device array routes
    through :func:`host_fetch` (counted, blocking-aware); host data passes
    through uncounted. The lint-clean spelling for code paths that legitimately
    handle both (``utils/operations.py``'s eager collectives, batch
    canonicalization)."""
    if callable(getattr(x, "is_ready", None)):
        return host_fetch(x)
    return np.asarray(x)


def record_input_wait(seconds: float):
    """The training thread waited ``seconds`` for an input batch that was not
    staged on device yet — one blocking host→device transfer from the hot
    loop's point of view (the thing the prefetch depth exists to avoid)."""
    _stats["h2d_blocking"] += 1
    _stats["input_wait_s"] += float(seconds)


def transfer_stats() -> dict:
    """Snapshot of every counter (both directions)."""
    return dict(_stats)


def reset_transfer_stats():
    _stats["fetches"] = 0
    _stats["blocking"] = 0
    _stats["h2d_puts"] = 0
    _stats["h2d_blocking"] = 0
    _stats["input_wait_s"] = 0.0
    _stats["resets"] += 1
