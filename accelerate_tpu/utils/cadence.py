"""Shared windowed-cadence predicate.

Windowed dispatch (``Accelerator.build_train_window``) hands hooks one
boundary per K steps, so every every-N-steps consumer (snapshot capture,
straggler exchange) must fire when ANY in-window step crossed its cadence —
a boundary-only ``step % N == 0`` silently degrades the cadence to
``lcm(K, N)``. One definition so the consumers cannot drift apart.
"""

from __future__ import annotations


def window_cadence_due(step: int, window: int, every_steps: int,
                       include_step0: bool = False) -> bool:
    """True when any step in ``(step - window, step]`` lands on the cadence.

    ``include_step0`` controls whether step 0 (and negatives) count: snapshot
    capture wants them (a run's first boundary should capture), the straggler
    exchange does not (there is no step-time window to exchange before the
    first completed step).
    """
    lo = step - max(int(window), 1)
    return any(
        (include_step0 or s > 0) and s % every_steps == 0
        for s in range(lo + 1, step + 1)
    )
