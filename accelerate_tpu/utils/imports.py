"""Availability probes.

Reference parity: ``src/accelerate/utils/imports.py`` (542 LoC of ``is_*_available``
probes, :61-250+). The TPU build's dependency surface is much smaller — JAX is the
substrate, not an optional backend — so probes cover the libraries this framework
can *optionally* use, and GPU-era probes exist as honest ``False`` parity slots.
"""

from __future__ import annotations

import functools
import importlib.metadata
import importlib.util


@functools.lru_cache(maxsize=None)
def _is_package_available(pkg_name: str, metadata_name: str | None = None) -> bool:
    if importlib.util.find_spec(pkg_name) is None:
        return False
    try:
        importlib.metadata.version(metadata_name or pkg_name)
        return True
    except importlib.metadata.PackageNotFoundError:
        # Namespace/source-only packages have a spec but no dist metadata.
        return True


def is_jax_available() -> bool:
    return _is_package_available("jax")


def is_flax_available() -> bool:
    return _is_package_available("flax")


def is_optax_available() -> bool:
    return _is_package_available("optax")


def is_orbax_available() -> bool:
    return _is_package_available("orbax")


def is_chex_available() -> bool:
    return _is_package_available("chex")


def is_torch_available() -> bool:
    return _is_package_available("torch")


def is_transformers_available() -> bool:
    return _is_package_available("transformers")


def is_datasets_available() -> bool:
    return _is_package_available("datasets")


def is_safetensors_available() -> bool:
    return _is_package_available("safetensors")


def is_einops_available() -> bool:
    return _is_package_available("einops")


def is_torchdata_stateful_dataloader_available() -> bool:
    if not _is_package_available("torchdata"):
        return False
    try:
        from torchdata.stateful_dataloader import StatefulDataLoader  # noqa: F401

        return True
    except ImportError:
        return False


def is_tpu_available(check_device: bool = True) -> bool:
    """Whether a real TPU backend is reachable (reference ``is_torch_xla_available``)."""
    if not is_jax_available():
        return False
    if not check_device:
        return True
    import jax

    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def is_pallas_available() -> bool:
    """Whether jax.experimental.pallas imports (the custom-kernel path)."""
    try:
        from jax.experimental import pallas  # noqa: F401

        return True
    except ImportError:
        return False


# Tracker backends (reference tracking.py guards on these).
def is_tensorboard_available() -> bool:
    return _is_package_available("tensorboardX") or _is_package_available(
        "tensorboard", "tensorboard"
    )


def is_wandb_available() -> bool:
    return _is_package_available("wandb")


def is_comet_ml_available() -> bool:
    return _is_package_available("comet_ml")


def is_mlflow_available() -> bool:
    return _is_package_available("mlflow")


def is_aim_available() -> bool:
    return _is_package_available("aim")


def is_clearml_available() -> bool:
    return _is_package_available("clearml")


def is_dvclive_available() -> bool:
    return _is_package_available("dvclive")


def is_rich_available() -> bool:
    return _is_package_available("rich")


def is_tqdm_available() -> bool:
    return _is_package_available("tqdm")


def is_pandas_available() -> bool:
    return _is_package_available("pandas")


def is_matplotlib_available() -> bool:
    return _is_package_available("matplotlib")


# GPU-era parity slots: these backends do not exist in the TPU stack. Honest False
# keeps downstream feature-gating code portable from the reference ecosystem.
def is_cuda_available() -> bool:
    return False


def is_deepspeed_available() -> bool:
    return False


def is_megatron_lm_available() -> bool:
    return False


def is_bnb_available() -> bool:
    return False


def is_transformer_engine_available() -> bool:
    return False


def is_msamp_available() -> bool:
    return False


def is_torchao_available() -> bool:
    return False
