"""Pytree collectives & data-movement veneer — the L2 communication layer.

Reference parity: ``src/accelerate/utils/operations.py`` (866 LoC). The reference
wraps torch.distributed point ops (all_gather/broadcast/all_reduce) applied
recursively over nested containers; each rank holds a *local* tensor. Under JAX
there are two regimes and this module bridges both:

- **host-level** (outside jit, one value per process on a pod):
  ``jax.experimental.multihost_utils`` — ``process_allgather`` /
  ``broadcast_one_to_all`` ride a tiny compiled collective over ICI/DCN. These are
  the direct analogs of the reference's eager NCCL calls.
- **global arrays** (the steady state inside our framework): a ``jax.Array`` is
  already global across the mesh; ``gather`` just makes it fully addressable.

Collectives *inside* the compiled step (psum/all_gather/ppermute) are not here —
XLA inserts them from sharding annotations (GSPMD), or ``parallel/`` modules spell
them with ``shard_map``. That split — eager veneer here, compiled collectives by
annotation — is the TPU-native answer to the reference's single eager API.

The reference's nested-container idiom (``recursively_apply`` :84-133) maps to
``jax.tree_util``; the debug-mode shape sanitizer (``verify_operation`` :363-415)
is reimplemented on process_allgather.
"""

from __future__ import annotations

import pickle
from functools import wraps
from typing import Any, Callable, Mapping

import numpy as np

import jax
import jax.numpy as jnp

from .environment import parse_flag_from_env
from .constants import ENV_DEBUG_MODE
from .transfer import host_view


def PartialState():
    """Lazy accessor for the state singleton (breaks the utils↔state import cycle)."""
    from ..state import PartialState as _PartialState

    return _PartialState()


class DistributedOperationException(Exception):
    """Raised by debug-mode pre-checks when processes would call a collective with
    mismatched structure (reference ``operations.py:354-360``)."""


def is_tensor_like(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray)) or hasattr(x, "__jax_array__")


def recursively_apply(
    func: Callable,
    data: Any,
    *args,
    test_type: Callable = is_tensor_like,
    error_on_other_type: bool = False,
    **kwargs,
):
    """Apply ``func`` to every tensor leaf of a nested list/tuple/dict structure.

    Reference ``operations.py:84-133``. Non-tensor leaves pass through unless
    ``error_on_other_type``.
    """
    if isinstance(data, (list, tuple)):
        out = [
            recursively_apply(
                func, o, *args, test_type=test_type, error_on_other_type=error_on_other_type, **kwargs
            )
            for o in data
        ]
        if isinstance(data, tuple):
            if hasattr(data, "_fields"):  # namedtuple
                return type(data)(*out)
            return tuple(out)
        return out
    if isinstance(data, Mapping):
        return type(data)(
            {
                k: recursively_apply(
                    func, v, *args, test_type=test_type, error_on_other_type=error_on_other_type, **kwargs
                )
                for k, v in data.items()
            }
        )
    if test_type(data):
        return func(data, *args, **kwargs)
    if error_on_other_type:
        raise TypeError(
            f"Unsupported type {type(data)} passed — only nested containers of arrays are supported."
        )
    return data


# --------------------------------------------------------------------- movement
def send_to_device(data, device=None, non_blocking: bool = False, skip_keys=None):
    """Recursively place arrays on a device or sharding (reference :135-185).

    ``device`` may be a ``jax.Device``, a ``jax.sharding.Sharding``, or the strings
    ``"cpu"`` / ``"device"``. JAX transfers are always async; ``non_blocking`` is a
    parity slot.
    """
    state = PartialState()
    if device is None or device == "device":
        device = state.device
    elif device == "cpu":
        device = jax.local_devices(backend="cpu")[0] if jax.default_backend() != "cpu" else state.device
    if isinstance(skip_keys, str):
        skip_keys = [skip_keys]

    def _put(t):
        return jax.device_put(t, device)

    if skip_keys:
        # Propagate skip_keys through every nesting level (reference :164-177).
        if isinstance(data, Mapping):
            return type(data)(
                {
                    k: (v if k in skip_keys else send_to_device(v, device, skip_keys=skip_keys))
                    for k, v in data.items()
                }
            )
        if isinstance(data, (list, tuple)):
            out = [send_to_device(v, device, skip_keys=skip_keys) for v in data]
            if isinstance(data, tuple):
                return type(data)(*out) if hasattr(data, "_fields") else tuple(out)
            return out
    return recursively_apply(_put, data)


def get_data_structure(data):
    """Shapes+dtypes pytree describing ``data`` (reference :188-210)."""
    return recursively_apply(lambda t: jax.ShapeDtypeStruct(np.shape(t), host_view(t).dtype if not isinstance(t, jax.Array) else t.dtype), data)


def find_batch_size(data):
    """First dimension of the first array leaf (reference :254-274)."""
    leaves = [l for l in jax.tree_util.tree_leaves(data) if is_tensor_like(l)]
    if not leaves:
        raise ValueError(f"Cannot find batch size in {type(data)}")
    if leaves[0].ndim == 0:
        raise ValueError("0-d array has no batch dimension")
    return leaves[0].shape[0]


def ignorant_find_batch_size(data):
    try:
        return find_batch_size(data)
    except (ValueError, TypeError):
        return None


def listify(data):
    """Arrays → nested Python lists (reference :277-292)."""
    return recursively_apply(lambda t: host_view(t).tolist(), data)


def slice_tensors(data, tensor_slice, process_index=None, num_processes=None):
    """Apply ``[tensor_slice]`` to every array leaf (reference :570-585)."""
    return recursively_apply(lambda t: t[tensor_slice], data)


def concatenate(data, dim: int = 0):
    """Concatenate a list of structurally-identical pytrees leafwise (reference :587-610)."""
    first = data[0]
    if isinstance(first, (list, tuple)):
        return type(first)(concatenate([d[i] for d in data], dim=dim) for i in range(len(first)))
    if isinstance(first, Mapping):
        return type(first)({k: concatenate([d[k] for d in data], dim=dim) for k in first.keys()})
    return jnp.concatenate([jnp.asarray(d) for d in data], axis=dim)


def convert_to_fp32(data):
    """Cast half-precision leaves to fp32 (reference :764-786)."""

    def _cast(t):
        t = jnp.asarray(t)
        if t.dtype in (jnp.bfloat16, jnp.float16):
            return t.astype(jnp.float32)
        return t

    return recursively_apply(_cast, data)


class ConvertOutputsToFp32:
    """Picklable post-step fp32 cast wrapper (reference :788-823)."""

    def __init__(self, model_forward):
        self.model_forward = model_forward
        wraps(model_forward)(self)

    def __call__(self, *args, **kwargs):
        return convert_to_fp32(self.model_forward(*args, **kwargs))

    def __getstate__(self):
        raise pickle.PicklingError(
            "Cannot pickle a wrapped forward; unwrap with extract_model_from_parallel first."
        )


convert_outputs_to_fp32 = ConvertOutputsToFp32


# -------------------------------------------------------------- debug sanitizer
def _operation_signature(data) -> list:
    return [
        (tuple(np.shape(l)), str(host_view(l).dtype) if not isinstance(l, jax.Array) else str(l.dtype))
        for l in jax.tree_util.tree_leaves(data)
        if is_tensor_like(l)
    ]


def verify_operation(function):
    """Debug-mode collective pre-check (reference ``operations.py:363-396``): with
    ``ACCELERATE_DEBUG_MODE=1`` every process gathers every process's leaf
    shapes/dtypes before the collective and raises ``DistributedOperationException``
    on mismatch — turning a hang into an error message."""

    @wraps(function)
    def wrapper(*args, **kwargs):
        state = PartialState()
        if not (getattr(state, "debug", False) or parse_flag_from_env(ENV_DEBUG_MODE)) or state.num_processes == 1:
            return function(*args, **kwargs)
        tensor = kwargs.get("tensor", args[0] if args else None)
        sig = _operation_signature(tensor)
        sigs = gather_object([sig])
        if not all(s == sigs[0] for s in sigs):
            raise DistributedOperationException(
                f"Cannot apply {function.__name__}: process shapes/dtypes mismatch.\n"
                + "\n".join(f"  - Process {i}: {s}" for i, s in enumerate(sigs))
            )
        return function(*args, **kwargs)

    return wrapper


# ----------------------------------------------------------------- collectives
def _is_global_unaddressable(x) -> bool:
    return isinstance(x, jax.Array) and not x.is_fully_addressable


def _host_allgather(t, tiled: bool):
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(host_view(t), tiled=tiled)


@verify_operation
def gather(tensor):
    """All-gather along dim 0 (reference :418-434).

    - Global (multi-host-sharded) ``jax.Array`` → materialized everywhere.
    - Host-local array on a pod → concatenation of every process's value
      (shape ``(num_processes * B, ...)``), matching the reference contract.
    - Single process → unchanged.
    """
    state = PartialState()

    def _gather_one(t):
        if _is_global_unaddressable(t):
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(t, tiled=True)
        if state.num_processes > 1:
            return _host_allgather(t, tiled=True)
        return t

    return recursively_apply(_gather_one, tensor)


def gather_object(object: Any):
    """Gather arbitrary picklable objects from every process into a list
    (reference :444-461; notably *not* implemented for torch-XLA there — native
    JAX multihost makes it straightforward, via length-padded pickle buffers)."""
    state = PartialState()
    if state.num_processes == 1:
        return list(object) if isinstance(object, list) else [object]
    payload = np.frombuffer(pickle.dumps(object), dtype=np.uint8)
    length = np.array([payload.size], dtype=np.int64)
    lengths = _host_allgather(length, tiled=True)
    max_len = int(lengths.max())
    padded = np.zeros(max_len, dtype=np.uint8)
    padded[: payload.size] = payload
    buffers = _host_allgather(padded, tiled=False)  # (num_processes, max_len)
    out = []
    for i in range(state.num_processes):
        obj = pickle.loads(buffers[i, : int(lengths[i])].tobytes())
        if isinstance(object, list):
            out.extend(obj)
        else:
            out.append(obj)
    return out


@verify_operation
def broadcast(tensor, from_process: int = 0):
    """Broadcast array leaves from one process to all (reference :538-557)."""
    state = PartialState()
    if state.num_processes == 1:
        return tensor
    from jax.experimental import multihost_utils

    def _bcast(t):
        if _is_global_unaddressable(t):
            return t  # a global sharded array is already consistent on all hosts
        return multihost_utils.broadcast_one_to_all(
            host_view(t), is_source=state.process_index == from_process
        )

    return recursively_apply(_bcast, tensor)


def broadcast_object_list(object_list: list, from_process: int = 0):
    """Broadcast a list of picklable objects (reference :560-577). In-place like
    the reference: returns the list with every slot replaced by rank
    ``from_process``'s value."""
    state = PartialState()
    if state.num_processes == 1:
        return object_list
    from jax.experimental import multihost_utils

    payload = np.frombuffer(pickle.dumps(list(object_list)), dtype=np.uint8)
    size = multihost_utils.broadcast_one_to_all(
        np.array([payload.size], dtype=np.int64), is_source=state.process_index == from_process
    )
    buf = np.zeros(int(size[0]), dtype=np.uint8)
    if state.process_index == from_process:
        buf[:] = payload
    buf = multihost_utils.broadcast_one_to_all(buf, is_source=state.process_index == from_process)
    received = pickle.loads(buf.tobytes())
    for i, v in enumerate(received):
        object_list[i] = v
    return object_list


@verify_operation
def reduce(tensor, reduction: str = "mean", scale: float = 1.0):
    """Elementwise cross-process reduce of host-local values (reference :723-761).
    Used e.g. by LocalSGD parameter averaging. ``reduction`` ∈ {"sum", "mean",
    "none"} — "none" returns the input unchanged, matching the reference."""
    if reduction not in ("sum", "mean", "none"):
        raise ValueError(f"reduction must be sum/mean/none, got {reduction!r}")
    if reduction == "none":
        return tensor
    state = PartialState()

    def _reduce_one(t):
        if _is_global_unaddressable(t):
            # A global sharded array is one logical value — already "reduced".
            out = jnp.asarray(t)
        elif state.num_processes == 1:
            out = jnp.asarray(t)
        else:
            stacked = _host_allgather(t, tiled=False)
            out = jnp.sum(jnp.asarray(stacked), axis=0)
            if reduction == "mean":
                out = out / state.num_processes
        return out * scale

    return recursively_apply(_reduce_one, tensor)


@verify_operation
def pad_across_processes(tensor, dim: int = 0, pad_index: int = 0, pad_first: bool = False):
    """Pad each process's array along ``dim`` to the global max size so a gather is
    rectangular (reference :627-679)."""
    state = PartialState()

    def _pad_one(t):
        if _is_global_unaddressable(t):
            return t  # global arrays are rectangular by construction
        t = host_view(t)
        if dim >= t.ndim:
            return t
        size = np.array(t.shape, dtype=np.int64)
        sizes = _host_allgather(size, tiled=False) if state.num_processes > 1 else size[None]
        max_size = int(np.max(sizes[:, dim]))
        if max_size == t.shape[dim]:
            return t
        new_shape = list(t.shape)
        new_shape[dim] = max_size
        out = np.full(new_shape, pad_index, dtype=t.dtype)
        sl = [slice(None)] * t.ndim
        if pad_first:
            sl[dim] = slice(max_size - t.shape[dim], max_size)
        else:
            sl[dim] = slice(0, t.shape[dim])
        out[tuple(sl)] = t
        return out

    return recursively_apply(_pad_one, tensor)


def pad_input_tensors(tensor, batch_size: int, num_processes: int, dim: int = 0):
    """Pad batch so it divides evenly across processes (reference :682-720),
    repeating the final row(s)."""
    remainder = batch_size % num_processes
    if remainder == 0:
        return tensor
    to_pad = num_processes - remainder

    def _pad_one(t):
        t = host_view(t)
        if t.shape[0] != batch_size:
            return t
        pad_rows = np.repeat(t[-1:], to_pad, axis=0)
        return np.concatenate([t, pad_rows], axis=0)

    return recursively_apply(_pad_one, tensor)


class GatheredParameters:
    """No-op parity shim for DeepSpeed zero3's param-gather context
    (reference :848-866): under GSPMD a sharded param is usable directly — XLA
    all-gathers on demand — so user code written against this context just works."""

    def __init__(self, params, modifier_rank=None, fwd_module=None, enabled=True):
        self.params = params

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def _tpu_gather(tensor):  # parity alias (reference :300-313)
    return gather(tensor)


def _gpu_gather(tensor):  # parity alias (reference :315-351)
    return gather(tensor)
