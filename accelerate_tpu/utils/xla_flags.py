"""Curated XLA / libtpu latency-hiding flag presets.

Once per-op efficiency is tuned, the next MFU points come from *overlap*:
letting XLA's latency-hiding scheduler move collectives (and the grad
all-reduce / fsdp reduce-scatter GSPMD inserted) behind compute instead of
serializing them at their def-use sites. Those schedulers sit behind a set of
``LIBTPU_INIT_ARGS`` flags that must be in the environment **before the TPU
backend initializes** — which is why :class:`~..state.PartialState` installs
the preset first thing, before the compilation cache, the distributed
rendezvous, or any ``jax.default_backend()`` touch.

The presets are additive token lists (each token ``--flag=value``):

- ``latency`` — the latency-hiding scheduler plus async all-gather /
  reduce-scatter / collective-permute / all-reduce fusion: the standard
  overlap recipe for dp/fsdp training.
- ``collective_matmul`` — everything in ``latency`` plus windowed-einsum
  (collective matmul): tp/sp all-gathers are decomposed and overlapped with
  the partial matmuls that consume them.

Flags ride ``LIBTPU_INIT_ARGS`` (read by libtpu only), so installing a preset
on a CPU/GPU rig is inert rather than a flag-parse crash — the selection is
still echoed into telemetry snapshots so bench rows record what was asked.

Selection surface: ``launch --xla_preset`` / ``ClusterConfig.xla_preset`` /
``ACCELERATE_XLA_PRESET`` (see docs/performance.md "Dispatch amortization").
"""

from __future__ import annotations

import logging
import os

from .constants import ENV_XLA_PRESET

logger = logging.getLogger(__name__)

_LATENCY_TOKENS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_tpu_enable_data_parallel_all_reduce_opt=true",
    "--xla_tpu_data_parallel_opt_different_sized_ops=true",
)

XLA_PRESETS: dict[str, tuple[str, ...]] = {
    "off": (),
    "latency": _LATENCY_TOKENS,
    "collective_matmul": _LATENCY_TOKENS + (
        # Windowed einsum: decompose the tp/sp all-gather feeding a matmul and
        # overlap each window's transfer with the previous window's compute.
        "--xla_jf_spmd_threshold_for_windowed_einsum_mib=0",
        "--xla_tpu_spmd_rewrite_einsum_with_reshape=true",
    ),
}

_active_preset: str | None = None
_active_flags: tuple = ()


def active_preset() -> str | None:
    """The preset installed in this process (None = none requested)."""
    return _active_preset


def active_preset_flags() -> tuple:
    """The AS-RESOLVED flag tokens of the installed preset: each preset token,
    with an operator's explicit ``LIBTPU_INIT_ARGS`` value winning over the
    preset's where both name the same flag. Empty when no preset is installed.
    The autotuner's evidence report attaches this so a ranked candidate records
    the exact flags its trial ran under, not just the preset name."""
    return _active_flags


def _reset_active_preset():
    """Test hook: forget the install record (env flags are left as-is)."""
    global _active_preset, _active_flags
    _active_preset = None
    _active_flags = ()


def normalize_preset_name(name: str | None) -> str:
    """Canonical preset key for ``name`` (''/'none' → 'off'), or raise a
    ValueError that ENUMERATES the valid preset names. The single validation
    home: ``launch --xla_preset``, ``install_xla_preset``, and the tuner's
    candidate space all route here so every surface fails with the same
    name-listing message."""
    key = (name or "").strip().lower()
    if key in ("", "none"):
        key = "off"
    if key not in XLA_PRESETS:
        raise ValueError(
            f"unknown xla preset {name!r}: valid presets are "
            f"{', '.join(sorted(XLA_PRESETS))} (utils/xla_flags.XLA_PRESETS)"
        )
    return key


def preset_flags(name: str | None) -> tuple:
    """The canonical flag-token tuple of a (validated) preset name — () for
    'off'. Raises the enumerating ValueError on an unknown name."""
    return tuple(XLA_PRESETS[normalize_preset_name(name)])


def install_xla_preset(name: str) -> str | None:
    """Merge the named preset's tokens into ``LIBTPU_INIT_ARGS`` (idempotent:
    tokens already present — from an operator's own env or a previous install —
    are kept, not duplicated, and an operator's explicit ``--flag=`` setting
    wins over the preset's). Returns the installed name, or None for 'off';
    :func:`active_preset_flags` then reports the resolved token list.

    Must run before the first TPU backend touch in the process; installing
    after is recorded (telemetry echoes the ask) but warned about, since
    libtpu reads the variable once at init.
    """
    global _active_preset, _active_flags
    key = normalize_preset_name(name)
    if key == "off":
        _active_preset = None
        _active_flags = ()
        return None
    existing = os.environ.get("LIBTPU_INIT_ARGS", "")
    tokens = existing.split()
    present_flags = {t.split("=", 1)[0] for t in tokens}
    added = [
        t for t in XLA_PRESETS[key] if t.split("=", 1)[0] not in present_flags
    ]
    if added:
        os.environ["LIBTPU_INIT_ARGS"] = " ".join(tokens + added)
    if _backend_already_initialized():
        logger.warning(
            "xla preset %r installed after the backend initialized; libtpu has "
            "already read LIBTPU_INIT_ARGS — relaunch (or set the preset via "
            "`launch --xla_preset` / ACCELERATE_XLA_PRESET) for it to apply.",
            key,
        )
    _active_preset = key
    # Resolve each preset token against the merged env: the value actually in
    # LIBTPU_INIT_ARGS wins (an operator override stays visible as-overridden).
    resolved = dict(
        t.split("=", 1) for t in os.environ["LIBTPU_INIT_ARGS"].split() if "=" in t
    )
    _active_flags = tuple(
        f"{flag}={resolved.get(flag, value)}"
        for flag, value in (t.split("=", 1) for t in XLA_PRESETS[key])
    )
    return key


def install_preset_from_env() -> str | None:
    """The env-contract install ``PartialState`` runs at init (before backend
    creation): ACCELERATE_XLA_PRESET names the preset; unset/empty = nothing."""
    raw = os.environ.get(ENV_XLA_PRESET, "").strip()
    if not raw:
        return None
    return install_xla_preset(raw)


def _backend_already_initialized() -> bool:
    try:
        from jax._src import xla_bridge

        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return False
