"""Seeding & cross-process RNG synchronization.

Reference parity: ``src/accelerate/utils/random.py`` — ``set_seed`` (:39-76) and
``synchronize_rng_state(s)`` (:78-156), which broadcast rank-0's RNG state so all
ranks shuffle identically at each epoch (used by ``DataLoaderShard.__iter__``,
``data_loader.py:558-559``).

JAX's explicit PRNG keys make most of this trivial (SURVEY.md §2.7 rng row): a key
is data, deterministic everywhere by construction — so "synchronizing" the JAX
stream means agreeing on a seed once. What still needs real synchronization is
host-side numpy/python RNG used by samplers and user code on a pod.
"""

from __future__ import annotations

import random
from typing import Iterable

import numpy as np

import jax

from .dataclasses import RNGType


def set_seed(seed: int, device_specific: bool = False, deterministic: bool = False):
    """Seed python/numpy and return a fresh JAX key (reference :39-76).

    ``device_specific`` offsets by process index so each host draws different data
    noise while model init stays controlled by explicit keys.
    """
    if device_specific:
        seed += jax.process_index()
    random.seed(seed)
    np.random.seed(seed % (2**32))
    try:
        import torch

        torch.manual_seed(seed)
    except ImportError:
        pass
    return jax.random.key(seed)


def synchronize_rng_state(rng_type: RNGType | str | None = None, generator=None):
    """Broadcast process-0's RNG state for one stream (reference :78-130)."""
    from .operations import broadcast_object_list

    rng_type = RNGType(rng_type) if rng_type is not None else None
    if rng_type == RNGType.PYTHON:
        state = [random.getstate()]
        broadcast_object_list(state, from_process=0)
        random.setstate(state[0])
    elif rng_type == RNGType.NUMPY:
        state = [np.random.get_state()]
        broadcast_object_list(state, from_process=0)
        np.random.set_state(state[0])
    elif rng_type == RNGType.TORCH:
        try:
            import torch

            state = [torch.get_rng_state().numpy()]
            broadcast_object_list(state, from_process=0)
            torch.set_rng_state(torch.from_numpy(state[0]))
        except ImportError:
            pass
    elif rng_type == RNGType.JAX:
        # JAX keys are pure data: nothing process-local to synchronize. Kept for
        # API parity; generators below cover the stateful host streams.
        pass
    elif rng_type == RNGType.GENERATOR:
        if generator is None:
            return
        state = [generator.bit_generator.state if isinstance(generator, np.random.Generator) else None]
        broadcast_object_list(state, from_process=0)
        if state[0] is not None and isinstance(generator, np.random.Generator):
            generator.bit_generator.state = state[0]


def synchronize_rng_states(rng_types: Iterable[str], generator=None):
    """Reference :132-156."""
    for rng_type in rng_types:
        synchronize_rng_state(rng_type, generator=generator)
