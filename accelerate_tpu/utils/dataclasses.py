"""Plugin & kwargs-handler dataclasses — the L3 configuration surface.

Reference parity: ``src/accelerate/utils/dataclasses.py`` (2,823 LoC). The reference
needs a large adapter surface because each strategy drives a different external
engine; here strategies collapse onto mesh axes, so plugins mostly *declare shape*
and the engine is always GSPMD. Handlers kept:

- ``KwargsHandler`` base with ``to_kwargs()`` default-diffing (reference :64-78)
- ``GradientAccumulationPlugin`` (reference :734-760)
- ``FullyShardedDataParallelPlugin`` equivalent (reference :1481) → fsdp axis size +
  remat/offload policy
- ``TorchTensorParallelPlugin`` equivalent (reference :2062) → tp axis size
- ``MegatronLMPlugin`` equivalent (reference :2102) → tp×pp×dp + sp
- ``AutocastKwargs``/``DistributedDataParallelKwargs``-analogue slots where they
  still mean something on TPU.
"""

from __future__ import annotations

import copy
import enum
from dataclasses import dataclass


class KwargsHandler:
    """Base: diff against defaults, mirroring reference ``dataclasses.py:64-78``."""

    def to_dict(self):
        return copy.deepcopy(self.__dict__)

    def to_kwargs(self):
        default_dict = self.__class__().to_dict()
        this_dict = self.to_dict()
        return {k: v for k, v in this_dict.items() if default_dict[k] != v}


def resolve_remat_policy(name: str, save_names: tuple = ()):
    """Map a config ``remat_policy`` string to a ``jax.checkpoint`` policy.

    Every ``jax.checkpoint_policies`` attribute name works unchanged;
    ``"names_saveable"`` additionally resolves to
    ``save_only_these_names(*save_names)`` — the policy keyed off the
    ``checkpoint_name`` tags the model zoo plants on block intermediates
    (Llama tags ``attn_out``/``mlp_out``), so remat keeps exactly the named
    residual-stream contributions instead of every dot output.
    """
    import jax

    if name == "names_saveable":
        if not save_names:
            raise ValueError(
                "remat_policy='names_saveable' needs a non-empty remat_save_names "
                "tuple (the checkpoint_name tags to keep, e.g. ('attn_out', 'mlp_out'))."
            )
        return jax.checkpoint_policies.save_only_these_names(*save_names)
    try:
        return getattr(jax.checkpoint_policies, name)
    except AttributeError:
        raise ValueError(
            f"Unknown remat_policy {name!r}: expected 'names_saveable' or a "
            "jax.checkpoint_policies attribute (e.g. 'nothing_saveable', "
            "'dots_with_no_batch_dims_saveable')."
        ) from None


class EnumWithContains(enum.EnumMeta):
    def __contains__(cls, item):
        try:
            cls(item)
        except ValueError:
            return False
        return True


class BaseEnum(enum.Enum, metaclass=EnumWithContains):
    def __str__(self):
        return self.value

    @classmethod
    def list(cls):
        return list(map(str, cls))


class PrecisionType(str, BaseEnum):
    NO = "no"
    BF16 = "bf16"
    FP16 = "fp16"
    FP8 = "fp8"


class RNGType(str, BaseEnum):
    """Which RNG streams to synchronize across processes at epoch boundaries
    (reference ``utils/dataclasses.py:613-620``). JAX's explicit keys make GENERATOR
    the only stream that matters; the others are kept for API parity with host-side
    numpy/python shuffling."""

    JAX = "jax"
    NUMPY = "numpy"
    PYTHON = "python"
    TORCH = "torch"
    GENERATOR = "generator"


@dataclass
class GradientAccumulationPlugin(KwargsHandler):
    """Reference ``dataclasses.py:734-760``."""

    num_steps: int = None
    adjust_scheduler: bool = True
    sync_with_dataloader: bool = True
    sync_each_batch: bool = False

    def __post_init__(self):
        if self.sync_with_dataloader is None:
            self.sync_with_dataloader = True


@dataclass
class AutocastKwargs(KwargsHandler):
    """Reference ``dataclasses.py:228-245``. On TPU "autocast" is a dtype policy
    applied when params are cast into the jitted step, not a context manager."""

    enabled: bool = True
    cache_enabled: bool = None  # parity slot; meaningless under XLA


@dataclass
class JaxShardingKwargs(KwargsHandler):
    """Knobs for the compiled train step — the analog of
    ``DistributedDataParallelKwargs`` (reference :151-226): what that handler tunes
    about NCCL bucketing/overlap, XLA's latency-hiding scheduler does automatically;
    what remains user-meaningful is donation and remat."""

    donate_params: bool = True  # donate param/opt buffers to the step (halves HBM)
    remat_policy: str | None = None  # None|'minimal'|'full'|'dots_saveable'...
    spmd_auto: bool = False  # let XLA auto-partition instead of explicit rules
    # Gradient-compression comm hook (reference DistributedDataParallelKwargs
    # comm_hook fp16/bf16 compressors :130-226): cast gradients to this dtype
    # *before* the cross-device reduction (all-reduce / reduce-scatter runs on
    # half the bytes), converting back after. None = full-precision reduce.
    grad_reduce_dtype: str | None = None  # None | 'bf16' | 'fp16'

    def __post_init__(self):
        if self.grad_reduce_dtype not in (None, "bf16", "fp16"):
            raise ValueError(
                f"grad_reduce_dtype must be None|'bf16'|'fp16', got {self.grad_reduce_dtype!r}"
            )


@dataclass
class FullyShardedDataParallelPlugin(KwargsHandler):
    """GSPMD full-shard config — reference ``dataclasses.py:1481`` distilled to the
    fields that mean something under XLA SPMD:

    - sharding happens via the ``fsdp`` mesh axis (≈ FULL_SHARD / ZeRO-3);
      ``reshard_after_forward`` ≈ XLA's default behavior (all-gather per use).
    - ``min_shard_size`` plays auto_wrap_policy's role: tensors smaller than this
      stay replicated (sharding tiny tensors wastes collective latency).
    - ``cpu_offload`` → host-memory offload of the sharded optimizer state.
    - ``activation_checkpointing`` → ``jax.checkpoint`` policy on block boundaries.
    """

    fsdp_size: int = -1  # -1: all non-tp/pp devices
    reshard_after_forward: bool = True
    min_shard_size: int = 2**14
    shard_axis_preference: tuple = ()  # param dims preferred for sharding, default largest
    cpu_offload: bool = False
    activation_checkpointing: bool = False
    state_dict_type: str = "SHARDED_STATE_DICT"  # or FULL_STATE_DICT on save

    def __post_init__(self):
        if self.state_dict_type not in ("SHARDED_STATE_DICT", "FULL_STATE_DICT"):
            raise ValueError(f"invalid state_dict_type {self.state_dict_type}")


@dataclass
class TensorParallelPlugin(KwargsHandler):
    """Reference ``TorchTensorParallelPlugin`` (``dataclasses.py:2062-2098``). The
    reference requires models pre-sharded by transformers' tp_plan; here the plan is
    our logical sharding rules applied to any param pytree (parallel/sharding.py)."""

    tp_size: int = 1

    def __post_init__(self):
        if self.tp_size < 1:
            raise ValueError("tp_size must be >= 1")


@dataclass
class PipelineParallelPlugin(KwargsHandler):
    """Pipeline stages over the ``pp`` mesh axis (reference exposes PP only through
    Megatron ``pp_degree`` dataclasses.py:2110 and inference pippy inference.py:124)."""

    pp_size: int = 1
    num_microbatches: int = 0  # 0 = auto (defaults to pp_size microbatches)
    schedule: str = "gpipe"  # autodiff'd GPipe wavefront (parallel/pipeline.py)


@dataclass
class SequenceParallelPlugin(KwargsHandler):
    """Sequence/context parallelism over the ``sp`` axis. The reference has NO
    native implementation (SURVEY.md §2.4): this exceeds parity.

    ``ring_attention=True`` → ppermute ring with streaming softmax
    (``parallel/ring.py``; scales past the head count, O(S/sp) memory);
    ``False`` → Ulysses-style head↔sequence all-to-all (``parallel/ulysses.py``;
    exact single-kernel attention, needs heads divisible by sp)."""

    sp_size: int = 1
    ring_attention: bool = True


@dataclass
class MegatronStylePlugin(KwargsHandler):
    """Composed 3-D parallelism (reference ``MegatronLMPlugin`` dataclasses.py:2102)."""

    tp_size: int = 1
    pp_size: int = 1
    sp_size: int = 1
    fsdp_size: int = 1
    sequence_parallelism: bool = False


@dataclass
class Fp8RecipeKwargs(KwargsHandler):
    """Low-precision matmul recipe — the TPU answer to the reference's fp8
    recipe handlers (``TERecipeKwargs``/``AORecipeKwargs``/``MSAMPRecipeKwargs``,
    reference ``dataclasses.py:298-407``). TPUs through v5p have no fp8 ALUs;
    ``mixed_precision="fp8"`` maps onto dynamically-quantized int8 matmuls with
    straight-through-estimator backward (``ops/int8.py``) — quantization-aware
    training rather than TransformerEngine's delayed-scaling fp8.

    This is a QAT-for-deployment knob, NOT a throughput lever: measured on
    v5e, XLA's int8 ``dot_general`` lowering runs BELOW bf16 peak even with
    pre-quantized operands (81 TOPS vs 104 TFLOP/s at bench shapes — the
    nominal 2x int8 MXU path is never engaged), so int8 QAT trains slower
    than bf16 at every swept shape while matching int8 inference numerics
    (PERF.md, r4 sweep).

    ``backend="int8"`` swaps eligible model matmuls to the QAT path;
    ``backend="bf16"`` keeps plain bf16 compute (the documented fallback)."""

    backend: str = "int8"  # 'int8' (QAT matmuls) | 'bf16' (cast-only fallback)

    def __post_init__(self):
        if self.backend not in ("int8", "bf16"):
            raise ValueError(f"fp8 recipe backend must be int8|bf16, got {self.backend!r}")


@dataclass
class ProfileKwargs(KwargsHandler):
    """Reference ``dataclasses.py:438-552`` builds torch.profiler; here it drives
    ``jax.profiler`` (perfetto/tensorboard trace)."""

    output_trace_dir: str | None = None
    with_flops: bool = False  # cost analysis via jax.stages cost_analysis
    record_shapes: bool = False  # parity slot
    profile_memory: bool = False  # parity slot — device memory profile

    def build(self):
        import jax.profiler

        return jax.profiler


@dataclass
class ProjectConfiguration(KwargsHandler):
    """Checkpoint/logging folder layout (reference ``dataclasses.py:862-922``)."""

    project_dir: str = None
    logging_dir: str = None
    automatic_checkpoint_naming: bool = False
    total_limit: int = None
    iteration: int = 0
    save_on_each_node: bool = False  # parity slot: ckpt I/O is per-process-sharded here

    def set_directories(self, project_dir: str = None):
        self.project_dir = project_dir
        if self.logging_dir is None:
            self.logging_dir = project_dir

    def __post_init__(self):
        if self.logging_dir is None:
            self.logging_dir = self.project_dir


@dataclass
class DataLoaderConfiguration(KwargsHandler):
    """Reference ``dataclasses.py:791-860``."""

    split_batches: bool = False
    dispatch_batches: bool | None = None
    even_batches: bool = True
    use_seedable_sampler: bool = False
    non_blocking: bool = False  # parity slot; device feed is always async in JAX
    data_seed: int | None = None
    use_stateful_dataloader: bool = False


def add_model_config_to_megatron_parser(*a, **k):  # pragma: no cover - parity stub
    raise NotImplementedError("Megatron arg-parsing is not applicable on TPU")
