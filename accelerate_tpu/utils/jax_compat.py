"""Version-compat shims over moving JAX APIs.

``jax.shard_map`` graduated out of ``jax.experimental.shard_map`` (where it
takes ``check_rep``/``auto``) into the top-level namespace (where it takes
``check_vma``/``axis_names``). The repo targets the modern spelling; this
shim translates it for the 0.4.x runtimes the CPU rigs carry, so call sites
(parallel/pipeline.py's GPipe and 1F1B schedules) stay version-agnostic.
"""

from __future__ import annotations

import jax


def has_native_shard_map() -> bool:
    """Whether this jax carries top-level ``jax.shard_map`` (partial-auto
    manual mapping). When False, :func:`shard_map` falls back to FULL-MANUAL
    ``jax.experimental.shard_map``: mesh axes the specs omit are treated as
    replicated, so dp-replicated inputs are all-gathered at the region
    boundary — the pp plan's zero-all-gather HLO property (and the program
    auditor's dp-all-gather gate on shard_map programs) holds only on native
    runtimes. tests/test_hlo_collectives.py keys its precise skip on this."""
    import jax

    return getattr(jax, "shard_map", None) is not None


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` (absent on 0.4.x): the static size of a mapped
    mesh axis. ``psum`` of the literal 1 constant-folds to the axis size on
    every version, inside any mapped context."""
    native = getattr(jax.lax, "axis_size", None)
    if native is not None:
        return native(axis_name)
    return jax.lax.psum(1, axis_name)


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None, check_vma=None):
    """``jax.shard_map`` with the modern keyword surface on any jax version.

    ``axis_names`` restricts MANUAL mapping to those mesh axes (the rest stay
    automatic/GSPMD); on 0.4.x that is expressed inversely via ``auto`` =
    every other axis. ``check_vma`` (varying-mesh-axes checking) maps onto the
    old ``check_rep`` replication check — both default off here because the
    pipeline schedules intentionally produce stage-varying values.
    """
    native = getattr(jax, "shard_map", None)
    if native is not None:
        kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return native(f, **kwargs)
    from jax.experimental.shard_map import shard_map as _shard_map

    # 0.4.x partial-auto (auto = the non-manual axes) lowers through a
    # PartitionId instruction XLA:CPU rejects, so the fallback goes fully
    # manual instead. Specs mention only the manual axes, so the body traces
    # at the same per-device shapes either way; axes the specs omit are
    # treated as replicated — redundant compute rather than auto-partitioned
    # compute on those axes, which the modern native path above avoids.
    return _shard_map(
        f,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_rep=bool(check_vma),
    )
