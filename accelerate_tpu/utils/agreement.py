"""All-host agreement for host-side flag words, without device collectives.

The preferred transport for "does ANY host want X?" is a tiny device-side sum
collective (the ``check_trigger`` idiom) — it rides the same interconnect as
training. But some backends cannot run multiprocess computations at all (the
2-process CPU test harness is one), and the question still needs answering.
This helper carries the bits over the JAX coordination service instead: each
rank publishes its word in the KV store, everyone meets at a barrier, then
ORs all ranks' words. Callers must provide a namespace that is unique per
exchange AND identical across ranks (same construction/call order — the SPMD
contract these exchanges exist to protect).
"""

from __future__ import annotations


def kv_all_gather(
    value: str,
    num_processes: int,
    process_index: int,
    namespace: str,
    timeout_ms: int = 120_000,
) -> list[str]:
    """All-ranks gather of one string via the coordination-service KV store;
    returns ``[value]`` unchanged when no distributed client is up
    (single-process, or tests faking a state object). The generic transport
    under :func:`kv_or_exchange` and the telemetry straggler exchange."""
    from jax._src.distributed import global_state as dist_state

    client = dist_state.client
    if client is None:
        return [value]
    client.key_value_set(f"{namespace}/{process_index}", value)
    client.wait_at_barrier(f"{namespace}/barrier", timeout_ms)
    gathered = [
        client.blocking_key_value_get(f"{namespace}/{rank}", timeout_ms)
        for rank in range(num_processes)
    ]
    # Namespaces are single-use, and the fallback path runs once per step:
    # without cleanup the coordinator accrues num_processes keys per exchange
    # for the life of the job. The second barrier keeps rank 0's directory
    # delete from racing a slower rank's reads.
    client.wait_at_barrier(f"{namespace}/done", timeout_ms)
    if process_index == 0:
        try:
            client.key_value_delete(namespace)
        except Exception:
            pass  # cleanup is best-effort; correctness never depends on it
    return gathered


def kv_or_exchange(
    local_flags: int,
    num_processes: int,
    process_index: int,
    namespace: str,
    timeout_ms: int = 120_000,
) -> int:
    """OR of every rank's ``local_flags`` via the coordination-service KV
    store; returns ``local_flags`` unchanged when no distributed client is up
    (single-process, or tests faking a state object)."""
    agreed = 0
    for word in kv_all_gather(
        str(int(local_flags)), num_processes, process_index, namespace, timeout_ms
    ):
        agreed |= int(word)
    return agreed
