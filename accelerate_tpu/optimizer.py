"""AcceleratedOptimizer — imperative facade over an optax transform.

Reference parity: ``src/accelerate/optimizer.py:57`` wraps a torch optimizer to
(1) skip stepping while gradients accumulate, (2) integrate the GradScaler for
fp16, (3) all-reduce XLA gradients before stepping (:149-155). Here:

- gradients arrive already globally correct: the compiled forward/backward runs
  under GSPMD, which inserts the cross-device reduction the reference does by hand
  with ``xm.all_reduce`` — so (3) disappears by construction;
- (1) is the same bookkeeping against ``GradientState``;
- (2) is a dynamic loss-scaler maintained as device-side state inside the jitted
  update (overflow check + conditional skip via ``lax.cond`` — no host sync).

The wrapped object is an ``optax.GradientTransformation``; parameters live in the
shared ``TrainHandle`` (see ``accelerator.py``) that the prepared model also
points at, so ``optimizer.step()`` visibly updates what ``model(...)`` uses next —
preserving the reference's mutable-object feel over pure-functional cores.
"""

from __future__ import annotations

import logging
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .state import AcceleratorState, GradientState

logger = logging.getLogger(__name__)


def _tree_zeros_like(tree):
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


_accumulate_grads_fn = None


def _accumulate_grads(accum, new, scale):
    # Lazily jitted so the donation decision (safe_donate_argnums — donation is
    # unsafe on CPU when the persistent compilation cache is active) is made
    # after the backend and cache are configured, not at import time.
    global _accumulate_grads_fn
    if _accumulate_grads_fn is None:
        from .utils.environment import safe_donate_argnums

        _accumulate_grads_fn = jax.jit(
            lambda accum, new, scale: jax.tree_util.tree_map(
                lambda a, g: a + g * scale, accum, new
            ),
            donate_argnums=safe_donate_argnums((0,)),
        )
    return _accumulate_grads_fn(accum, new, scale)


@jax.jit
def _scale_grads(grads, scale):
    return jax.tree_util.tree_map(lambda g: g * scale, grads)


def _global_norm(grads):
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in jax.tree_util.tree_leaves(grads)))


_STEP_COUNTERS = None  # telemetry.metrics.cached_handles accessor


def _count_optimizer_step(skipped: bool):
    """Publish applied/overflow-skipped updates into the telemetry registry;
    each step pays only the .inc() (cached_handles hoists the lookup)."""
    global _STEP_COUNTERS
    if _STEP_COUNTERS is None:
        from .telemetry.metrics import cached_handles

        _STEP_COUNTERS = cached_handles(lambda registry: (
            registry.counter(
                "accelerate_optimizer_steps_total", "Optimizer updates applied"
            ),
            registry.counter(
                "accelerate_optimizer_skipped_steps_total",
                "Optimizer updates skipped on fp16 overflow",
            ),
        ))
    _STEP_COUNTERS()[skipped].inc()


class GradScalerState:
    """Dynamic loss-scaler (fp16) state, mirroring torch GradScaler semantics the
    reference relies on (``optimizer.py:162-177``): on non-finite grads the step is
    skipped and the scale halves; after ``growth_interval`` good steps it doubles."""

    def __init__(self, init_scale=2.0**15, growth_factor=2.0, backoff_factor=0.5, growth_interval=2000):
        self.scale = float(init_scale)
        self.growth_factor = growth_factor
        self.backoff_factor = backoff_factor
        self.growth_interval = growth_interval
        self._good_steps = 0

    def update(self, found_inf: bool):
        if found_inf:
            self.scale *= self.backoff_factor
            self._good_steps = 0
        else:
            self._good_steps += 1
            if self._good_steps >= self.growth_interval:
                self.scale *= self.growth_factor
                self._good_steps = 0


class AcceleratedOptimizer:
    """Wraps ``optax.GradientTransformation``. Constructed by ``Accelerator.prepare``."""

    def __init__(self, tx, handle=None, scaler: GradScalerState | None = None,
                 host_offload: bool = False, zero_sharding: bool = False,
                 zero_rules=None, kernels: str | None = None):
        import optax

        if not isinstance(tx, optax.GradientTransformation):
            raise TypeError(f"expected an optax.GradientTransformation, got {type(tx)}")
        self.tx = tx
        self.handle = handle  # TrainHandle: .params, .param_shardings, .mesh
        self.scaler = scaler
        # ZeRO-Offload analog (FullyShardedDataParallelPlugin.cpu_offload):
        # optimizer state parks in host RAM between steps and rides through the
        # device only transiently inside step() — HBM holds params + grads only.
        self.host_offload = host_offload
        # Cross-replica (ZeRO-style) sharding of the optimizer state and the
        # weight update across the dp axis (arxiv 2004.13336; ROADMAP item 2):
        # opt-state leaves get the params' layout further partitioned along
        # dp, and the update runs reduce-scatter(grads) → sharded clip+update
        # → all-gather(new params), expressed as sharding constraints so
        # GSPMD inserts (and the xla_flags presets overlap) the collectives.
        self.zero_sharding = bool(zero_sharding)
        self._zero_rules = zero_rules
        # Pallas kernel-layer spec for the imperative update path (None = the
        # ACCELERATE_KERNELS env contract, resolved at _build_update_fn time;
        # Accelerator.prepare passes its own spec through).
        self.kernels = kernels
        # The per-param update-path shardings (pytree congruent with params);
        # None while inactive (zero off, dp==1, or nothing partitionable).
        self.zero_param_shardings = None
        self.gradient_state = GradientState()
        self.accelerator_state = AcceleratorState()
        self.opt_state = None
        self.opt_shardings = None
        self._host_mode = None  # 'pinned' | 'gather', probed on first offload
        self._accum_grads = None
        self._pending_clip_norm = None
        self._step_was_skipped = False
        # Device-side finite flag of the last update, resolved to a host bool
        # LAZILY (property access / next step / checkpoint) so step() never
        # stalls the dispatch thread on a device→host sync.
        self._pending_finite = None
        self._update_fn = None
        self._step_count = 0  # optimizer steps actually applied

    # ------------------------------------------------------------------ setup
    def _plan_zero_shardings(self):
        """The cross-replica plan for the update path: each param's base
        layout further partitioned along dp (parallel/sharding.py
        ``plan_zero_shardings`` — regex-tree rules from the module's
        ``zero_sharding_rules()`` when it defines any, shape-aware fallback
        otherwise). Returns None when inactive or nothing gained a dp dim."""
        if not self.zero_sharding or self.handle is None:
            return None
        from .parallel.sharding import plan_zero_shardings

        mesh = self.handle.mesh
        if mesh is None or mesh.shape.get("dp", 1) <= 1:
            return None
        rules = self._zero_rules
        if rules is None:
            rules_fn = getattr(self.handle.module, "zero_sharding_rules", None)
            rules = rules_fn() if callable(rules_fn) else None
        plan = plan_zero_shardings(
            self.handle.params, self.handle.param_shardings, mesh, rules=rules
        )
        base_leaves = jax.tree_util.tree_leaves(
            self.handle.param_shardings, is_leaf=lambda s: hasattr(s, "spec")
        )
        plan_leaves = jax.tree_util.tree_leaves(
            plan, is_leaf=lambda s: hasattr(s, "spec")
        )

        def spec_axes(sharding):
            axes = set()
            for entry in tuple(getattr(sharding, "spec", None) or ()):
                if entry is None:
                    continue
                axes.update(entry if isinstance(entry, (tuple, list)) else (entry,))
            return axes

        # Engagement = at least one leaf actually GAINED the dp axis (by
        # value, not object identity: a rule that restates the base layout
        # builds fresh NamedShardings yet partitions nothing, and must not
        # activate the constrained update path or the auditor contract).
        if not any(
            "dp" in spec_axes(p) and "dp" not in spec_axes(b)
            for p, b in zip(plan_leaves, base_leaves)
        ):
            return None  # nothing partitionable: stay on the replicated path
        return plan

    def _plan_opt_shardings(self):
        """Opt-state leaves that mirror a param shape inherit that param's
        sharding (ZeRO-style sharded optimizer state under fsdp); scalars and
        the rest replicate. This is the GSPMD answer to DeepSpeed's partitioned
        optimizer (SURVEY.md §2.4 ZeRO row). With ``zero_sharding`` active the
        inherited layout is the dp-partitioned ZeRO plan, so the moments (and
        any fp32 master copies mirroring param shapes) drop to ~1/dp per chip."""
        params = self.handle.params
        self.zero_param_shardings = self._plan_zero_shardings()
        mirror = (
            self.zero_param_shardings
            if self.zero_param_shardings is not None
            else self.handle.param_shardings
        )
        shape_to_sharding = {}
        for p, s in zip(
            jax.tree_util.tree_leaves(params),
            jax.tree_util.tree_leaves(mirror, is_leaf=lambda s: hasattr(s, "spec")),
        ):
            shape_to_sharding.setdefault(np.shape(p), s)

        opt_shapes = jax.eval_shape(self.tx.init, params)
        from jax.sharding import NamedSharding, PartitionSpec as P

        replicated = NamedSharding(self.handle.mesh, P())
        return jax.tree_util.tree_map(
            lambda l: shape_to_sharding.get(tuple(l.shape), replicated), opt_shapes
        )

    def _ensure_initialized(self):
        if self.opt_shardings is None and self.handle is not None:
            # Also covers opt_state arriving via load_state_dict: the sharding
            # plan is derivable from the params regardless of who set the state.
            self.opt_shardings = self._plan_opt_shardings()
        if self.opt_state is None:
            self.opt_state = jax.jit(self.tx.init, out_shardings=self.opt_shardings)(
                self.handle.params
            )
            if self.host_offload:
                self.opt_state = self._to_host(self.opt_state)

    def _build_update_fn(self):
        import optax

        from .utils.environment import safe_donate_argnums

        tx = self.tx
        # ZeRO: constrain the update region to the dp-partitioned plan so
        # GSPMD lowers it as reduce-scatter(grads) → sharded clip+update →
        # all-gather(new params). The named scopes ride into the collectives'
        # op_name metadata — how the program auditor attributes the
        # deliberate dp all-gather as ZeRO traffic, not a zero-sync violation.
        zero_specs = self.zero_param_shardings
        gather_specs = self.handle.param_shardings if zero_specs is not None else None
        # Pallas fused-update kernel (ops/pallas/fused_update.py) on the
        # imperative path too: same registry resolution + optax-family plan
        # as the fused builders (_fused_step_body), same reference fallback.
        from .ops.registry import resolve_backend

        kernel_backend = resolve_backend("fused_update", self.kernels)
        fused_plan = None
        if kernel_backend != "reference":
            from .ops.pallas.fused_update import plan_fused_update

            fused_plan = plan_fused_update(tx)

        @partial(jax.jit, donate_argnums=safe_donate_argnums((0, 1, 2)))
        def _update(params, opt_state, grads, max_clip_norm, inv_scale):
            grads = jax.tree_util.tree_map(lambda g: g * inv_scale, grads)
            if zero_specs is not None:
                with jax.named_scope("zero_update"):
                    grads = jax.lax.with_sharding_constraint(grads, zero_specs)
                    params_u = jax.lax.with_sharding_constraint(params, zero_specs)
            else:
                params_u = params
            # One scalar reduce: with ZeRO on, the global norm (and through it
            # the GradScaler found-inf flag) is computed on the SHARDED grads —
            # per-shard partial sums plus a single cross-replica scalar sum.
            gnorm = _global_norm(grads)
            # clip_grad_norm_ semantics (reference accelerator.py:2630): scale down
            # when over the limit; max_clip_norm<=0 disables.
            clip_factor = jnp.where(
                (max_clip_norm > 0) & (gnorm > max_clip_norm),
                max_clip_norm / (gnorm + 1e-6),
                1.0,
            )
            grads = jax.tree_util.tree_map(lambda g: g * clip_factor, grads)
            finite = jnp.isfinite(gnorm)

            def do_step(_):
                if fused_plan is not None:
                    from .ops.pallas.fused_update import fused_update_apply

                    # The clip factor is already applied to `grads` above (the
                    # imperative path scales before the cond so gnorm reads
                    # the scaled values); factor 1.0 keeps the kernel's fused
                    # pre-scale a no-op — same chain, same order. No
                    # zero_buffer: this path has no accumulation buffer to
                    # reset, and an unused pallas output would still cost a
                    # grads-sized HBM write on the compiled path.
                    new_params, new_opt, _ = fused_update_apply(
                        params_u, opt_state, grads, plan=fused_plan,
                        clip_factor=jnp.float32(1.0),
                        interpret=(kernel_backend == "interpret"),
                        shardings=zero_specs, zero_buffer=False,
                    )
                else:
                    updates, new_opt = tx.update(grads, opt_state, params_u)
                    new_params = optax.apply_updates(params_u, updates)
                if gather_specs is not None:
                    with jax.named_scope("zero_gather_params"):
                        new_params = jax.lax.with_sharding_constraint(
                            new_params, gather_specs
                        )
                return new_params, new_opt

            def skip(_):
                return params, opt_state

            new_params, new_opt = jax.lax.cond(finite, do_step, skip, None)
            return new_params, new_opt, gnorm, finite

        return _update

    # -------------------------------------------------------------- grad flow
    def _accumulate(self, grads, scale: float = 1.0):
        """Add freshly computed grads (already globally reduced by GSPMD) into the
        accumulation buffer — the explicit-pytree version of torch's ``.grad +=``."""
        self._ensure_initialized()
        if self._accum_grads is None:
            self._accum_grads = _scale_grads(grads, jnp.float32(scale)) if scale != 1.0 else grads
        else:
            self._accum_grads = _accumulate_grads(self._accum_grads, grads, jnp.float32(scale))

    @property
    def grads(self):
        return self._accum_grads

    @property
    def zero_active(self) -> bool:
        """Whether the cross-replica (ZeRO) plan actually engaged: requested,
        dp > 1, and at least one param gained a dp partition. Valid after
        ``_ensure_initialized()`` (the builders call it first)."""
        return self.zero_param_shardings is not None

    # --------------------------------------------------------------- stepping
    def step(self, closure=None):
        if closure is not None:
            raise NotImplementedError("closures are not supported")
        if not self.gradient_state.sync_gradients:
            return  # accumulating: reference optimizer.py:162 skips the real step
        if self._accum_grads is None:
            logger.warning("optimizer.step() called with no accumulated gradients; skipping")
            return
        self._ensure_initialized()
        if self._update_fn is None:
            self._update_fn = self._build_update_fn()
        # The previous step's outcome must be final before its scale is read
        # (backoff/growth ordering is unchanged — only the sync moved off the
        # dispatch path to where the value is already materialized).
        self._resolve_pending_finite()
        inv_scale = 1.0 / self.scaler.scale if self.scaler is not None else 1.0
        clip = self._pending_clip_norm if self._pending_clip_norm is not None else -1.0
        if self.host_offload:
            # Host → mesh with the proper shardings; jit refuses to mix a
            # single-device host tree with mesh-sharded params implicitly.
            self.opt_state = jax.device_put(self.opt_state, self.opt_shardings)
        new_params, new_opt, gnorm, finite = self._update_fn(
            self.handle.params, self.opt_state, self._accum_grads, jnp.float32(clip), jnp.float32(inv_scale)
        )
        self.handle.params = new_params
        self.opt_state = self._to_host(new_opt) if self.host_offload else new_opt
        self._accum_grads = None
        self._pending_clip_norm = None
        self.handle.last_grad_norm = gnorm
        if self.scaler is not None:
            # NO host sync here: the device flag resolves lazily through the
            # step_was_skipped property, the next step(), or a checkpoint —
            # the hot loop stays async (the health guard reads the same flag
            # via gnorm without ever forcing it).
            self._pending_finite = finite
        else:
            self._step_was_skipped = False
            self._step_count += 1
            _count_optimizer_step(skipped=False)

    def _to_host(self, tree):
        """Move the optimizer state to host memory (async device→host DMA); the
        next step's device_put brings it back with its mesh shardings.

        Preferred mechanism: keep the NamedSharding and switch the memory kind
        to pinned_host — each host keeps only its own shards (works on
        multi-host meshes, preserves the ZeRO-style partitioning). Backends
        without memory kinds (the CPU test platform) fall back to a
        single-local-device gather."""

        if self._host_mode is None:
            # Probe memory-kind support ONCE (not per leaf per step, and so a
            # later transient pinned-host failure surfaces instead of silently
            # degrading to a gather that cannot work on multi-host meshes).
            probe = next(
                (x for x in jax.tree_util.tree_leaves(tree) if isinstance(x, jax.Array)), None
            )
            self._host_mode = "gather"
            if probe is not None:
                try:
                    jax.device_put(probe, probe.sharding.with_memory_kind("pinned_host"))
                    self._host_mode = "pinned"
                except Exception:
                    pass

        if self._host_mode == "pinned":
            return jax.tree_util.tree_map(
                lambda x: jax.device_put(x, x.sharding.with_memory_kind("pinned_host"))
                if isinstance(x, jax.Array) else x,
                tree,
            )
        cpu = jax.local_devices(backend="cpu")[0]
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, cpu) if isinstance(x, jax.Array) else x, tree
        )

    def _resolve_pending_finite(self):
        """Apply the deferred overflow outcome of the last fp16 step: one
        counted device→host fetch (utils/transfer.py) — by the time anything
        calls this, the update has long since executed, so the fetch is a copy
        rather than a stall."""
        if self._pending_finite is None:
            return
        from .utils.transfer import host_fetch

        found_inf = not bool(host_fetch(self._pending_finite))
        self._pending_finite = None
        self._step_was_skipped = found_inf
        self.scaler.update(found_inf)
        if not found_inf:
            self._step_count += 1
        _count_optimizer_step(skipped=found_inf)

    @property
    def step_was_skipped(self) -> bool:
        """Whether the last ``step()`` was skipped on overflow (reference :186-189).
        Accessing it resolves the deferred device-side flag — consumers that
        need THIS step's verdict (``AcceleratedScheduler.step`` must not count
        an LR step for a skipped update) inherently pay the fetch here; loops
        without such a consumer never pay it at all."""
        self._resolve_pending_finite()
        return self._step_was_skipped

    def zero_grad(self, set_to_none: bool = True):
        """Drop accumulated grads — a no-op while accumulating (reference :114-122)."""
        if self.gradient_state.sync_gradients:
            self._accum_grads = None

    # ------------------------------------------------------------- inspection
    @property
    def param_groups(self):
        """Torch-flavored introspection: one group with current lr if discoverable."""
        lr = self.learning_rate
        return [{"params": jax.tree_util.tree_leaves(self.handle.params), "lr": lr}]

    @property
    def learning_rate(self):
        state = self.opt_state
        if state is None:
            return None
        hp = getattr(state, "hyperparams", None)
        if isinstance(state, tuple):
            for s in state:
                hp = getattr(s, "hyperparams", None) or hp
        if hp and "learning_rate" in hp:
            from .utils.transfer import host_fetch

            return float(host_fetch(hp["learning_rate"]))
        return None

    def set_learning_rate(self, lr: float):
        """Write through to ``optax.inject_hyperparams`` state if present."""
        state = self.opt_state
        if state is None:
            return False

        def visit(s):
            hp = getattr(s, "hyperparams", None)
            if hp is not None and "learning_rate" in hp:
                hp["learning_rate"] = jnp.asarray(lr, dtype=jnp.asarray(hp["learning_rate"]).dtype)
                return True
            return False

        if visit(state):
            return True
        if isinstance(state, tuple):
            return any(visit(s) for s in state)
        return False

    def state_dict(self):
        self._resolve_pending_finite()  # scale/step_count must be final
        return {"opt_state": self.opt_state, "step_count": self._step_count,
                "scale": self.scaler.scale if self.scaler else None}

    def load_state_dict(self, state_dict):
        self.opt_state = state_dict["opt_state"]
        self._step_count = state_dict.get("step_count", 0)
        if self.scaler is not None and state_dict.get("scale") is not None:
            self.scaler.scale = state_dict["scale"]
