"""Ulysses-style all-to-all sequence parallelism over the mesh ``sp`` axis.

The second canonical long-context strategy next to ring attention
(``parallel/ring.py``): instead of rotating KV blocks around a ring, two
``all_to_all`` collectives re-shard the activations for the attention op —

- inbound: (B, S/sp, H, D) sequence-sharded → (B, S, H/sp, D) head-sharded.
  Every device then holds the FULL sequence for its slice of heads, so
  attention is computed exactly (any mask/causal structure, no streaming
  softmax) by the ordinary dense/flash kernel;
- outbound: the mirror all_to_all restores sequence sharding for the
  position-wise rest of the layer (MLP/norms run on S/sp rows).

Trade-off vs ring (the DeepSpeed-Ulysses analysis): all-to-all moves
O(B·S·H·D/sp) per device regardless of sp and needs ``H % sp == 0``, but
attention itself stays a single fused kernel over the full sequence — better
at moderate sp and plentiful heads; ring wins when sp exceeds the head count
or at extreme S where even one full-sequence score row is too big. The
reference has NO native implementation of either (SURVEY.md §2.4: SP exists
only as a Megatron passthrough flag).

Selection: ``SequenceParallelPlugin(ring_attention=False)`` or
``attention_impl="ulysses"`` on a model config.
"""

from __future__ import annotations

import numpy as np

import jax
from jax import lax
from jax.sharding import PartitionSpec as P


def ulysses_attention(q, k, v, *, causal=True, mask=None, mesh=None, axis_name: str = "sp"):
    """Sequence-parallel exact attention via head↔sequence all-to-all.

    q/k/v: (B, S, H, D) global arrays with S sharded on ``axis_name``; heads may
    simultaneously be sharded on ``tp``. Requires the per-device head count to
    divide by the ``sp`` degree."""
    from ..ops.attention import dense_attention

    if mesh is None:
        from ..state import PartialState

        mesh = PartialState().mesh
    sp = mesh.shape.get(axis_name, 1)
    if sp == 1:
        return dense_attention(q, k, v, causal=causal, mask=mask)

    tp = mesh.shape.get("tp", 1)
    B, S, H, D = q.shape
    if (H // tp if H % tp == 0 else H) % sp != 0:
        raise ValueError(
            f"Ulysses needs heads divisible by sp: {H} heads / tp={tp} across sp={sp}. "
            "Use ring attention (SequenceParallelPlugin(ring_attention=True)) instead."
        )

    from .sharding import batch_axes_for

    batch_axes = batch_axes_for(B, mesh)
    head_axis = "tp" if H % tp == 0 and tp > 1 else None
    qkv_spec = P(batch_axes, axis_name, head_axis, None)
    mask_spec = P(batch_axes, axis_name)

    def local(q, k, v, mask):
        # Inbound: scatter heads (axis 2), gather sequence (axis 1).
        q, k, v = (
            lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1, tiled=True)
            for t in (q, k, v)
        )
        if mask is not None:
            mask = lax.all_gather(mask, axis_name, axis=1, tiled=True)
        out = dense_attention(q, k, v, causal=causal, mask=mask)
        # Outbound: scatter sequence back, gather heads.
        return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)

    from ..utils.jax_compat import shard_map

    if mask is None:
        fn = shard_map(
            lambda q, k, v: local(q, k, v, None),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec,
            check_vma=False,
        )
        return fn(q, k, v)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, mask)
