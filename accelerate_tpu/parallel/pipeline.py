"""GPipe pipeline-parallel *training* schedule over the mesh ``pp`` axis.

This replaces the round-2 "pp = shard the layer-stack dim under GSPMD" design,
whose HLO all-gathered each stage's weights to the data every step (the traffic
pattern of FSDP, growing with model size). Here stage weights are **stationary**
— each pp rank keeps its own contiguous block of layers — and the *activations*
move stage-to-stage through ``lax.ppermute``, microbatch by microbatch, exactly
the communication shape of a real pipeline.

Reference parity: the reference's training-side PP is Megatron's ``pp_degree``
passthrough (``src/accelerate/utils/dataclasses.py:2110-2111``) and its native
scheduler is the GPipe-style pippy wrapper for inference
(``src/accelerate/inference.py:73-96``). This module is the TPU-native training
scheduler those defer to elsewhere.

Design (validated numerically against the plain ``lax.scan`` forward):

- ``jax.shard_map`` manual over **only** the ``pp`` axis (``axis_names={'pp'}``)
  — tp/fsdp/dp/sp stay *auto*, so GSPMD keeps partitioning the per-stage matmuls
  (Megatron tp all-reduces, fsdp weight gathers) inside each stage unchanged.
- The global batch is split into ``M`` microbatches **per data shard** (a
  layout-only reshape/transpose — see ``microbatch``), so microbatch indexing
  never crosses the (dp, fsdp) batch sharding and costs zero communication.
- A ``lax.scan`` over ``M + P - 1`` ticks runs the classic GPipe wavefront:
  stage 0 feeds a fresh microbatch each tick, every stage applies its layer
  block, the result ppermutes to the next stage, the last stage banks finished
  microbatches into an output buffer.
- **Backward is autodiff**: ppermute's transpose is the reverse-ring ppermute
  and the tick-scan reverses, yielding the GPipe backward wavefront (all
  forwards, then all backwards) with no hand-written schedule. Per-microbatch
  gradient contributions accumulate into each stage's stationary weights.
- Read-only per-microbatch context (rotary tables, attention mask) is *not*
  ppermuted: it is replicated over pp, and stage ``s`` at tick ``t`` indexes
  microbatch ``t - s`` locally — only the residual stream (+ tiny aux scalars)
  rides the ring.

Bubble fraction is ``(P-1)/(M+P-1)`` — pick ``num_microbatches >= 4*pp`` for
utilization; correctness holds for any ``M >= 1``. One semantic note: ops that
group over the whole batch see per-microbatch groups instead — for MoE with a
finite capacity factor, expert-capacity competition (token dropping) happens
within each microbatch, the standard behavior of pipelined MoE stacks
(GShard/Megatron); drop-free capacity is exactly batch-separable. Memory is GPipe-shaped: the
tick-scan saves one boundary activation per tick per stage, with intermediate
layer activations governed by the model's own ``remat`` flag exactly as in the
non-pipelined path.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _data_axes_size(mesh: Mesh) -> int:
    return (
        mesh.shape.get("dcn", 1)
        * mesh.shape.get("dp", 1)
        * mesh.shape.get("fsdp", 1)
    )


def microbatch(x, mesh: Mesh, num_microbatches: int):
    """(B, ...) -> (M, B//M, ...) with each microbatch drawing an equal
    contiguous chunk from every (dp, fsdp) batch shard.

    The naive ``reshape(M, B//M, ...)`` would put the data sharding on the
    microbatch dim, so indexing microbatches inside the pipeline would
    all-gather the batch across data shards every tick. This permuted split is
    layout-only (per-shard reshape + transpose), pinned by a sharding
    constraint; ``unmicrobatch`` inverts it so batch order round-trips exactly.
    """
    dpf = _data_axes_size(mesh)
    M = num_microbatches
    B = x.shape[0]
    mb = B // (dpf * M)
    x = x.reshape(dpf, M, mb, *x.shape[1:])
    x = jnp.swapaxes(x, 0, 1)
    x = x.reshape(M, dpf * mb, *x.shape[3:])
    return lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(None, ("dcn", "dp", "fsdp"), *([None] * (x.ndim - 2))))
    )


def unmicrobatch(xs, mesh: Mesh):
    """Inverse of ``microbatch``: (M, B//M, ...) -> (B, ...) in original order."""
    dpf = _data_axes_size(mesh)
    M, Bm = xs.shape[0], xs.shape[1]
    mb = Bm // dpf
    x = xs.reshape(M, dpf, mb, *xs.shape[2:])
    x = jnp.swapaxes(x, 0, 1)
    x = x.reshape(M * Bm, *xs.shape[2:])
    return lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(("dcn", "dp", "fsdp"), *([None] * (x.ndim - 1))))
    )


@dataclass
class PipelineSpec:
    """Everything the model forward needs to route its layer stack through the
    pipeline: the mesh (for the pp axis + batch layout) and the microbatch
    count. Built by the Accelerator from ``PipelineParallelPlugin`` and passed
    into ``module.apply(..., pipeline=spec)`` for pipeline-capable models."""

    mesh: Mesh
    num_microbatches: int

    def run(self, module, stage_layers, x, ctx):
        """Drive ``module.block`` over the pipelined layer stack.

        ``stage_layers`` is the stacked-layer param subtree (leading dim ``L``
        sharded on ``pp``); ``x`` is the (B, S, H) residual stream; ``ctx`` the
        model's read-only per-batch context dict (leaves with a leading batch
        dim are microbatched; ``None`` leaves pass through).

        Returns ``(x_out, aux)`` where ``aux`` maps each of the module's
        ``scan_aux_keys`` to its scalar mean over layers and microbatches
        (empty dict for dense models).
        """
        mesh = self.mesh
        M = self.num_microbatches
        n_stages = mesh.shape["pp"]
        dpf = _data_axes_size(mesh)
        B = x.shape[0]
        if B % (dpf * M) != 0:
            raise ValueError(
                f"Pipeline needs batch {B} divisible by data-parallel degree x "
                f"num_microbatches = {dpf}*{M}; adjust the batch size or "
                f"PipelineParallelPlugin(num_microbatches=...)."
            )
        aux_keys = tuple(getattr(module, "scan_aux_keys", ()) or ())
        cfg = getattr(module, "config", None)
        remat = bool(getattr(cfg, "remat", False))
        remat_policy = getattr(cfg, "remat_policy", "nothing_saveable")

        # Context entries without a leading batch dim (or None) replicate
        # across microbatches instead of being split.
        ctx_whole = {k for k, v in ctx.items() if v is None or jnp.ndim(v) == 0 or v.shape[0] != B}
        # The residual stream crosses the shard_map boundary in f32: the
        # transpose of a pp-replicated input is a psum of its cotangent, and a
        # bf16 all-reduce trips XLA CPU's promotion pass on the virtual test
        # mesh. Compute inside stays in the model's dtype.
        compute_dtype = x.dtype
        xs = microbatch(x, mesh, M).astype(jnp.float32)
        ctx_mb = {k: (v if k in ctx_whole else microbatch(v, mesh, M)) for k, v in ctx.items()}

        def per_stage(stage_layers, xs, ctx_mb):
            xs = xs.astype(compute_dtype)
            stage = lax.axis_index("pp")

            def stage_fn(x, ctx_local):
                def block_body(carry, layer):
                    x, aux_acc = carry
                    ctx_call = dict(ctx_local)
                    x = module.block(layer, x, ctx_call)
                    aux = tuple(ctx_call.pop(k) for k in aux_keys)
                    aux_acc = tuple(a + v for a, v in zip(aux_acc, aux))
                    return (x, aux_acc), None

                if remat:
                    policy = getattr(jax.checkpoint_policies, remat_policy)
                    block_body = jax.checkpoint(block_body, policy=policy)
                zero_aux = tuple(jnp.zeros((), jnp.float32) for _ in aux_keys)
                (x, aux), _ = lax.scan(block_body, (x, zero_aux), stage_layers)
                return x, aux

            def tick(carry, t):
                state, aux_state, outputs, aux_out = carry
                # Stage s processes microbatch (t - s); clip keeps the gather
                # in-bounds during drain ticks (results there are discarded).
                m_in = jnp.clip(t, 0, M - 1)
                m_here = jnp.clip(t - stage, 0, M - 1)
                inp = lax.dynamic_index_in_dim(xs, m_in, keepdims=False)
                ctx_local = {
                    k: (v if k in ctx_whole else lax.dynamic_index_in_dim(v, m_here, keepdims=False))
                    for k, v in ctx_mb.items()
                }
                x_in = jnp.where(stage == 0, inp, state)
                aux_in = tuple(jnp.where(stage == 0, jnp.zeros((), jnp.float32), a) for a in aux_state)
                y, aux_y = stage_fn(x_in, ctx_local)
                aux_y = tuple(a + b for a, b in zip(aux_in, aux_y))
                # Last stage banks the finished microbatch.
                out_idx = jnp.clip(t - (n_stages - 1), 0, M - 1)
                write = (stage == n_stages - 1) & (t >= n_stages - 1)
                cur = lax.dynamic_index_in_dim(outputs, out_idx, keepdims=False)
                outputs = lax.dynamic_update_index_in_dim(
                    outputs, jnp.where(write, y, cur), out_idx, 0
                )
                aux_out = tuple(
                    lax.dynamic_update_index_in_dim(
                        ao, jnp.where(write, ay, lax.dynamic_index_in_dim(ao, out_idx, keepdims=False)), out_idx, 0
                    )
                    for ao, ay in zip(aux_out, aux_y)
                )
                perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
                state = lax.ppermute(y, "pp", perm)
                aux_state = tuple(lax.ppermute(a, "pp", perm) for a in aux_y)
                return (state, aux_state, outputs, aux_out), None

            outputs = jnp.zeros_like(xs)
            aux_out = tuple(jnp.zeros((M,), jnp.float32) for _ in aux_keys)
            state = jnp.zeros_like(xs[0])
            aux_state = tuple(jnp.zeros((), jnp.float32) for _ in aux_keys)
            (state, aux_state, outputs, aux_out), _ = lax.scan(
                tick, (state, aux_state, outputs, aux_out), jnp.arange(M + n_stages - 1)
            )
            # Finished microbatches live only on the last stage (zeros
            # elsewhere): psum over pp broadcast-sums them everywhere so the
            # result re-enters the GSPMD world replicated over pp, matching
            # the non-pipelined activation layout. The sum runs in f32: exact
            # (one non-zero contribution per element) and it sidesteps XLA
            # CPU's bf16 all-reduce promotion crash on the virtual test mesh.
            out_dtype = outputs.dtype
            outputs = lax.psum(outputs.astype(jnp.float32), "pp").astype(out_dtype)
            aux_out = tuple(lax.psum(a, "pp") for a in aux_out)
            return outputs, aux_out

        out, aux_out = jax.shard_map(
            per_stage,
            mesh=mesh,
            in_specs=(P("pp"), P(), P()),
            out_specs=(P(), P()),
            axis_names={"pp"},
            check_vma=False,
        )(stage_layers, xs, ctx_mb)
        x_out = unmicrobatch(out, mesh)
        n_layers = jax.tree_util.tree_leaves(stage_layers)[0].shape[0]
        aux = {k: jnp.mean(a) / n_layers for k, a in zip(aux_keys, aux_out)}
        return x_out, aux


def resolve_pipeline_spec(module, params, mesh: Mesh, num_microbatches: int = 0):
    """Decide whether the pipelined schedule applies, returning a
    ``PipelineSpec`` or ``None`` (falls back to the GSPMD layer-dim sharding).

    Engages when the mesh has pp > 1, the module advertises
    ``pipeline_capable`` (the embed/block/head stage protocol with a
    context-dict block signature), and the layer count splits evenly across
    stages — the same divisibility the sharding planner requires before it
    places the layer stack on ``pp``.
    """
    pp = mesh.shape.get("pp", 1)
    if pp <= 1 or not getattr(module, "pipeline_capable", False):
        return None
    cfg = getattr(module, "config", None)
    ws = getattr(cfg, "layer_windows", None)
    if ws is not None and len(set(ws)) > 1:
        # Mixed attention regimes need per-layer static config inside the
        # stage body; the pipeline's uniform stage scan can't express that —
        # fall back to the GSPMD layer-dim sharding.
        return None
    layers = params.get("layers") if isinstance(params, dict) else None
    if not layers:
        return None
    n_layers = jax.tree_util.tree_leaves(layers)[0].shape[0]
    if n_layers % pp != 0:
        return None
    if num_microbatches <= 0:
        num_microbatches = pp  # default: one microbatch in flight per stage
    return PipelineSpec(mesh=mesh, num_microbatches=num_microbatches)
